"""Device join-probe conformance (VERDICT r2 next #7): the on-condition
cross-product mask — the reference JoinProcessor's per-event find() hot
loop — evaluated as one [n, m] broadcast program on the device, backend-
identical to the host numpy path.

Reference: query/input/stream/join/JoinProcessor.java:36-122."""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback

STREAMS = """
define stream L (id int, price float);
define stream R (id int, threshold float);
"""


def run_app(app, sends, engine=None):
    prefix = f"@app:engine('{engine}') " if engine else ""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("@app:playback " + prefix + app)
    out = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: out.extend(tuple(e.data) for e in evs)))
    rt.start()
    for sid, row, ts in sends:
        rt.get_input_handler(sid).send(row, timestamp=ts)
    qr = rt.query_runtimes["q"]
    backend, reason = qr.backend, qr.backend_reason
    rt.shutdown()
    return backend, reason, out


def assert_parity(app, sends, expect_device=True):
    bh, _, host = run_app(app, sends, engine="host")
    bd, reason, dev = run_app(app, sends)
    if expect_device:
        assert bd == "device", f"probe did not compile: {reason}"
    else:
        assert bd == "host", "expected host fallback"
    assert host == dev, f"host={host} dev={dev}"
    return host


def _sends(n=30, seed=4):
    rng = np.random.default_rng(seed)
    out, t = [], 1_000_000
    for _ in range(n):
        if rng.integers(0, 2):
            out.append(("L", [int(rng.integers(0, 5)),
                              float(np.float32(rng.uniform(0, 100)))], t))
        else:
            out.append(("R", [int(rng.integers(0, 5)),
                              float(np.float32(rng.uniform(0, 100)))], t))
        t += 100
    return out


def test_window_window_range_join_device():
    app = STREAMS + """
        @info(name='q')
        from L#window.length(5) join R#window.length(5)
            on L.price > R.threshold and L.id == R.id
        select L.id as lid, L.price as p, R.threshold as t
        insert into Out;"""
    out = assert_parity(app, _sends())
    assert len(out) > 3


def test_outer_join_device():
    app = STREAMS + """
        @info(name='q')
        from L#window.length(4) left outer join R#window.length(4)
            on L.price > R.threshold
        select L.id as lid, R.id as rid insert into Out;"""
    assert_parity(app, _sends(seed=9))


def test_unidirectional_device():
    app = STREAMS + """
        @info(name='q')
        from L#window.length(3) unidirectional join R#window.length(6)
            on L.price > R.threshold
        select L.price as p, R.threshold as t insert into Out;"""
    assert_parity(app, _sends(seed=11))


def test_stream_table_range_join_device():
    """Non-indexable (range) condition against a table: host has no hash
    path — the device cross probe carries it."""
    app = """
        define stream L (id int, price float);
        define table T (tid int, threshold float);
        define stream Fill (tid int, threshold float);
        from Fill insert into T;
        @info(name='q')
        from L join T on L.price > T.threshold
        select L.id as lid, T.tid as tid insert into Out;"""
    sends = [("Fill", [1, 10.0], 1_000_000),
             ("Fill", [2, 50.0], 1_000_100),
             ("L", [7, 30.0], 1_000_200),     # beats threshold 10 only
             ("L", [8, 60.0], 1_000_300)]     # beats both
    out = assert_parity(app, sends)
    assert out == [(7, 1), (8, 1), (8, 2)]


def test_indexed_equality_join_stays_host_hash():
    """A PK-indexed equality condition keeps the host O(1) hash probe
    (recorded reason) — brute force on device would be slower."""
    app = """
        define stream L (id int, price float);
        @PrimaryKey('tid')
        define table T (tid int, threshold float);
        define stream Fill (tid int, threshold float);
        from Fill insert into T;
        @info(name='q')
        from L join T on L.id == T.tid
        select L.id as lid, T.threshold as t insert into Out;"""
    sends = [("Fill", [1, 10.0], 1_000_000), ("L", [1, 5.0], 1_000_100)]
    b, reason, out = run_app(app, sends)
    assert b == "host" and "hash probe" in (reason or "")
    assert out == [(1, 10.0)]


def test_double_attrs_device_exact():
    """Round 5: DOUBLE compares ride monotone 64-bit keys split into two
    exact i32 lanes (plan/join_lanes.py) — no f32 rounding; parity incl.
    values that differ only below f32 precision, and -0.0 == 0.0."""
    app = """
        define stream L (id int, price double);
        define stream R (id int, threshold double);
        @info(name='q')
        from L#window.length(8) join R#window.length(8)
            on L.price > R.threshold
        select L.id as lid, R.id as rid insert into Out;"""
    eps = 1e-12
    sends = [("L", [1, 5.0], 1_000_000),
             ("R", [2, 5.0 - eps], 1_000_100),    # just below: matches
             ("R", [3, 5.0], 1_000_200),          # equal: no match
             ("R", [4, 5.0 + eps], 1_000_300),    # just above: no match
             ("L", [7, 50.1], 1_000_600),
             ("R", [8, 50.099999999999994], 1_000_700)]
    out = assert_parity(app, sends)
    assert (1, 2) in out and (7, 8) in out and (1, 4) not in out


def test_big_int_ids_guard_to_host_mask():
    """INT ids beyond 2^24 can't ride f32 probe lanes exactly: that chunk
    uses the host mask — results stay identical either way."""
    app = STREAMS.replace("id int", "id long") + """
        @info(name='q')
        from L#window.length(3) join R#window.length(3)
            on L.id == R.id
        select L.price as p, R.threshold as t insert into Out;"""
    big = 20_000_000
    sends = [("L", [big, 5.0], 1_000_000),
             ("R", [big, 3.0], 1_000_100),
             ("R", [big + 1, 4.0], 1_000_200)]
    assert_parity(app, sends)


def test_named_window_join_device():
    app = """
        define stream L (id int, price float);
        define stream W (id int, threshold float);
        define window Win (id int, threshold float) length(4);
        from W insert into Win;
        @info(name='q')
        from L join Win on L.price > Win.threshold and L.id == Win.id
        select L.id as lid, Win.threshold as t insert into Out;"""
    sends = [("W", [1, 10.0], 1_000_000), ("W", [2, 90.0], 1_000_100),
             ("L", [1, 50.0], 1_000_200), ("L", [2, 95.0], 1_000_300)]
    assert_parity(app, sends)


def test_string_equality_join_device():
    """`on A.symbol == B.symbol` rides shared dictionary-code lanes."""
    app = """
        define stream L (symbol string, price float);
        define stream R (symbol string, qty int);
        @info(name='q')
        from L#window.length(3) join R#window.length(3)
            on L.symbol == R.symbol and L.price > 10.0
        select L.symbol as s, L.price as p, R.qty as q insert into Out;"""
    sends = [("L", ["IBM", 50.0], 1_000_000),
             ("R", ["IBM", 5], 1_000_100),
             ("R", ["WSO2", 7], 1_000_200),
             ("L", ["WSO2", 60.0], 1_000_300),
             ("L", ["IBM", 4.0], 1_000_400)]       # fails price filter
    out = assert_parity(app, sends)
    assert ("IBM", 50.0, 5) in out and ("WSO2", 60.0, 7) in out


def test_string_order_compare_device():
    """Round 5: string ORDER compares ride per-probe union rank lanes
    (plan/join_lanes.py) — parity for var-vs-var order joins."""
    app = """
        define stream L (symbol string, price float);
        define stream R (symbol string, qty int);
        @info(name='q')
        from L#window.length(3) join R#window.length(3)
            on L.symbol > R.symbol
        select L.price as p, R.qty as q insert into Out;"""
    sends = [("L", ["b", 1.0], 1_000_000), ("R", ["a", 2], 1_000_100),
             ("R", ["c", 3], 1_000_200), ("L", ["aa", 4.0], 1_000_300),
             ("L", ["ca", 5.0], 1_000_400)]
    out = assert_parity(app, sends)
    assert (1.0, 2) in out and (4.0, 2) in out and (5.0, 3) in out


def test_string_join_with_nulls_guards_to_host_mask():
    """A null symbol in a chunk guards that probe to the host mask —
    null == null must stay FALSE (reference compare law)."""
    app = """
        define stream L (symbol string, price float);
        define stream R (symbol string, qty int);
        @info(name='q')
        from L#window.length(3) join R#window.length(3)
            on L.symbol == R.symbol
        select L.price as p, R.qty as q insert into Out;"""
    sends = [("L", [None, 1.0], 1_000_000),
             ("R", [None, 2], 1_000_100),
             ("L", ["IBM", 3.0], 1_000_200),
             ("R", ["IBM", 4], 1_000_300)]
    out = assert_parity(app, sends)
    assert (3.0, 4) in out and (1.0, 2) not in out


def test_f32_unsafe_float_literal_keys_exactly():
    """Round 5 (supersedes the ADVICE r3 host pin): a float constant not
    exactly representable in float32 (50.1) now compiles via the exact
    64-bit key lanes — the borderline FLOAT-vs-literal compare matches
    the host float64 promotion exactly."""
    app = """
    define stream L (sym string, price float);
    define stream R (sym string, price float);
    @info(name='q')
    from L#window.length(10) join R#window.length(10)
        on L.price > R.price and R.price < 50.1
    select L.sym as ls, R.sym as rs insert into Out;
    """
    sends = [("L", ["l1", 60.0], 1_000_000),
             ("R", ["r1", float(np.float32(50.1))], 1_000_100),
             ("R", ["r2", 50.25], 1_000_200)]
    out = assert_parity(app, sends)
    # np.float32(50.1) = 50.099998... < 50.1 → r1 joins; 50.25 doesn't
    assert ("l1", "r1") in out and ("l1", "r2") not in out
