"""Multi-host distributed backend (parallel/distributed.py) on the virtual
8-device CPU mesh — the num_processes=1 degenerate case of the code path
that tests/test_multihost.py additionally executes with TWO real OS
processes over localhost DCN (global sharded arrays assembled from
process-local data, sharded step, host-local shard readback)."""
import numpy as np
import pytest

from siddhi_tpu.parallel import distributed as dist
from siddhi_tpu.parallel.mesh import partition_mesh

APP = """
define stream S (partition int, price float, kind int);
@info(name='q')
from every e1=S[kind == 0 and price > 50.0] -> e2=S[kind == 1 and price > e1.price]
    within 10 sec
select e1.price as p1, e2.price as p2
insert into Out;
"""


def _flat_events(n, n_partitions, seed=0):
    rng = np.random.default_rng(seed)
    t = n // n_partitions
    pids = np.repeat(np.arange(n_partitions), t)
    cols = {"partition": pids.astype(np.float32),
            "price": rng.uniform(0, 100, n).astype(np.float32),
            "kind": rng.integers(0, 2, n).astype(np.float32)}
    ts = 1_000_000 + np.arange(n, dtype=np.int64)
    return pids, cols, ts


def test_host_partition_math():
    assert dist.host_partition_range(64, process_id=0, num_processes=4) == \
        (0, 16)
    assert dist.host_partition_range(64, process_id=3, num_processes=4) == \
        (48, 64)
    assert dist.host_for_partition(0, 64, num_processes=4) == 0
    assert dist.host_for_partition(17, 64, num_processes=4) == 1
    assert dist.host_for_partition(63, 64, num_processes=4) == 3


def test_init_distributed_noop_without_env(monkeypatch):
    monkeypatch.delenv(dist.COORD_ENV, raising=False)
    assert dist.init_distributed() is False


def test_distributed_bank_matches_unsharded():
    from siddhi_tpu.ops.nfa import build_block_step, pack_blocks
    from siddhi_tpu.plan.nfa_compiler import CompiledPatternNFA
    import jax

    n_partitions, t = 32, 8
    pids, cols, ts = _flat_events(n_partitions * t, n_partitions)
    bank = dist.DistributedPatternBank(APP, n_partitions=n_partitions,
                                       n_slots=8)
    assert bank.local_range == (0, n_partitions)   # single process owns all
    block = pack_blocks(pids, cols, ts, np.zeros(len(pids), np.int32),
                        n_partitions, base_ts=1_000_000)
    local_mask, local_ts, stats = bank.step_local(block)
    assert local_mask.shape[0] == n_partitions

    # unsharded single-device reference on the same workload
    nfa = CompiledPatternNFA(APP, n_partitions=n_partitions, n_slots=8)
    step = jax.jit(build_block_step(nfa.spec))
    _, (mask, _caps, _ts, _e, _s) = step(nfa.carry, block)
    expected = int(np.asarray(mask).astype(np.int64).sum())
    assert stats["matches"] == expected
    assert int(local_mask.astype(np.int64).sum()) == expected
    assert stats["matches"] > 0
    assert stats["dropped"] == 0


def test_distributed_bank_shard_readback_partition_rows():
    """local_rows returns rows in global partition order — the host-local
    egress path decodes the right partitions' matches."""
    from siddhi_tpu.ops.nfa import pack_blocks
    n_partitions, t = 16, 4
    pids, cols, ts = _flat_events(n_partitions * t, n_partitions, seed=2)
    # deterministic single match in partition 5: kind0@60 then kind1@70
    cols["kind"][:] = 0
    cols["price"][:] = 1.0
    rows = np.flatnonzero(pids == 5)
    cols["kind"][rows[0]], cols["price"][rows[0]] = 0, 60.0
    cols["kind"][rows[1]], cols["price"][rows[1]] = 1, 70.0
    bank = dist.DistributedPatternBank(APP, n_partitions=n_partitions,
                                       n_slots=8)
    block = pack_blocks(pids, cols, ts, np.zeros(len(pids), np.int32),
                        n_partitions, base_ts=1_000_000)
    local_mask, _local_ts, stats = bank.step_local(block)
    assert stats["matches"] == 1
    per_partition = local_mask.reshape(n_partitions, -1).sum(axis=1)
    assert per_partition[5] == 1 and per_partition.sum() == 1
