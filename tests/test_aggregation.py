"""Incremental aggregation behavioural tests (reference model: siddhi-core
aggregation/*TestCase — define aggregation, aggregate by time, query with
within/per via store queries and joins)."""
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback

APP = """
define stream TradeStream (symbol string, price double, volume long, ts long);
define aggregation TradeAgg
from TradeStream
select symbol, avg(price) as avgPrice, sum(price) as total, count() as n
group by symbol
aggregate by ts every sec ... year;
"""


def setup():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP)
    rt.start()
    h = rt.get_input_handler("TradeStream")
    # two events in the same second, one in the next minute
    h.send(["WSO2", 50.0, 1, 1496289950000])
    h.send(["WSO2", 70.0, 1, 1496289950500])
    h.send(["WSO2", 60.0, 1, 1496290016000])
    h.send(["IBM", 100.0, 1, 1496289950000])
    return m, rt


def test_store_query_per_seconds():
    m, rt = setup()
    events = rt.query("""
        from TradeAgg within 1496289940000, 1496290020000 per 'seconds'
        select AGG_TIMESTAMP, symbol, avgPrice, total, n
    """)
    rows = sorted([e.data for e in events], key=lambda r: (r[0], r[1]))
    assert rows == [
        [1496289950000, "IBM", 100.0, 100.0, 1],
        [1496289950000, "WSO2", 60.0, 120.0, 2],
        [1496290016000, "WSO2", 60.0, 60.0, 1],
    ]
    rt.shutdown()


def test_store_query_per_minutes_rollup():
    m, rt = setup()
    events = rt.query("""
        from TradeAgg within 1496289900000, 1496290100000 per 'minutes'
        select AGG_TIMESTAMP, symbol, total, n
    """)
    rows = sorted([e.data for e in events], key=lambda r: (r[0], r[1]))
    # minute buckets: 1496289900000 (events 1,2,IBM) and 1496289960000
    assert rows == [
        [1496289900000, "IBM", 100.0, 1],
        [1496289900000, "WSO2", 120.0, 2],
        [1496289960000, "WSO2", 60.0, 1],
    ]
    rt.shutdown()


def test_store_query_on_filter():
    m, rt = setup()
    events = rt.query("""
        from TradeAgg on symbol == 'WSO2'
        within 1496289940000, 1496290020000 per 'seconds'
        select symbol, total
    """)
    assert sorted(e.data for e in events) == [["WSO2", 60.0],
                                              ["WSO2", 120.0]]
    rt.shutdown()


def test_aggregation_join():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP + """
        define stream QueryStream (symbol string, start long, end long);
        @info(name='query1')
        from QueryStream as q join TradeAgg as a
        on a.symbol == q.symbol
        within 1496289940000, 1496290020000
        per 'seconds'
        select a.symbol as symbol, a.total as total, a.n as n
        insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: got.extend(e.data for e in evs)))
    rt.start()
    h = rt.get_input_handler("TradeStream")
    h.send(["WSO2", 50.0, 1, 1496289950000])
    h.send(["WSO2", 70.0, 1, 1496289950500])
    rt.get_input_handler("QueryStream").send(["WSO2", 0, 0])
    rt.shutdown()
    assert got == [["WSO2", 120.0, 2]]


def test_aggregation_snapshot_restore():
    m, rt = setup()
    snap = rt.snapshot()
    rt.shutdown()
    m2 = SiddhiManager()
    rt2 = m2.create_siddhi_app_runtime(APP)
    rt2.restore(snap)
    rt2.start()
    rt2.get_input_handler("TradeStream").send(
        ["WSO2", 40.0, 1, 1496289950800])
    events = rt2.query("""
        from TradeAgg within 1496289940000, 1496290020000 per 'seconds'
        select symbol, total, n
    """)
    rows = sorted(e.data for e in events)
    assert ["WSO2", 160.0, 3] in rows
    rt2.shutdown()


def test_renamed_group_by_output():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream T (symbol string, price double, ts long);
        define aggregation A
        from T select symbol as sym, sum(price) as total
        group by symbol
        aggregate by ts every sec ... min;
    """)
    rt.start()
    rt.get_input_handler("T").send(["WSO2", 10.0, 1496289950000])
    events = rt.query("from A within 1496289940000, 1496290020000 "
                      "per 'seconds' select sym, total")
    assert [e.data for e in events] == [["WSO2", 10.0]]
    rt.shutdown()


def test_last_value_is_per_bucket():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream T (symbol string, price double, ts long);
        define aggregation A
        from T select symbol, price as lastPrice, sum(price) as total
        group by symbol
        aggregate by ts every sec ... min;
    """)
    rt.start()
    h = rt.get_input_handler("T")
    h.send(["WSO2", 10.0, 1496289950000])
    h.send(["WSO2", 99.0, 1496289951000])   # next second bucket
    events = rt.query("from A within 1496289940000, 1496290020000 "
                      "per 'seconds' select AGG_TIMESTAMP, lastPrice")
    rows = sorted(e.data for e in events)
    assert rows == [[1496289950000, 10.0], [1496289951000, 99.0]]
    rt.shutdown()


def test_aggregation_purge():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:playback
        define stream T (symbol string, price double, ts long);
        @purge(enable='true', interval='10 sec',
               @retentionPeriod(sec='1 min', min='1 hour'))
        define aggregation A
        from T select symbol, sum(price) as total
        group by symbol
        aggregate by ts every sec ... min;
    """)
    rt.start()
    agg = rt.aggregations["A"]
    h = rt.get_input_handler("T")
    h.send(["WSO2", 10.0, 1_000_000], timestamp=1_000_000)
    h.send(["WSO2", 20.0, 1_200_000], timestamp=1_200_000)
    # the scheduled purge already ran on virtual-time advance: the first
    # sec bucket (1,000,000) fell past the 1-minute retention
    assert len(agg.buckets["sec"]) == 1
    assert len(agg.buckets["min"]) == 2     # minute retention = 1 hour
    agg.purge(1_200_000 + 3_700_000)        # past the minute retention too
    assert len(agg.buckets["sec"]) == 0
    assert len(agg.buckets["min"]) == 0
    rt.shutdown()
