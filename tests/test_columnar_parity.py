"""Columnar-vs-per-event rim parity (round 11).

The zero-copy host rim claims `send_batch` (columns in) and
`ColumnarStreamCallback` (columns out) are the SAME engine as the legacy
per-event `send` / `StreamCallback` shims — not a parallel code path
with its own semantics.  These tests feed seeded randomized batches
through both rims of the same app and require bit-identical delivery:

  * send vs send_batch over every column dtype (INT/LONG/FLOAT/DOUBLE/
    BOOL/STRING), through a filter+select query;
  * an @Async + @quarantine app: poison rows rejected identically on
    both rims, clean rows delivered identically;
  * a partitioned windowed aggregation (per-key state);
  * StreamCallback vs ColumnarStreamCallback on the same run deliver
    identical content;
  * the rim counters: a pure columnar run materializes ZERO Event
    objects, the legacy per-event shims materialize exactly once and
    only when an element is touched.
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from siddhi_tpu import (ColumnarStreamCallback, SiddhiManager,  # noqa: E402
                        StreamCallback)
from siddhi_tpu.core.profiling import rim_stats  # noqa: E402

IN = ("define stream In (symbol string, price float, weight double, "
      "volume long, rank int, flag bool);\n")
SEL = ("@info(name='q') from In[volume > 40] "
       "select symbol, price, weight, volume, rank, flag "
       "insert into Out;\n")


def _feed(n, seed):
    """Seeded random columns in the stream's native dtypes + rows view
    of the same values (the rows are derived FROM the columns, so both
    rims ingest identical scalars)."""
    rng = np.random.default_rng(seed)
    pool = np.asarray(["IBM", "WSO2", "ORCL", "MSFT"], object)
    cols = {
        "symbol": pool[rng.integers(0, len(pool), n)],
        "price": rng.uniform(0, 100, n).astype(np.float32),
        "weight": rng.uniform(-5, 5, n),
        "volume": rng.integers(0, 100, n).astype(np.int64),
        "rank": rng.integers(-3, 3, n).astype(np.int32),
        "flag": rng.integers(0, 2, n).astype(bool),
    }
    ts = 1_000_000 + np.cumsum(rng.integers(0, 5, n)).astype(np.int64)
    rows = [[cols["symbol"][i], float(cols["price"][i]),
             float(cols["weight"][i]), int(cols["volume"][i]),
             int(cols["rank"][i]), bool(cols["flag"][i])]
            for i in range(n)]
    return cols, ts, rows


def _run(app, sends, columnar_cb=False, batches=None):
    """One runtime, one feed, one capture.  `sends` is a list of
    (row, ts) for the per-event rim; `batches` is a list of
    (columns, ts_array) for the columnar rim (exactly one of the two)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    got = []
    if columnar_cb:
        def on_chunk(chunk):
            lanes = [chunk.columns[n].tolist() for n in chunk.names]
            got.extend(zip(chunk.timestamps.tolist(), map(tuple, zip(*lanes))))
        rt.add_callback("Out", ColumnarStreamCallback(on_chunk))
    else:
        rt.add_callback("Out", StreamCallback(
            lambda evs: got.extend((e.timestamp, tuple(e.data))
                                   for e in evs)))
    rt.start()
    h = rt.get_input_handler("In")
    if batches is not None:
        for cols, ts in batches:
            h.send_batch(cols, timestamps=ts)
    else:
        for row, ts in sends:
            h.send(row, ts)
    rt.flush()
    rt.shutdown()
    return got


def _split(cols, ts, parts):
    """Slice a columnar feed into `parts` send_batch calls."""
    edges = np.linspace(0, len(ts), parts + 1).astype(int)
    return [({k: v[a:b] for k, v in cols.items()}, ts[a:b])
            for a, b in zip(edges[:-1], edges[1:]) if b > a]


def test_send_vs_send_batch_bit_identical_all_dtypes():
    cols, ts, rows = _feed(300, seed=7)
    per_event = _run(IN + SEL, list(zip(rows, ts.tolist())))
    columnar = _run(IN + SEL, None, batches=_split(cols, ts, 4))
    assert len(per_event) > 0
    assert per_event == columnar


def test_stream_callback_vs_columnar_callback_identical():
    cols, ts, rows = _feed(240, seed=11)
    batches = _split(cols, ts, 3)
    legacy = _run(IN + SEL, None, batches=batches)
    columnar = _run(IN + SEL, None, batches=batches, columnar_cb=True)
    assert len(legacy) > 0
    assert legacy == columnar


def test_async_quarantine_parity_and_rejects():
    app = ("@Async(buffer.size='64', batch.size.max='50') "
           "@quarantine(ts.slack.ms='1000') " + IN + SEL)
    cols, ts, rows = _feed(200, seed=3)
    # poison a few prices: NaN rows must be rejected by BOTH rims
    bad = np.zeros(len(ts), bool)
    bad[[10, 77, 131]] = True
    cols = dict(cols)
    cols["price"] = cols["price"].copy()
    cols["price"][bad] = np.nan
    rows = [r if not bad[i] else
            [r[0], float("nan")] + r[2:] for i, r in enumerate(rows)]
    per_event = _run(app, list(zip(rows, ts.tolist())))
    columnar = _run(app, None, batches=_split(cols, ts, 5))
    clean = _run(IN + SEL, None,
                 batches=_split({k: v[~bad] for k, v in cols.items()},
                                ts[~bad], 1))
    assert len(per_event) > 0
    assert per_event == columnar == clean


def test_partitioned_window_aggregation_parity():
    app = (IN + "partition with (symbol of In) begin "
           "@info(name='q') from In#window.length(3) "
           "select symbol, sum(volume) as t, max(price) as mp "
           "insert into Out; end;\n")
    cols, ts, rows = _feed(180, seed=23)
    per_event = _run(app, list(zip(rows, ts.tolist())))
    columnar = _run(app, None, batches=_split(cols, ts, 6))
    assert len(per_event) == 180
    assert per_event == columnar


def test_columnar_run_materializes_zero_events():
    cols, ts, _rows = _feed(160, seed=5)
    r0 = rim_stats().events_materialized
    got = _run(IN + SEL, None, batches=_split(cols, ts, 2),
               columnar_cb=True)
    assert len(got) > 0
    assert rim_stats().events_materialized == r0, \
        "columnar send_batch -> ColumnarStreamCallback run built Events"


def test_legacy_shim_materializes_lazily_and_once():
    cols, ts, _rows = _feed(120, seed=9)
    seen = []
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(IN + SEL)
    rt.add_callback("Out", StreamCallback(seen.append))
    rt.start()
    rt.get_input_handler("In").send_batch(cols, timestamps=ts)
    rt.flush()
    rt.shutdown()
    assert seen
    # delivery alone (len/bool) builds nothing ...
    r0 = rim_stats().events_materialized
    n = sum(len(evs) for evs in seen)
    assert rim_stats().events_materialized == r0
    # ... first element access materializes the view, exactly once
    events = [e for evs in seen for e in evs]
    assert len(events) == n > 0
    assert rim_stats().events_materialized == r0 + n
    for evs in seen:
        list(evs)
    assert rim_stats().events_materialized == r0 + n, \
        "re-iterating a LazyEvents view re-materialized its Events"
    assert all(isinstance(e.timestamp, int) for e in events)


def _lazy_view(n=8):
    """A pending LazyEvents over a small chunk built straight from
    columns (no engine run needed for sequence-protocol edges)."""
    from siddhi_tpu.core.event import EventChunk, LazyEvents
    cols = {"symbol": np.asarray(["S%d" % i for i in range(n)], object),
            "price": np.arange(n, dtype=np.float64)}
    chunk = EventChunk.from_columns(["symbol", "price"],
                                    np.arange(n, dtype=np.int64), cols)
    return LazyEvents(chunk)


def test_lazy_events_sequence_protocol_edges():
    n = 8
    lazy = _lazy_view(n)
    r0 = rim_stats().events_materialized
    # len/bool/repr are delivery-path operations: none may materialize
    assert len(lazy) == n and bool(lazy)
    assert repr(lazy) == f"LazyEvents(n={n}, pending)"
    assert rim_stats().events_materialized == r0, \
        "len/bool/repr on a pending view built Events"
    # element access materializes exactly once; the counter moves by n
    assert lazy[0].data[0] == "S0"
    assert rim_stats().events_materialized == r0 + n
    # negative indices and slices behave like the list they stand for
    assert lazy[-1].data[0] == "S%d" % (n - 1)
    assert [e.data[0] for e in lazy[2:5]] == ["S2", "S3", "S4"]
    assert [e.data[0] for e in lazy[::-1]][0] == "S%d" % (n - 1)
    with np.testing.assert_raises(IndexError):
        lazy[n]
    # iteration after materialization reuses the same Event objects
    assert list(lazy)[0] is lazy[0]
    assert rim_stats().events_materialized == r0 + n, \
        "slices / re-iteration after materialize re-built Events"
    assert repr(lazy) == f"LazyEvents(n={n}, materialized={n})"


def test_lazy_events_empty_view():
    lazy = _lazy_view(0)
    r0 = rim_stats().events_materialized
    assert len(lazy) == 0 and not lazy
    assert list(lazy) == []
    assert lazy[0:3] == []
    assert rim_stats().events_materialized == r0
