"""@extension metadata decorator: arity validation + docgen rendering
(reference: siddhi-annotations @Extension/@Parameter/@Example + doc-gen
mojos; util/SiddhiExtensionLoader.java:50-101 annotation index)."""
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.query_api.definition import AttrType
from siddhi_tpu.utils.errors import SiddhiAppCreationError
from siddhi_tpu.utils.extension import FunctionExtension, extension


@extension(namespace="t", name="double_it",
           description="Doubles a numeric column",
           parameters=[("value", "numeric", "the column to double")],
           returns="double",
           examples=["t:double_it(price)"])
class DoubleIt(FunctionExtension):
    return_type = AttrType.DOUBLE

    def apply(self, col):
        return col * 2


@extension(namespace="t", name="addall",
           parameters=[("values...", "numeric", "columns to add")],
           returns="double")
class AddAll(FunctionExtension):
    return_type = AttrType.DOUBLE

    def apply(self, *cols):
        out = cols[0]
        for c in cols[1:]:
            out = out + c
        return out


APP = """
define stream S (a double, b double);
from S select {call} as r insert into Out;
"""


def make(call):
    m = SiddhiManager()
    m.set_extension("t:double_it", DoubleIt)
    m.set_extension("t:addall", AddAll)
    rt = m.create_siddhi_app_runtime(APP.format(call=call))
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    return rt, got


def test_metadata_extension_runs():
    rt, got = make("t:double_it(a)")
    rt.get_input_handler("S").send([3.0, 1.0])
    rt.shutdown()
    assert [e.data[0] for e in got] == [6.0]


def test_arity_validated_from_metadata():
    with pytest.raises(SiddhiAppCreationError, match="takes 1 arguments"):
        make("t:double_it(a, b)")


def test_variadic_metadata_allows_many():
    rt, got = make("t:addall(a, b)")
    rt.get_input_handler("S").send([3.0, 4.0])
    rt.shutdown()
    assert [e.data[0] for e in got] == [7.0]


def test_docgen_renders_metadata():
    from siddhi_tpu.tools.docgen import generate_markdown
    m = SiddhiManager()
    m.set_extension("t:double_it", DoubleIt)
    md = generate_markdown(m.siddhi_context.extension_registry)
    assert "### `t:double_it`" in md
    assert "Doubles a numeric column" in md
    assert "| `value` | numeric | the column to double |" in md
    assert "**Returns:** `double`" in md
    assert "t:double_it(price)" in md
