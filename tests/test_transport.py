"""Transport & fault-tolerance tests (reference model: transport/
InMemoryTransportTestCase + TestFailingInMemorySink/Source retry paths,
SourceHandler/SinkHandler HA SPI)."""
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.source_sink import (InMemoryBroker, InMemorySink,
                                         SinkHandler, SinkHandlerManager,
                                         SourceHandler, SourceHandlerManager)
from siddhi_tpu.utils.errors import ConnectionUnavailableError

APP = """
@source(type='inMemory', topic='in_t', @map(type='passThrough'))
define stream In (symbol string, price float);
@sink(type='inMemory', topic='out_t', @map(type='passThrough'))
define stream Out (symbol string, price float);
from In[price > 10] select symbol, price insert into Out;
"""


class Collect:
    def __init__(self):
        self.items = []

    def on_message(self, msg):
        self.items.append(msg)


def test_inmemory_transport_roundtrip():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP)
    col = Collect()
    col.topic = "out_t"
    InMemoryBroker.subscribe(col)
    rt.start()
    InMemoryBroker.publish("in_t", [["IBM", 50.0], ["X", 5.0]])
    rt.shutdown()
    InMemoryBroker.unsubscribe(col)
    assert len(col.items) == 1


def test_failing_sink_retries_then_succeeds():
    """Publish raises ConnectionUnavailable twice, then works (reference
    TestFailingInMemorySink connect-retry semantics)."""
    m = SiddhiManager()

    attempts = []

    class FailingSink(InMemorySink):
        def publish(self, payload, event):
            attempts.append(1)
            if len(attempts) < 3:
                raise ConnectionUnavailableError("down")
            super().publish(payload, event)

    m.set_extension("sink:flaky", FailingSink)
    rt = m.create_siddhi_app_runtime("""
        define stream In (symbol string);
        @sink(type='flaky', topic='flaky_t', @map(type='passThrough'))
        define stream Out (symbol string);
        from In select symbol insert into Out;
    """)
    col = Collect()
    col.topic = "flaky_t"
    InMemoryBroker.subscribe(col)
    rt.start()
    rt.get_input_handler("In").send(["IBM"])
    rt.shutdown()
    InMemoryBroker.unsubscribe(col)
    assert len(attempts) == 3       # two failures + one success
    assert len(col.items) == 1


def test_sink_handler_suppresses_on_passive_node():
    m = SiddhiManager()

    class PassiveSinkHandler(SinkHandler):
        def handle(self, payload, event):
            return None             # passive: publish nothing

    class Mgr(SinkHandlerManager):
        def generate_sink_handler(self, sink):
            return PassiveSinkHandler()

    m.set_sink_handler_manager(Mgr())
    rt = m.create_siddhi_app_runtime(APP)
    col = Collect()
    col.topic = "out_t"
    InMemoryBroker.subscribe(col)
    rt.start()
    InMemoryBroker.publish("in_t", [["IBM", 50.0]])
    rt.shutdown()
    InMemoryBroker.unsubscribe(col)
    assert col.items == []


def test_source_handler_filters_events():
    m = SiddhiManager()

    class DropAll(SourceHandler):
        def handle(self, events):
            return None

    class Mgr(SourceHandlerManager):
        def generate_source_handler(self, source):
            return DropAll()

    m.set_source_handler_manager(Mgr())
    rt = m.create_siddhi_app_runtime(APP)
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    InMemoryBroker.publish("in_t", [["IBM", 50.0]])
    rt.shutdown()
    assert got == []


def test_config_manager_reader():
    from siddhi_tpu.utils.config import InMemoryConfigManager
    cm = InMemoryConfigManager({"kafka.bootstrap": "b:9092",
                                "global": "x"},
                               {"shard.id": "3"})
    r = cm.generate_config_reader("kafka")
    assert r.read_config("bootstrap") == "b:9092"
    assert r.read_config("global") == "x"
    assert r.read_config("missing", "d") == "d"
    assert r.get_all_configs() == {"bootstrap": "b:9092"}
    assert cm.extract_system_configs("shard.id") == "3"
