"""Conformance: batched TPU NFA vs the host oracle pattern engine.

The oracle (core/pattern.py) mirrors the reference semantics test-by-test;
here the compiled NFA must produce the exact same match set on randomized
event streams across many partitions (SURVEY.md §7.6 exact-match
conformance).
"""
import numpy as np
import pytest

from siddhi_tpu import QueryCallback, SiddhiManager
from siddhi_tpu.plan.nfa_compiler import CompiledPatternNFA

APP = """
define stream S (partition int, price float, kind int);
@info(name='q')
from every e1=S[kind == 0 and price > 50.0] -> e2=S[kind == 1 and price > e1.price]
select e1.price as p1, e2.price as p2
insert into Out;
"""

APP_WITHIN = """
define stream S (partition int, price float, kind int);
@info(name='q')
from every e1=S[kind == 0 and price > 50.0] -> e2=S[kind == 1 and price > e1.price]
    within 1 sec
select e1.price as p1, e2.price as p2
insert into Out;
"""

APP3 = """
define stream S (partition int, price float, kind int);
@info(name='q')
from every e1=S[kind == 0] -> e2=S[kind == 1 and price > e1.price] -> e3=S[kind == 2 and price > e2.price]
select e1.price as p1, e2.price as p2, e3.price as p3
insert into Out;
"""


def oracle_matches(app, events_by_partition):
    """Run the host oracle once per partition (partition isolation)."""
    out = []
    for p, events in events_by_partition.items():
        m = SiddhiManager()
        # pin the host engine: this runtime IS the oracle the device path
        # is checked against
        rt = m.create_siddhi_app_runtime(
            "@app:playback @app:engine('host') " + app)
        got = []
        rt.add_callback("q", QueryCallback(
            lambda ts, cur, exp: got.extend(
                tuple(e.data) for e in (cur or []))))
        rt.start()
        h = rt.get_input_handler("S")
        for ts, row in events:
            h.send(row, timestamp=ts)
        rt.shutdown()
        out.extend((p, g) for g in got)
    return sorted(out, key=lambda x: (x[0], x[1]))


def gen_events(seed, n, n_partitions, kinds=2):
    rng = np.random.default_rng(seed)
    pids = rng.integers(0, n_partitions, n)
    prices = rng.uniform(0.0, 100.0, n).astype(np.float32)
    kind = rng.integers(0, kinds, n).astype(np.int32)
    ts = np.cumsum(rng.integers(1, 200, n)).astype(np.int64) + 1_000_000
    return pids, prices, kind, ts


def run_tpu(app, pids, prices, kind, ts, n_partitions, n_slots=16):
    nfa = CompiledPatternNFA(app, n_partitions=n_partitions, n_slots=n_slots)
    cols = {"partition": pids.astype(np.float32), "price": prices,
            "kind": kind.astype(np.float32)}
    return nfa.process_events(pids, cols, ts)


def assert_equal_matches(app, seed, n, n_partitions, outputs, n_slots=16):
    pids, prices, kind, ts = gen_events(seed, n, n_partitions,
                                        kinds=len(outputs))
    tpu = run_tpu(app, pids, prices, kind, ts, n_partitions, n_slots)
    tpu_set = sorted((p, tuple(round(v[o], 3) for o in outputs))
                     for p, _, v in tpu)
    events_by_partition = {}
    for i in range(n):
        events_by_partition.setdefault(int(pids[i]), []).append(
            (int(ts[i]), [int(pids[i]), float(prices[i]), int(kind[i])]))
    oracle = oracle_matches(app, events_by_partition)
    oracle_set = sorted((p, tuple(round(x, 3) for x in g))
                        for p, g in oracle)
    assert tpu_set == oracle_set


def test_two_state_chain_conformance():
    assert_equal_matches(APP, seed=1, n=400, n_partitions=8,
                         outputs=["p1", "p2"])


def test_two_state_chain_many_partitions():
    assert_equal_matches(APP, seed=2, n=1000, n_partitions=32,
                         outputs=["p1", "p2"])


def test_within_conformance():
    assert_equal_matches(APP_WITHIN, seed=3, n=500, n_partitions=8,
                         outputs=["p1", "p2"])


def test_three_state_chain_conformance():
    assert_equal_matches(APP3, seed=4, n=400, n_partitions=8,
                         outputs=["p1", "p2", "p3"], n_slots=32)


def test_sharded_step_runs_on_virtual_mesh():
    """Partition axis sharded over the 8 virtual CPU devices (conftest):
    the engine's jit_engine_step path vs the unsharded compile."""
    import jax
    import jax.numpy as jnp
    from siddhi_tpu.ops.nfa import make_carry, pack_blocks
    from siddhi_tpu.parallel.mesh import (jit_engine_step, partition_mesh,
                                          shard_carry)
    n_partitions = 16
    nfa = CompiledPatternNFA(APP, n_partitions=n_partitions, n_slots=8,
                             mesh=None)
    mesh = partition_mesh()
    carry = shard_carry(make_carry(nfa.spec, n_partitions), mesh)
    step = jit_engine_step(nfa.spec, mesh)
    pids, prices, kind, ts = gen_events(7, 256, n_partitions)
    cols = {"partition": pids.astype(np.float32), "price": prices,
            "kind": kind.astype(np.float32)}
    codes = np.zeros(len(pids), np.int32)
    block = pack_blocks(pids, cols, ts, codes, n_partitions,
                        base_ts=int(ts[0]))
    carry, (mask, caps, mts, _enter, _seq) = step(carry, block)
    assert len({d for v in carry.values()
                for d in v.sharding.device_set}) == 8
    # same events through the unsharded path must match exactly
    tpu = nfa.process_events(pids, cols, ts)
    assert int(jnp.sum(mask.astype(jnp.int32))) == len(tpu)


def test_pattern_bank_counts_match_individual_runs():
    """N parameterized NFAs stepped together == N separate compiles."""
    import numpy as np
    from siddhi_tpu.ops.nfa import pack_blocks
    from siddhi_tpu.plan.nfa_compiler import CompiledPatternBank

    def app_for(thr):
        return f"""
        define stream S (partition int, price float, kind int);
        @info(name='q')
        from every e1=S[kind == 0 and price > {thr}] -> e2=S[kind == 1 and price > e1.price]
        select e1.price as p1, e2.price as p2
        insert into Out;
        """

    thresholds = [10.0, 30.0, 50.0, 70.0, 90.0]
    apps = [app_for(t) for t in thresholds]
    n_partitions = 8
    pids, prices, kind, ts = gen_events(11, 600, n_partitions)
    cols = {"partition": pids.astype(np.float32), "price": prices,
            "kind": kind.astype(np.float32)}

    bank = CompiledPatternBank(apps, n_partitions=n_partitions, n_slots=16)
    block = pack_blocks(pids, cols, ts, np.zeros(len(pids), np.int32),
                        n_partitions, base_ts=int(ts[0]))
    counts = np.asarray(bank.process_block(block))

    expected = []
    for a in apps:
        matches = run_tpu(a, pids, prices, kind, ts, n_partitions, 16)
        expected.append(len(matches))
    assert counts.tolist() == expected
    assert counts.sum() > 0
    # higher threshold → fewer (or equal) matches
    assert counts.tolist() == sorted(counts.tolist(), reverse=True)


def test_pattern_bank_match_ring_payloads():
    """ring > 0: the bounded decode ring's payloads must be real matches —
    every decoded (pattern, partition, ts, captures) row appears in that
    pattern's individually-compiled match list, and every ringed partition's
    payload is its LAST match of the block."""
    import numpy as np
    from siddhi_tpu.ops.nfa import pack_blocks
    from siddhi_tpu.plan.nfa_compiler import CompiledPatternBank

    def app_for(thr):
        return f"""
        define stream S (partition int, price float, kind int);
        @info(name='q')
        from every e1=S[kind == 0 and price > {thr}] -> e2=S[kind == 1 and price > e1.price]
        select e1.price as p1, e2.price as p2
        insert into Out;
        """

    thresholds = [10.0, 40.0, 70.0]
    apps = [app_for(t) for t in thresholds]
    n_partitions = 8
    pids, prices, kind, ts = gen_events(13, 400, n_partitions)
    cols = {"partition": pids.astype(np.float32), "price": prices,
            "kind": kind.astype(np.float32)}

    bank = CompiledPatternBank(apps, n_partitions=n_partitions, n_slots=16,
                               ring=4)
    bank.base_ts = int(ts[0])
    block = pack_blocks(pids, cols, ts, np.zeros(len(pids), np.int32),
                        n_partitions, base_ts=int(ts[0]))
    counts, rcnt, rpid, rcaps, rts, rok = bank.process_block(block)
    decoded = bank.decode_ring(rcnt, rpid, rcaps, rts, rok)

    assert np.asarray(counts).sum() > 0 and len(decoded["pattern"]) > 0
    for i, a in enumerate(apps):
        matches = run_tpu(a, pids, prices, kind, ts, n_partitions, 16)
        # per-partition: ts of the last matching event + all matches
        last_ts = {}
        payloads = {}
        for p, mts, vals in matches:
            last_ts[p] = max(last_ts.get(p, 0), mts)
            payloads.setdefault(p, []).append(
                (mts, round(vals["p1"], 3), round(vals["p2"], 3)))
        sel = decoded["pattern"] == i
        for part, mts, p1, p2 in zip(decoded["partition"][sel],
                                     decoded["ts"][sel],
                                     decoded["p1"][sel],
                                     decoded["p2"][sel]):
            assert part in payloads, (i, part)
            # the ring holds a match from the partition's LAST matching
            # event (several slots may complete on that same event)
            assert mts == last_ts[part]
            assert (mts, round(float(p1), 3), round(float(p2), 3)) \
                in payloads[part], (i, part)


APP_COUNT = """
define stream S (partition int, price float, kind int);
@info(name='q')
from e1=S[kind == 0 and price > 20.0]<3:3> -> e2=S[kind == 1 and price > e1[0].price]
select e1[0].price as p0, e1[last].price as pl, e2.price as p2
insert into Out;
"""


def test_count_chain_conformance():
    """Leading kleene <3:3>, non-every (the reference-supported shape):
    exact-match conformance vs the oracle."""
    assert_equal_matches(APP_COUNT, seed=21, n=500, n_partitions=8,
                         outputs=["p0", "pl", "p2"])


def test_nonevery_chain_single_match():
    """Without `every`, only the initial partial exists — one match."""
    app = APP.replace("from every e1", "from e1")
    assert_equal_matches(app, seed=23, n=400, n_partitions=8,
                         outputs=["p1", "p2"])


def test_every_count_single_shot_conformance():
    """`every A<3:3> -> B` is effectively single-shot in the reference
    (PATTERN start states never re-init; the every re-arm clone can never
    re-reach min) — exact conformance vs the oracle."""
    app = APP_COUNT.replace("from e1", "from every e1")
    assert_equal_matches(app, seed=29, n=400, n_partitions=8,
                         outputs=["p0", "pl", "p2"])


def test_count_last_bank_grows_until_max():
    """Between min-forward and the next state's match the shared chain keeps
    growing: e1[last] must reflect appends after arming (reference shares
    the StateEvent object), freezing at max."""
    import numpy as np
    app = """
    define stream S (partition int, price float, kind int);
    @info(name='q')
    from e1=S[kind == 0]<2:4> -> e2=S[kind == 1]
    select e1[0].price as p0, e1[last].price as pl, e2.price as p2
    insert into Out;
    """
    prices = np.asarray([1, 2, 3, 9], np.float32)
    kind = np.asarray([0, 0, 0, 1], np.int32)
    pids = np.zeros(4, np.int64)
    ts = 1_000_000 + np.arange(4, dtype=np.int64)
    tpu = run_tpu(app, pids, prices, kind, ts, 1, 8)
    got = [(v["p0"], v["pl"], v["p2"]) for _, _, v in tpu]
    assert got == [(1.0, 3.0, 9.0)]


def test_int32_ts_rebase_across_long_streams():
    """Stream time beyond ~24.8 days must rebase the int32 ts origin and
    keep `within` semantics intact (ADVICE: silent overflow guard)."""
    nfa = CompiledPatternNFA(APP_WITHIN, n_partitions=2, n_slots=8)
    day = 86_400_000
    base = 1_000_000

    def send(ts_list, prices, kinds):
        n = len(ts_list)
        return nfa.process_events(
            np.zeros(n, np.int64),
            {"partition": np.zeros(n, np.float32),
             "price": np.asarray(prices, np.float32),
             "kind": np.asarray(kinds, np.float32)},
            np.asarray(ts_list, np.int64))

    got = send([base, base + 100], [60.0, 70.0], [0, 1])
    assert [(m[2]["p1"], m[2]["p2"]) for m in got] == [(60.0, 70.0)]
    # 40 days later: would overflow int32 ms offsets without the rebase
    far = base + 40 * day
    got2 = send([far, far + 100], [55.0, 80.0], [0, 1])
    assert [(m[2]["p1"], m[2]["p2"]) for m in got2] == [(55.0, 80.0)]
    assert got2[0][1] == far + 100          # decoded ts stays absolute
    # a partial armed just before the rebase still honours `within`
    far2 = far + 40 * day
    send([far2], [65.0], [0])
    got3 = send([far2 + 40 * day], [99.0], [1])   # way past within 1 sec
    assert got3 == []
