"""Sequence query behavioural tests (strict contiguity).

Modeled on the reference suites (siddhi-core query/sequence/:
SequenceTestCase, EverySequenceTestCase, CountSequenceTestCase,
LogicalSequenceTestCase).
"""
from siddhi_tpu import QueryCallback, SiddhiManager

STREAMS = """
define stream Stream1 (symbol string, price float, volume int);
define stream Stream2 (symbol string, price float, volume int);
"""


def make(app):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback("query1", QueryCallback(
        lambda ts, cur, exp: got.extend(e.data for e in (cur or []))))
    rt.start()
    return m, rt, got


def test_simple_sequence():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from e1=Stream1[price > 20], e2=Stream2[price > e1.price]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;
    """)
    rt.get_input_handler("Stream1").send(["WSO2", 55.6, 100])
    rt.get_input_handler("Stream2").send(["IBM", 55.7, 100])
    rt.shutdown()
    assert got == [["WSO2", "IBM"]]


def test_sequence_strictness_broken_by_intermediate():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from e1=Stream1[price > 20], e2=Stream1[price > e1.price]
        select e1.price as p1, e2.price as p2
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s1.send(["A", 25.0, 1])
    s1.send(["B", 10.0, 1])   # breaks the sequence (strict next must match)
    s1.send(["C", 30.0, 1])
    rt.shutdown()
    assert got == []


def test_every_sequence():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from every e1=Stream1[price > 20], e2=Stream1[price > e1.price]
        select e1.price as p1, e2.price as p2
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s1.send(["A", 25.0, 1])
    s1.send(["B", 30.0, 1])    # match (25, 30); every re-arms: B starts new
    s1.send(["C", 40.0, 1])    # match (30, 40)
    rt.shutdown()
    assert got == [[25.0, 30.0], [30.0, 40.0]]


def test_sequence_with_kleene_plus():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from every e1=Stream2[price > 20]+, e2=Stream1[price > e1[0].price]
        select e1[0].price as price1, e1[1].price as price2, e2.price as price3
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s2.send(["A", 25.0, 1])
    s2.send(["B", 30.0, 1])
    s1.send(["C", 35.0, 1])
    rt.shutdown()
    assert got == [[25.0, 30.0, 35.0]]


def test_sequence_kleene_star():
    # reference SequenceTestCase.testQuery4 scenario
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from every e1=Stream2[price > 20]*, e2=Stream1[price > e1[0].price]
        select e1[0].price as price1, e1[1].price as price2, e2.price as price3
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["WSO2", 59.6, 100])    # e1 empty → e1[0].price null → no match
    s2.send(["WSO2", 55.6, 100])
    s2.send(["IBM", 55.7, 100])
    s1.send(["WSO2", 57.6, 100])
    rt.shutdown()
    import pytest
    assert got == [pytest.approx([55.6, 55.7, 57.6])]


def test_logical_or_sequence():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from every e1=Stream1[price > 20] or e2=Stream2[price > 30], e3=Stream1[price > 40]
        select e1.price as p1, e2.price as p2, e3.price as p3
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s1.send(["A", 25.0, 1])
    s1.send(["B", 45.0, 1])
    rt.shutdown()
    assert got == [[25.0, None, 45.0]]
