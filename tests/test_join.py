"""Join query behavioural tests (reference model: siddhi-core query/join/
JoinTestCase, OuterJoinTestCase — windowed stream joins, table joins,
unidirectional, outer joins)."""
import pytest

from siddhi_tpu import QueryCallback, SiddhiManager, StreamCallback

STREAMS = """
define stream TickStream (symbol string, price float);
define stream NewsStream (symbol string, headline string);
"""


def make(app, q="query1"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback(q, QueryCallback(
        lambda ts, cur, exp: got.extend(e.data for e in (cur or []))))
    rt.start()
    return m, rt, got


def test_window_join_basic():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from TickStream#window.length(10) join NewsStream#window.length(10)
            on TickStream.symbol == NewsStream.symbol
        select TickStream.symbol as symbol, price, headline
        insert into Out;
    """)
    t = rt.get_input_handler("TickStream")
    n = rt.get_input_handler("NewsStream")
    t.send(["IBM", 100.0])
    t.send(["WSO2", 50.0])
    n.send(["IBM", "ibm news"])          # joins buffered IBM tick
    rt.shutdown()
    assert got == [["IBM", 100.0, "ibm news"]]


def test_join_both_directions_trigger():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from TickStream#window.length(10) as t join NewsStream#window.length(10) as s
            on t.symbol == s.symbol
        select t.symbol as symbol, t.price as price, s.headline as headline
        insert into Out;
    """)
    t = rt.get_input_handler("TickStream")
    n = rt.get_input_handler("NewsStream")
    n.send(["IBM", "early news"])
    t.send(["IBM", 100.0])               # tick arrival also triggers
    rt.shutdown()
    assert got == [["IBM", 100.0, "early news"]]


def test_unidirectional_join():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from TickStream#window.length(10) unidirectional join NewsStream#window.length(10)
            on TickStream.symbol == NewsStream.symbol
        select TickStream.symbol as symbol, headline
        insert into Out;
    """)
    t = rt.get_input_handler("TickStream")
    n = rt.get_input_handler("NewsStream")
    n.send(["IBM", "n1"])    # right arrival must NOT trigger
    t.send(["IBM", 100.0])   # left arrival triggers
    n.send(["IBM", "n2"])    # right arrival must NOT trigger
    rt.shutdown()
    assert got == [["IBM", "n1"]]


def test_left_outer_join():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from TickStream#window.length(10) left outer join NewsStream#window.length(10)
            on TickStream.symbol == NewsStream.symbol
        select TickStream.symbol as symbol, headline
        insert into Out;
    """)
    t = rt.get_input_handler("TickStream")
    t.send(["IBM", 100.0])     # no news yet → null headline
    rt.shutdown()
    assert got == [["IBM", None]]


def test_table_join():
    m, rt, got = make("""
        define stream CheckStream (symbol string);
        define table PriceTable (symbol string, price float);
        define stream AddStream (symbol string, price float);
        from AddStream insert into PriceTable;
        @info(name = 'query1')
        from CheckStream join PriceTable
            on CheckStream.symbol == PriceTable.symbol
        select CheckStream.symbol as symbol, PriceTable.price as price
        insert into Out;
    """)
    rt.get_input_handler("AddStream").send(["IBM", 77.0])
    rt.get_input_handler("AddStream").send(["WSO2", 23.0])
    rt.get_input_handler("CheckStream").send(["IBM"])
    rt.shutdown()
    assert got == [["IBM", 77.0]]


def test_self_join():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from TickStream#window.length(10) as a join TickStream#window.length(10) as b
            on a.price < b.price
        select a.price as pa, b.price as pb
        insert into Out;
    """)
    t = rt.get_input_handler("TickStream")
    t.send(["X", 10.0])
    t.send(["Y", 20.0])   # arrival probes: (20 joins buffered 10 on b-side? )
    rt.shutdown()
    # second arrival: probes opposite buffer [10] twice (a-side and b-side
    # receivers both get the event): a=20,b=10 fails; a=10(buf)... the b-side
    # receiver arrival emits a=10,b=20
    assert [sorted(g) for g in got] == [[10.0, 20.0]]


def test_named_window_join():
    m, rt, got = make("""
        define stream S (symbol string, price float);
        define stream Q (symbol string);
        define window W (symbol string, price float) length(5);
        from S insert into W;
        @info(name = 'query1')
        from Q join W on Q.symbol == W.symbol
        select W.symbol as symbol, W.price as price
        insert into Out;
    """)
    rt.get_input_handler("S").send(["IBM", 42.0])
    rt.get_input_handler("Q").send(["IBM"])
    rt.shutdown()
    assert got == [["IBM", 42.0]]


def test_join_with_group_by_aggregation():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from TickStream#window.lengthBatch(2) join NewsStream#window.length(10)
            on TickStream.symbol == NewsStream.symbol
        select TickStream.symbol as symbol, sum(price) as total
        group by TickStream.symbol
        insert into Out;
    """)
    n = rt.get_input_handler("NewsStream")
    t = rt.get_input_handler("TickStream")
    n.send(["IBM", "x"])
    t.send(["IBM", 10.0])
    t.send(["IBM", 15.0])
    rt.shutdown()
    assert got[-1] == ["IBM", pytest.approx(25.0)]
