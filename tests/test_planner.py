"""Device/host planner: backend routing, fallback reasons, and
backend-identical output for a corpus of pattern apps run through the
PUBLIC SiddhiQL API on both engines (VERDICT r1 item 1: the quick-start
path must execute on device with no API change)."""
import numpy as np
import pytest

from siddhi_tpu import QueryCallback, SiddhiManager, StreamCallback
from siddhi_tpu.utils.errors import SiddhiAppCreationError

CORPUS = [
    # (name, app, streams→rows)
    ("chain2", """
        define stream A (k int, v float);
        @info(name='q')
        from every e1=A[v > 10.0] -> e2=A[v > e1.v]
        select e1.v as v1, e2.v as v2 insert into Out;
     """, [("A", [1, 11.0]), ("A", [1, 12.0]), ("A", [1, 5.0]),
           ("A", [1, 13.0])]),
    ("chain3_within", """
        define stream A (k int, v float);
        @info(name='q')
        from every e1=A[v > 1.0] -> e2=A[v > e1.v] -> e3=A[v > e2.v]
            within 1 sec
        select e1.v as v1, e2.v as v2, e3.v as v3 insert into Out;
     """, [("A", [1, 2.0]), ("A", [1, 3.0]), ("A", [1, 4.0]),
           ("A", [1, 1.5]), ("A", [1, 9.0])]),
    ("two_streams", """
        define stream A (v float);
        define stream B (w float);
        @info(name='q')
        from every e1=A[v > 0.0] -> e2=B[w > e1.v]
        select e1.v as v1, e2.w as v2 insert into Out;
     """, [("A", [1.0]), ("B", [0.5]), ("B", [2.0]), ("A", [3.0]),
           ("B", [4.0])]),
    ("no_every", """
        define stream A (v float);
        @info(name='q')
        from e1=A[v > 10.0] -> e2=A[v > e1.v]
        select e1.v as v1, e2.v as v2 insert into Out;
     """, [("A", [11.0]), ("A", [12.0]), ("A", [13.0])]),
    ("leading_count", """
        define stream A (v float);
        @info(name='q')
        from every e1=A[v > 0.0]<2:4> -> e2=A[v < 0.0]
        select e1[0].v as first_v, e2.v as last_v insert into Out;
     """, [("A", [1.0]), ("A", [2.0]), ("A", [-1.0]), ("A", [3.0]),
           ("A", [4.0]), ("A", [-2.0])]),
]


def run_app(app, sends, engine=None):
    prefix = f"@app:engine('{engine}') " if engine else ""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(prefix + app)
    out = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: out.extend(tuple(e.data) for e in evs)))
    rt.start()
    ts = 1_000_000
    for sid, row in sends:
        rt.get_input_handler(sid).send(row, timestamp=ts)
        ts += 100
    backend = rt.query_runtimes["q"].backend
    rt.shutdown()
    return backend, out


@pytest.mark.parametrize("name,app,sends", CORPUS,
                         ids=[c[0] for c in CORPUS])
def test_backend_identical_output(name, app, sends):
    bh, host = run_app(app, sends, engine="host")
    bd, dev = run_app(app, sends)            # auto → device for this corpus
    assert bh == "host"
    assert bd == "device", f"{name} did not plan onto the device"
    assert host == dev


def test_unsupported_shapes_fall_back_with_reason():
    cases = {
        # string equality/captures are dictionary-encoded and ORDER-vs-
        # constant lowers onto host-computed 0/1 lanes (round 4);
        # CROSS-STATE string order stays host-only (codes carry no order)
        "string_order_cross_state": """
            define stream A (s string, v float);
            @info(name='q')
            from every e1=A[v > 0.0] -> e2=A[s > e1.s]
            select e1.v as v1, e2.v as v2 insert into Out;
        """,
        "nested_every": """
            define stream A (v float);
            @info(name='q')
            from e1=A[v > 0.0] -> every (every e2=A[v > e1.v])
            select e1.v as v1, e2.v as v2 insert into Out;
        """,
        "leading_absent_sequence": """
            define stream A (v float);
            define stream B (w float);
            @info(name='q')
            from not B[w > 0.0] for 1 sec, e2=A[v > 0.0]
            select e2.v as v2 insert into Out;
        """,
        "logical_absent_side": """
            define stream A (v float);
            define stream B (w float);
            @info(name='q')
            from e1=A[v > 0.0] -> not B[w > 0.0] and e3=A[v > 10.0]
            select e1.v as v1 insert into Out;
        """,
        "consecutive_counts": """
            define stream A (v float);
            define stream B (w float);
            @info(name='q')
            from every e1=A[v > 0.0]<1:2> -> e2=A[v < 0.0]<1:2>
                -> e3=B[w > 0.0]
            select e3.w as w3 insert into Out;
        """,
        "pattern_group_by": """
            define stream A (k int, v float);
            @info(name='q')
            from every e1=A[v > 0.0] -> e2=A[v > e1.v]
            select e1.v as v1, e2.v as v2 group by k insert into Out;
        """,
    }
    for name, app in cases.items():
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(app)
        qr = rt.query_runtimes["q"]
        assert qr.backend == "host", name
        assert qr.backend_reason, name
        rt.shutdown()


def test_engine_device_mode_raises_on_unsupported():
    m = SiddhiManager()
    with pytest.raises(SiddhiAppCreationError):
        m.create_siddhi_app_runtime("""
            @app:engine('device')
            define stream A (s string, v float);
            @info(name='q')
            from every e1=A[v > 0.0] -> e2=A[s > e1.s]
            select e1.v as v1 insert into Out;
        """)


def test_device_pattern_query_callback_and_int_types():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream A (k int, v float);
        @info(name='q')
        from every e1=A[v > 10.0] -> e2=A[v > e1.v]
        select e1.k as k1, e2.v as v2 insert into Out;
    """)
    assert rt.query_runtimes["q"].backend == "device"
    got = []
    rt.add_callback("q", QueryCallback(
        lambda ts, cur, exp: got.extend(tuple(e.data) for e in (cur or []))))
    rt.start()
    h = rt.get_input_handler("A")
    h.send([7, 11.0])
    h.send([8, 12.0])
    rt.shutdown()
    assert got == [(7, 12.0)]
    assert isinstance(got[0][0], int)


def test_device_pattern_persistence_roundtrip():
    from siddhi_tpu import InMemoryPersistenceStore
    app = """
        define stream A (v float);
        @info(name='q')
        from every e1=A[v > 10.0] -> e2=A[v > e1.v]
        select e1.v as v1, e2.v as v2 insert into Out;
    """
    m = SiddhiManager()
    m.set_persistence_store(InMemoryPersistenceStore())
    rt = m.create_siddhi_app_runtime(app)
    assert rt.query_runtimes["q"].backend == "device"
    rt.start()
    rt.get_input_handler("A").send([11.0], timestamp=1_000_000)
    rev = rt.persist()
    rt.shutdown()

    rt2 = m.create_siddhi_app_runtime(app)
    out = []
    rt2.add_callback("Out", StreamCallback(
        lambda evs: out.extend(tuple(e.data) for e in evs)))
    rt2.start()
    rt2.restore_revision(rev)
    rt2.get_input_handler("A").send([12.0], timestamp=1_000_100)
    rt2.shutdown()
    assert out == [(11.0, 12.0)]     # partial armed pre-snapshot completes


PART_APP = """
    define stream S (sym int, price float, kind int);
    partition with (sym of S) begin
    @info(name='q')
    from every e1=S[kind == 0 and price > 50.0]
        -> e2=S[kind == 1 and price > e1.price]
    select e1.price as p1, e2.price as p2
    insert into Out;
    end;
"""


def run_partition(app, rows, engine=None):
    prefix = (f"@app:engine('{engine}') " if engine else "") + \
        "@app:playback "
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(prefix + app)
    out = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: out.extend(tuple(e.data) for e in evs)))
    rt.start()
    h = rt.get_input_handler("S")
    ts = 1_000_000
    for r in rows:
        h.send(r, timestamp=ts)
        ts += 10
    dm = rt.partition_runtimes[0].device_mode
    rt.shutdown()
    return dm, out


def test_partitioned_pattern_device_parity():
    """Keys become NFA lanes (slab grows past the initial capacity of 8);
    output must equal the host per-key clone machinery exactly."""
    rng = np.random.default_rng(11)
    rows = [[int(rng.integers(0, 13)), float(rng.uniform(0, 100)),
             int(rng.integers(0, 2))] for _ in range(180)]
    dm_h, host = run_partition(PART_APP, rows, engine="host")
    dm_d, dev = run_partition(PART_APP, rows)
    assert not dm_h and dm_d
    assert sorted(host) == sorted(dev)
    assert len(dev) > 0


def test_partition_purge_falls_back_to_host():
    app = PART_APP.replace("partition with",
                           "@purge(enable='true', interval='1 min', "
                           "idle.period='5 min') partition with")
    dm, _ = run_partition(app, [[0, 60.0, 0], [0, 70.0, 1]])
    assert not dm


def test_partition_non_pattern_query_falls_back():
    app = """
        define stream S (sym int, price float);
        partition with (sym of S) begin
        @info(name='q')
        from S[price > 0.0] select sym, price insert into Out;
        end;
    """
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    assert not rt.partition_runtimes[0].device_mode
    assert rt.partition_runtimes[0].fallback_reason
    rt.shutdown()


WAGG_PART_APP = """
    define stream S (k int, v float);
    partition with (k of S) begin
    @info(name='q')
    from S[v > 2.0]#window.length(5)
    select k, sum(v) as total, count() as n, avg(v) as mean,
           min(v) as lo, max(v) as hi
    group by k
    insert into Out;
    end;
"""


def test_partitioned_windowed_agg_device_parity():
    """Partition keys become group lanes of the sliding-window ring slab;
    per-event running aggregates must match the host per-key instances."""
    rng = np.random.default_rng(17)
    rows = [[int(rng.integers(0, 11)),
             float(np.float32(rng.uniform(0, 10)))] for _ in range(120)]
    dm_h, host = run_partition(WAGG_PART_APP, rows, engine="host")
    dm_d, dev = run_partition(WAGG_PART_APP, rows)
    assert not dm_h and dm_d
    assert len(host) == len(dev) > 0
    for a, b in zip(host, dev):
        assert a[0] == b[0] and a[2] == b[2]
        assert a[1] == pytest.approx(b[1], abs=1e-3)
        assert a[3] == pytest.approx(b[3], abs=1e-3)
        assert a[4] == pytest.approx(b[4], abs=1e-4)     # min
        assert a[5] == pytest.approx(b[5], abs=1e-4)     # max


TIME_WAGG_PART_APP = """
    define stream S (k int, v float);
    partition with (k of S) begin
    @info(name='q')
    from S[v > 2.0]#window.time(200)
    select k, sum(v) as total, count() as n, min(v) as lo, max(v) as hi
    group by k
    insert into Out;
    end;
"""


def test_partitioned_time_window_device_parity():
    """Sliding time windows route to the device ring kernel (masked-
    reduction expiry); per-event running aggregates match the host per-key
    instances across expiry boundaries (sends are 10ms apart, window
    200ms, so entries continuously expire)."""
    rng = np.random.default_rng(23)
    rows = [[int(rng.integers(0, 7)),
             float(np.float32(rng.uniform(0, 10)))] for _ in range(120)]
    dm_h, host = run_partition(TIME_WAGG_PART_APP, rows, engine="host")
    dm_d, dev = run_partition(TIME_WAGG_PART_APP, rows)
    assert not dm_h and dm_d
    assert len(host) == len(dev) > 0
    for a, b in zip(host, dev):
        assert a[0] == b[0] and a[2] == b[2]
        assert a[1] == pytest.approx(b[1], abs=1e-3)
        assert a[3] == pytest.approx(b[3], abs=1e-4)
        assert a[4] == pytest.approx(b[4], abs=1e-4)


EXTTIME_WAGG_PART_APP = """
    define stream S (k int, ets long, v float);
    partition with (k of S) begin
    @info(name='q')
    from S[v > 2.0]#window.externalTime(ets, 200)
    select k, sum(v) as total, count() as n
    group by k
    insert into Out;
    end;
"""


def test_partitioned_external_time_window_device_parity():
    """externalTime(tsAttr, t) rides the same device time-ring, driven by
    the event's own timestamp attribute."""
    rng = np.random.default_rng(31)
    ets = 1_000_000
    rows = []
    for _ in range(100):
        ets += int(rng.integers(1, 120))
        rows.append([int(rng.integers(0, 5)), ets,
                     float(np.float32(rng.uniform(0, 10)))])
    dm_h, host = run_partition(EXTTIME_WAGG_PART_APP, rows, engine="host")
    dm_d, dev = run_partition(EXTTIME_WAGG_PART_APP, rows)
    assert not dm_h and dm_d
    assert len(host) == len(dev) > 0
    for a, b in zip(host, dev):
        assert a[0] == b[0] and a[2] == b[2]
        assert a[1] == pytest.approx(b[1], abs=1e-3)


def test_wagg_int_sum_compiles_via_grouped_kernel():
    """Exact integer sums ride the grouped-agg kernel's i32 hi/lo lanes
    (ops/grouped_agg.py) — no more host fallback for INT/LONG values."""
    app = WAGG_PART_APP.replace("v float", "v int").replace("v > 2.0",
                                                            "v > 2")
    dm, out = run_partition(app, [[0, 3], [0, 4], [1, 9]])
    assert dm
    dm_h, out_h = run_partition("@app:engine('host') " + app,
                                [[0, 3], [0, 4], [1, 9]])
    assert not dm_h and sorted(out) == sorted(out_h)


def test_filter_project_device_parity():
    app = """
        define stream S (symbol string, price float, volume long);
        @info(name='q')
        from S[price > 100.0 and volume > 5]
        select symbol, price, price * 2.0 as dbl
        insert into Out;
    """
    sends = [("S", ["IBM", 101.0, 10]), ("S", ["X", 50.0, 99]),
             ("S", ["GOOG", 700.0, 1]), ("S", ["MSFT", 200.0, 50])]
    bh, host = run_app(app, sends, engine="host")
    bd, dev = run_app(app, sends)
    assert bh == "host" and bd == "device"
    assert host == dev == [("IBM", 101.0, 202.0), ("MSFT", 200.0, 400.0)]


def test_filter_select_star_device():
    app = """
        define stream S (symbol string, price float);
        @info(name='q')
        from S[price > 10.0] select * insert into Out;
    """
    sends = [("S", ["A", 11.0]), ("S", ["B", 5.0])]
    bd, dev = run_app(app, sends)
    assert bd == "device"
    assert dev == [("A", 11.0)]


def test_filter_string_condition_compiles_to_device():
    # round 4: string predicates lower onto per-chunk order-preserving
    # code lanes (plan/str_lanes.py) — ==/!=/order/is-null compile
    app = """
        define stream S (symbol string, price float);
        @info(name='q')
        from S[symbol == 'IBM'] select price insert into Out;
    """
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    qr = rt.query_runtimes["q"]
    assert qr.backend == "device"
    rt.shutdown()


def test_window_agg_query_compiles_to_device():
    """Round 3: plain length-window aggregation queries compile onto the
    grouped-agg kernel (previously host-only)."""
    app = """
        define stream S (v float);
        @info(name='q')
        from S#window.length(3) select sum(v) as s insert into Out;
    """
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    assert rt.query_runtimes["q"].backend == "device"
    rt.shutdown()
    # batch window kinds route to the device window path (round 4:
    # plan/dwin_compiler — window state on device, selector host)
    m2 = SiddhiManager()
    rt2 = m2.create_siddhi_app_runtime(app.replace("window.length(3)",
                                                   "window.lengthBatch(3)"))
    assert rt2.query_runtimes["q"].backend == "device"
    assert "dwin" in (rt2.query_runtimes["q"].backend_reason or "")
    rt2.shutdown()
    # sort windows gained a device kernel in round 5 (plan/dwin_compiler
    # DEVICE_KINDS) — they now route to the device window path too
    m3 = SiddhiManager()
    rt3 = m3.create_siddhi_app_runtime(app.replace(
        "window.length(3)", "window.sort(3, v)"))
    assert rt3.query_runtimes["q"].backend == "device"
    assert "dwin" in (rt3.query_runtimes["q"].backend_reason or "")
    rt3.shutdown()
    # genuinely unsupported window kinds still fall back with a reason
    m4 = SiddhiManager()
    rt4 = m4.create_siddhi_app_runtime(app.replace(
        "window.length(3)", "window.frequent(3)"))
    assert rt4.query_runtimes["q"].backend == "host"
    rt4.shutdown()


def test_slot_overflow_grow_and_replay_exact():
    """The single-device engine path must GROW-AND-REPLAY on slot
    overflow, never lose matches (review: the replay loop had no
    coverage).  Tiny initial ring + a burst that stacks many concurrent
    partials per key forces the replay branch repeatedly."""
    import numpy as np
    from siddhi_tpu.plan import planner as planner_mod

    app = """
    define stream S (sym string, price float, kind int);
    partition with (sym of S) begin
    @info(name='q')
    from every e1=S[kind == 0] -> e2=S[kind == 1 and price > e1.price]
    select e1.price as p1, e2.price as p2 insert into Out;
    end;
    """
    rng = np.random.default_rng(12)
    n = 600
    cols = {"sym": np.asarray([f"k{i}" for i in rng.integers(0, 3, n)],
                              object),
            "price": rng.uniform(0, 100, n).astype(np.float32),
            "kind": rng.integers(0, 2, n).astype(np.int32)}
    ts = 1_000_000 + np.arange(n, dtype=np.int64)

    def run(engine, slots=None):
        old = planner_mod.DEFAULT_SLOTS
        if slots is not None:
            planner_mod.DEFAULT_SLOTS = slots
        try:
            m = SiddhiManager()
            rt = m.create_siddhi_app_runtime(
                f"@app:playback @app:engine('{engine}') {app}"
                if engine else f"@app:playback {app}")
            got = []
            rt.add_callback("Out", StreamCallback(
                lambda evs: got.extend(
                    (round(e.data[0], 3), round(e.data[1], 3))
                    for e in evs)))
            rt.start()
            rt.get_input_handler("S").send_batch(cols, timestamps=ts)
            k = None
            for pr in rt.partition_runtimes:
                for qr in pr.device_query_runtimes.values():
                    k = qr.device_runtime.nfa.spec.n_slots
            rt.shutdown()
            return sorted(got), k
        finally:
            planner_mod.DEFAULT_SLOTS = old

    dev, k_final = run(None, slots=2)
    host, _ = run("host")
    assert k_final is not None and k_final > 2, \
        f"replay never grew the ring (K={k_final})"
    assert len(host) > 100 and dev == host


def test_compact_egress_cap_overflow_exact():
    """The compacted match egress must retrace with a doubled cap when a
    chunk yields more matches than the buffer (review: untested) — forced
    by shrinking the initial cap to 2."""
    import numpy as np

    app = """
    define stream S (sym string, price float, kind int);
    partition with (sym of S) begin
    @info(name='q')
    from every e1=S[kind == 0] -> e2=S[kind == 1 and price > e1.price]
    select e1.price as p1, e2.price as p2 insert into Out;
    end;
    """
    rng = np.random.default_rng(3)
    n = 400
    cols = {"sym": np.asarray([f"k{i}" for i in rng.integers(0, 2, n)],
                              object),
            "price": rng.uniform(0, 100, n).astype(np.float32),
            "kind": rng.integers(0, 2, n).astype(np.int32)}
    ts = 1_000_000 + np.arange(n, dtype=np.int64)

    def run(engine, tiny_cap=False):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(
            f"@app:playback @app:engine('{engine}') {app}"
            if engine else f"@app:playback {app}")
        got = []
        rt.add_callback("Out", StreamCallback(
            lambda evs: got.extend(
                (round(e.data[0], 3), round(e.data[1], 3))
                for e in evs)))
        rt.start()
        if tiny_cap:
            for pr in rt.partition_runtimes:
                for qr in pr.device_query_runtimes.values():
                    qr.device_runtime.nfa._egress_cap = 2
        rt.get_input_handler("S").send_batch(cols, timestamps=ts)
        caps = [qr.device_runtime.nfa._egress_cap
                for pr in rt.partition_runtimes
                for qr in pr.device_query_runtimes.values()] \
            if tiny_cap else []
        rt.shutdown()
        return sorted(got), caps

    dev, caps = run(None, tiny_cap=True)
    host, _ = run("host")
    assert caps and caps[0] > 2, "cap never grew"
    assert len(host) > 100 and dev == host
