"""Worker for tests/test_multihost.py: one OS process of a 2-process
jax.distributed cluster (localhost DCN, 4 virtual CPU devices per
process) driving DistributedPatternBank.step_local on its own partition
range.  Writes its local match rows + global stats as JSON.

Usage: multihost_worker.py <coordinator> <num_procs> <pid> <out.json>
"""
import json
import os
import sys

# 4 virtual CPU devices: XLA_FLAGS must be set before backend init; the
# platform itself is forced via jax.config.update below — the env var
# alone is a no-op in this image (the sitecustomize hook snapshots
# JAX_PLATFORMS at interpreter start; see tests/conftest.py)
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=4")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np  # noqa: E402

from siddhi_tpu.parallel import distributed as dist  # noqa: E402

APP = """
define stream S (partition int, price float, kind int);
@info(name='q')
from every e1=S[kind == 0 and price > 50.0]
    -> e2=S[kind == 1 and price > e1.price] within 10 sec
select e1.price as p1, e2.price as p2
insert into Out;
"""

N_PARTITIONS = 16
T_PER_BLOCK = 8
N_BLOCKS = 4


def global_events(block: int):
    """Deterministic global event set — every process generates the same
    stream and keeps only the partitions it owns."""
    rng = np.random.default_rng(1234 + block)
    P, T = N_PARTITIONS, T_PER_BLOCK
    base = 1_000_000 + block * T * 1000
    cols = {"partition": np.repeat(np.arange(P), T).astype(np.float32),
            "price": rng.uniform(0, 100, P * T).astype(np.float32),
            "kind": rng.integers(0, 2, P * T).astype(np.float32)}
    ts = base + np.tile(np.arange(T, dtype=np.int64) * 500, P)
    return cols, ts


def pack_local(cols, ts, lo, hi):
    from siddhi_tpu.ops.nfa import pack_blocks
    pids = cols["partition"].astype(np.int64)
    keep = (pids >= lo) & (pids < hi)
    block = pack_blocks(
        pids[keep] - lo,
        {k: v[keep] for k, v in cols.items()},
        ts[keep], np.zeros(int(keep.sum()), np.int32),
        hi - lo, base_ts=1_000_000)
    return block


def main():
    coord, nproc, pid, out_path = sys.argv[1:5]
    ok = dist.init_distributed(coord, int(nproc), int(pid))
    assert ok and jax.process_count() == int(nproc), \
        f"distributed init failed: {jax.process_count()}"
    assert len(jax.devices()) == 4 * int(nproc), len(jax.devices())

    bank = dist.DistributedPatternBank(APP, n_partitions=N_PARTITIONS,
                                       n_slots=8)
    lo, hi = bank.local_range
    results = {"pid": int(pid), "range": [lo, hi], "blocks": []}
    for b in range(N_BLOCKS):
        cols, ts = global_events(b)
        mask, mts, stats = bank.step_local(pack_local(cols, ts, lo, hi))
        # host-local egress: only this host's partitions appear
        assert mask.shape[0] == hi - lo
        per_p = mask.sum(axis=(1, 2)).astype(int).tolist()
        results["blocks"].append({
            "local_matches": int(mask.sum()),
            "per_partition": per_p,
            "stats": stats,
        })
    with open(out_path, "w") as f:
        json.dump(results, f)


if __name__ == "__main__":
    main()
