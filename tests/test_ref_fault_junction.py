"""Fault-stream + junction conformance ported from the reference corpus
(stream/FaultStreamTestCase — custom throwing extension, @OnError LOG vs
STREAM, `!stream` consumers; stream/JunctionTestCase — fan-out and relay;
stream/CallbackTestCase — stream callbacks by id)."""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.query_api.definition import AttrType
from siddhi_tpu.utils.extension import FunctionExtension

STREAMS = "define stream cseEventStream (symbol string, price float, " \
    "volume long);\n"


class FaultFunction(FunctionExtension):
    """≙ the reference's custom:fault() test extension
    (stream/FaultFunctionExtension.java): throws during evaluation."""
    return_type = AttrType.DOUBLE

    def apply(self, *args):
        raise RuntimeError("Error when running the function fault()")


def _mgr():
    m = SiddhiManager()
    m.set_extension("custom:fault", FaultFunction)
    return m


def _run(m, app, sends, streams=("outputStream",)):
    rt = m.create_siddhi_app_runtime(app)
    got = {s: [] for s in streams}
    for s in streams:
        rt.add_callback(s, StreamCallback(
            lambda evs, _s=s: got[_s].extend(tuple(e.data) for e in evs)))
    rt.start()
    for sid, row in sends:
        try:
            rt.get_input_handler(sid).send(row)
        except Exception:  # noqa: BLE001 — LOG action surfaces to sender
            pass
    rt.shutdown()
    return got


# -------------------------------------------------- FaultStreamTestCase

def test_fault_default_log_no_output():
    """faultStreamTest1: no @OnError — the failing event produces no
    output and the engine keeps running."""
    got = _run(_mgr(), STREAMS + """
        @info(name='query1')
        from cseEventStream[custom:fault() > volume]
        select symbol, price insert into outputStream;""",
        [("cseEventStream", ["IBM", 0.0, 100]),
         ("cseEventStream", ["WSO2", 1.0, 10])])
    assert got["outputStream"] == []


def test_fault_explicit_log_action():
    """faultStreamTest2: @OnError(action='log') behaves like the default."""
    got = _run(_mgr(), """
        @OnError(action='log')
        """ + STREAMS + """
        @info(name='query1')
        from cseEventStream[custom:fault() > volume]
        select symbol, price insert into outputStream;""",
        [("cseEventStream", ["IBM", 0.0, 100])])
    assert got["outputStream"] == []


def test_fault_stream_action_unconsumed():
    """faultStreamTest3: action='stream' with no !stream consumer — the
    fault event is dropped silently, normal output stays empty."""
    got = _run(_mgr(), """
        @OnError(action='stream')
        """ + STREAMS + """
        @info(name='query1')
        from cseEventStream[custom:fault() > volume]
        select symbol, price insert into outputStream;""",
        [("cseEventStream", ["IBM", 0.0, 100])])
    assert got["outputStream"] == []


def test_fault_stream_consumer_receives_error_payload():
    """faultStreamTest4: a `from !cseEventStream` query sees the failing
    event's attributes plus _error."""
    m = _mgr()
    rt = m.create_siddhi_app_runtime("""
        @OnError(action='stream')
        """ + STREAMS + """
        @info(name='query1')
        from cseEventStream[custom:fault() > volume]
        select symbol, price insert into outputStream;
        @info(name='query2')
        from !cseEventStream
        select symbol, price, _error insert into faultStream;""")
    ok, fault = [], []
    rt.add_callback("outputStream", StreamCallback(
        lambda evs: ok.extend(tuple(e.data) for e in evs)))
    rt.add_callback("faultStream", StreamCallback(
        lambda evs: fault.extend(tuple(e.data) for e in evs)))
    rt.start()
    rt.get_input_handler("cseEventStream").send(["IBM", 0.0, 100])
    rt.shutdown()
    assert ok == []
    assert len(fault) == 1
    assert fault[0][0] == "IBM" and fault[0][1] == pytest.approx(0.0)
    assert "fault()" in str(fault[0][2])


def test_two_onerror_streams_isolated():
    """faultStreamTest10 shape: two @OnError streams route independently."""
    m = _mgr()
    rt = m.create_siddhi_app_runtime("""
        @OnError(action='stream')
        define stream A (v long);
        @OnError(action='stream')
        define stream B (v long);
        from A[custom:fault() > v] select v insert into OutA;
        from B select v insert into OutB;
        from !A select v, _error insert into FaultA;
        from !B select v, _error insert into FaultB;""")
    fa, fb, ob = [], [], []
    rt.add_callback("FaultA", StreamCallback(
        lambda evs: fa.extend(tuple(e.data) for e in evs)))
    rt.add_callback("FaultB", StreamCallback(
        lambda evs: fb.extend(tuple(e.data) for e in evs)))
    rt.add_callback("OutB", StreamCallback(
        lambda evs: ob.extend(tuple(e.data) for e in evs)))
    rt.start()
    rt.get_input_handler("A").send([1])
    rt.get_input_handler("B").send([2])
    rt.shutdown()
    assert len(fa) == 1 and fa[0][0] == 1
    assert fb == []
    assert ob == [(2,)]


# ----------------------------------------------------- JunctionTestCase

def test_junction_fanout_to_multiple_queries():
    """multiThreadedTest shape: one stream feeds N queries; each sees
    every event."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (v int);
        from S select v insert into Out1;
        from S select v insert into Out2;
        from S select v insert into Out3;""")
    outs = {f"Out{i}": [] for i in (1, 2, 3)}
    for s in outs:
        rt.add_callback(s, StreamCallback(
            lambda evs, _s=s: outs[_s].extend(e.data[0] for e in evs)))
    rt.start()
    h = rt.get_input_handler("S")
    for i in range(20):
        h.send([i])
    rt.shutdown()
    for s, vals in outs.items():
        assert vals == list(range(20)), s


def test_junction_relay_chain():
    """oneToOneTest shape: query output re-enters another junction —
    events relay A → B → C in order."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream A (symbol string, price int);
        from A select symbol, price insert into B;
        from B select symbol, price insert into C;""")
    got = []
    rt.add_callback("C", StreamCallback(
        lambda evs: got.extend(tuple(e.data) for e in evs)))
    rt.start()
    rt.get_input_handler("A").send(["IBM", 10])
    rt.get_input_handler("A").send(["WSO2", 20])
    rt.shutdown()
    assert got == [("IBM", 10), ("WSO2", 20)]


def test_stream_callback_by_stream_id_sees_inner_stream():
    """CallbackTestCase shape: a StreamCallback attached to an
    intermediate stream id observes the relay traffic."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream A (v int);
        from A[v > 0] select v insert into Mid;
        from Mid select v * 2 as v insert into Out;""")
    mid, out = [], []
    rt.add_callback("Mid", StreamCallback(
        lambda evs: mid.extend(e.data[0] for e in evs)))
    rt.add_callback("Out", StreamCallback(
        lambda evs: out.extend(e.data[0] for e in evs)))
    rt.start()
    for v in (-1, 1, 2):
        rt.get_input_handler("A").send([v])
    rt.shutdown()
    assert mid == [1, 2]
    assert out == [2, 4]
