"""Device selection-tail conformance (round 19): the query's having /
order-by / limit / offset tail compiled into the egress kernel
(plan/select_compiler.py + ops/select.py) must be VALUE-IDENTICAL to
the host QuerySelector over the same chunks — a randomized sweep over
group-by arity x having x order direction x limit/offset, plus the
blocked-shape routing contract, the SIDDHI_TPU_SELECT kill switch, and
persist/restore of the selector-bearing device state.

Reference: query/selector/QuerySelector.java:226-320 (order-by /
limit / offset post-processing), OrderByEventComparator."""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.snapshot import InMemoryPersistenceStore

STREAM = "define stream S (sym string, user string, price float, " \
         "volume long);\n"


def run_batches(app, batches, engine=None):
    """Feed column batches through the public API; returns (device_hit,
    rows, selection routes by query name)."""
    prefix = "@app:playback "
    if engine:
        prefix += f"@app:engine('{engine}') "
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(prefix + app)
    out = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: out.extend(tuple(e.data) for e in evs)))
    rt.start()
    for cols, ts in batches:
        rt.get_input_handler("S").send_batch(cols, timestamps=ts)
    routes = {n: q.selection_route for n, q in rt.query_runtimes.items()}
    backends = {n: q.backend for n, q in rt.query_runtimes.items()}
    device = any(b == "device" for b in backends.values()) or \
        any(pr.device_mode for pr in rt.partition_runtimes)
    rt.shutdown()
    return device, out, routes


def _batches(n_chunks=2, n=48, seed=0, n_sym=3, n_user=4):
    """Integer-valued float prices: exact in f32, f64 and the device's
    two-float pairs alike, so sort keys tie identically on every path."""
    rng = np.random.default_rng(seed)
    out, t0 = [], 1_000_000
    for _ in range(n_chunks):
        cols = {
            "sym": np.asarray(
                [f"s{i}" for i in rng.integers(0, n_sym, n)], object),
            "user": np.asarray(
                [f"u{i}" for i in rng.integers(0, n_user, n)], object),
            "price": rng.integers(1, 100, n).astype(np.float32),
            "volume": rng.integers(-50, 50, n).astype(np.int64),
        }
        out.append((cols, t0 + np.arange(n, dtype=np.int64) * 100))
        t0 += n * 100
    return out


def _norm(rows):
    """Float payloads compare through float32 (conformance-corpus
    convention): host sums float64, device exact two-float f32."""
    return [tuple(float(np.float32(v)) if isinstance(v, float) else v
                  for v in r) for r in rows]


def assert_parity(app, batches, expect_device=True):
    _, host, _ = run_batches(app, batches, engine="host")
    dev, rows, routes = run_batches(app, batches)
    assert dev == expect_device, f"device={dev}"
    assert _norm(host) == _norm(rows), \
        f"host={host[:6]}... dev={rows[:6]}..."
    assert len(host) > 0
    return routes


# ------------------------------------------------- randomized sweep

AGGS = ("sum(price) as t, count() as n, max(price) as hi, "
        "min(volume) as lo")
HAVINGS = [None, "t > 50.0", "n >= 2", "not (t < 30.0)",
           "lo > -45 and n > 1", "hi >= 10.0 or lo < 0"]
ORDERS = [[], ["t desc"], ["n asc", "t desc"], ["hi asc"],
          ["lo desc", "n desc"]]


@pytest.mark.parametrize("seed", range(10))
def test_randomized_parity_sweep(seed):
    """Group-by arity x having x order-by direction x limit/offset,
    asserted EXACTLY against the host QuerySelector on the same chunks
    (running aggregation — no window — so limit/offset is
    device-legal)."""
    rng = np.random.default_rng(100 + seed)
    keys = ["sym"] if rng.integers(0, 2) == 0 else ["sym", "user"]
    having = HAVINGS[rng.integers(0, len(HAVINGS))]
    order = ORDERS[rng.integers(0, len(ORDERS))]
    limit = [None, 2, 3][rng.integers(0, 3)]
    offset = 1 if (limit is not None and rng.integers(0, 2)) else None
    q = (f"@info(name='q') from S select {', '.join(keys)}, {AGGS} "
         f"group by {', '.join(keys)}")
    if having:
        q += f" having {having}"
    if order:
        q += " order by " + ", ".join(order)
    if limit is not None:
        q += f" limit {limit}"
    if offset is not None:
        q += f" offset {offset}"
    q += " insert into Out;"
    routes = assert_parity(STREAM + q, _batches(n_chunks=3, seed=seed))
    active = bool(having or order or limit is not None or
                  offset is not None)
    if active:
        # the tail must actually ride the egress kernel, not merely
        # agree with the host by accident of a silent fallback
        assert routes["q"]["backend"] == "device", routes["q"]


def test_windowed_having_order_parity():
    """Sliding length window + having + multi-key order-by: one of the
    burned-down host-fallback shapes (docs/device_coverage.md)."""
    app = STREAM + (
        "@info(name='q') from S#window.length(4) "
        "select sym, sum(price) as t, max(price) as hi, count() as n "
        "group by sym having not (t < 10.0) "
        "order by hi desc, t asc insert into Out;")
    routes = assert_parity(app, _batches(n_chunks=2, seed=5))
    assert routes["q"]["backend"] == "device"


def test_time_window_having_order_parity():
    app = STREAM + (
        "@info(name='q') from S#window.time(10 sec) "
        "select sym, sum(price) as t group by sym "
        "having t > 20.0 order by t desc insert into Out;")
    routes = assert_parity(app, _batches(n_chunks=2, seed=6))
    assert routes["q"]["backend"] == "device"


def test_minmax_forever_having_order_parity():
    app = STREAM + (
        "@info(name='q') from S select sym, maxForever(price) as mx, "
        "minForever(volume) as mn, count() as n group by sym "
        "having mx > 5.0 order by mn asc insert into Out;")
    routes = assert_parity(app, _batches(n_chunks=2, seed=7))
    assert routes["q"]["backend"] == "device"


def test_keyed_having_per_key_parity():
    """Partitioned (keyed) having rides the device kernel; global
    emission order across keys differs from the host's per-key-sub-chunk
    oracle even WITHOUT selection (pre-existing chunking artifact, see
    test_device_grouped_agg.assert_parity unordered=...), so keyed
    parity is per-key subsequence equality."""
    app = STREAM + (
        "partition with (sym of S) begin\n"
        "@info(name='q') from S#window.length(4) "
        "select sym, sum(price) as t, count() as n group by sym "
        "having t > 20.0 insert into Out;\nend;")
    batches = _batches(n_chunks=2, seed=3)
    _, host, _ = run_batches(app, batches, engine="host")
    dev, rows, _ = run_batches(app, batches)
    assert dev
    assert len(host) > 0
    for s in sorted({r[0] for r in host} | {r[0] for r in rows}):
        assert _norm([r for r in host if r[0] == s]) == \
            _norm([r for r in rows if r[0] == s]), f"key {s}"


# --------------------------------------------- blocked-shape routing

@pytest.mark.parametrize("frag,reason_sub", [
    # float64 division: avg/stddev atoms never compile
    ("select sym, avg(price) as m group by sym having m > 1.0",
     "float64 division"),
    # exact int64 sum exceeds the two-float compare range
    ("select sym, sum(volume) as t group by sym having t > 10",
     "two-float compare"),
    # group-key columns live host-side
    ("select sym, count() as n group by sym having sym == 's1'",
     "key columns"),
])
def test_blocked_atoms_stay_host(frag, reason_sub):
    app = STREAM + f"@info(name='q') from S {frag} insert into Out;"
    routes = assert_parity(app, _batches(n_chunks=2, seed=9),
                           expect_device=False)
    route = routes["q"]
    assert route["backend"] == "host"
    assert reason_sub in route["reason"], route["reason"]


def test_windowed_limit_stays_host():
    """limit over a sliding window shares slots with expired rows on
    the host path — gated host-only, value-identical fallback."""
    app = STREAM + (
        "@info(name='q') from S#window.length(4) "
        "select sym, sum(price) as t group by sym "
        "having t > 0.0 order by t desc limit 2 insert into Out;")
    _, host, _ = run_batches(app, _batches(n_chunks=2, seed=4),
                             engine="host")
    dev, rows, routes = run_batches(app, _batches(n_chunks=2, seed=4))
    assert _norm(host) == _norm(rows)
    route = routes["q"]
    assert route["backend"] == "host"
    assert "expired" in route["reason"], route["reason"]


def test_keyed_order_limit_stays_host():
    """Partition clones don't surface per-clone selection_route; the
    static gate (analyzer SP012) carries the keyed routing verdict."""
    from siddhi_tpu.analysis import analyze
    app = STREAM + (
        "partition with (sym of S) begin\n"
        "@info(name='q') from S select sym, sum(price) as t "
        "group by sym order by t desc limit 1 insert into Out;\nend;")
    _, host, _ = run_batches(app, _batches(n_chunks=2, seed=8),
                             engine="host")
    _, rows, _ = run_batches(app, _batches(n_chunks=2, seed=8))
    assert len(host) > 0
    for s in sorted({r[0] for r in host} | {r[0] for r in rows}):
        assert _norm([r for r in host if r[0] == s]) == \
            _norm([r for r in rows if r[0] == s]), f"key {s}"
    sp012 = [d for d in analyze("@app:playback " + app).diagnostics
             if d.code == "SP012"]
    assert sp012 and "partition" in sp012[0].message, sp012


def test_select_kill_switch(monkeypatch):
    """SIDDHI_TPU_SELECT=0 pins a device-expressible tail back to the
    host selector — parity still holds, route says why."""
    monkeypatch.setenv("SIDDHI_TPU_SELECT", "0")
    app = STREAM + (
        "@info(name='q') from S select sym, sum(price) as t "
        "group by sym having t > 10.0 order by t desc limit 2 "
        "insert into Out;")
    routes = assert_parity(app, _batches(n_chunks=2, seed=10),
                           expect_device=False)
    route = routes["q"]
    assert route["backend"] == "host"
    assert "SIDDHI_TPU_SELECT" in route["reason"], route["reason"]


# ------------------------------------------------- persist / restore

def test_persist_restore_device_selector_state():
    """Snapshot a device run mid-stream, restore into a fresh runtime,
    continue — the continuation must equal the chunk-2 emissions of a
    continuously-fed host oracle (the selector itself is stateless; the
    state that must survive is the grouped-agg planes it selects
    over)."""
    body = STREAM + (
        "@info(name='q') from S select sym, sum(price) as t, "
        "count() as n group by sym having t > 20.0 "
        "order by t desc limit 3 insert into Out;")
    b1, b2 = _batches(n_chunks=2, seed=11)

    store = InMemoryPersistenceStore()
    m = SiddhiManager()
    m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime("@app:playback " + body)
    out1 = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: out1.extend(tuple(e.data) for e in evs)))
    rt.start()
    rt.get_input_handler("S").send_batch(b1[0], timestamps=b1[1])
    assert rt.query_runtimes["q"].selection_route["backend"] == "device"
    rt.persist()
    rt.shutdown()

    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    rt2 = m2.create_siddhi_app_runtime("@app:playback " + body)
    out2 = []
    rt2.add_callback("Out", StreamCallback(
        lambda evs: out2.extend(tuple(e.data) for e in evs)))
    rt2.start()
    rt2.restore_last_revision()
    assert rt2.query_runtimes["q"].selection_route["backend"] == "device"
    rt2.get_input_handler("S").send_batch(b2[0], timestamps=b2[1])
    rt2.shutdown()

    _, host, _ = run_batches(body, [b1], engine="host")
    mark = len(host)
    _, host_full, _ = run_batches(body, [b1, b2], engine="host")
    assert host_full[:mark] == host
    assert _norm(host_full[mark:]) == _norm(out2)
    assert len(out2) > 0
