"""Window conformance tests modeled on the reference window suites
(query/window/LengthWindowTestCase, LengthBatchWindowTestCase,
TimeWindowTestCase, TimeBatchWindowTestCase, ExternalTimeWindowTestCase,
ExternalTimeBatchWindowTestCase, TimeLengthWindowTestCase,
SortWindowTestCase, FrequentWindowTestCase, LossyFrequentWindowTestCase —
the CURRENT/EXPIRED emission algebra of ARCH.md:238-268).
Time windows run under @app:playback with explicit timestamps.
"""
from ref_harness import run_query

CSE = "define stream cse (symbol string, price float, volume int);\n"
Q = "@info(name = 'query1') "


def test_length_under_capacity_no_expiry():
    run_query(CSE + Q + """
        from cse#window.length(4) select symbol, price, volume
        insert all events into out;""",
        [("cse", ["IBM", 700.0, 0]), ("cse", ["WSO2", 60.5, 1])],
        [("IBM", 700.0, 0), ("WSO2", 60.5, 1)], expected_removed=[])


def test_length_sliding_expiry_order():
    run_query(CSE + Q + """
        from cse#window.length(2) select symbol, price, volume
        insert all events into out;""",
        [("cse", ["A", 1.0, 1]), ("cse", ["B", 2.0, 2]),
         ("cse", ["C", 3.0, 3]), ("cse", ["D", 4.0, 4])],
        [("A", 1.0, 1), ("B", 2.0, 2), ("C", 3.0, 3), ("D", 4.0, 4)],
        expected_removed=[("A", 1.0, 1), ("B", 2.0, 2)])


def test_length_window_sum_slides():
    run_query(CSE + Q + """
        from cse#window.length(2) select symbol, sum(price) as total
        insert into out;""",
        [("cse", ["A", 10.0, 1]), ("cse", ["B", 20.0, 2]),
         ("cse", ["C", 30.0, 3])],
        [("A", 10.0), ("B", 30.0), ("C", 50.0)])


def test_length_batch_emits_on_full():
    run_query(CSE + Q + """
        from cse#window.lengthBatch(3) select symbol, price, volume
        insert into out;""",
        [("cse", ["A", 1.0, 1]), ("cse", ["B", 2.0, 2]),
         ("cse", ["C", 3.0, 3]), ("cse", ["D", 4.0, 4])],
        [("A", 1.0, 1), ("B", 2.0, 2), ("C", 3.0, 3)])


def test_length_batch_sum_resets_per_batch():
    run_query(CSE + Q + """
        from cse#window.lengthBatch(2) select sum(price) as total
        insert into out;""",
        [("cse", ["A", 10.0, 1]), ("cse", ["B", 20.0, 2]),
         ("cse", ["C", 30.0, 3]), ("cse", ["D", 40.0, 4])],
        [(30.0,), (70.0,)])


def test_length_batch_expired_previous_batch():
    run_query(CSE + Q + """
        from cse#window.lengthBatch(2) select symbol, price, volume
        insert all events into out;""",
        [("cse", ["A", 1.0, 1]), ("cse", ["B", 2.0, 2]),
         ("cse", ["C", 3.0, 3]), ("cse", ["D", 4.0, 4])],
        [("A", 1.0, 1), ("B", 2.0, 2), ("C", 3.0, 3), ("D", 4.0, 4)],
        expected_removed=[("A", 1.0, 1), ("B", 2.0, 2)])


def test_time_window_expires_after_period():
    run_query(CSE + Q + """
        from cse#window.time(1 sec) select symbol, price, volume
        insert all events into out;""",
        [("cse", ["A", 1.0, 1], 1000), ("cse", ["B", 2.0, 2], 1400)],
        [("A", 1.0, 1), ("B", 2.0, 2)],
        expected_removed=[("A", 1.0, 1), ("B", 2.0, 2)],
        playback=True, advance_to=3000)


def test_time_window_sum_decays():
    run_query(CSE + Q + """
        from cse#window.time(1 sec) select sum(volume) as total
        insert into out;""",
        [("cse", ["A", 1.0, 10], 1000), ("cse", ["B", 2.0, 20], 1300),
         ("cse", ["C", 3.0, 30], 2100)],
        [(10,), (30,), (50,)], playback=True, advance_to=4000)


def test_time_batch_flushes_on_boundary():
    run_query(CSE + Q + """
        from cse#window.timeBatch(1 sec) select symbol, volume
        insert into out;""",
        [("cse", ["A", 1.0, 1], 1000), ("cse", ["B", 2.0, 2], 1400),
         ("cse", ["C", 3.0, 3], 2100)],
        [("A", 1), ("B", 2), ("C", 3)], playback=True, advance_to=4000)


def test_time_batch_sum_per_window():
    run_query(CSE + Q + """
        from cse#window.timeBatch(1 sec) select sum(volume) as total
        insert into out;""",
        [("cse", ["A", 1.0, 10], 1000), ("cse", ["B", 2.0, 20], 1400),
         ("cse", ["C", 3.0, 30], 2100)],
        [(30,), (30,)], playback=True, advance_to=4000)


def test_external_time_expiry_by_event_ts():
    run_query("""
        define stream cse (ts long, symbol string, volume int);
        @info(name = 'query1')
        from cse#window.externalTime(ts, 1 sec) select symbol, volume
        insert all events into out;""",
        [("cse", [1000, "A", 1]), ("cse", [1800, "B", 2]),
         ("cse", [2200, "C", 3])],
        [("A", 1), ("B", 2), ("C", 3)],
        expected_removed=[("A", 1)])


def test_external_time_batch_by_event_ts():
    run_query("""
        define stream cse (ts long, symbol string, volume int);
        @info(name = 'query1')
        from cse#window.externalTimeBatch(ts, 1 sec) select symbol, volume
        insert into out;""",
        [("cse", [1000, "A", 1]), ("cse", [1200, "B", 2]),
         ("cse", [2100, "C", 3]), ("cse", [3300, "D", 4])],
        [("A", 1), ("B", 2), ("C", 3)])


def test_time_length_caps_both_ways():
    run_query(CSE + Q + """
        from cse#window.timeLength(1 sec, 2) select symbol, volume
        insert all events into out;""",
        [("cse", ["A", 1.0, 1], 1000), ("cse", ["B", 2.0, 2], 1100),
         ("cse", ["C", 3.0, 3], 1200)],
        [("A", 1), ("B", 2), ("C", 3)],
        expected_removed=[("A", 1), ("B", 2), ("C", 3)],
        playback=True, advance_to=3000)


def test_sort_window_keeps_smallest():
    # sort(2, volume, 'asc'): keeps the 2 smallest volumes, expels the rest
    run_query(CSE + Q + """
        from cse#window.sort(2, volume) select symbol, volume
        insert all events into out;""",
        [("cse", ["A", 1.0, 50]), ("cse", ["B", 2.0, 20]),
         ("cse", ["C", 3.0, 40]), ("cse", ["D", 4.0, 10])],
        [("A", 50), ("B", 20), ("C", 40), ("D", 10)],
        expected_removed=[("A", 50), ("C", 40)])


def test_frequent_window_top_occurrences():
    run_query(CSE + Q + """
        from cse#window.frequent(1, symbol) select symbol, volume
        insert into out;""",
        [("cse", ["A", 1.0, 1]), ("cse", ["A", 1.0, 2]),
         ("cse", ["B", 2.0, 3]), ("cse", ["A", 1.0, 4])],
        [("A", 1), ("A", 2), ("A", 4)])


def test_lossy_frequent_window():
    run_query(CSE + Q + """
        from cse#window.lossyFrequent(0.5, 0.1, symbol)
        select symbol, volume insert into out;""",
        [("cse", ["A", 1.0, 1]), ("cse", ["A", 1.0, 2]),
         ("cse", ["B", 2.0, 3]), ("cse", ["A", 1.0, 4])],
        [("A", 1), ("A", 2), ("B", 3), ("A", 4)])


def test_hopping_overlap_window_gt_hop():
    # window 2s, hop 1s: each event is CURRENT in two successive hops,
    # expiring once when it slides out (HopingWindowTestCase shape)
    run_query(CSE + Q + """
        from cse#window.hoping(2 sec, 1 sec) select symbol, volume
        insert all events into out;""",
        [("cse", ["A", 1.0, 1], 1000), ("cse", ["B", 2.0, 2], 1600),
         ("cse", ["C", 3.0, 3], 2300), ("cse", ["D", 4.0, 4], 3100)],
        [("A", 1), ("B", 2), ("B", 2), ("C", 3), ("C", 3), ("D", 4),
         ("D", 4)],
        expected_removed=[("A", 1), ("B", 2), ("C", 3), ("D", 4)],
        playback=True, advance_to=6000)


def test_hopping_tumbling_window_eq_hop():
    # window == hop degenerates to tumbling; an event exactly at
    # boundary - window is excluded (strict > cut)
    run_query(CSE + Q + """
        from cse#window.hopping(1 sec, 1 sec) select symbol, volume
        insert all events into out;""",
        [("cse", ["A", 1.0, 1], 1000), ("cse", ["B", 2.0, 2], 1400),
         ("cse", ["C", 3.0, 3], 2100)],
        [("B", 2), ("C", 3)],
        expected_removed=[("B", 2), ("C", 3)],
        playback=True, advance_to=4000)


def test_hopping_gap_window_lt_hop():
    # window 1s, hop 2s: only events inside the trailing 1s of each hop
    # are sampled; the rest never emit
    run_query(CSE + Q + """
        from cse#window.hoping(1 sec, 2 sec) select symbol, volume
        insert all events into out;""",
        [("cse", ["A", 1.0, 1], 1000), ("cse", ["B", 2.0, 2], 2500),
         ("cse", ["C", 3.0, 3], 4900)],
        [("B", 2), ("C", 3)],
        expected_removed=[("B", 2)],
        playback=True, advance_to=6000)


def test_hopping_sum_per_hop():
    # each hop's RESET row clears the accumulator, then the window's
    # rows re-accumulate (running sum per CURRENT row, no is_batch)
    run_query(CSE + Q + """
        from cse#window.hoping(2 sec, 1 sec) select sum(volume) as total
        insert into out;""",
        [("cse", ["A", 1.0, 10], 1000), ("cse", ["B", 2.0, 20], 1600),
         ("cse", ["C", 3.0, 30], 2300)],
        [(10,), (30,), (20,), (50,), (30,)],
        playback=True, advance_to=5000)


def test_delay_window_holds_events():
    run_query(CSE + Q + """
        from cse#window.delay(1 sec) select symbol, volume
        insert into out;""",
        [("cse", ["A", 1.0, 1], 1000), ("cse", ["B", 2.0, 2], 1200)],
        [("A", 1), ("B", 2)], playback=True, advance_to=4000)


def test_session_window_groups_by_gap():
    run_query(CSE + Q + """
        from cse#window.session(1 sec) select sum(volume) as total
        insert into out;""",
        [("cse", ["A", 1.0, 10], 1000), ("cse", ["B", 2.0, 20], 1300)],
        [(10,), (30,)], playback=True, advance_to=5000)


def test_batch_window_per_chunk():
    run_query(CSE + Q + """
        from cse#window.batch() select sum(volume) as total
        insert into out;""",
        [("cse", ["A", 1.0, 10]), ("cse", ["B", 2.0, 20])],
        [(10,), (20,)])


def test_window_filter_then_window():
    run_query(CSE + Q + """
        from cse[price > 1.0]#window.length(2) select symbol, sum(volume) as t
        insert into out;""",
        [("cse", ["A", 0.5, 10]), ("cse", ["B", 2.0, 20]),
         ("cse", ["C", 3.0, 30]), ("cse", ["D", 4.0, 40])],
        [("B", 20), ("C", 50), ("D", 70)])


def test_window_group_by_with_length():
    run_query(CSE + Q + """
        from cse#window.length(4) select symbol, sum(volume) as t
        group by symbol insert into out;""",
        [("cse", ["A", 1.0, 10]), ("cse", ["B", 1.0, 20]),
         ("cse", ["A", 1.0, 30])],
        [("A", 10), ("B", 20), ("A", 40)])
