"""Conformance: TPU windowed-aggregation kernel vs the host oracle.

Covers BASELINE config 2 (length-window filter+groupBy aggregation over
partition keys) — the kernel's running sums/counts must match the host
runtime's partitioned query exactly.
"""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.ops.nfa import pack_blocks
from siddhi_tpu.ops.windowed_agg import (build_wagg_step,
                                         build_wagg_step_pallas,
                                         make_wagg_carry)
from siddhi_tpu.plan.wagg_compiler import CompiledWindowedAgg

APP = """
define stream S (k int, v float);
@info(name='q')
from S[v > 2.0]#window.length(5)
select k, sum(v) as total, count() as n
group by k
insert into Out;
"""


def gen(seed, n, n_partitions):
    rng = np.random.default_rng(seed)
    pids = rng.integers(0, n_partitions, n)
    vals = rng.uniform(0.0, 10.0, n).astype(np.float32)
    ts = 1_000_000 + np.arange(n, dtype=np.int64)
    return pids, vals, ts


def oracle_final(pids, vals, ts, n_partitions):
    """Host oracle: same query, partitioned; final per-key (sum, count)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (k int, v float);
        partition with (k of S) begin
        @info(name='q')
        from S[v > 2.0]#window.length(5)
        select k, sum(v) as total, count() as n group by k
        insert into Out; end;
    """)
    last = {}
    rt.add_callback("Out", StreamCallback(
        lambda evs: [last.__setitem__(e.data[0], (e.data[1], e.data[2]))
                     for e in evs]))
    rt.start()
    h = rt.get_input_handler("S")
    h.send_batch({"k": pids.astype(np.int32), "v": vals}, timestamps=ts)
    rt.shutdown()
    return last


def test_wagg_conformance_vs_oracle():
    n_partitions = 16
    pids, vals, ts = gen(5, 400, n_partitions)
    agg = CompiledWindowedAgg(APP, n_partitions=n_partitions,
                              t_per_block=32, use_pallas=False)
    cols = {"k": pids.astype(np.float32), "v": vals}
    i = 0
    while i < len(pids):
        j = min(i + 200, len(pids))
        block = pack_blocks(pids[i:j], {k: v[i:j] for k, v in cols.items()},
                            ts[i:j], np.zeros(j - i, np.int32),
                            n_partitions, base_ts=int(ts[0]))
        agg.process_block(block)
        i = j
    got = agg.current_aggregates()
    expected = oracle_final(pids, vals, ts, n_partitions)
    for k, (total, n) in expected.items():
        assert got["total"][k] == pytest.approx(total, rel=1e-5)
        assert int(got["n"][k]) == n


def test_wagg_pallas_interpret_matches_jnp():
    """Pallas kernel (interpret mode on CPU) == jnp scan, exactly."""
    from jax.experimental import pallas as pl
    import jax.numpy as jnp
    P, W, T = 256, 16, 8
    rng = np.random.default_rng(0)
    values = rng.uniform(0, 10, (P, T)).astype(np.float32)
    accepted = rng.random((P, T)) < 0.7
    import jax
    step_j = jax.jit(build_wagg_step(W))
    c1, (s1, n1) = step_j(make_wagg_carry(P, W), values, accepted)

    orig = pl.pallas_call

    def patched(*a, **k):
        k["interpret"] = True
        return orig(*a, **k)
    pl.pallas_call = patched
    try:
        step_p = build_wagg_step_pallas(W, T)
        c2, (s2, n2) = step_p(make_wagg_carry(P, W), jnp.asarray(values),
                              jnp.asarray(accepted))
    finally:
        pl.pallas_call = orig
    assert np.allclose(np.asarray(s1), np.asarray(s2))
    assert (np.asarray(n1) == np.asarray(n2)).all()
    assert np.allclose(np.asarray(c1.ring), np.asarray(c2.ring))
    assert (np.asarray(c1.pos) == np.asarray(c2.pos)).all()


def test_wagg_minmax_matches_naive():
    """min/max lanes reduce the live ring exactly — compare against a naive
    per-lane sliding-window reference."""
    import jax
    P, W, T = 8, 5, 64
    rng = np.random.default_rng(3)
    values = rng.uniform(0, 100, (P, T)).astype(np.float32)
    accepted = rng.random((P, T)) < 0.6
    step = jax.jit(build_wagg_step(W, want_minmax=True))
    _, (s, n, mn, mx) = step(make_wagg_carry(P, W), values, accepted)
    mn, mx = np.asarray(mn), np.asarray(mx)
    for p in range(P):
        win = []
        for t in range(T):
            if accepted[p, t]:
                win.append(values[p, t])
                win = win[-W:]
            if win:
                assert mn[p, t] == pytest.approx(min(win)), (p, t)
                assert mx[p, t] == pytest.approx(max(win)), (p, t)


def test_wagg_minmax_pallas_interpret_matches_jnp():
    from jax.experimental import pallas as pl
    import jax
    import jax.numpy as jnp
    P, W, T = 256, 7, 8
    rng = np.random.default_rng(4)
    values = rng.uniform(0, 10, (P, T)).astype(np.float32)
    accepted = rng.random((P, T)) < 0.7
    step_j = jax.jit(build_wagg_step(W, want_minmax=True))
    _, (s1, n1, mn1, mx1) = step_j(make_wagg_carry(P, W), values, accepted)
    orig = pl.pallas_call

    def patched(*a, **k):
        k["interpret"] = True
        return orig(*a, **k)
    pl.pallas_call = patched
    try:
        step_p = build_wagg_step_pallas(W, T, want_minmax=True)
        _, (s2, n2, mn2, mx2) = step_p(make_wagg_carry(P, W),
                                       jnp.asarray(values),
                                       jnp.asarray(accepted))
    finally:
        pl.pallas_call = orig
    assert np.allclose(np.asarray(mn1), np.asarray(mn2))
    assert np.allclose(np.asarray(mx1), np.asarray(mx2))
    assert np.allclose(np.asarray(s1), np.asarray(s2))


def test_wagg_minmax_end_to_end_vs_oracle():
    """min/max through CompiledWindowedAgg vs the partitioned host query."""
    n_partitions = 8
    pids, vals, ts = gen(11, 300, n_partitions)
    agg = CompiledWindowedAgg("""
        define stream S (k int, v float);
        @info(name='q')
        from S[v > 2.0]#window.length(5)
        select k, min(v) as lo, max(v) as hi, sum(v) as total
        group by k
        insert into Out;
    """, n_partitions=n_partitions, t_per_block=32, use_pallas=False)
    block = pack_blocks(pids, {"k": pids.astype(np.float32), "v": vals},
                        ts, np.zeros(len(pids), np.int32), n_partitions,
                        base_ts=int(ts[0]))
    agg.process_block(block)
    got = agg.current_aggregates()

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (k int, v float);
        partition with (k of S) begin
        @info(name='q')
        from S[v > 2.0]#window.length(5)
        select k, min(v) as lo, max(v) as hi group by k
        insert into Out; end;
    """)
    last = {}
    rt.add_callback("Out", StreamCallback(
        lambda evs: [last.__setitem__(e.data[0], (e.data[1], e.data[2]))
                     for e in evs]))
    rt.start()
    rt.get_input_handler("S").send_batch(
        {"k": pids.astype(np.int32), "v": vals}, timestamps=ts)
    rt.shutdown()
    assert last, "oracle produced nothing"
    for k, (lo, hi) in last.items():
        assert got["lo"][k] == pytest.approx(lo, rel=1e-6), k
        assert got["hi"][k] == pytest.approx(hi, rel=1e-6), k


TIME_APP = """
define stream S (k int, v float);
@info(name='q')
from S[v > 2.0]#window.time(1 sec)
select k, sum(v) as total, count() as n, min(v) as lo, max(v) as hi
group by k
insert into Out;
"""


def _naive_time_window(pids, vals, ts, span_ms, accepted):
    """Per-event sliding-time reference: (sum, count, min, max) over each
    lane's events with ts_e > ts_now - span."""
    out = {}
    hist = {}
    results = []
    for p, v, t, ok in zip(pids, vals, ts, accepted):
        if not ok:
            results.append(None)
            continue
        h = hist.setdefault(p, [])
        h.append((t, v))
        live = [(tt, vv) for tt, vv in h if tt > t - span_ms]
        hist[p] = live
        vs = [vv for _, vv in live]
        results.append((sum(vs), len(vs), min(vs), max(vs)))
    return results


def test_time_wagg_kernel_matches_naive():
    import jax
    from siddhi_tpu.ops.windowed_agg import (build_time_wagg_step,
                                             make_time_wagg_carry)
    P, T, W, SPAN = 4, 128, 16, 50
    rng = np.random.default_rng(9)
    values = rng.uniform(0, 10, (P, T)).astype(np.float32)
    ts = np.cumsum(rng.integers(1, 20, (P, T)), axis=1).astype(np.int32)
    accepted = rng.random((P, T)) < 0.8
    step = jax.jit(build_time_wagg_step(SPAN, W, want_minmax=True))
    carry, (s, c, mn, mx) = step(make_time_wagg_carry(P, W), values,
                                 ts, accepted)
    assert not np.asarray(carry.overflow).any()
    s, c = np.asarray(s), np.asarray(c)
    mn, mx = np.asarray(mn), np.asarray(mx)
    for p in range(P):
        ref = _naive_time_window([p] * T, values[p], ts[p], SPAN,
                                 accepted[p])
        for t in range(T):
            if ref[t] is None:
                continue
            rs, rc, rmn, rmx = ref[t]
            assert c[p, t] == rc, (p, t)
            assert s[p, t] == pytest.approx(rs, rel=1e-5), (p, t)
            assert mn[p, t] == pytest.approx(rmn), (p, t)
            assert mx[p, t] == pytest.approx(rmx), (p, t)


def test_time_wagg_conformance_vs_oracle():
    """End-to-end: CompiledWindowedAgg time mode vs the partitioned host
    oracle, absolute epoch-scale timestamps (exercises the i32 rebase)."""
    n_partitions = 8
    rng = np.random.default_rng(6)
    n = 300
    pids = rng.integers(0, n_partitions, n)
    vals = rng.uniform(0.0, 10.0, n).astype(np.float32)
    base = 1 << 41                      # ~2.2e12: epoch-like ms
    ts = base + np.cumsum(rng.integers(1, 300, n)).astype(np.int64)
    agg = CompiledWindowedAgg(TIME_APP, n_partitions=n_partitions,
                              use_pallas=False)
    cols = {"k": pids.astype(np.float32), "v": vals}
    i = 0
    while i < n:
        j = min(i + 100, n)
        block, rows = pack_blocks(pids[i:j],
                                  {k: v[i:j] for k, v in cols.items()},
                                  ts[i:j], np.zeros(j - i, np.int32),
                                  n_partitions, base_ts=int(ts[i]),
                                  return_rows=True)
        ts64 = np.zeros(block["__ts"].shape, np.int64)
        ts64[pids[i:j], rows] = ts[i:j]
        block["__ts64"] = ts64
        agg.process_block(block)
        i = j
    got = agg.current_aggregates()

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:playback
        define stream S (k int, v float);
        partition with (k of S) begin
        @info(name='q')
        from S[v > 2.0]#window.time(1 sec)
        select k, sum(v) as total, count() as n, min(v) as lo, max(v) as hi
        group by k insert into Out; end;
    """)
    last = {}
    rt.add_callback("Out", StreamCallback(
        lambda evs: [last.__setitem__(e.data[0], tuple(e.data[1:]))
                     for e in evs]))
    rt.start()
    rt.get_input_handler("S").send_batch(
        {"k": pids.astype(np.int32), "v": vals}, timestamps=ts)
    rt.shutdown()
    assert last, "oracle produced nothing"
    for k, (total, cnt, lo, hi) in last.items():
        assert int(got["n"][k]) == cnt, k
        assert got["total"][k] == pytest.approx(total, rel=1e-4), k
        assert got["lo"][k] == pytest.approx(lo, rel=1e-6), k
        assert got["hi"][k] == pytest.approx(hi, rel=1e-6), k


def test_time_wagg_overflow_grows_and_stays_exact(monkeypatch):
    """More in-window events than ring capacity: the compiler grows the
    ring and replays the block — results stay exact."""
    import siddhi_tpu.plan.wagg_compiler as wc
    monkeypatch.setattr(wc, "TIME_CAPACITY_START", 4)
    agg = CompiledWindowedAgg(TIME_APP, n_partitions=2, use_pallas=False)
    assert agg.window == 4
    n = 40                              # 40 events inside one 1s window
    pids = np.zeros(n, np.int64)
    vals = np.linspace(3.0, 9.0, n).astype(np.float32)
    ts = 1_000_000 + np.arange(n, dtype=np.int64) * 10
    block, rows = pack_blocks(pids, {"k": pids.astype(np.float32),
                                     "v": vals}, ts,
                              np.zeros(n, np.int32), 2,
                              base_ts=int(ts[0]), return_rows=True)
    ts64 = np.zeros(block["__ts"].shape, np.int64)
    ts64[pids, rows] = ts
    block["__ts64"] = ts64
    agg.process_block(block)
    assert agg.window >= n              # grew past the event count
    got = agg.current_aggregates()
    assert int(got["n"][0]) == n
    assert got["total"][0] == pytest.approx(float(vals.sum()), rel=1e-5)
    assert got["lo"][0] == pytest.approx(3.0)
    assert got["hi"][0] == pytest.approx(9.0)


def test_external_time_rejects_bad_shapes():
    from siddhi_tpu.utils.errors import SiddhiAppCreationError
    head = "define stream S (k int, ets long, txt string, v float);\n"
    for window in ("externalTime(ets)",          # missing window length
                   "externalTime(bogus, 200)",   # unknown attribute
                   "externalTime(txt, 200)"):    # non-integer attribute
        with pytest.raises(SiddhiAppCreationError):
            CompiledWindowedAgg(head + f"""
                @info(name='q')
                from S#window.{window}
                select k, sum(v) as total group by k insert into Out;
            """, n_partitions=4, use_pallas=False)


def test_time_wagg_rejects_far_past_timestamps():
    """An event timestamp ~25 days older than the pinned base must fail
    loudly (runtime data error — the junction's @OnError boundary routes
    it), not wrap i32 into the far future."""
    from siddhi_tpu.utils.errors import SiddhiAppRuntimeException
    agg = CompiledWindowedAgg(TIME_APP, n_partitions=2, use_pallas=False)

    def block_at(ts0):
        pids = np.zeros(2, np.int64)
        ts = np.asarray([ts0, ts0 + 1], np.int64)
        vals = np.asarray([5.0, 6.0], np.float32)
        b, rows = pack_blocks(pids, {"k": pids.astype(np.float32),
                                     "v": vals}, ts,
                              np.zeros(2, np.int32), 2,
                              base_ts=int(ts[0]), return_rows=True)
        ts64 = np.zeros(b["__ts"].shape, np.int64)
        ts64[pids, rows] = ts
        b["__ts64"] = ts64
        return b

    base = 1 << 41
    agg.process_block(block_at(base))
    with pytest.raises(SiddhiAppRuntimeException):
        agg.process_block(block_at(base - (1 << 31) - 10_000))


def test_wagg_rejects_distinct_aggregate_args():
    """sum(x) + avg(y) can't share the single value lane — must be rejected
    at compile time, not silently aggregate the wrong column."""
    from siddhi_tpu.utils.errors import SiddhiAppCreationError
    with pytest.raises(SiddhiAppCreationError):
        CompiledWindowedAgg("""
            define stream S (k int, x float, y float);
            @info(name='q')
            from S#window.length(5)
            select k, sum(x) as sx, avg(y) as ay group by k
            insert into Out;
        """, n_partitions=4)


def test_wagg_same_arg_multiple_aggs_ok():
    c = CompiledWindowedAgg("""
        define stream S (k int, x float);
        @info(name='q')
        from S#window.length(5)
        select k, sum(x) as sx, avg(x) as ax, count() as n group by k
        insert into Out;
    """, n_partitions=4, use_pallas=False)
    pids = np.array([0, 1, 0, 1], np.int32)
    vals = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
    ts = 1_000_000 + np.arange(4, dtype=np.int64)
    block = pack_blocks(pids, {"k": pids.astype(np.float32), "x": vals},
                        ts, np.zeros(4, np.int32), 4, base_ts=1_000_000)
    c.process_block(block)
    agg = c.current_aggregates()
    assert agg["sx"][0] == pytest.approx(4.0)
    assert agg["ax"][1] == pytest.approx(3.0)
    assert agg["n"][0] == 2
