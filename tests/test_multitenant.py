"""Cross-tenant super-dispatch (round 14): packed-vs-unpacked equivalence.

The TenantPacker (plan/xtenant.py) buckets small automata from
DIFFERENT apps by shape class and steps every pending tenant in ONE
jitted gang dispatch per bucket per ingest wall, with all co-scheduled
match buffers riding one shared egress slab.  That must be invisible in
match semantics: randomized round-robin feeds produce bit-identical
per-app matches vs the ``SIDDHI_TPU_XTENANT=0`` kill switch, for B in
{1, 4}, with heterogeneous query kinds (pattern and sequence) sharing
one bucket, and through a forced single-tenant grow-and-replay.

Plus the structural claims: packed tenants REALLY pay fewer device
dispatches per ingest wall than the per-app path; one tenant's slot
overflow rewinds and re-keys ONLY that tenant (co-tenants keep their
gang results); shutting a packed tenant down evicts it without
disturbing co-tenants' matches; the cost model prices a packed bucket
byte-exactly against the live carries (packing changes dispatch count,
never bytes); plan dumps surface ``packed=<bucket>``; 100 create/
shutdown cycles leak no engine threads and leave the packer empty; and
the per-tenant quota + packer series render exposition-clean.
Runs on the conftest-forced virtual 8-device CPU mesh.
"""
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from siddhi_tpu import SiddhiManager, StreamCallback  # noqa: E402
from siddhi_tpu.ops.nfa import BATCH_ENV  # noqa: E402
from siddhi_tpu.plan.xtenant import (XTENANT_ENV,  # noqa: E402
                                     resolve_xtenant, tenant_packer)

BASE = 1_000_000


@pytest.fixture(autouse=True)
def _single_device(monkeypatch):
    # the packer's eligible population is single-device small automata
    # (meshed NFAs donate their carries and can never rewind, so they
    # never pack) — pin the operator escape hatch so the runtimes this
    # module builds come up mesh-free on the 8-device conftest CPU mesh
    monkeypatch.setenv("SIDDHI_TPU_MESH", "off")


def _pattern_app(i, thr, e2="v > e1.v"):
    return (f"@app:name('mt{i}') @app:pipeline('4') "
            "define stream S (k int, v double); "
            f"@info(name='q') from every e1=S[v > {thr}] -> "
            f"e2=S[{e2}] select e1.v as a, e2.v as b insert into Out;")


def _sequence_app(i, thr):
    # a different query KIND (sequence `,` not pattern `->`) with the
    # same shape class (S=2, same captures) — heterogeneous condition
    # programs must coexist in one gang trace
    return (f"@app:name('mt{i}') @app:pipeline('4') "
            "define stream S (k int, v double); "
            f"@info(name='q') from every e1=S[v > {thr}], "
            "e2=S[v > e1.v] select e1.v as a, e2.v as b insert into Out;")


def _run_tenants(apps, seed, packed, walls=4, events=10, on_wall=None):
    """Round-robin feed `walls` walls of one block per app; returns
    (per-app sorted match tuples, per-app NFAs' final (n_slots, bucket
    label), packer snapshot).  Same seed both modes so parity is exact
    by construction.  `on_wall(wall, rts)` runs between walls (used to
    shut a tenant down mid-stream)."""
    prev = os.environ.get(XTENANT_ENV)
    os.environ[XTENANT_ENV] = "1" if packed else "0"
    try:
        m = SiddhiManager()
        matches = [[] for _ in apps]
        rts = []
        for i, app in enumerate(apps):
            rt = m.create_siddhi_app_runtime(app)
            rt.add_callback("Out", StreamCallback(
                lambda evs, _s=matches[i]: _s.extend(
                    tuple(e.data) for e in evs)))
            rt.start()
            rts.append(rt)
        rng = np.random.default_rng(seed)
        t0 = BASE
        for w in range(walls):
            for rt in rts:
                if rt is None:
                    rng.uniform(0.0, 1.0, events)   # keep streams aligned
                    continue
                h = rt.get_input_handler("S")
                h.send_batch(
                    {"k": np.arange(events, dtype=np.int64) % 4,
                     "v": rng.uniform(0.0, 1.0, events)},
                    timestamps=t0 + np.arange(events, dtype=np.int64))
            t0 += events
            if on_wall is not None:
                on_wall(w, rts)
        shapes = []
        for rt in rts:
            if rt is None:
                shapes.append(None)
                continue
            rt.flush()
            nfa = next(iter(rt.query_runtimes.values())).device_runtime.nfa
            b = getattr(nfa, "_tenant_bucket", None)
            shapes.append((nfa.spec.n_slots, b.label if b else None))
        snap = tenant_packer().snapshot()
        m.shutdown()
        return [sorted(s) for s in matches], shapes, snap
    finally:
        if prev is None:
            os.environ.pop(XTENANT_ENV, None)
        else:
            os.environ[XTENANT_ENV] = prev


# ------------------------------------------------------------------ parity

@pytest.mark.parametrize("B", [1, 4])
def test_packed_matches_unpacked(B, monkeypatch):
    """Mixed query kinds (two patterns + one sequence) share ONE bucket
    and the gang-stepped matches are bit-identical to the kill-switch
    per-app path, across randomized feeds and B in {1, 4}."""
    monkeypatch.setenv(BATCH_ENV, str(B))
    apps = [_pattern_app(0, 0.1), _sequence_app(1, 0.3),
            _pattern_app(2, 0.5)]
    total = 0
    for seed in (0, 1, 2):
        mp, sp, snap = _run_tenants(apps, seed, packed=True)
        mu, su, _ = _run_tenants(apps, seed, packed=False)
        assert mp == mu, f"B={B} seed={seed}: packed matches diverged"
        labels = {s[1] for s in sp}
        assert len(labels) == 1 and None not in labels, \
            f"tenants did not share one bucket: {sp}"
        assert len(snap["buckets"]) == 1
        assert snap["buckets"][0]["flush_total"] > 0
        assert all(s[1] is None for s in su), \
            "kill switch left tenants packed"
        total += sum(len(s) for s in mp)
    assert total > 0, "degenerate parity grid (0 matches)"


def test_packed_pays_fewer_dispatches(monkeypatch):
    """The structural point of the layer: N co-bucketed tenants fed
    round-robin pay ~O(1) gang dispatches per wall packed, O(N) with
    the SIDDHI_TPU_XTENANT=0 kill switch."""
    from siddhi_tpu.core.profiling import profiler
    prof = profiler()
    was = prof.enabled
    prof.enable()
    apps = [_pattern_app(i, 0.1 * (i % 5)) for i in range(4)]

    def measured(packed):
        d0 = prof.total_dispatches()
        _run_tenants(apps, 7, packed=packed, walls=3)
        return prof.total_dispatches() - d0

    try:
        dp, du = measured(True), measured(False)
        assert dp < du, f"packed {dp} dispatches !< unpacked {du}"
        assert prof.stats("nfa.xstep").dispatch_count > 0
    finally:
        if not was:
            prof.disable()


def test_grow_and_replay_bucket_granularity():
    """One greedy tenant overflows its K=8 slot ring (its e2 almost
    never fires, so every event parks a partial); the planner must
    rewind, grow and replay ONLY that tenant — matches stay bit-exact
    vs unpacked for greedy AND co-tenant, and the growth re-keys the
    greedy tenant into its own bucket while the co-tenant stays put."""
    apps = [_pattern_app(0, 0.0, e2="v > 0.97"),   # greedy: partials pile
            _pattern_app(1, 0.2)]                   # normal co-tenant
    mp, sp, snap = _run_tenants(apps, 3, packed=True, walls=5, events=12)
    mu, su, _ = _run_tenants(apps, 3, packed=False, walls=5, events=12)
    assert sp[0][0] > 8, \
        f"greedy tenant never overflowed K=8 (K={sp[0][0]}) — the " \
        "bucket-granularity replay path was not exercised"
    assert su[0][0] == sp[0][0], "packed grew to a different K"
    assert mp == mu, "grow-and-replay diverged from the unpacked path"
    assert sum(len(s) for s in mp) > 0
    assert sp[0][1] != sp[1][1], \
        "slot growth did not re-key the grown tenant"
    assert len(snap["buckets"]) == 2


def test_shutdown_evicts_without_disturbing_cotenants():
    """Shutting one packed tenant down mid-stream must flush its
    pending block, retire its final matches, and leave co-tenants'
    subsequent matches bit-identical to the unpacked run of the same
    scenario (their carries were never rewound or re-stepped)."""
    apps = [_pattern_app(i, 0.1 * i) for i in range(3)]

    def kill_middle(w, rts):
        if w == 2:
            rts[1].shutdown()
            rts[1] = None

    mp, sp, snap = _run_tenants(apps, 5, packed=True, walls=5,
                                on_wall=kill_middle)
    mu, _, _ = _run_tenants(apps, 5, packed=False, walls=5,
                            on_wall=kill_middle)
    assert mp == mu
    assert len(mp[0]) > 0 and len(mp[2]) > 0
    # the survivor bucket holds exactly the two remaining tenants
    assert snap["tenants_total"] == 2
    assert sorted(t for b in snap["buckets"] for t in b["tenants"]) == \
        ["mt0/q", "mt2/q"]


def test_kill_switch_and_eligibility():
    from siddhi_tpu.plan.xtenant import resolve_bucket_cap
    prev = os.environ.get(XTENANT_ENV)
    try:
        os.environ[XTENANT_ENV] = "0"
        assert resolve_xtenant() is False
        os.environ.pop(XTENANT_ENV, None)
        assert resolve_xtenant() is True
        assert resolve_xtenant(False) is False
        os.environ["SIDDHI_TPU_XTENANT_BUCKET"] = "3"
        assert resolve_bucket_cap() == 3
    finally:
        os.environ.pop("SIDDHI_TPU_XTENANT_BUCKET", None)
        if prev is None:
            os.environ.pop(XTENANT_ENV, None)
        else:
            os.environ[XTENANT_ENV] = prev


# ------------------------------------------------------------ cost model / IR

def test_cost_model_packed_bucket_byte_exact():
    """packed_bucket_state_bytes prices the bucket as the SUM of its
    tenants' live carries — packing changes dispatch count, never
    bytes — and the egress model covers every tenant's slab share."""
    from siddhi_tpu.analysis.cost_model import (nfa_egress_bytes,
                                                packed_bucket_egress_bytes,
                                                packed_bucket_state_bytes)
    from siddhi_tpu.analysis.plan_ir import automaton_ir_from_nfa
    prev = os.environ.get(XTENANT_ENV)
    os.environ[XTENANT_ENV] = "1"
    try:
        m = SiddhiManager()
        rts = [m.create_siddhi_app_runtime(a) for a in
               (_pattern_app(0, 0.1), _sequence_app(1, 0.4))]
        for rt in rts:
            rt.start()
        nfas = [next(iter(rt.query_runtimes.values())).device_runtime.nfa
                for rt in rts]
        bucket = nfas[0]._tenant_bucket
        assert bucket is not None and bucket is nfas[1]._tenant_bucket
        irs = [automaton_ir_from_nfa(n, "q") for n in nfas]
        live = sum(int(np.asarray(v).nbytes)
                   for n in nfas for v in n.carry.values())
        assert packed_bucket_state_bytes(irs) == live
        assert packed_bucket_egress_bytes(irs) == \
            sum(nfa_egress_bytes(a) for a in irs) > 0
        m.shutdown()
    finally:
        if prev is None:
            os.environ.pop(XTENANT_ENV, None)
        else:
            os.environ[XTENANT_ENV] = prev


def test_plan_ir_surfaces_packing():
    """Plan dumps and as_dict carry the bucket assignment; the kill
    switch removes it (goldens for unpacked plans are unchanged)."""
    from siddhi_tpu.analysis import extract_plan
    prev = os.environ.get(XTENANT_ENV)
    try:
        os.environ[XTENANT_ENV] = "1"
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(_pattern_app(0, 0.1))
        rt.start()
        plan = extract_plan(rt)
        a = plan.automata[0]
        assert a.packed and a.pack_bucket.startswith("S")
        assert a.as_dict()["packed"] is True
        assert a.as_dict()["pack_bucket"] == a.pack_bucket
        assert f"packed={a.pack_bucket}" in plan.dump()
        m.shutdown()

        os.environ[XTENANT_ENV] = "0"
        m2 = SiddhiManager()
        rt2 = m2.create_siddhi_app_runtime(_pattern_app(0, 0.1))
        rt2.start()
        a2 = extract_plan(rt2).automata[0]
        assert not a2.packed and a2.pack_bucket == ""
        assert "packed=" not in extract_plan(rt2).dump()
        m2.shutdown()
    finally:
        if prev is None:
            os.environ.pop(XTENANT_ENV, None)
        else:
            os.environ[XTENANT_ENV] = prev


# ------------------------------------------------------------ lifecycle

def test_hundred_apps_no_thread_or_tenant_leak():
    """100 tenant create/start/shutdown cycles: no engine threads left
    behind (the conftest sentinel would flag them too, but this pins
    the count at the source) and the packer registry drains to its
    pre-test population."""
    packer = tenant_packer()
    tenants0 = packer.snapshot()["tenants_total"]
    threads0 = {t.name for t in threading.enumerate()}
    m = SiddhiManager()
    rts = [m.create_siddhi_app_runtime(_pattern_app(i, 0.1 * (i % 7)))
           for i in range(100)]
    for rt in rts:
        rt.start()
    assert packer.snapshot()["tenants_total"] == tenants0 + 100
    # one shape class, first-fit under the default bucket cap
    from siddhi_tpu.plan.xtenant import resolve_bucket_cap
    want = -(-100 // resolve_bucket_cap())
    assert len(packer.snapshot()["buckets"]) == want
    m.shutdown()
    assert packer.snapshot()["tenants_total"] == tenants0
    assert packer.snapshot()["buckets"] == []
    deadline = time.time() + 5.0
    while time.time() < deadline:
        leaked = [t.name for t in threading.enumerate()
                  if t.name.startswith("siddhi-")
                  and t.name not in threads0]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, f"leaked engine threads: {leaked}"


# ------------------------------------------------------------ fair share

QUOTA_APP = ("@app:name('{name}') @app:quota(rate='{rate}', burst='{burst}') "
             "define stream S (v double); "
             "@info(name='q') from S[v > 0.5] select v insert into Out;")


def test_quota_sheds_greedy_admits_quiet():
    """Token-bucket admission at the ingest boundary: a burst beyond
    the quota is shed tail-first with reason=quota and one quota_breach
    flight emit per episode; a tenant inside its quota is untouched."""
    from siddhi_tpu.core.overload import fair_share
    m = SiddhiManager()
    greedy = m.create_siddhi_app_runtime(
        QUOTA_APP.format(name="greedy", rate=5, burst=10))
    quiet = m.create_siddhi_app_runtime(
        QUOTA_APP.format(name="quiet", rate=100, burst=200))
    seen = {"greedy": [], "quiet": []}
    for name, rt in (("greedy", greedy), ("quiet", quiet)):
        rt.add_callback("Out", StreamCallback(
            lambda evs, _s=seen[name]: _s.extend(e.data[0] for e in evs)))
        rt.start()
    vs = np.linspace(0.6, 0.9, 50)
    greedy.get_input_handler("S").send_batch({"v": vs})
    quiet.get_input_handler("S").send_batch({"v": vs[:8]})
    greedy.flush()
    quiet.flush()
    snap = fair_share().snapshot()
    assert snap["greedy"]["admitted"] == 10      # burst-capped
    assert snap["greedy"]["shed"] == 40
    assert snap["quiet"]["admitted"] == 8 and snap["quiet"]["shed"] == 0
    # shed is tail-first: exactly the first `burst` events were admitted
    assert seen["greedy"] == list(vs[:10])
    assert seen["quiet"] == list(vs[:8])
    m.shutdown()
    assert not fair_share().snapshot(), "quotas survived shutdown"


def test_tenant_metrics_exposition_clean():
    """The per-tenant quota/admission and packer series render through
    prometheus_text with exactly one HELP/TYPE header per family,
    headers before samples, every sample line `name{labels} value`."""
    from siddhi_tpu.core.overload import fair_share
    from siddhi_tpu.core.statistics import prometheus_text
    prev = os.environ.get(XTENANT_ENV)
    os.environ[XTENANT_ENV] = "1"
    m = SiddhiManager()
    try:
        rt = m.create_siddhi_app_runtime(
            QUOTA_APP.format(name="mquota", rate=3, burst=4))
        rt.start()
        rt.get_input_handler("S").send_batch(
            {"v": np.linspace(0.6, 0.9, 20)})
        rt.flush()
        prt = m.create_siddhi_app_runtime(_pattern_app(9, 0.1))
        prt.start()
        prt.get_input_handler("S").send_batch(
            {"k": np.zeros(8, np.int64),
             "v": np.linspace(0.1, 0.9, 8)},
            timestamps=BASE + np.arange(8, dtype=np.int64))
        prt.flush()
        text = prometheus_text(
            [], tenants=[fair_share(), tenant_packer()])
    finally:
        m.shutdown()
        if prev is None:
            os.environ.pop(XTENANT_ENV, None)
        else:
            os.environ[XTENANT_ENV] = prev

    lines = text.splitlines()
    helps, types, first_sample = {}, {}, {}
    for i, ln in enumerate(lines):
        if ln.startswith("# HELP "):
            name = ln.split()[2]
            assert name not in helps, f"duplicate HELP for {name}"
            helps[name] = i
        elif ln.startswith("# TYPE "):
            name = ln.split()[2]
            assert name not in types, f"duplicate TYPE for {name}"
            types[name] = i
        elif ln:
            metric, _, value = ln.rpartition(" ")
            assert metric and (value == "+Inf" or float(value) is not None)
            first_sample.setdefault(ln.split("{")[0].split(" ")[0], i)
    assert set(helps) == set(types)
    for s, i in first_sample.items():
        assert s in helps, f"series {s} has no HELP/TYPE header"
        assert helps[s] < i and types[s] < i
    for want in ("siddhi_tenant_quota_rate", "siddhi_tenant_quota_level",
                 "siddhi_tenant_admitted_total", "siddhi_tenant_shed_total",
                 "siddhi_xtenant_tenants",
                 "siddhi_xtenant_gang_flushes_total"):
        assert want in first_sample, f"no samples for {want}"
    assert any('app="mquota"' in ln for ln in lines
               if ln.startswith("siddhi_tenant_quota_rate"))
    assert any(ln.startswith("siddhi_xtenant_tenants{bucket=")
               for ln in lines)


# ------------------------------------------------------------ REST load

@pytest.mark.slow
def test_rest_fair_share_under_concurrent_load():
    """10 tenant apps behind one REST service, hammered concurrently:
    the greedy tenants' overflow is shed by THEIR quotas, quiet tenants
    see zero shed, and /metrics stays exposition-clean with per-tenant
    series for all 10."""
    from siddhi_tpu.service import SiddhiService
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"

    def req(method, url, payload=None):
        data = payload.encode() if isinstance(payload, str) else payload
        r = urllib.request.Request(url, data=data, method=method)
        with urllib.request.urlopen(r) as resp:
            return resp.read().decode()

    try:
        for i in range(10):
            rate, burst = ((4, 8) if i < 5 else (10_000, 20_000))
            req("POST", f"{base}/siddhi/artifact/deploy",
                QUOTA_APP.format(name=f"ten{i}", rate=rate, burst=burst))

        body = ("[" + ",".join('{"data": [0.7]}' for _ in range(20)) + "]")

        def hammer(i, rounds):
            for _ in range(rounds):
                req("POST", f"{base}/siddhi/apps/ten{i}/streams/S", body)

        threads = [threading.Thread(
            target=hammer, args=(i, 5 if i < 5 else 2), daemon=True)
            for i in range(10)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads)

        from siddhi_tpu.core.overload import fair_share
        snap = fair_share().snapshot()
        for i in range(5):      # greedy: 100 events vs burst 8
            assert snap[f"ten{i}"]["shed"] > 0, f"ten{i} never shed"
            assert snap[f"ten{i}"]["admitted"] >= 8
        for i in range(5, 10):  # quiet: 40 events, quota 20k
            assert snap[f"ten{i}"]["shed"] == 0, f"ten{i} was shed"
            assert snap[f"ten{i}"]["admitted"] == 40

        with urllib.request.urlopen(f"{base}/metrics") as r:
            text = r.read().decode()
        for ln in text.splitlines():
            if ln and not ln.startswith("#"):
                metric, _, value = ln.rpartition(" ")
                assert metric and (value == "+Inf"
                                   or float(value) is not None)
        for i in range(10):
            assert f'app="ten{i}"' in text
        assert "# HELP siddhi_tenant_shed_total" in text
    finally:
        svc.stop()
