"""Window extension SPI: custom windows resolve from the extension
registry by `ns:name`, and GroupingWindowProcessor gives per-key state
partitioning (reference: window extension holders resolved by
SiddhiExtensionLoader + GroupingWindowProcessor.java SPI base)."""
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.window import (GroupingWindowProcessor,
                                    LengthWindowProcessor, WindowProcessor)
from siddhi_tpu.utils.errors import SiddhiAppCreationError
from siddhi_tpu.utils.extension import extension


@extension(namespace="custom", name="keepLast",
           description="Sliding window of the last n events",
           parameters=[("n", "int", "window length")])
class KeepLastWindow(WindowProcessor):
    def __init__(self, app_ctx, names, params, compile_expr):
        super().__init__(app_ctx, names)
        self.inner = LengthWindowProcessor(app_ctx, names,
                                           int(params[0].value))

    def on_data(self, chunk):
        self.inner.next = self.next
        self.inner.lock = self.lock
        self.inner.on_data(chunk)

    def find_chunk(self):
        return self.inner.find_chunk()

    def current_state(self):
        return self.inner.current_state()

    def restore_state(self, s):
        self.inner.restore_state(s)


@extension(namespace="custom", name="lengthPerKey",
           description="length(n) window isolated per group key",
           parameters=[("key", "attribute", "group key"),
                       ("n", "int", "per-key window length")])
class LengthPerKeyWindow(GroupingWindowProcessor):
    def __init__(self, app_ctx, names, params, compile_expr):
        super().__init__(app_ctx, names, compile_expr(params[0]))
        self.n = int(params[1].value)

    def make_inner(self):
        return LengthWindowProcessor(self.app_ctx, self.names, self.n)


def make(app):
    m = SiddhiManager()
    m.set_extension("custom:keepLast", KeepLastWindow)
    m.set_extension("custom:lengthPerKey", LengthPerKeyWindow)
    rt = m.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    return rt, got


def test_custom_window_from_siddhiql():
    rt, got = make("""
        define stream S (sym string, p double);
        from S#window.custom:keepLast(2) select sym, sum(p) as t
        insert into Out;
    """)
    h = rt.get_input_handler("S")
    for i, p in enumerate([1.0, 2.0, 4.0]):
        h.send([f"s{i}", p])
    rt.shutdown()
    # sliding sums over the last-2 window: 1 | 1+2 | (expire 1) 2+4
    assert [e.data[1] for e in got] == [1.0, 3.0, 6.0]


def test_grouping_window_isolates_keys():
    rt, got = make("""
        define stream S (sym string, p double);
        from S#window.custom:lengthPerKey(sym, 1) select sym, sum(p) as t
        insert into Out;
    """)
    h = rt.get_input_handler("S")
    h.send(["A", 1.0])
    h.send(["B", 10.0])
    h.send(["A", 2.0])     # evicts A's 1.0 only; B's window untouched
    rt.shutdown()
    # running sums: 1 | 1+10 | (A's 1 expires) 10+2
    assert [e.data[1] for e in got] == [1.0, 11.0, 12.0]


def test_unknown_namespaced_window_raises():
    m = SiddhiManager()
    with pytest.raises(SiddhiAppCreationError, match="nope:missing"):
        m.create_siddhi_app_runtime("""
            define stream S (p double);
            from S#window.nope:missing(1) select p insert into Out;
        """)


def test_grouping_window_state_roundtrip():
    rt, got = make("""
        define stream S (sym string, p double);
        from S#window.custom:lengthPerKey(sym, 2) select sym, sum(p) as t
        insert into Out;
    """)
    h = rt.get_input_handler("S")
    h.send(["A", 1.0])
    h.send(["B", 10.0])
    qr = rt.query_runtimes["query_0"]
    wp = qr.windows[0]
    state = wp.current_state()
    wp2 = LengthPerKeyWindow.__new__(LengthPerKeyWindow)
    GroupingWindowProcessor.__init__(wp2, wp.app_ctx, wp.names, wp.key_expr)
    wp2.n = wp.n
    wp2.restore_state(state)
    found = wp2.find_chunk()
    rt.shutdown()
    assert sorted(found.columns["sym"].tolist()) == ["A", "B"]
