"""Runtime numeric sentinels (core/numguard.py) — the live half of the
NS0xx verifier.

Covers: the off-by-default env contract, the device sentinel plane
(ops/grouped_agg.sentinel_plane), bit-identical match outputs with
NUMGUARD on vs off, NS101 flight-bus incidents (positive, negative and
the per-site rate limit), the static-NS003 verdict cross-validated by
an armed sentinel run on a constructed overflow feed (with the
@numeric(sum='compensated') remediation proven at host parity), the
static-NS005 count-saturation verdict witnessed through the slab sync
path, the stream-years ts32 wraparound feed (device == host oracle
across the rebase with the guard armed), and the Prometheus /
GET /stats surfaces."""
import json
import math
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from siddhi_tpu import SiddhiManager, StreamCallback  # noqa: E402
from siddhi_tpu.core import numguard  # noqa: E402
from siddhi_tpu.core.flight import flight  # noqa: E402
from siddhi_tpu.core.numguard import (NUMGUARD_ENV,  # noqa: E402
                                      NumericSentinels,
                                      all_numeric_sentinels,
                                      numeric_sentinels, numguard_enabled,
                                      reset_numguard)

from chaos import wraparound_feed  # noqa: E402


@pytest.fixture(autouse=True)
def _numguard_isolation(monkeypatch):
    """Disarmed and empty registry around every test; the flight bus is
    drained so NS101 assertions see only their own incidents."""
    monkeypatch.delenv(NUMGUARD_ENV, raising=False)
    reset_numguard()
    flight().reset()
    yield
    reset_numguard()
    flight().reset()


# ---------------------------------------------------------- off switch

def test_numguard_disabled_by_default():
    assert numguard_enabled() is False


@pytest.mark.parametrize("val,armed", [
    ("1", True), ("true", True), ("on", True), ("yes", True),
    ("0", False), ("off", False), ("", False), ("no", False)])
def test_numguard_env_values(monkeypatch, val, armed):
    monkeypatch.setenv(NUMGUARD_ENV, val)
    assert numguard_enabled() is armed


def test_engine_holds_no_sentinels_when_disarmed():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:name('plainrun') @app:playback
        define stream S (sym string, price float, volume long);
        @info(name='q') from S#window.length(4)
        select sym, sum(price) as t group by sym insert into Out;
    """)
    rt.start()
    rt.get_input_handler("S").send(["A", 1.0, 1], timestamp=1_000_000)
    rt.shutdown()
    assert numeric_sentinels("plainrun", create=False) is None
    assert all_numeric_sentinels() == []


# ------------------------------------------------------ sentinel plane

def test_sentinel_plane_counts_flags():
    import jax.numpy as jnp
    from siddhi_tpu.ops.grouped_agg import sentinel_plane
    near = int(0.95 * (1 << 31))
    hi, lo = near // 65536, near % 65536
    fsum_hi = jnp.asarray([[1.0, jnp.inf], [jnp.nan, 2.0]], jnp.float32)
    isum_hi = jnp.asarray([[hi, 0], [0, 0]], jnp.int32)
    isum_lo = jnp.asarray([[lo, 0], [0, 0]], jnp.int32)
    gcnt = jnp.asarray([[2_000_000_000, 3], [1, 0]], jnp.int32)
    plane = np.asarray(sentinel_plane(fsum_hi, isum_hi, isum_lo, gcnt))
    assert plane.tolist() == [1, 1, 2]     # near-int, near-cnt, nonfinite


def test_sentinel_plane_all_clear():
    import jax.numpy as jnp
    from siddhi_tpu.ops.grouped_agg import sentinel_plane
    z = jnp.zeros((3, 4), jnp.int32)
    f = jnp.ones((3, 4), jnp.float32)
    plane = np.asarray(sentinel_plane(f, z, z, z))
    assert plane.tolist() == [0, 0, 0]


# --------------------------------------------------- sentinel counters

def test_observe_hooks_and_snapshot():
    s = NumericSentinels("t")
    assert s.observe_floats("a", np.asarray([1.0, np.inf, np.nan])) == 2
    assert s.observe_floats("a", np.asarray([1.0, 2.0])) == 0
    assert s.observe_ints("b", np.asarray([2_000_000_000, 5])) == 1
    assert s.observe_counts("c", np.asarray([2_100_000_000])) == 1
    assert s.observe_counts("c", np.asarray([10, 20])) == 0
    assert s.observe_precision("d", np.asarray([3.4e7, 1.0])) == 1
    assert s.observe_precision("d", np.asarray([100.0])) == 0
    s.note_rebase("e", 12345)
    snap = s.snapshot()
    assert snap["trips"]["a:nonfinite"] == 2
    assert snap["trips"]["b:int_near_overflow"] == 1
    assert snap["trips"]["c:count_near_saturation"] == 1
    assert snap["trips"]["d:precision_exceeded"] == 1
    assert snap["trips_total"] == 5
    assert snap["ts_rebase_total"] == 1
    assert snap["ts_headroom_ms"] == 12345
    lines = s.prometheus_lines()
    assert any(ln.startswith("siddhi_numeric_sentinel_trips_total")
               for ln in lines)
    assert any(ln.startswith("siddhi_numeric_precision_exceeded_total")
               for ln in lines)
    assert any(ln.startswith("siddhi_numeric_ts_rebase_total")
               for ln in lines)
    s.reset()
    assert s.snapshot()["trips_total"] == 0


def test_observe_sentinel_plane_folds_device_flags():
    s = NumericSentinels("t")
    assert s.observe_sentinel_plane("g", np.asarray([2, 1, 3])) == 6
    snap = s.snapshot()
    assert snap["trips"]["g:int_near_overflow"] == 2
    assert snap["trips"]["g:count_near_saturation"] == 1
    assert snap["trips"]["g:nonfinite"] == 3
    assert s.observe_sentinel_plane("g", np.asarray([0, 0, 0])) == 0


# --------------------------------------------------- NS101 flight bus

def test_ns101_incident_emitted_and_rate_limited():
    s = NumericSentinels("nsapp")
    for _ in range(6):                     # > MAX_INCIDENTS_PER_SITE
        s.observe_floats("site.x", np.asarray([np.nan]))
    incs = [i for i in flight().incidents()
            if i["kind"] == "numeric_sentinel"]
    assert len(incs) == numguard.MAX_INCIDENTS_PER_SITE
    bundle = flight().bundle(incs[-1]["id"])
    det = bundle["detail"]
    assert det["code"] == "NS101"
    assert det["site"] == "site.x" and det["kind"] == "nonfinite"
    # trips keep counting past the incident cap
    assert s.snapshot()["trips"]["site.x:nonfinite"] == 6


def test_no_ns101_below_thresholds():
    s = NumericSentinels("quiet")
    s.observe_floats("a", np.asarray([1.0, 2.0]))
    s.observe_ints("a", np.asarray([100, -100]))
    s.observe_counts("a", np.asarray([1000]))
    s.observe_precision("a", np.asarray([100.0]))
    assert [i for i in flight().incidents()
            if i["kind"] == "numeric_sentinel"] == []
    assert s.snapshot()["trips_total"] == 0


# ------------------------------------- bit-identical outputs, on vs off

GAGG_APP = """
    @app:name('gbit') @app:playback
    define stream S (sym string, price float, volume long);
    @info(name='q') from S#window.length(5)
    select sym, sum(price) as t, sum(volume) as tv, count() as c
    group by sym insert into Out;
"""


def _run_gagg(armed, app=GAGG_APP, engine=None, feed=None):
    if armed:
        os.environ[NUMGUARD_ENV] = "1"
    else:
        os.environ.pop(NUMGUARD_ENV, None)
    try:
        prefix = f"@app:engine('{engine}') " if engine else ""
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(prefix + app)
        out = []
        rt.add_callback("Out", StreamCallback(
            lambda evs: out.extend(tuple(e.data) for e in evs)))
        rt.start()
        h = rt.get_input_handler("S")
        rows = feed or _feed()
        for row, ts in rows:
            h.send(list(row), timestamp=ts)
        device = any(q.backend == "device"
                     for q in rt.query_runtimes.values())
        rt.shutdown()
        return device, out
    finally:
        os.environ.pop(NUMGUARD_ENV, None)


def _feed(n=60, seed=4):
    rng = np.random.default_rng(seed)
    return [([f"s{rng.integers(0, 3)}",
              float(np.float32(rng.uniform(1, 100))),
              int(rng.integers(-1000, 1000))], 1_000_000 + i * 100)
            for i in range(n)]


def test_gagg_outputs_bit_identical_with_numguard_on():
    dev_off, out_off = _run_gagg(False)
    dev_on, out_on = _run_gagg(True)
    assert dev_off and dev_on, "grouped agg did not hit the device path"
    assert out_on == out_off        # bit-identical, not approx
    assert len(out_on) > 0
    # the armed run actually watched: registry holds the app's sentinels
    assert numeric_sentinels("gbit", create=False) is not None


def test_gagg_sentinel_plane_trips_on_overflow_feed():
    """A constructed near-overflow int-sum feed (|sum| past 90% of the
    2^31 exact-int ceiling) must trip the DEVICE sentinel plane while
    outputs stay bit-identical with the guard off."""
    feed = [(["A", 1.0, 1_000_000_000], 1_000_000 + i * 100)
            for i in range(4)]             # running int sum -> 4e9 lane
    app = """
        @app:name('gov') @app:playback
        define stream S (sym string, price float, volume long);
        @info(name='q') from S
        select sym, sum(volume) as tv group by sym insert into Out;
    """
    dev_off, out_off = _run_gagg(False, app=app, feed=feed)
    dev_on, out_on = _run_gagg(True, app=app, feed=feed)
    assert dev_on and dev_off
    assert out_on == out_off
    guard = numeric_sentinels("gov", create=False)
    assert guard is not None
    trips = guard.snapshot()["trips"]
    assert trips.get("gagg.step:int_near_overflow", 0) > 0, trips
    incs = [i for i in flight().incidents()
            if i["kind"] == "numeric_sentinel"]
    assert incs, "device sentinel trip emitted no NS101 incident"


# ------------------------- NS003 cross-validation on an overflow feed

NAIVE_AGG = """
    @app:name('iaggns') @app:rate(1000)
    @attr:range('price', 0, 40000000)
    define stream S (symbol string, price double, ts long);
    {anno}define aggregation Agg
    from S
    select symbol, sum(price) as total
    group by symbol
    aggregate by ts every sec ... min;
"""

AGG_Q = """
    from Agg within 1496200000000, 1496400000000 per 'seconds'
    select AGG_TIMESTAMP, symbol, total
"""


def _run_iagg(app, sends, armed):
    if armed:
        os.environ[NUMGUARD_ENV] = "1"
    else:
        os.environ.pop(NUMGUARD_ENV, None)
    try:
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(app)
        rt.start()
        h = rt.get_input_handler("S")
        for row in sends:
            h.send(list(row))              # one chunk per event: the
        rows = rt.query(AGG_Q)             # running slab takes each +1
        agg = rt.aggregations["Agg"]
        rt.shutdown()
        return sorted([e.data for e in rows]), agg
    finally:
        os.environ.pop(NUMGUARD_ENV, None)


def _overflow_sends(n_ones=200):
    base_ts = 1496289950_000
    sends = [["A", 33554432.0, base_ts]]   # 2^25: past the f32 budget
    sends += [["A", 1.0, base_ts + 1 + i] for i in range(n_ones)]
    return sends


def test_static_ns003_cross_validated_by_armed_sentinel_run():
    from siddhi_tpu.analysis.ranges import analyze_numeric
    from siddhi_tpu.plan.iagg_compiler import DeviceAggregationRuntime
    app = NAIVE_AGG.format(anno="")
    # static half: the verifier predicts the precision escape
    rep = analyze_numeric(app)
    assert any(d.code == "NS003" for d in rep.findings)
    # runtime half: the armed sentinel run witnesses it live
    rows, agg = _run_iagg(app, _overflow_sends(), armed=True)
    assert isinstance(agg, DeviceAggregationRuntime)
    assert agg._compensated is False
    guard = numeric_sentinels("iaggns", create=False)
    assert guard is not None
    trips = guard.snapshot()["trips"]
    assert any(k.startswith("iagg.") and k.endswith("precision_exceeded")
               for k in trips), trips
    # and the naive f32 slab really did lose the +1s (the defect NS003
    # warns about): every increment under the 2^25 spacing vanished
    total = next(r[2] for r in rows if r[1] == "A")
    assert total == 33554432.0


def test_compensated_remediation_matches_host_oracle_exactly():
    """@numeric(sum='compensated'): the TwoSum error lane carries the
    sub-ulp increments, so the device slab equals the host cascade's
    float64 total EXACTLY past the f32 cliff — and the armed run stays
    precision-quiet (negative NS101/precision witness)."""
    from siddhi_tpu.analysis.ranges import analyze_numeric
    from siddhi_tpu.plan.iagg_compiler import DeviceAggregationRuntime
    sends = _overflow_sends()
    comp_app = NAIVE_AGG.format(anno="@numeric(sum='compensated')\n    ")
    assert not any(d.code == "NS003"
                   for d in analyze_numeric(comp_app).findings)
    host_rows, _ = _run_iagg(
        "@app:engine('host') " + NAIVE_AGG.format(anno=""), sends,
        armed=False)
    comp_rows, comp_agg = _run_iagg(comp_app, sends, armed=True)
    assert isinstance(comp_agg, DeviceAggregationRuntime)
    assert comp_agg._compensated is True
    assert comp_rows == host_rows          # exact, past the f32 cliff
    total = next(r[2] for r in comp_rows if r[1] == "A")
    assert total == 33554432.0 + 200.0
    guard = numeric_sentinels("iaggns", create=False)
    trips = guard.snapshot()["trips"] if guard else {}
    assert not any(k.endswith("precision_exceeded") for k in trips), trips


def test_compensated_survives_persist_restore():
    """The compensated residual is re-banked on restore, so a snapshot
    round-trip keeps the exact total (persistent schema unchanged: the
    host-format buckets dict is what persists)."""
    from siddhi_tpu import InMemoryPersistenceStore
    sends = _overflow_sends()
    comp_app = NAIVE_AGG.format(anno="@numeric(sum='compensated')\n    ")
    m = SiddhiManager()
    m.set_persistence_store(InMemoryPersistenceStore())
    rt = m.create_siddhi_app_runtime(comp_app)
    rt.start()
    h = rt.get_input_handler("S")
    for row in sends[:100]:
        h.send(list(row))
    rev = rt.persist()
    rt.shutdown()
    rt2 = m.create_siddhi_app_runtime(comp_app)
    rt2.start()
    rt2.restore_revision(rev)
    h2 = rt2.get_input_handler("S")
    for row in sends[100:]:
        h2.send(list(row))
    rows = sorted([e.data for e in rt2.query(AGG_Q)])
    rt2.shutdown()
    total = next(r[2] for r in rows if r[1] == "A")
    assert total == 33554432.0 + 200.0


# ------------------------- NS005 cross-validation through the slab sync

def test_static_ns005_cross_validated_by_count_sentinel():
    """Static NS005 predicts count-lane saturation; the armed witness
    fires when a slab count lane actually nears 2^31 (reconstructed
    through the engine's own restore path — feeding 2e9 events is not a
    test, rewriting the persisted bucket payload is)."""
    from siddhi_tpu.analysis.ranges import analyze_numeric
    app = """
        @app:name('cntns') @app:rate(1000000)
        define stream S (symbol string, price double, ts long);
        define aggregation Agg
        from S
        select symbol, sum(price) as total, count() as n
        group by symbol
        aggregate by ts every sec ... hour;
    """
    rep = analyze_numeric(app)
    assert any(d.code == "NS005" for d in rep.findings)
    os.environ[NUMGUARD_ENV] = "1"
    try:
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(app)
        rt.start()
        rt.get_input_handler("S").send(["A", 5.0, 1496289950_000])
        agg = rt.aggregations["Agg"]
        agg._sync()
        guard = numeric_sentinels("cntns", create=False)
        assert guard is not None
        assert guard.snapshot()["trips"] == {}     # negative: tiny count
        # saturate the persisted count lane, rebuild, re-witness
        for dur in agg.durations:
            for key, row in agg.buckets[dur].items():
                for b, fn in enumerate(agg.base_fns):
                    if fn == "count":
                        row[b] = 2_000_000_000
        agg._rebuild_slabs()
        agg._dirty = True
        agg._sync()
        trips = guard.snapshot()["trips"]
        assert any(k.startswith("iagg.") and
                   k.endswith("count_near_saturation")
                   for k in trips), trips
        rt.shutdown()
    finally:
        os.environ.pop(NUMGUARD_ENV, None)


# ----------------------------------- ts32 wraparound (stream-years feed)

WRAP_APP = """
    @app:name('wrapns') @app:playback
    define stream S (sym string, price float, volume long);
    @info(name='q') from S#window.time(60 sec)
    select sym, sum(price) as t, count() as c
    group by sym insert into Out;
"""


def _norm(rows):
    return [tuple(float(np.float32(v)) if isinstance(v, float) else v
                  for v in r) for r in rows]


def test_wraparound_device_matches_host_oracle_numguard_armed():
    """Satellite 2: a seeded stream-years feed crosses the int32-ms
    horizon (>= 1 device rebase); device == host oracle across the
    wrap, the guard counts the rebases, and outputs stay bit-identical
    armed vs disarmed."""
    feed = wraparound_feed(300, seed=11)
    _, host = _run_gagg(False, app=WRAP_APP, engine="host", feed=feed)
    dev_hit, dev_off = _run_gagg(False, app=WRAP_APP, feed=feed)
    reset_numguard()
    dev_hit_on, dev_on = _run_gagg(True, app=WRAP_APP, feed=feed)
    assert dev_hit and dev_hit_on, "wrap app did not hit the device path"
    assert dev_on == dev_off               # guard is observation-only
    assert _norm(host) == _norm(dev_on)
    assert len(host) >= 300
    guard = numeric_sentinels("wrapns", create=False)
    assert guard is not None
    snap = guard.snapshot()
    assert snap["ts_rebase_total"] > 0, \
        f"40-day feed never rebased the ts32 ring: {snap}"
    assert snap["ts_headroom_ms"] is not None and \
        snap["ts_headroom_ms"] > 0


# ------------------------------------------------------------ surfaces

def test_prometheus_exposition_carries_numeric_series():
    from siddhi_tpu.core.statistics import prometheus_text
    s = numeric_sentinels("promapp")
    s.observe_floats("x", np.asarray([np.nan]))
    s.note_rebase("x", 777)
    text = prometheus_text([])
    assert "# TYPE siddhi_numeric_sentinel_trips_total counter" in text
    assert 'siddhi_numeric_nonfinite_total{app="promapp",site="x"} 1' \
        in text
    assert 'siddhi_numeric_ts_rebase_total{app="promapp"} 1' in text
    assert 'siddhi_numeric_ts_headroom_ms{app="promapp"} 777' in text


def test_stats_endpoint_carries_numguard_section(monkeypatch):
    import urllib.request
    from siddhi_tpu.service.rest import SiddhiService
    monkeypatch.setenv(NUMGUARD_ENV, "1")
    svc = SiddhiService(port=0).start()
    try:
        base = f"http://127.0.0.1:{svc.port}"
        app = ("@app:name('ngstat') "
               "@app:statistics(reporter='console', interval='300') "
               "define stream S (sym string, price float, volume long); "
               "@info(name='q') from S#window.length(4) "
               "select sym, sum(price) as t group by sym "
               "insert into Out;")
        req = urllib.request.Request(
            f"{base}/siddhi/artifact/deploy", data=app.encode(),
            method="POST")
        with urllib.request.urlopen(req, timeout=30):
            pass
        data = json.dumps([{"data": ["A", 2.5, 1]}]).encode()
        req = urllib.request.Request(
            f"{base}/siddhi/apps/ngstat/streams/S", data=data,
            method="POST")
        with urllib.request.urlopen(req, timeout=30):
            pass
        with urllib.request.urlopen(f"{base}/stats", timeout=30) as r:
            doc = json.loads(r.read().decode())
        ng = doc["apps"]["ngstat"].get("numguard")
        assert ng is not None, f"no numguard section: {doc['apps']}"
        assert ng["armed"] is True
        assert ng["trips_total"] == 0      # clean feed, quiet guard
    finally:
        svc.stop()
