"""Compile-time semantic analyzer (siddhi_tpu/analysis): one positive +
one clean fixture per diagnostic code, strict-mode promotion, source
spans, CLI, /stats embedding, and an end-to-end validation of the
SP001 retrace-hazard prediction against the PR 1 KernelProfiler
compile counters."""
import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from siddhi_tpu import SiddhiManager, StreamCallback  # noqa: E402
from siddhi_tpu.analysis import CATALOG, Severity, analyze  # noqa: E402
from siddhi_tpu.utils.errors import SiddhiAppValidationException  # noqa: E402

S = "define stream S (sym string, price float, vol long);\n"


def codes(app, **kw):
    return analyze(app, **kw).codes()


def diags(app, code, **kw):
    return [d for d in analyze(app, **kw).diagnostics if d.code == code]


# ------------------------------------------------------------- name errors

def test_sa000_parse_error_carries_position():
    d, = diags("define stream S (a int;", "SA000")
    assert d.severity == Severity.ERROR
    assert d.line == 1


def test_sa001_unknown_source():
    assert "SA001" in codes(S + "from Missing select * insert into Out;")
    assert "SA001" not in codes(S + "from S select * insert into Out;")


def test_sa002_unknown_attribute_with_line():
    app = S + "from S[prce > 10]\nselect sym insert into Out;"
    d, = diags(app, "SA002")
    assert d.line == 2 and d.col == 8
    assert "prce" in d.message
    assert not diags(S + "from S[price > 10] select sym insert into Out;",
                     "SA002")


def test_sa003_ambiguous_attribute():
    app = (S + "define stream R (sym string, price float);\n"
           "from S#window.length(2) join R#window.length(2) "
           "on S.sym == R.sym select price insert into Out;")
    assert "SA003" in codes(app)
    ok = (S + "define stream R (sym string, price float);\n"
          "from S#window.length(2) join R#window.length(2) "
          "on S.sym == R.sym select S.price insert into Out;")
    assert "SA003" not in codes(ok)


def test_sa004_type_mismatch():
    assert "SA004" in codes(
        S + "from S select sym * 2 as x insert into Out;")
    assert "SA004" in codes(
        S + "from S[sym > 5] select sym insert into Out;")
    assert "SA004" in codes(
        S + "from S[price and vol > 1] select sym insert into Out;")
    # string + is concatenation, not a mismatch
    assert "SA004" not in codes(
        S + "from S select sym + '!' as x insert into Out;")


def test_sa005_non_boolean_condition():
    assert "SA005" in codes(
        S + "from S[price + 1] select sym insert into Out;")
    assert "SA005" not in codes(
        S + "from S[price > 1] select sym insert into Out;")


def test_sa006_lossy_promotion():
    d, = diags(S + "from S[vol > price] select sym insert into Out;",
               "SA006")
    assert "2^24" in d.message
    # pure integer comparison is exact
    assert not diags(S + "from S[vol > 100] select sym insert into Out;",
                     "SA006")


def test_sa007_unknown_function():
    assert "SA007" in codes(
        S + "from S select frob:nicate(price) as x insert into Out;")
    assert "SA007" not in codes(
        S + "from S select math:sqrt(price) as x insert into Out;")
    # script functions are known
    app = ("define function twice[python] return double { data[0] * 2 };\n"
           + S + "from S select twice(price) as x insert into Out;")
    assert "SA007" not in codes(app)


def test_sa008_insert_schema_mismatch():
    assert "SA008" in codes(
        S + "define stream Out (a int);\n"
        "from S select sym, price insert into Out;")       # arity
    assert "SA008" in codes(
        S + "define stream Out (a int);\n"
        "from S select sym as a insert into Out;")         # type
    assert "SA008" not in codes(
        S + "define stream Out (a float);\n"
        "from S select price as a insert into Out;")


# --------------------------------------------------------- unbounded state

def test_sa020_within_less_every_pattern():
    bad = (S + "from every e1=S[price > 1] -> e2=S[price > e1.price]\n"
           "select e1.price as p insert into Out;")
    assert "SA020" in codes(bad)
    good = (S + "from every e1=S[price > 1] -> e2=S[price > e1.price] "
            "within 5 sec select e1.price as p insert into Out;")
    assert "SA020" not in codes(good)


def test_sa021_pkless_table_append():
    assert "SA021" in codes(
        S + "define table T (sym string);\n"
        "from S select sym insert into T;")
    assert "SA021" not in codes(
        S + "@PrimaryKey('sym') define table T (sym string);\n"
        "from S select sym insert into T;")


def test_sa022_windowless_grouped_aggregation():
    assert "SA022" in codes(
        S + "from S select sym, sum(price) as t group by sym "
        "insert into Out;")
    assert "SA022" not in codes(
        S + "from S#window.length(8) select sym, sum(price) as t "
        "group by sym insert into Out;")


# -------------------------------------------------------- partition safety

def test_sa030_partition_shared_table_write():
    app = (S + "define table T (sym string);\n"
           "partition with (sym of S) begin\n"
           "from S select sym insert into T;\nend;")
    assert "SA030" in codes(app)
    outside = (S + "define table T (sym string);\n"
               "from S select sym insert into T;")
    assert "SA030" not in codes(outside)


def test_sa031_partition_shared_window_write():
    app = (S + "define window W (sym string) length(5);\n"
           "partition with (sym of S) begin\n"
           "from S select sym insert into W;\nend;")
    assert "SA031" in codes(app)


# --------------------------------------------------------------- dead code

def test_sa040_unused_stream():
    assert "SA040" in codes(
        S + "define stream Orphan (x int);\n"
        "from S select sym insert into Out;")
    # @source-annotated streams are externally fed, not dead
    assert "SA040" not in codes(
        S + "@source(type='inMemory', topic='t') "
        "define stream Orphan (x int);\n"
        "from S select sym insert into Out;")


def test_sa041_unused_attribute():
    d, = diags(S + "from S select sym, price insert into Out;", "SA041")
    assert "vol" in d.message
    assert not diags(S + "from S select * insert into Out;", "SA041")


# ------------------------------------------------------------ perf hazards

def test_sp001_retrace_only_on_device_modes():
    bad = (S + "from every e1=S[price > 1] -> e2=S[price > e1.price]\n"
           "select e1.price as p insert into Out;")
    assert "SP001" in codes(bad)
    assert "SP001" not in codes(bad, engine="host")


def test_sp002_partition_lane_growth_info():
    app = (S + "partition with (sym of S) begin\n"
           "from S select sym, price insert into Out;\nend;")
    d, = diags(app, "SP002")
    assert d.severity == Severity.INFO
    assert not diags(app, "SP002", engine="host")


def test_sp003_dynamic_window_param():
    assert "SP003" in codes(
        S + "from S#window.length(vol) select sym insert into Out;")
    assert "SP003" not in codes(
        S + "from S#window.length(5) select sym insert into Out;")
    # externalTime's FIRST param is legitimately an attribute
    assert "SP003" not in codes(
        S + "from S#window.externalTime(vol, 1 sec) "
        "select sym insert into Out;")


def test_sp010_host_fallback_prediction():
    # group-by on a pattern query is host-only
    app = (S + "from every e1=S[price > 1] -> e2=S[price > 2] "
           "within 5 sec select e1.sym as k, count() as c group by k "
           "insert into Out;")
    assert "SP010" in codes(app)
    clean = (S + "from every e1=S[price > 1] -> e2=S[price > 2] "
             "within 5 sec select e1.price as p insert into Out;")
    assert "SP010" not in codes(clean)


def test_sp011_int_precision_above_2p24():
    app = (S + "from every e1=S[vol > 20000000] -> e2=S[vol > e1.vol] "
           "within 5 sec select e1.vol as v insert into Out;")
    assert "SP011" in codes(app)
    small = (S + "from every e1=S[vol > 200] -> e2=S[vol > e1.vol] "
             "within 5 sec select e1.vol as v insert into Out;")
    assert "SP011" not in codes(small)


# ------------------------------------------------- acceptance fixture

ACCEPTANCE = """define stream S (sym string, price float, vol long);
define table T (sym string, price float);
@info(name='q1')
from S[prce > 10]
select sym, price
insert into Alerts;
@info(name='q2')
from every e1=S[price > 100] -> e2=S[price > e1.price]
select e1.price as p1, e2.price as p2
insert into Out;
partition with (sym of S)
begin
  @info(name='q3')
  from S select sym, price insert into T;
end;
"""


def test_acceptance_fixture_three_codes_with_lines():
    r = analyze(ACCEPTANCE)
    by_code = {d.code: d for d in r.diagnostics}
    # >= 3 distinct codes across the three seeded problems
    assert {"SA002", "SA020", "SA030"} <= set(by_code)
    assert len(r.codes()) >= 3
    assert by_code["SA002"].line == 4          # misspelled attribute
    assert by_code["SA020"].line == 8          # within-less every
    assert by_code["SA030"].line == 14         # partition table write
    assert not r.ok


def test_acceptance_fixture_strict_fails_fast():
    m = SiddhiManager()
    with pytest.raises(SiddhiAppValidationException):
        m.create_siddhi_app_runtime(ACCEPTANCE, strict=True)
    assert not m.runtimes        # nothing was built or registered


def test_strict_promotes_warning_only_app():
    app = (S + "from every e1=S[price > 1] -> e2=S[price > e1.price]\n"
           "select e1.price as p insert into Out;")
    m = SiddhiManager()
    with pytest.raises(SiddhiAppValidationException):
        m.create_siddhi_app_runtime(app, strict=True)
    # non-strict builds fine and carries the result
    rt = m.create_siddhi_app_runtime(app)
    try:
        assert rt.analysis is not None
        assert "SA020" in rt.analysis.codes()
    finally:
        rt.shutdown()


def test_strict_accepts_clean_app():
    app = (S + "from S[price > 10] select sym, price, vol "
           "insert into Out;")
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app, strict=True)
    try:
        assert rt.analysis.ok and not rt.analysis.warnings
    finally:
        rt.shutdown()


def test_fluent_api_app_analyzes_without_positions():
    from siddhi_tpu.query_api import (Expression, Query, Selector,
                                      SiddhiApp, SingleInputStream,
                                      StreamDefinition)
    app = SiddhiApp()
    app.define_stream(
        StreamDefinition("S").attribute("a", "int"))
    q = (Query.query()
         .from_(SingleInputStream("S"))
         .select(Selector().select("b", Expression.variable("missing")))
         .insert_into("Out"))
    app.add_query(q)
    r = analyze(app)
    assert "SA002" in r.codes()
    d, = [d for d in r.diagnostics if d.code == "SA002"]
    assert d.line == -1          # no text, no spans — must not crash


# ------------------------------------------------------------ integration

def test_stats_surface_embeds_analysis():
    from siddhi_tpu.service.rest import SiddhiService
    svc = SiddhiService(port=0)
    app = ("@app:name('ana') " + S +
           "from every e1=S[price > 1] -> e2=S[price > e1.price]\n"
           "select e1.price as p insert into Out;")
    rt = svc.manager.create_siddhi_app_runtime(app)
    try:
        doc = svc._stats_json()
        ana = doc["apps"]["ana"]["analysis"]
        assert any(d["code"] == "SA020" for d in ana)
        assert all("severity" in d and "line" in d for d in ana)
    finally:
        rt.shutdown()


def test_cli_pretty_json_and_exit_codes(tmp_path, capsys):
    from siddhi_tpu.analyze import main
    bad = tmp_path / "bad.siddhi"
    bad.write_text(ACCEPTANCE)
    assert main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "SA002" in out and "bad.siddhi:4" in out

    assert main([str(bad), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert not doc["ok"]
    assert any(d["code"] == "SA020" for d in doc["diagnostics"])

    warn_only = tmp_path / "warn.siddhi"
    warn_only.write_text(
        S + "from every e1=S[price > 1] -> e2=S[price > e1.price]\n"
        "select e1.price as p insert into Out;")
    assert main([str(warn_only)]) == 0
    capsys.readouterr()
    assert main([str(warn_only), "--strict"]) == 1
    capsys.readouterr()

    clean = tmp_path / "ok.siddhi"
    clean.write_text(S + "from S[price > 1] select sym, price, vol "
                     "insert into Out;")
    assert main([str(clean), "--strict"]) == 0


def test_catalog_docs_cover_every_code():
    text = open(os.path.join(os.path.dirname(__file__), "..", "docs",
                             "analysis.md")).read()
    for code in CATALOG:
        assert code in text, f"docs/analysis.md missing {code}"


def test_catalog_docs_are_generated_verbatim():
    """docs/analysis.md embeds catalog_markdown() output verbatim, so
    the document can never drift from diagnostics.CATALOG — adding a
    code without regenerating (`python -m siddhi_tpu.analyze
    --catalog-md`) fails here."""
    from siddhi_tpu.analysis import catalog_markdown
    text = open(os.path.join(os.path.dirname(__file__), "..", "docs",
                             "analysis.md")).read()
    assert catalog_markdown() in text, (
        "docs/analysis.md catalog section is stale — regenerate with "
        "python -m siddhi_tpu.analyze --catalog-md")


# ------------------------------------------- SP001 vs KernelProfiler (e2e)

def test_sp001_prediction_matches_kernel_profiler_retraces():
    """The retrace-hazard pass predicts that a within-less `every`
    pattern grows its slot ring and re-JITs.  Validate end-to-end: feed
    enough arming events to overflow the default 8-slot ring and assert
    the KernelProfiler compile counters actually rose — the analyzer's
    SP001 is a *prediction* of exactly this counter movement."""
    from siddhi_tpu import enable_profiling, profiler

    app = (S + "@info(name='q') "
           "from every e1=S[vol == 0] -> e2=S[vol == 1 and "
           "price > e1.price] select e1.price as p1 insert into Out;")
    assert "SP001" in codes(app)

    was_enabled = profiler().enabled
    enable_profiling()
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    try:
        dev = getattr(rt.query_runtimes["q"], "device_runtime", None)
        if dev is None or dev.backend != "device":
            pytest.skip("device pattern path unavailable on this backend")
        rt.add_callback("Out", StreamCallback(lambda evs: None))
        rt.start()
        h = rt.get_input_handler("S")

        def arm_batch(t0):
            n = 8
            h.send_batch({"sym": np.asarray(["k"] * n, object),
                          "price": np.arange(n, dtype=np.float32),
                          "vol": np.zeros(n, np.int64)},
                         timestamps=t0 + np.arange(n, dtype=np.int64))

        arm_batch(1_000)             # warmup: compiles, fills 8 slots
        rt.flush()
        before = sum(k["compile_count"]
                     for k in profiler().snapshot().values())
        arm_batch(2_000)             # same shape → only growth recompiles
        rt.flush()
        after = sum(k["compile_count"]
                    for k in profiler().snapshot().values())
        assert after > before, (
            "slot-ring growth should have re-JIT'd the NFA step "
            f"(compile_count {before} -> {after})")
    finally:
        rt.shutdown()
        if not was_enabled:
            from siddhi_tpu import disable_profiling
            disable_profiling()


def test_bench_retrace_counter_helper():
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    prof_a = {"nfa.step": {"compile_count": 4},
              "egress": {"compile_count": 1}}
    prof_b = {"filter.program": {"compile_count": 2}}
    assert bench.retrace_count(prof_a, prof_b, None) == 4
    assert bench.retrace_count({}) == 0
