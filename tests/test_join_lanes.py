"""Round-5 join-probe lanes (plan/join_lanes.py) + string-function
filter lanes (plan/str_lanes.py): randomized device-vs-host parity.

- STRING order/equality joins ride per-probe union rank lanes;
- DOUBLE compares ride monotone 64-bit keys split into exact i32 pairs;
- compare-class string functions (str:length/contains/startsWith/
  endsWith/equalsIgnoreCase) lower onto per-chunk numeric lanes in the
  device filter path.
"""
import numpy as np
import pytest

from siddhi_tpu import QueryCallback, SiddhiManager, StreamCallback


def run_join(app, sends, engine=None):
    m = SiddhiManager()
    pre = "@app:playback " + (f"@app:engine('{engine}') " if engine else "")
    rt = m.create_siddhi_app_runtime(pre + app)
    out = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: out.extend(tuple(e.data) for e in evs)))
    rt.start()
    for sid, row, ts in sends:
        rt.get_input_handler(sid).send(row, timestamp=ts)
    qr = rt.query_runtimes["q"]
    backend = "device" if qr.backend == "device" else "host"
    rt.shutdown()
    return backend, out


def join_parity(app, sends):
    bd, dev = run_join(app, sends)
    bh, host = run_join(app, sends, engine="host")
    assert bd == "device" and bh == "host"
    assert dev == host, f"dev={dev[:5]} host={host[:5]}"
    return dev


@pytest.mark.parametrize("seed", [3, 11])
def test_join_double_order_fuzz(seed):
    app = """
    define stream L (id int, v double);
    define stream R (id int, v double);
    @info(name='q')
    from L#window.length(6) join R#window.length(6)
        on L.v > R.v and R.v > 2.0000001
    select L.id as lid, R.id as rid insert into Out;"""
    rng = np.random.default_rng(seed)
    sends, t = [], 1_000_000
    for i in range(40):
        side = "L" if rng.integers(0, 2) else "R"
        # values with sub-f32 structure: many collide after f32 rounding
        v = float(rng.integers(0, 8)) + float(rng.uniform(0, 1e-6))
        sends.append((side, [i, v], t))
        t += 50
    assert join_parity(app, sends)


@pytest.mark.parametrize("seed", [5, 13])
def test_join_string_order_fuzz(seed):
    app = """
    define stream L (s string, id int);
    define stream R (s string, id int);
    @info(name='q')
    from L#window.length(5) join R#window.length(5)
        on L.s > R.s and L.s != 'qq'
    select L.id as lid, R.id as rid insert into Out;"""
    rng = np.random.default_rng(seed)
    words = ["a", "ab", "b", "ba", "qq", "z", "", "aa"]
    sends, t = [], 1_000_000
    for i in range(40):
        side = "L" if rng.integers(0, 2) else "R"
        sends.append((side, [words[int(rng.integers(0, len(words)))], i], t))
        t += 50
    assert join_parity(app, sends)


def test_join_string_const_thresholds():
    app = """
    define stream L (s string, id int);
    define stream R (s string, id int);
    @info(name='q')
    from L#window.length(5) join R#window.length(5)
        on L.s == R.s and R.s >= 'b'
    select L.id as lid, R.id as rid insert into Out;"""
    sends = [("L", ["b", 1], 1_000_000), ("R", ["b", 2], 1_000_100),
             ("L", ["a", 3], 1_000_200), ("R", ["a", 4], 1_000_300),
             ("R", ["c", 5], 1_000_400), ("L", ["c", 6], 1_000_500)]
    out = join_parity(app, sends)
    assert (1, 2) in out and (6, 5) in out and (3, 4) not in out


def test_join_double_nan_routes_to_host_mask():
    """NaN compares are three-valued (always false) — a NaN column guards
    that probe to the host mask; results identical either way."""
    app = """
    define stream L (id int, v double);
    define stream R (id int, v double);
    @info(name='q')
    from L#window.length(4) join R#window.length(4)
        on L.v > R.v
    select L.id as lid, R.id as rid insert into Out;"""
    sends = [("L", [1, float("nan")], 1_000_000),
             ("R", [2, 1.0], 1_000_100),
             ("L", [3, 5.0], 1_000_200)]
    out = join_parity(app, sends)
    assert (3, 2) in out and (1, 2) not in out


# ------------------------------------------------------- string fn lanes

def run_filter(app, rows, engine=None):
    m = SiddhiManager()
    pre = "@app:playback " + (f"@app:engine('{engine}') " if engine else "")
    rt = m.create_siddhi_app_runtime(pre + app)
    got = []
    rt.add_callback("q", QueryCallback(lambda ts, cur, exp: got.extend(
        tuple(e.data) for e in (cur or []))))
    rt.start()
    h = rt.get_input_handler("S")
    t = 1_000_000
    for row in rows:
        h.send(row, timestamp=t)
        t += 100
    backend = rt.query_runtimes["q"].backend
    rt.shutdown()
    return backend, got


ROWS = [["alpha", 1.0], ["Beta", 2.0], ["gamma-x", 3.0], [None, 4.0],
        ["", 5.0], ["ALPHA", 6.0]]


@pytest.mark.parametrize("cond,expect_device", [
    ("str:length(s) > 4", True),
    ("str:length(s) == 5", True),
    ("str:length(s) != 5", True),          # null → false (guarded lane)
    ("str:contains(s, 'a')", True),
    ("str:startsWith(s, 'a')", True),
    ("str:endsWith(s, 'x')", True),
    ("str:equalsIgnoreCase(s, 'alpha')", True),
    ("str:length(s) + v > 6.0", True),
    # negated: null → fn false → `not` true, on BOTH engines (two-valued
    # contract; the string extension is outside the reference core)
    ("not str:contains(s, 'a')", True),
])
def test_string_fn_filter_parity(cond, expect_device):
    app = ("define stream S (s string, v float);\n"
           f"@info(name='q') from S[{cond}] "
           "select s, v insert into Out;")
    bd, dev = run_filter(app, ROWS)
    bh, host = run_filter(app, ROWS, engine="host")
    assert bh == "host"
    assert bd == ("device" if expect_device else "host")
    assert dev == host, f"{cond}: dev={dev} host={host}"
