"""Test configuration: force a virtual 8-device CPU mesh so sharding tests run
without TPU hardware (the driver separately dry-runs multi-chip compilation).

Must run before any jax import: the axon TPU plugin registers itself whenever
PALLAS_AXON_POOL_IPS is set, regardless of JAX_PLATFORMS, so both are forced.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# Build the native .so if the toolchain is present and it's missing/stale, so
# test runs exercise the real C++ path rather than the numpy fallback.
import subprocess

_here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_so = os.path.join(_here, "siddhi_tpu", "_native.so")
_src = os.path.join(_here, "native", "eventpack.cpp")
if os.path.exists(_src) and (
        not os.path.exists(_so)
        or os.path.getmtime(_so) < os.path.getmtime(_src)):
    subprocess.run(["make", "-C", os.path.join(_here, "native")],
                   capture_output=True)
