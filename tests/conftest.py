"""Test configuration: force a virtual 8-device CPU mesh so sharding tests run
without TPU hardware (the driver separately dry-runs multi-chip compilation).

Must run before any jax import: the axon TPU plugin registers itself whenever
PALLAS_AXON_POOL_IPS is set, regardless of JAX_PLATFORMS, so both are forced.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
