"""Test configuration: force a virtual 8-device CPU mesh so sharding tests run
without TPU hardware (the driver separately dry-runs multi-chip compilation).

The axon TPU plugin registers itself from a sitecustomize hook AT INTERPRETER
START (before conftest runs), importing jax with JAX_PLATFORMS=axon already
snapshotted — so scrubbing os.environ here is NOT enough: the platform choice
must be overridden through jax.config on the already-imported module.  The
backend itself is still uninitialised at conftest time (no jax.devices() call
has happened), so the override + XLA_FLAGS below take effect.  A hard assert
guards the whole suite: round-2's conftest silently lost this fight and every
"virtual 8-device" test actually ran on the single real TPU chip."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8 and jax.devices()[0].platform == "cpu", \
    (f"test suite needs the virtual 8-device CPU mesh, got "
     f"{len(jax.devices())}x {jax.devices()[0].platform} — the axon plugin "
     f"won the platform fight again (see conftest docstring)")

# Build the native .so if the toolchain is present and it's missing/stale, so
# test runs exercise the real C++ path rather than the numpy fallback.
import subprocess

_here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_so = os.path.join(_here, "siddhi_tpu", "_native.so")
_src = os.path.join(_here, "native", "eventpack.cpp")
if os.path.exists(_src) and (
        not os.path.exists(_so)
        or os.path.getmtime(_so) < os.path.getmtime(_src)):
    subprocess.run(["make", "-C", os.path.join(_here, "native")],
                   capture_output=True)


# --------------------------------------------------------------------------
# Device-hit telemetry (VERDICT r2 next #6): ref_harness.run_query records
# whether each conformance test actually exercised the device engine.  At
# session end the per-suite counts are written to docs/device_hits.json;
# when the session collected every suite listed in tests/device_hit_floor
# .json (i.e. a full run), a drop below the floor FAILS the run, and the
# generated table in docs/conformance_map.md is refreshed.

_COLLECTED_FILES = set()


def pytest_configure(config):
    # tier-1 runs with -m 'not slow'; long chaos soaks opt out with it
    config.addinivalue_line(
        "markers", "slow: long-running chaos soak (excluded from tier-1)")


def pytest_collection_modifyitems(session, config, items):
    for it in items:
        _COLLECTED_FILES.add(it.nodeid.split("::")[0].split("/")[-1])


def _device_hit_counts():
    import sys
    rh = None
    for name, mod in list(sys.modules.items()):
        if name.endswith("ref_harness") and getattr(mod, "TELEMETRY", None):
            rh = mod
            break
    if rh is None:
        return None
    per = {}
    for nodeid, dev in rh.TELEMETRY:
        suite = nodeid.split("::")[0].split("/")[-1]
        test = nodeid.split(" ")[0]
        tot, hits = per.setdefault(suite, (set(), set()))
        tot.add(test)
        if dev:
            hits.add(test)
    return {s: {"tests": len(t), "device_hits": len(h)}
            for s, (t, h) in sorted(per.items())}


def pytest_sessionfinish(session, exitstatus):
    import json
    if exitstatus != 0:
        # aborted/failing runs have partial telemetry — don't clobber the
        # generated docs or mask the real failure with a floor error
        return
    counts = _device_hit_counts()
    if not counts:
        return
    floor_path = os.path.join(_here, "tests", "device_hit_floor.json")
    floor = {}
    if os.path.exists(floor_path):
        with open(floor_path) as f:
            floor = json.load(f)
    # partial run (e.g. -k filters): don't clobber full-run telemetry.
    # Guard on EXECUTED suites (a -k run still collects every file before
    # deselection, review r5) and require each to have executed at least
    # its floor's worth of tests
    if floor and not (set(floor) <= _COLLECTED_FILES and
                      all(counts.get(s, {}).get("tests", 0) >= need
                          for s, need in floor.items())):
        return
    out = os.path.join(_here, "docs", "device_hits.json")
    with open(out, "w") as f:
        json.dump(counts, f, indent=1, sort_keys=True)
    if not floor:
        return
    _refresh_conformance_map(counts)
    bad = {s: (counts.get(s, {}).get("device_hits", 0), need)
           for s, need in floor.items()
           if counts.get(s, {}).get("device_hits", 0) < need}
    if bad:
        import pytest
        pytest.exit(
            "device-hit regression: " + ", ".join(
                f"{s} hit {got}<{need}" for s, (got, need) in bad.items()),
            returncode=1)


def _refresh_conformance_map(counts):
    path = os.path.join(_here, "docs", "conformance_map.md")
    if not os.path.exists(path):
        return
    begin, end = "<!-- device-hit:begin -->", "<!-- device-hit:end -->"
    rows = "\n".join(
        f"| `{s}` | {c['tests']} | {c['device_hits']} |"
        for s, c in counts.items())
    total_t = sum(c["tests"] for c in counts.values())
    total_h = sum(c["device_hits"] for c in counts.values())
    block = (f"{begin}\n## Device-hit telemetry (generated by the test "
             f"run)\n\nPer conformance suite: how many `run_query` tests "
             f"re-executed on the DEVICE engine (planner-compiled) and "
             f"asserted backend-identical output — the floor is enforced "
             f"by `tests/device_hit_floor.json` on full runs.\n\n"
             f"| suite | harness tests | device-validated |\n|---|---|---|\n"
             f"{rows}\n| **total** | **{total_t}** | **{total_h}** |\n{end}")
    with open(path) as f:
        text = f.read()
    if begin in text:
        import re
        text = re.sub(re.escape(begin) + ".*?" + re.escape(end), block,
                      text, flags=re.S)
    else:
        text = text.rstrip() + "\n\n" + block + "\n"
    with open(path, "w") as f:
        f.write(text)


# --------------------------------------------------------------------------
# Engine-thread leak sentinel (PR 13): every engine thread carries a
# siddhi- prefixed name from core/threads.py, so after each test file we
# can assert the file joined what it started.  Non-daemon leftovers are a
# hard failure (they block interpreter exit); daemon leftovers get a
# short grace join, then fail too — a daemon junction worker still alive
# after its module means some shutdown() path was skipped.

import pytest


@pytest.fixture(autouse=True, scope="module")
def _engine_thread_leak_sentinel(request):
    yield
    import threading
    import time as _time
    from siddhi_tpu.core.threads import attribute

    deadline = _time.monotonic() + 2.0
    leftovers = [t for t in threading.enumerate()
                 if t.name.startswith("siddhi-") and t.is_alive()]
    while leftovers and _time.monotonic() < deadline:
        for t in leftovers:
            t.join(timeout=0.1)
        leftovers = [t for t in threading.enumerate()
                     if t.name.startswith("siddhi-") and t.is_alive()]
    assert not leftovers, (
        f"{request.module.__name__} leaked engine threads: "
        + "; ".join(f"{t.name} (daemon={t.daemon}) — {attribute(t.name)}"
                    for t in leftovers))


# Lock-witness arming (PR 13): the chaos/resilience/overload files run
# with the runtime lock-witness armed against the static lock graph, so
# every tier-1 run doubles as a lock-order race regression gate.  The
# teardown asserts the GLOBAL witness saw no inversions; seeded
# inversion scenarios (tests/chaos.py LockOrderInversion) use private
# LockWitness instances precisely so this gate stays meaningful.

_WITNESSED_FILES = {"test_resilience", "test_overload", "test_flight"}
_STATIC_EDGES_CACHE = []


@pytest.fixture(autouse=True, scope="module")
def _lock_witness_gate(request):
    if request.module.__name__ not in _WITNESSED_FILES:
        yield
        return
    from siddhi_tpu.core import lockwitness
    if not _STATIC_EDGES_CACHE:
        from siddhi_tpu.analysis.engine import static_lock_edges
        _STATIC_EDGES_CACHE.append(static_lock_edges())
    w = lockwitness.arm(static_edges=_STATIC_EDGES_CACHE[0])
    w.reset()
    try:
        yield
        inv = w.inversions()
        assert not inv, (
            f"{request.module.__name__}: lock-witness observed lock-order "
            f"inversions (LW001): {inv}")
    finally:
        lockwitness.disarm()
        w.reset()
