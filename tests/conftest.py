"""Test configuration: force a virtual 8-device CPU mesh so sharding tests run
without TPU hardware (the driver separately dry-runs multi-chip compilation)."""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS",
    (os.environ.get("XLA_FLAGS", "") +
     " --xla_force_host_platform_device_count=8").strip())
