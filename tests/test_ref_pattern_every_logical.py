"""Port of the reference pattern conformance suites
query/pattern/EveryPatternTestCase.java (9 @Tests) and
query/pattern/LogicalPatternTestCase.java (19 @Tests).
Expected payloads are the reference's own assertions; ref_harness re-runs
each app on the device engine when the planner compiles it.
"""
from ref_harness import run_query

S12 = """
define stream Stream1 (symbol string, price float, volume int);
define stream Stream2 (symbol string, price float, volume int);
"""
S12B = """
define stream Stream1 (symbol string, price float, volume int);
define stream Stream2 (symbol string, price1 float, volume int);
"""
S123 = S12 + "define stream Stream3 (symbol string, price float, volume int);\n"
S1 = "define stream Stream1 (symbol string, price float, volume int);\n"
Q = "@info(name = 'query1') "


# ------------------------------------------------ EveryPatternTestCase

def test_every_1_plain_chain():
    run_query(S12 + Q + """
        from e1=Stream1[price>20] -> e2=Stream2[price>e1.price]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream2", ["IBM", 55.7, 100])],
        [("WSO2", "IBM")])


def test_every_2_no_every_single_match():
    run_query(S12B + Q + """
        from e1=Stream1[price>20] -> e2=Stream2[price1>e1.price]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream1", ["GOOG", 55.6, 100]),
         ("Stream2", ["IBM", 55.7, 100])],
        [("WSO2", "IBM")])


def test_every_3_two_partials_one_closer():
    run_query(S12B + Q + """
        from every e1=Stream1[price>20] -> e2=Stream2[price1>e1.price]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream1", ["GOOG", 55.6, 100]),
         ("Stream2", ["IBM", 55.7, 100])],
        [("WSO2", "IBM"), ("GOOG", "IBM")])


def test_every_4_prefix_group():
    run_query(S12 + Q + """
        from every ( e1=Stream1[price>20] -> e3=Stream1[price>20] )
             -> e2=Stream2[price>e1.price]
        select e1.price as price1, e3.price as price3, e2.price as price2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream1", ["GOOG", 54.0, 100]),
         ("Stream2", ["IBM", 57.7, 100])],
        [(55.6, 54.0, 57.7)])


def test_every_5_prefix_group_two_rounds():
    run_query(S12 + Q + """
        from every ( e1=Stream1[price>20] -> e3=Stream1[price>20] )
             -> e2=Stream2[price>e1.price]
        select e1.price as price1, e3.price as price3, e2.price as price2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream1", ["GOOG", 54.0, 100]),
         ("Stream1", ["WSO2", 53.6, 100]), ("Stream1", ["GOOG", 53.0, 100]),
         ("Stream2", ["IBM", 57.7, 100])],
        [(55.6, 54.0, 57.7), (53.6, 53.0, 57.7)])


def test_every_6_mid_chain_group():
    run_query(S12 + Q + """
        from e4=Stream1[symbol=='MSFT']
             -> every ( e1=Stream1[price>20] -> e3=Stream1[price>20] )
             -> e2=Stream2[price>e1.price]
        select e1.price as price1, e3.price as price3, e2.price as price2
        insert into OutputStream;""",
        [("Stream1", ["MSFT", 55.6, 100]), ("Stream1", ["WSO2", 55.7, 100]),
         ("Stream1", ["GOOG", 54.0, 100]), ("Stream1", ["WSO2", 53.6, 100]),
         ("Stream1", ["GOOG", 53.0, 100]), ("Stream2", ["IBM", 57.7, 100])],
        [(55.7, 54.0, 57.7), (53.6, 53.0, 57.7)])


def test_every_7_whole_chain_group():
    run_query(S1 + Q + """
        from every ( e1=Stream1[price>20] -> e3=Stream1[price>20] )
        select e1.price as price1, e3.price as price3
        insert into OutputStream;""",
        [("Stream1", ["MSFT", 55.6, 100]), ("Stream1", ["WSO2", 57.6, 100]),
         ("Stream1", ["GOOG", 54.0, 100]), ("Stream1", ["WSO2", 53.6, 100])],
        [(55.6, 57.6), (54.0, 53.6)])


def test_every_8_single_state():
    run_query(S1 + Q + """
        from every e1=Stream1[price>20]
        select e1.price as price1
        insert into OutputStream;""",
        [("Stream1", ["MSFT", 55.6, 100]), ("Stream1", ["WSO2", 57.6, 100])],
        [(55.6,), (57.6,)])


def test_every_9_duplicate_ref_overwrite():
    run_query(S1 + Q + """
        from every e1=Stream1[symbol == 'MSFT'] -> e1=Stream1[symbol == 'WSO2']
        select e1.price as price1
        insert into OutputStream;""",
        [("Stream1", ["MSFT", 55.6, 100]), ("Stream1", ["MSFT", 77.6, 100]),
         ("Stream1", ["WSO2", 57.6, 100])],
        [(55.6,), (77.6,)])


# ---------------------------------------------- LogicalPatternTestCase

def test_logical_1_or_first_side():
    run_query(S12 + Q + """
        from e1=Stream1[price > 20]
             -> e2=Stream2[price > e1.price] or e3=Stream2['IBM' == symbol]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream2", ["GOOG", 59.6, 100])],
        [("WSO2", "GOOG")])


def test_logical_2_or_second_side_null_first():
    run_query(S12 + Q + """
        from e1=Stream1[price > 20]
             -> e2=Stream2[price > e1.price] or e3=Stream2['IBM' == symbol]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream2", ["IBM", 10.7, 100])],
        [("WSO2", None)])


def test_logical_3_or_single_shot():
    run_query(S12 + Q + """
        from e1=Stream1[price > 20]
             -> e2=Stream2[price > e1.price] or e3=Stream2['IBM' == symbol]
        select e1.symbol as symbol1, e2.price as price2, e3.price as price3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream2", ["IBM", 72.7, 100]),
         ("Stream2", ["IBM", 75.7, 100])],
        [("WSO2", 72.7, None)])


def test_logical_4_and_two_events():
    run_query(S12 + Q + """
        from e1=Stream1[price > 20]
             -> e2=Stream2[price > e1.price] and e3=Stream2['IBM' == symbol]
        select e1.symbol as symbol1, e2.price as price2, e3.price as price3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream2", ["GOOG", 72.7, 100]),
         ("Stream2", ["IBM", 4.7, 100])],
        [("WSO2", 72.7, 4.7)])


def test_logical_5_and_same_event_both_sides():
    run_query(S12 + Q + """
        from e1=Stream1[price > 20]
             -> e2=Stream2[price > e1.price] and e3=Stream2['IBM' == symbol]
        select e1.symbol as symbol1, e2.price as price2, e3.price as price3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream2", ["IBM", 72.7, 100]),
         ("Stream2", ["IBM", 75.7, 100])],
        [("WSO2", 72.7, 72.7)])


def test_logical_6_and_cross_streams():
    run_query(S12 + Q + """
        from e1=Stream1[price > 20]
             -> e2=Stream2[price > e1.price] and e3=Stream1['IBM' == symbol]
        select e1.symbol as symbol1, e2.price as price2, e3.price as price3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream2", ["IBM", 72.7, 100]),
         ("Stream1", ["IBM", 75.7, 100])],
        [("WSO2", 72.7, 75.7)])


def test_logical_7_leading_and():
    run_query(S12 + Q + """
        from e1=Stream1[price > 20] and e2=Stream2[price >30]
             -> e3=Stream2['IBM' == symbol]
        select e1.symbol as symbol1, e2.price as price2, e3.price as price3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream2", ["GOOG", 72.7, 100]),
         ("Stream2", ["IBM", 4.7, 100])],
        [("WSO2", 72.7, 4.7)])


def test_logical_8_leading_or_first():
    run_query(S12 + Q + """
        from e1=Stream1[price > 20] or e2=Stream2[price >30]
             -> e3=Stream2['IBM' == symbol]
        select e1.symbol as symbol1, e2.price as price2, e3.price as price3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream2", ["GOOG", 72.7, 100]),
         ("Stream2", ["IBM", 4.7, 100])],
        [("WSO2", None, 4.7)])


def test_logical_9_leading_or_second():
    run_query(S12 + Q + """
        from e1=Stream1[price > 20] or e2=Stream2[price >30]
             -> e3=Stream2['IBM' == symbol]
        select e1.symbol as symbol1, e2.price as price2, e3.price as price3
        insert into OutputStream;""",
        [("Stream2", ["GOOG", 72.7, 100]), ("Stream2", ["IBM", 4.7, 100])],
        [(None, 72.7, 4.7)])


def test_logical_10_leading_or_direct():
    run_query(S12 + Q + """
        from e1=Stream1[price > 20] or e2=Stream2[price >30]
             -> e3=Stream2['IBM' == symbol]
        select e1.symbol as symbol1, e2.price as price2, e3.price as price3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream2", ["IBM", 4.7, 100])],
        [("WSO2", None, 4.7)])


def test_logical_11_every_then_and_pair():
    run_query(S123 + Q + """
        from every e1=Stream1[price >20]
             -> e2=Stream2['IBM' == symbol] and e3=Stream3['WSO2' == symbol]
        select e1.price as price1, e2.price as price2, e3.price as price3
        insert into OutputStream;""",
        [("Stream1", ["IBM", 25.5, 100]), ("Stream1", ["IBM", 59.65, 100]),
         ("Stream2", ["IBM", 45.5, 100]), ("Stream3", ["WSO2", 46.56, 100])],
        [(25.5, 45.5, 46.56), (59.65, 45.5, 46.56)], unordered=True)


def test_logical_12_every_then_or_pair():
    run_query(S123 + Q + """
        from every e1=Stream1[price >20]
             -> e2=Stream2['IBM' == symbol] or e3=Stream3['WSO2' == symbol]
        select e1.price as price1, e2.price as price2, e3.price as price3
        insert into OutputStream;""",
        [("Stream1", ["IBM", 25.5, 100]), ("Stream1", ["IBM", 59.65, 100]),
         ("Stream2", ["IBM", 45.5, 100])],
        [(25.5, 45.5, None), (59.65, 45.5, None)], unordered=True)


def test_logical_13_bare_and():
    run_query(S12 + Q + """
        from e1=Stream1[price > 20] and e2=Stream2[price >30]
        select e1.symbol as symbol1, e2.price as price2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 25.0, 100]), ("Stream2", ["IBM", 35.0, 100]),
         ("Stream1", ["GOOGLE", 45.0, 100]),
         ("Stream2", ["ORACLE", 55.0, 100])],
        [("WSO2", 35.0)])


def test_logical_14_bare_or():
    run_query(S12 + Q + """
        from e1=Stream1[price > 20] or e2=Stream2[price >30]
        select e1.symbol as symbol1, e2.price as price2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 25.0, 100]), ("Stream2", ["IBM", 35.0, 100]),
         ("Stream2", ["ORACLE", 45.0, 100])],
        [("WSO2", None)])


def test_logical_15_every_and():
    run_query(S12 + Q + """
        from every (e1=Stream1[price > 20] and e2=Stream2[price >30])
        select e1.symbol as symbol1, e2.price as price2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 25.0, 100]), ("Stream2", ["IBM", 35.0, 100]),
         ("Stream1", ["GOOGLE", 45.0, 100]),
         ("Stream2", ["ORACLE", 55.0, 100])],
        [("WSO2", 35.0), ("GOOGLE", 55.0)])


def test_logical_16_every_or():
    run_query(S12 + Q + """
        from every (e1=Stream1[price > 20] or e2=Stream2[price >30])
        select e1.symbol as symbol1, e2.price as price2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 25.0, 100]), ("Stream2", ["IBM", 35.0, 100]),
         ("Stream2", ["ORACLE", 45.0, 100])],
        [("WSO2", None), (None, 35.0), (None, 45.0)])


def test_logical_17_or_within_expired():
    run_query(S12 + Q + """
        from e1=Stream1[price > 20]
             -> e2=Stream2[price > e1.price] or e3=Stream2['IBM' == symbol]
             within 1 sec
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100], 1000),
         ("Stream2", ["GOOG", 59.6, 100], 2200)],
        [])


def test_logical_18_and_within_expired():
    run_query(S12 + Q + """
        from e1=Stream1[price > 20]
             -> e2=Stream2[price > e1.price] and e3=Stream2['IBM' == symbol]
             within 1 sec
        select e1.symbol as symbol1, e2.price as price2, e3.price as price3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100], 1000),
         ("Stream2", ["GOOG", 72.7, 100], 2200),
         ("Stream2", ["IBM", 4.7, 100], 2300)],
        [])


def test_logical_19_every_and_pair_then_next():
    run_query(S123 + Q + """
        from every (e1=Stream1[price>10] and e2=Stream2[price>20])
             -> e3=Stream3[price>30]
        select e1.symbol as symbol1, e2.symbol as symbol2,
               e3.symbol as symbol3
        insert into OutputStream;""",
        [("Stream1", ["ORACLE", 15.0, 100]),
         ("Stream2", ["MICROSOFT", 45.0, 100]),
         ("Stream1", ["IBM", 55.0, 100]), ("Stream2", ["WSO2", 65.0, 100]),
         ("Stream3", ["GOOGLE", 75.0, 100])],
        [("ORACLE", "MICROSOFT", "GOOGLE"), ("IBM", "WSO2", "GOOGLE")],
        unordered=True)
