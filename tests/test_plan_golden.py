"""Golden-file Plan-IR dumps for every shipped sample.

Each SiddhiQL app embedded in samples/*.py is built into a runtime, its
compiled plan extracted (analysis/plan_ir.py) and rendered with the
stable textual dump; the result is pinned under tests/golden/.  A
planner refactor that changes what actually compiles — a query silently
falling off the device path, an automaton gaining a state, a capture
bank widening — shows up as a reviewable golden diff instead of a
throughput mystery three rounds later.

Regenerate after an INTENTIONAL planner change with:

    REGEN_PLAN_GOLDEN=1 python -m pytest tests/test_plan_golden.py

Acceptance rider: every sample must be PV-error-free (the plan verifier
finds no malformed/dead automata in shipped showcase code).
"""
import ast
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from siddhi_tpu import SiddhiManager  # noqa: E402
from siddhi_tpu.analysis import Severity, extract_plan, verify_plan  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SAMPLES_DIR = os.path.join(ROOT, "samples")
GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "golden")
REGEN = os.environ.get("REGEN_PLAN_GOLDEN") == "1"


def _apps_in(path):
    """SiddhiQL app literals in a sample .py (same extraction as
    test_samples_analysis): plain strings verbatim, f-string slots tried
    as '0' then '' keeping the variant that parses."""
    tree = ast.parse(open(path).read())
    apps = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if "define stream" in node.value and ";" in node.value:
                apps.append([node.value])
        elif isinstance(node, ast.JoinedStr):
            variants = []
            for filler in ("0", ""):
                text = "".join(str(v.value) if isinstance(v, ast.Constant)
                               else filler for v in node.values)
                variants.append(text)
            if "define stream" in variants[0] and ";" in variants[0]:
                apps.append(variants)
    return [v for v in apps
            if not any(v is not w and v[0] in w[0] for w in apps)]


def _sample_files():
    return sorted(f for f in os.listdir(SAMPLES_DIR) if f.endswith(".py"))


def _manager():
    """Manager with the extensions the samples register at runtime
    (quickstart_extension's custom:plus), so its app builds here too."""
    from siddhi_tpu.query_api.definition import AttrType
    from siddhi_tpu.utils.extension import FunctionExtension

    class _Plus(FunctionExtension):
        return_type = AttrType.DOUBLE

        def apply(self, *cols):
            out = cols[0]
            for c in cols[1:]:
                out = out + c
            return out

    m = SiddhiManager()
    m.set_extension("custom:plus", _Plus)
    return m


def _build_plan(variants):
    """First parseable variant -> (dump text, verifier diagnostics)."""
    m = _manager()
    last = None
    for text in variants:
        try:
            rt = m.create_siddhi_app_runtime(text)
        except Exception as e:  # noqa: BLE001 — try the next variant
            last = e
            continue
        try:
            plan = extract_plan(rt)
            report = verify_plan(plan)
            return plan.dump(), report.diagnostics
        finally:
            rt.shutdown()
    raise AssertionError(f"no app variant builds: {last}")


@pytest.mark.parametrize("fname", _sample_files())
def test_sample_plan_matches_golden(fname):
    apps = _apps_in(os.path.join(SAMPLES_DIR, fname))
    assert apps, f"{fname}: no SiddhiQL app string found"
    for i, variants in enumerate(apps):
        dump, diags = _build_plan(variants)
        pv_errors = [d for d in diags
                     if d.code.startswith("PV") and
                     d.severity == Severity.ERROR]
        assert not pv_errors, (
            f"{fname} app #{i} has plan-verifier ERRORS:\n" +
            "\n".join(d.render(fname) for d in pv_errors))
        golden = os.path.join(
            GOLDEN_DIR, f"{fname[:-3]}__app{i}.plan.txt")
        if REGEN:
            os.makedirs(GOLDEN_DIR, exist_ok=True)
            with open(golden, "w") as f:
                f.write(dump)
            continue
        assert os.path.exists(golden), (
            f"missing golden {os.path.relpath(golden, ROOT)} — run "
            f"REGEN_PLAN_GOLDEN=1 pytest tests/test_plan_golden.py")
        want = open(golden).read()
        assert dump == want, (
            f"{fname} app #{i}: Plan-IR dump changed.  If the planner "
            f"change is intentional, regenerate with "
            f"REGEN_PLAN_GOLDEN=1.\n--- golden\n{want}\n--- now\n{dump}")
