"""Resilience contract tests (robustness PR), driven by the deterministic
chaos harness in tests/chaos.py:

  * flaky sink: N failures then recovery → 100% delivery, zero drops, and
    the junction/ingest thread never blocks on the backoff (p99 bound);
  * permanently dead sink: every event lands in the error store, and
    ``replay_errors`` drains it once the endpoint heals;
  * @OnError(action='STORE'/'WAIT') on stream junctions;
  * periodic checkpoints (@app:persist) under playback virtual time;
  * crash recovery: SIGKILL a child engine mid-stream, restart with
    ``recover=True``, replay from the last acked offset — every match at
    least once, duplicates bounded by one checkpoint interval;
  * torn snapshot writes → typed CannotRestoreStateError, atomic
    FileSystemPersistenceStore saves, numeric revision ordering;
  * snapshot ↔ NFA micro-batching compatibility (persist at B=4, restore
    at B=1, and vice versa).

Every injected failure is scripted or seeded; no assertion depends on a
wall-clock sleep (rendezvous go through ``SinkRetryWorker.join`` /
subprocess ack files / playback virtual time).
"""
import os
import signal
import subprocess
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import chaos  # noqa: E402  (tests/ is on sys.path via conftest)
from siddhi_tpu import (FileSystemPersistenceStore,  # noqa: E402
                        InMemoryPersistenceStore, SiddhiManager,
                        StreamCallback)
from siddhi_tpu.core.resilience import (CircuitBreaker,  # noqa: E402
                                        InMemoryErrorStore, RetryPolicy,
                                        make_entry)
from siddhi_tpu.core.statistics import (LatencyTracker,  # noqa: E402
                                        prometheus_text)
from siddhi_tpu.utils.errors import CannotRestoreStateError  # noqa: E402


def _mk(app, store=None, error_store=None):
    m = SiddhiManager()
    chaos.register(m)
    if store is not None:
        m.set_persistence_store(store)
    if error_store is not None:
        m.set_error_store(error_store)
    return m, m.create_siddhi_app_runtime(app)


# ================================================================ unit layer

def test_retry_policy_deterministic_ladder():
    p = RetryPolicy(max_attempts=6, base_delay_s=0.05, multiplier=2.0,
                    max_delay_s=0.5, jitter=0.2, budget_s=None, seed=7)
    ladder = p.delays()
    assert ladder == p.delays()                     # same seed → same jitter
    assert len(ladder) == 5
    # exponential shape survives the ±10% jitter; the cap bites at 0.5 s
    assert 0.04 <= ladder[0] <= 0.06
    assert ladder[1] > ladder[0] and ladder[2] > ladder[1]
    assert all(d <= 0.5 * 1.1 for d in ladder)
    assert RetryPolicy(seed=8).delays() != RetryPolicy(seed=7).delays()


def test_retry_policy_budget_caps_ladder():
    p = RetryPolicy(max_attempts=50, base_delay_s=1.0, multiplier=1.0,
                    jitter=0.0, budget_s=3.0)
    assert p.delays() == [1.0, 1.0, 1.0]


def test_retry_policy_from_options_ms_knobs():
    p = RetryPolicy.from_options({
        "retry.max.attempts": "3", "retry.base.delay.ms": "10",
        "retry.multiplier": "3.0", "retry.max.delay.ms": "90",
        "retry.jitter": "0", "retry.budget.ms": "1000", "retry.seed": "4"})
    assert p.max_attempts == 3 and p.jitter == 0 and p.seed == 4
    assert p.delays() == [0.01, 0.03]


def test_circuit_breaker_state_machine():
    vc = chaos.VirtualClock()
    transitions = []
    b = CircuitBreaker(failure_threshold=2, reset_timeout_s=5.0, clock=vc,
                       on_transition=lambda old, new:
                       transitions.append((old, new)))
    assert b.state == "closed" and b.allow()
    b.record_failure()
    assert b.state == "closed"                      # below threshold
    b.record_failure()
    assert b.state == "open" and not b.allow() and b.state_code == 1
    vc.advance(4.9)
    assert not b.allow()
    vc.advance(0.2)
    assert b.allow() and b.state == "half_open"     # probe window
    b.record_failure()                              # probe fails → re-open
    assert b.state == "open"
    vc.advance(5.0)
    assert b.allow()
    b.record_success()
    assert b.state == "closed" and b.state_code == 0
    assert ("closed", "open") in transitions
    assert ("half_open", "open") in transitions
    assert ("half_open", "closed") in transitions


def test_error_store_roundtrip_and_purge():
    store = InMemoryErrorStore(capacity=100)

    class _E:
        def __init__(self, ts, data):
            self.timestamp, self.data = ts, data

    e1 = make_entry("app", "S", "sink", RuntimeError("boom"),
                    [_E(1000, [1, "a"]), _E(1001, [2, "b"])])
    e2 = make_entry("app", "T", "stream", ValueError("bad"), [_E(2000, [3])])
    store.store(e1)
    store.store(e2)
    assert [e.id for e in store.list("app")] == [1, 2]
    assert store.list("app", stream_id="S")[0].events == \
        [(1000, (1, "a")), (1001, (2, "b"))]
    assert store.list("other") == []
    assert store.purge("app", ids=[1]) == 1
    assert [e.stream_id for e in store.list("app")] == ["T"]
    assert e2.summary()["origin"] == "stream"
    assert "ValueError" in e2.error


def test_sqlite_error_store_roundtrip():
    from siddhi_tpu.stores.sqlite import SqliteErrorStore

    class _E:
        def __init__(self, ts, data):
            self.timestamp, self.data = ts, data

    s = SqliteErrorStore(":memory:")
    try:
        eid = s.store(make_entry("app", "S", "sink", RuntimeError("x"),
                                 [_E(5, [1.5, "z"])], attempts=3))
        assert eid == 1
        got = s.list(app_name="app")
        assert len(got) == 1 and got[0].events == [(5, (1.5, "z"))]
        assert got[0].attempts == 3 and got[0].origin == "sink"
        assert s.count("app") == 1 and s.count("nope") == 0
        assert s.purge(app_name="app", ids=[eid]) == 1
        assert s.list(app_name="app") == []
    finally:
        s.close()


def test_app_errorstore_annotation_selects_backend():
    _, rt = _mk("@app:errorStore(type='memory', capacity='7')\n"
                "define stream s (v int);\n"
                "from s select v insert into Out;")
    assert isinstance(rt.error_store, InMemoryErrorStore)
    assert rt.error_store.capacity == 7
    rt.shutdown()
    from siddhi_tpu.stores.sqlite import SqliteErrorStore
    _, rt2 = _mk("@app:errorStore(type='sqlite')\n"
                 "define stream s (v int);\n"
                 "from s select v insert into Out;")
    assert isinstance(rt2.error_store, SqliteErrorStore)
    rt2.shutdown()


# ============================================================== flaky sinks

FLAKY_APP = """
define stream s (v int);
@sink(type='chaos', chaos.id='flaky', retry.base.delay.ms='60',
      retry.jitter='0', retry.max.attempts='20',
      circuit.failure.threshold='1000')
define stream outs (v int);
@info(name='q') from s select v insert into outs;
"""


def test_flaky_sink_zero_loss_and_nonblocking_ingest():
    """A sink failing its first 10 publishes recovers: every event is
    delivered (off-thread retries), nothing is dropped, and the sender
    never waits out a backoff (p99 well under the 60 ms retry delay)."""
    chaos.reset()
    chaos.SCRIPTS["flaky"] = chaos.FailureScript.fail_n(10)
    _, rt = _mk(FLAKY_APP)
    rt.start()
    h = rt.get_input_handler("s")
    lat = LatencyTracker("ingest")
    for i in range(100):
        lat.mark_in()
        h.send([i])
        lat.mark_out()
    sink = chaos.INSTANCES["flaky"]
    assert sink.retry_join(30.0), "retry queue did not drain"
    got = sorted(e.data[0] for e in chaos.delivered("flaky"))
    assert got == list(range(100)), "flaky sink lost or duplicated events"

    m = rt.resilience_metrics
    assert m.sink_retry_total.value(sink="outs") >= 1
    assert m.sink_publish_failed_total.value(sink="outs") >= 1
    assert sum(m.sink_dropped_total.series().values()) == 0
    assert sum(m.errors_stored_total.series().values()) == 0
    # the backoff ran on the retry worker, not the ingest path
    p99 = lat.percentiles_ms()["p99_ms"]
    assert p99 < 50.0, f"ingest p99 {p99:.1f} ms — retries blocked the sender"

    text = prometheus_text([], None, [m])
    assert '# TYPE siddhi_sink_retry_total counter' in text
    assert 'siddhi_sink_retry_total{app="' + rt.name + '",sink="outs"}' \
        in text
    assert 'siddhi_circuit_state{app="' + rt.name + '",sink="outs"} 0' \
        in text
    rt.shutdown()


DEAD_APP = """
@app:errorStore(type='memory')
define stream s (v int);
@sink(type='chaos', chaos.id='dead', retry.max.attempts='2',
      retry.base.delay.ms='1', retry.jitter='0',
      circuit.failure.threshold='3', circuit.reset.ms='0')
define stream outd (v int);
@info(name='q') from s select v insert into outd;
"""


def test_dead_sink_routes_to_error_store_and_replay_drains():
    chaos.reset()
    chaos.SCRIPTS["dead"] = chaos.FailureScript.fail_always()
    _, rt = _mk(DEAD_APP)
    rt.start()
    h = rt.get_input_handler("s")
    for i in range(30):
        h.send([i])
    assert chaos.INSTANCES["dead"].retry_join(30.0)
    entries = rt.error_store.list(app_name=rt.name)
    assert sum(len(e.events) for e in entries) == 30, \
        "a permanently dead sink must surrender every event to the store"
    assert all(e.origin == "sink" and e.stream_id == "outd"
               for e in entries)
    assert chaos.delivered("dead") == []
    m = rt.resilience_metrics
    assert m.errors_stored_total.value(stream="outd", origin="sink") == 30

    # endpoint heals → replay re-publishes through the original sink
    chaos.SCRIPTS["dead"].heal()
    replayed = rt.replay_errors()
    assert chaos.INSTANCES["dead"].retry_join(30.0)
    assert replayed == 30
    assert rt.error_store.count(rt.name) == 0, "replay must purge successes"
    got = sorted(e.data[0] for e in chaos.delivered("dead"))
    assert got == list(range(30))
    assert m.errors_replayed_total.value(stream="outd") == 30
    rt.shutdown()


def test_retry_queue_overflow_spills_to_error_store():
    """retry.queue.size bounds the in-flight retry backlog; overflow goes
    to the error store instead of growing without bound."""
    chaos.reset()
    chaos.SCRIPTS["tiny"] = chaos.FailureScript.fail_always()
    _, rt = _mk("""
        @app:errorStore(type='memory')
        define stream s (v int);
        @sink(type='chaos', chaos.id='tiny', retry.max.attempts='1000',
              retry.base.delay.ms='200', retry.jitter='0',
              retry.queue.size='2', circuit.failure.threshold='100000')
        define stream outt (v int);
        @info(name='q') from s select v insert into outt;
    """)
    rt.start()
    h = rt.get_input_handler("s")
    for i in range(20):
        h.send([i])
    # ≥ 17 events overflowed the 2-slot queue straight into the store
    # (the worker may have dequeued at most one task into flight)
    stored = sum(len(e.events)
                 for e in rt.error_store.list(app_name=rt.name))
    assert stored >= 17
    rt.shutdown()
    # shutdown drains the worker: every event is accounted for, none lost
    stored = sum(len(e.events)
                 for e in rt.error_store.list(app_name=rt.name))
    assert stored + len(chaos.delivered("tiny")) == 20


@pytest.mark.slow
def test_chaos_soak_seeded_partial_failures_no_loss():
    """Seeded 20%-failure soak: across 2000 events every single one ends
    up delivered or stored — never silently dropped."""
    chaos.reset()
    chaos.SCRIPTS["soak"] = chaos.FailureScript(fail_rate=0.2, seed=42)
    _, rt = _mk("""
        @app:errorStore(type='memory')
        define stream s (v int);
        @sink(type='chaos', chaos.id='soak', retry.max.attempts='4',
              retry.base.delay.ms='1', retry.jitter='0',
              circuit.failure.threshold='100000')
        define stream outk (v int);
        @info(name='q') from s select v insert into outk;
    """)
    rt.start()
    h = rt.get_input_handler("s")
    for i in range(2000):
        h.send([i])
    assert chaos.INSTANCES["soak"].retry_join(60.0)
    delivered = [e.data[0] for e in chaos.delivered("soak")]
    stored = [data[0] for entry in rt.error_store.list(app_name=rt.name)
              for _, data in entry.events]
    assert sorted(delivered + stored) == list(range(2000)), \
        "chaos soak lost events"
    rt.shutdown()


# ========================================================== @OnError actions

def test_onerror_store_captures_stream_failures_and_replays():
    chaos.reset()
    _, rt = _mk("""
        @app:errorStore(type='memory')
        define stream s (v int);
        @OnError(action='STORE')
        define stream o (v int);
        @info(name='q') from s select v insert into o;
    """)
    got, fail = [], [True]

    def cb(evs):
        if fail[0]:
            raise RuntimeError("downstream down")
        got.extend(e.data[0] for e in evs)

    rt.add_callback("o", StreamCallback(cb))
    rt.start()
    h = rt.get_input_handler("s")
    h.send([1])
    h.send([2])
    assert got == []
    entries = rt.error_store.list(app_name=rt.name)
    assert [e.origin for e in entries] == ["stream", "stream"]
    assert [e.stream_id for e in entries] == ["o", "o"]
    assert rt.resilience_metrics.errors_stored_total.value(
        stream="o", origin="stream") == 2

    fail[0] = False
    assert rt.replay_errors() == 2
    assert sorted(got) == [1, 2]
    assert rt.error_store.count(rt.name) == 0
    rt.shutdown()


def test_onerror_store_without_store_falls_back_to_log():
    """No error store configured: STORE degrades to the LOG path (and the
    analyzer flags it as SA050 — see test_analyzer_flags_onerror_store)."""
    _, rt = _mk("""
        define stream s (v int);
        @OnError(action='STORE')
        define stream o (v int);
        @info(name='q') from s select v insert into o;
    """)
    errors = []
    rt.app_ctx.exception_listeners.append(errors.append)

    def cb(evs):
        raise RuntimeError("nope")

    rt.add_callback("o", StreamCallback(cb))
    rt.start()
    rt.get_input_handler("s").send([1])
    assert errors, "without a store the failure surfaces to listeners"
    rt.shutdown()


def test_onerror_wait_blocks_until_receiver_heals():
    _, rt = _mk("""
        define stream s (v int);
        @OnError(action='WAIT', retry.max.attempts='6',
                 retry.base.delay.ms='1', retry.jitter='0')
        define stream w (v int);
        @info(name='q') from s select v insert into w;
    """)
    got, fails = [], [2]

    def cb(evs):
        if fails[0] > 0:
            fails[0] -= 1
            raise RuntimeError("transient")
        got.extend(e.data[0] for e in evs)

    rt.add_callback("w", StreamCallback(cb))
    rt.start()
    rt.get_input_handler("s").send([5])     # blocks through 2 retries
    assert got == [5]
    assert rt.resilience_metrics.onerror_wait_retries_total.value(
        stream="w") >= 2
    rt.shutdown()


def test_analyzer_flags_onerror_store_without_store():
    from siddhi_tpu.analysis import analyze
    app = ("@OnError(action='STORE') define stream s (v int);\n"
           "from s select v insert into Out;")
    assert "SA050" in analyze(app).codes()
    with_store = "@app:errorStore(type='memory')\n" + app
    assert "SA050" not in analyze(with_store).codes()
    bad_action = ("@OnError(action='EXPLODE') define stream s (v int);\n"
                  "from s select v insert into Out;")
    assert "SA051" in analyze(bad_action).codes()


# ======================================================= checkpoints/recovery

SUM_APP = """
@app:name('ckapp')
define stream S (v float);
@info(name='q') from S select sum(v) as total insert into Out;
"""


def test_checkpoint_scheduler_fires_on_playback_time():
    """@app:persist checkpoints ride the app Scheduler, so playback
    virtual time drives them deterministically — no wall-clock waits."""
    store = InMemoryPersistenceStore()
    m, rt = _mk("@app:playback @app:persist(interval='1 sec')\n" + SUM_APP,
                store=store)
    assert rt.checkpoint_scheduler is not None
    assert rt.checkpoint_scheduler.interval_ms == 1000
    rt.start()
    h = rt.get_input_handler("S")
    for k in range(6):                       # ts 1.0s … 6.0s virtual
        h.send([1.0], timestamp=1_000 * (k + 1))
    revs = store.revisions(rt.name)
    assert len(revs) >= 3, f"expected ≥3 periodic checkpoints, got {revs}"
    assert all(r.endswith("_full") for r in revs)
    assert rt.resilience_metrics.checkpoints_total.value() == len(revs)
    rt.shutdown()

    # the last checkpoint restores into a fresh runtime and the sum
    # continues from the checkpointed state
    m2, rt2 = _mk(SUM_APP, store=store)
    got = []
    rt2.add_callback("Out", StreamCallback(
        lambda evs: got.extend(e.data[0] for e in evs)))
    rt2.start()
    rt2.restore_last_revision()
    rt2.get_input_handler("S").send([1.0])
    rt2.shutdown()
    # ≥5 events were covered by the last checkpoint (the 6th may race the
    # final fire); continued sum reflects the restored accumulator
    assert got and got[-1] >= 6.0


def test_incremental_checkpoint_annotation():
    store = InMemoryPersistenceStore()
    m, rt = _mk("@app:playback "
                "@app:persist(interval='1 sec', incremental='true')\n"
                + SUM_APP, store=store)
    assert rt.checkpoint_scheduler.incremental is True
    rt.start()
    h = rt.get_input_handler("S")
    h.send([1.0], timestamp=1_000)
    base = rt.persist()                      # explicit full base
    assert base.endswith("_full")
    for k in range(3):
        h.send([1.0], timestamp=2_000 + 1_000 * k)
    assert any(r.endswith("_inc") for r in store.revisions(rt.name)), \
        "incremental='true' checkpoints must write _inc revisions"
    rt.shutdown()


def test_recover_flag_restores_last_revision():
    store = InMemoryPersistenceStore()
    m, rt = _mk(SUM_APP, store=store)
    rt.start()
    h = rt.get_input_handler("S")
    h.send([10.0])
    h.send([5.0])
    rev = rt.persist()
    rt.shutdown()

    rt2 = m.create_siddhi_app_runtime(SUM_APP, recover=True)
    assert rt2.recovered_revision == rev
    assert rt2.resilience_metrics.recovered.value() == 1
    got = []
    rt2.add_callback("Out", StreamCallback(
        lambda evs: got.extend(e.data[0] for e in evs)))
    rt2.start()
    rt2.get_input_handler("S").send([1.0])
    rt2.shutdown()
    assert got == [pytest.approx(16.0)]


def test_recover_flag_with_empty_store_is_noop():
    m, rt = _mk(SUM_APP, store=InMemoryPersistenceStore())
    rt.shutdown()
    rt2 = m.create_siddhi_app_runtime(SUM_APP, recover=True)
    assert rt2.recovered_revision is None
    assert rt2.resilience_metrics.recovered.value() == 0
    rt2.shutdown()


# ------------------------------------------------------- kill-and-recover

CHILD_TEMPLATE = '''
import os, sys, time
sys.path.insert(0, {repo!r})
from siddhi_tpu import (FileSystemPersistenceStore, SiddhiManager,
                        StreamCallback)

K, TARGET, EXTRA = {k}, {target}, {extra}
APP = {app!r}

store = FileSystemPersistenceStore({snapdir!r})
m = SiddhiManager()
m.set_persistence_store(store)
rt = m.create_siddhi_app_runtime(APP)
outf = open({outpath!r}, "a")

def cb(evs):
    for e in evs:
        outf.write(repr(float(e.data[0])) + chr(10))
        outf.flush()
        os.fsync(outf.fileno())

rt.add_callback("Out", StreamCallback(cb))
rt.start()
h = rt.get_input_handler("S")
for i in range(1, TARGET + EXTRA + 1):
    h.send([float(i)])
    if i % K == 0 and i <= TARGET:
        rt.persist()                     # durable up to offset i …
        tmp = {ackpath!r} + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(i)); f.flush(); os.fsync(f.fileno())
        os.replace(tmp, {ackpath!r})     # … acked atomically
with open({readypath!r} + ".tmp", "w") as f:
    f.write("ready"); f.flush(); os.fsync(f.fileno())
os.replace({readypath!r} + ".tmp", {readypath!r})
while True:                              # hold unpersisted tail in memory
    time.sleep(1)
'''


def test_sigkill_recover_replay_no_event_loss(tmp_path):
    """The acceptance scenario: a child engine checkpoints every K=25
    events, is SIGKILLed holding 15 unpersisted events, and a recovered
    runtime replays from the last acked offset.  Every match appears at
    least once; duplicates are bounded by one checkpoint interval."""
    K, TARGET, EXTRA = 25, 200, 15
    snapdir = str(tmp_path / "snaps")
    outpath = str(tmp_path / "out.txt")
    ackpath = str(tmp_path / "ack")
    readypath = str(tmp_path / "ready")
    script = tmp_path / "child.py"
    script.write_text(CHILD_TEMPLATE.format(
        repo=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        k=K, target=TARGET, extra=EXTRA, app=SUM_APP, snapdir=snapdir,
        outpath=outpath, ackpath=ackpath, readypath=readypath))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, str(script)], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        deadline = time.monotonic() + 180
        while not os.path.exists(readypath):
            if proc.poll() is not None:
                raise AssertionError(
                    "child engine died early:\n" +
                    proc.stderr.read().decode(errors="replace"))
            if time.monotonic() > deadline:
                raise AssertionError("child engine never reached ready")
            time.sleep(0.1)
        acked = int(open(ackpath).read())
        assert acked == TARGET
        os.kill(proc.pid, signal.SIGKILL)     # crash mid-stream
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    pre_crash = [float(line) for line in open(outpath)]
    m = SiddhiManager()
    m.set_persistence_store(FileSystemPersistenceStore(snapdir))
    rt = m.create_siddhi_app_runtime(SUM_APP, recover=True)
    assert rt.recovered_revision is not None, "recovery found no checkpoint"
    post = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: post.extend(float(e.data[0]) for e in evs)))
    rt.start()
    M = TARGET + EXTRA
    for i in range(acked + 1, M + 1):        # replay from last acked offset
        rt.get_input_handler("S").send([float(i)])
    rt.shutdown()

    # the restored accumulator held exactly sum(1..acked): replaying the
    # tail lands on the true total — state loss or tail inclusion in the
    # snapshot would both break this
    want_total = float(M * (M + 1) // 2)
    assert post[-1] == pytest.approx(want_total)
    # every match (running total T_i) observed at least once …
    want = {float(i * (i + 1) // 2) for i in range(1, M + 1)}
    seen = set(pre_crash) | set(post)
    assert want <= seen, f"lost matches: {sorted(want - seen)[:5]}"
    # … and duplicates bounded by one checkpoint interval
    dup = [v for v in post if v in set(pre_crash)]
    assert len(dup) <= K, f"{len(dup)} duplicate matches > interval K={K}"


# ========================================================= snapshot hygiene

def test_torn_snapshot_raises_typed_error(tmp_path):
    store = FileSystemPersistenceStore(str(tmp_path))
    m, rt = _mk(SUM_APP, store=store)
    rt.start()
    rt.get_input_handler("S").send([3.0])
    rev = rt.persist()
    rt.shutdown()
    blob = store.load("ckapp", rev)
    store.save("ckapp", rev, chaos.tear(blob, seed=5, mode="truncate"))

    m2, rt2 = _mk(SUM_APP, store=store)
    with pytest.raises(CannotRestoreStateError):
        rt2.restore_last_revision()
    rt2.shutdown()


def test_tearing_store_first_save_detected():
    store = chaos.TearingStore(InMemoryPersistenceStore(),
                               tear_ordinals=(1,), seed=9, mode="flip")
    m, rt = _mk(SUM_APP, store=store)
    rt.start()
    rt.get_input_handler("S").send([1.0])
    rt.persist()                                  # torn write
    rt.get_input_handler("S").send([1.0])
    rt.persist()                                  # clean write
    rt.shutdown()
    m2, rt2 = _mk(SUM_APP, store=store)
    rt2.restore_last_revision()                   # newest revision is clean
    assert store.saves == 2
    rt2.shutdown()


def test_filesystem_save_is_atomic_no_tmp_residue(tmp_path):
    fs = FileSystemPersistenceStore(str(tmp_path))
    fs.save("app", "100_app_full", b"payload")
    fs.save("app", "100_app_full", b"payload2")   # overwrite in place
    assert fs.load("app", "100_app_full") == b"payload2"
    leftovers = [p for root, _, files in os.walk(tmp_path)
                 for p in files if p.endswith(".tmp")]
    assert leftovers == [], "atomic save must not leave temp files"


def test_revision_ordering_is_numeric_not_lexicographic(tmp_path):
    fs = FileSystemPersistenceStore(str(tmp_path))
    fs.save("app", "9_app_full", b"old")
    fs.save("app", "10_app_full", b"new")         # lexicographically smaller
    assert fs.last_revision("app") == "10_app_full"
    assert fs.revisions("app") == ["9_app_full", "10_app_full"]
    mem = InMemoryPersistenceStore()
    mem.save("app", "9_app_full", b"old")
    mem.save("app", "10_app_full", b"new")
    assert mem.last_revision("app") == "10_app_full"


def test_persist_revisions_unique_under_burst():
    """Back-to-back persists within one millisecond must not collide on
    the same revision name (strictly-monotonic stamps)."""
    store = InMemoryPersistenceStore()
    m, rt = _mk(SUM_APP, store=store)
    rt.start()
    revs = [rt.persist() for _ in range(5)]
    assert len(set(revs)) == 5
    assert store.revisions(rt.name) == sorted(
        revs, key=lambda r: int(r.split("_")[0]))
    rt.shutdown()


# ==================================================== NFA batching × snapshot

PATTERN_APP = """
define stream A (v float);
@info(name='q')
from every e1=A[v > 10.0] -> e2=A[v > e1.v]
select e1.v as v1, e2.v as v2 insert into Out;
"""


@pytest.mark.parametrize("b_persist,b_restore", [(4, 1), (1, 4)])
def test_snapshot_compatible_across_nfa_batch_b(monkeypatch, b_persist,
                                                b_restore):
    """B changes the scan tick shape, not the carry layout: a snapshot
    persisted under SIDDHI_TPU_NFA_BATCH=4 restores at B=1 (and vice
    versa) and the armed partial match still completes."""
    from siddhi_tpu.ops.nfa import BATCH_ENV
    store = InMemoryPersistenceStore()
    monkeypatch.setenv(BATCH_ENV, str(b_persist))
    m = SiddhiManager()
    m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime(PATTERN_APP)
    assert rt.query_runtimes["q"].backend == "device"
    rt.start()
    rt.get_input_handler("A").send([11.0], timestamp=1_000_000)
    rev = rt.persist()
    rt.shutdown()

    monkeypatch.setenv(BATCH_ENV, str(b_restore))
    rt2 = m.create_siddhi_app_runtime(PATTERN_APP)
    out = []
    rt2.add_callback("Out", StreamCallback(
        lambda evs: out.extend(tuple(e.data) for e in evs)))
    rt2.start()
    rt2.restore_revision(rev)
    rt2.get_input_handler("A").send([12.0], timestamp=1_000_100)
    rt2.shutdown()
    assert out == [(11.0, 12.0)], \
        f"partial armed at B={b_persist} must complete after B={b_restore}"


# ============================================================ chaos harness

def test_source_connect_retries_through_chaos():
    chaos.reset()
    chaos.SCRIPTS["src"] = chaos.FailureScript.fail_n(2)
    _, rt = _mk("""
        @source(type='chaos', chaos.id='src', retry.base.delay.ms='1',
                retry.jitter='0')
        define stream s (v int);
        @info(name='q') from s select v insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: got.extend(e.data[0] for e in evs)))
    rt.start()
    src = chaos.INSTANCES["src"]
    assert src.connected and src.connect_attempts == 3
    src.emit([7])
    rt.shutdown()
    assert got == [7]


def test_chunk_scrambler_is_seeded_deterministic():
    class Rec:
        def __init__(self):
            self.rows = []

        def receive_chunk(self, chunk):
            self.rows.extend(e.data[0] for e in chunk.to_events())

    def run():
        _, rt = _mk("define stream s (v int);\n"
                    "@info(name='q') from s select v insert into Out;")
        rec = Rec()
        sc = chaos.ChunkScrambler(rec, seed=3, duplicate_rate=0.3)
        rt.junctions["Out"].subscribe(sc)
        rt.start()
        h = rt.get_input_handler("s")
        for i in range(20):
            h.send([i])
        assert rec.rows == []                 # held until release
        sc.release()
        rt.shutdown()
        return rec.rows

    a, b = run(), run()
    assert a == b, "same seed must scramble identically"
    assert sorted(set(a)) == list(range(20))  # nothing lost
    assert len(a) > 20                        # seeded duplicates occurred
    assert a != sorted(a)                     # seeded reorder occurred


def test_inject_fault_wraps_and_restores():
    class Obj:
        def step(self, x):
            return x * 2

    o = Obj()
    script = chaos.FailureScript.fail_n(1)
    restore = chaos.inject_fault(o, "step", script, error_cls=ValueError)
    with pytest.raises(ValueError):
        o.step(1)
    assert o.step(2) == 4
    restore()
    assert script.calls == 2 and script.failures == 1
