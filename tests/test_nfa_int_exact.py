"""Round-5 exact INT/LONG NFA capture payloads: selected integer attrs
ride three companion event lanes (hi 22 / mid 21 / lo 21 bits of the
sign-biased value — each exact in float32) through the same capture
banks, and decode reassembles the exact int64.  Retires the r4 plan-time
2^24 warning for payloads (conditions keep a narrowed warning).
Reference: event/stream/StreamEvent.java typed payload segments."""
import warnings

import numpy as np
import pytest

from siddhi_tpu import QueryCallback, SiddhiManager

S = "define stream S (sym string, vol long, q int, n int);\n"


def run(app, rows, engine=None):
    m = SiddhiManager()
    pre = "@app:playback " + (f"@app:engine('{engine}') " if engine else "")
    rt = m.create_siddhi_app_runtime(pre + app)
    got = []
    rt.add_callback("q", QueryCallback(lambda ts, cur, exp: got.extend(
        tuple(e.data) for e in (cur or []))))
    rt.start()
    h = rt.get_input_handler("S")
    t = 1_000_000
    for row in rows:
        h.send(row, timestamp=t)
        t += 100
    backend = rt.query_runtimes["q"].backend
    rt.shutdown()
    return backend, got


def parity(app, rows):
    bd, dev = run(app, rows)
    bh, host = run(app, rows, engine="host")
    assert bd == "device" and bh == "host"
    assert dev == host, f"dev={dev[:4]} host={host[:4]}"
    return dev


BIG = [(1 << 53) + 12345, -(1 << 40) - 7, (1 << 62) + 999,
       -(1 << 62) - 1, 2 ** 63 - 1, -(2 ** 63), 0, -1, 16_777_217]


def test_long_capture_exact_beyond_2_24():
    app = S + """@info(name='q')
    from every e1=S[n == 0] -> e2=S[n == 1]
    select e1.vol as v1, e2.vol as v2 insert into Out;"""
    rows = []
    for i in range(0, len(BIG) - 1, 2):
        rows.append(["a", BIG[i], 100 + i, 0])
        rows.append(["a", BIG[i + 1], 100 + i, 1])
    out = parity(app, rows)
    assert out and all(isinstance(v, (int, np.integer)) for r in out
                       for v in r)
    assert out[0] == (BIG[0], BIG[1])


def test_int_capture_exact():
    app = S + """@info(name='q')
    from every e1=S[n == 0] -> e2=S[n == 1]
    select e1.q as a, e2.q as b insert into Out;"""
    big_i = 2 ** 31 - 1
    rows = [["a", 1, big_i, 0], ["a", 1, -(2 ** 31), 1],
            ["a", 1, 16_777_217, 0], ["a", 1, 16_777_219, 1]]
    out = parity(app, rows)
    assert (big_i, -(2 ** 31)) in out and (16_777_217, 16_777_219) in out


def test_kleene_last_bank_exact():
    """Companion lanes ride the kleene last/index banks too."""
    app = S + """@info(name='q')
    from every e1=S[n == 0]<1:3> -> e2=S[n == 1]
    select e1[0].vol as a, e1[last].vol as b, e2.vol as g
    insert into Out;"""
    v1, v2, v3 = (1 << 52) + 3, (1 << 52) + 4, (1 << 52) + 5
    rows = [["a", v1, 0, 0], ["a", v2, 0, 0], ["a", v3, 0, 1]]
    out = parity(app, rows)
    assert (v1, v2, v3) in out


def test_payload_warning_retired_condition_warning_kept():
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        run(S + """@info(name='q')
        from every e1=S[n == 0] -> e2=S[n == 1]
        select e1.vol as v1 insert into Out;""", [["a", 1, 1, 0]])
    assert not [x for x in w if "NFA" in str(x.message)], \
        "payload-only integer selects must not warn"
    with warnings.catch_warnings(record=True) as w2:
        warnings.simplefilter("always")
        run(S + """@info(name='q')
        from every e1=S[n == 0] -> e2=S[vol > e1.vol]
        select e1.sym as s1 insert into Out;""", [["a", 1, 1, 0]])
    assert [x for x in w2 if "CONDITION" in str(x.message)], \
        "cross-state integer CONDITION compares keep the f32 warning"
