"""Absent/timer boundary stress (VERDICT r2 next #10): dense real events
interleaved with `not … for t` deadlines landing exactly at block
boundaries and TIMER-granularity edges, device vs host oracle.

The device path injects host-scheduled TIMER rows (stream code -2) through
the same NFA lanes as real events (ops/nfa.py make_timer_block); between
host scheduling granularity and device block boundaries there is an
ordering seam — these tests pin it to the oracle at the edges where it
would crack: deadline == block edge, deadline == event ts, deadlines with
no quiet gap, cascading absents, and re-arm floods.

Reference: AbsentStreamPreStateProcessor.java:63-96,231 (waitingTime
scheduling), util/Scheduler.java:180-211 (TIMER injection).
"""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback

STREAMS = """
define stream A (k int, v float);
define stream B (k int, w float);
define stream C (k int, u float);
"""


def run_app(app, batches, engine=None, until=None):
    """batches: list of either ('advance', ts) or a list of
    (stream, row, ts) sends delivered as ONE batch per stream in order —
    each batch is one device block (one junction chunk)."""
    prefix = f"@app:engine('{engine}') " if engine else ""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(prefix + app)
    out = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: out.extend(tuple(e.data) for e in evs)))
    rt.start()
    for batch in batches:
        if isinstance(batch, tuple) and batch[0] == "advance":
            rt.app_ctx.timestamp_generator.observe_event_time(batch[1])
            rt.app_ctx.scheduler.advance_to(batch[1])
            continue
        for sid, row, ts in batch:
            rt.get_input_handler(sid).send(row, timestamp=ts)
    if until is not None:
        rt.app_ctx.timestamp_generator.observe_event_time(until)
        rt.app_ctx.scheduler.advance_to(until)
    backend = rt.query_runtimes["q"].backend
    reason = rt.query_runtimes["q"].backend_reason
    rt.shutdown()
    return backend, reason, out


def assert_parity(app, batches, until=None, expect_device=True):
    bh, _, host = run_app(app, batches, engine="host", until=until)
    bd, reason, dev = run_app(app, batches, until=until)
    assert bh == "host"
    if expect_device:
        assert bd == "device", f"did not plan onto the device: {reason}"
    assert host == dev, f"host={host} dev={dev}"
    return host


def A(ts, v, k=1):
    return ("A", [k, v], ts)


def B(ts, w, k=1):
    return ("B", [k, w], ts)


def C(ts, u, k=1):
    return ("C", [k, u], ts)


ABSENT_APP = "@app:playback " + STREAMS + """
    @info(name='q')
    from every e1=A[v > 20.0] -> not B[w > e1.v] for 1 sec
    select e1.v as v1 insert into Out;
"""

ABSENT_THEN_APP = "@app:playback " + STREAMS + """
    @info(name='q')
    from every e1=A[v > 20.0] -> not B[w > e1.v] for 1 sec -> e3=C[u > 0.0]
    select e1.v as v1, e3.u as u3 insert into Out;
"""

CASCADE_APP = "@app:playback " + STREAMS + """
    @info(name='q')
    from every e1=A[v > 20.0] -> not B[w > 0.0] for 1 sec
         -> not C[u > 0.0] for 1 sec
    select e1.v as v1 insert into Out;
"""


# ------------------------------------------------- deadline at block edges

@pytest.mark.parametrize("edge_delta", [-1, 0, 1])
def test_deadline_at_block_boundary(edge_delta):
    """The arming block ends right where the deadline lands (±1 ms): a
    real event opens the next block exactly at/around deadline ts 2000."""
    batches = [
        [A(1000, 25.0)],                       # block 1: arm; deadline 2000
        [A(2000 + edge_delta, 30.0)],          # block 2 opens at the edge
    ]
    assert_parity(ABSENT_APP, batches, until=4000)


@pytest.mark.parametrize("gap", [0, 1, 999, 1000])
def test_dense_events_straddling_deadline(gap):
    """Dense A traffic while an earlier partial's deadline expires
    mid-block; suppressing B lands `gap` ms before the deadline."""
    batches = [
        [A(1000, 25.0), A(1200, 26.0), A(1400, 27.0)],
        [B(2000 - gap, 26.5)],                 # kills partials with v<26.5
        [A(2100, 30.0), A(2300, 31.0)],
        [B(2350, 100.0)],                      # kills everything armed
    ]
    assert_parity(ABSENT_APP, batches, until=5000)


def test_same_ts_event_and_deadline():
    """An event carrying EXACTLY the deadline timestamp — the oracle
    fires the absent at ts >= deadline before routing decisions differ."""
    batches = [
        [A(1000, 25.0)],
        [C(2000, 5.0)],        # C at the exact deadline of e1's absent
        [C(2500, 7.0)],
    ]
    assert_parity(ABSENT_THEN_APP, batches, until=4000)


def test_absent_then_state_captures_next_event():
    """After the quiet period confirms, the NEXT C completes — the device
    slot advancing on the deadline must capture events after, not at,
    the confirmation."""
    batches = [
        [A(1000, 25.0)],
        [C(1500, 3.0)],                  # before deadline: must NOT match
        ("advance", 2000),               # deadline fires between blocks
        [C(2200, 4.0)],                  # first C after confirmation
    ]
    assert_parity(ABSENT_THEN_APP, batches, until=4000)


# ---------------------------------------------------- cascading absents

def test_cascading_absents_quiet_stream():
    """A then two quiet seconds → both absents confirm off pure TIMER
    advances (no real events in between)."""
    assert_parity(CASCADE_APP, [[A(1000, 25.0)]], until=3500)


def test_cascading_absents_second_killed():
    """First absent confirms at 2000; a C inside the second window kills
    the chain."""
    batches = [
        [A(1000, 25.0)],
        ("advance", 2000),
        [C(2500, 1.0)],
    ]
    assert_parity(CASCADE_APP, batches, until=4000)


def test_cascading_absents_advance_exactly_on_deadlines():
    """Virtual time advanced to EXACTLY each cascaded deadline, one at a
    time (TIMER granularity edge: timers fire at notify_at precision)."""
    batches = [
        [A(1000, 25.0)],
        ("advance", 2000),
        ("advance", 3000),
    ]
    assert_parity(CASCADE_APP, batches, until=3000)


# ------------------------------------------------------- re-arm pressure

def test_rearm_flood_with_absent_deadlines():
    """Many armed partials with staggered deadlines expiring across block
    boundaries; every A re-arms (slot pressure + deadline bookkeeping)."""
    rng = np.random.default_rng(5)
    batches = []
    t = 1000
    for _ in range(6):
        blk = []
        for _ in range(4):
            blk.append(A(t, float(21 + rng.integers(0, 40))))
            t += rng.integers(100, 400)
        batches.append(blk)
        if rng.integers(0, 2):
            batches.append([B(t, float(rng.integers(10, 70)))])
            t += 150
    assert_parity(ABSENT_APP, batches, until=t + 3000)


def test_partitioned_absent_deadlines_per_key():
    """Keyed lanes: each key's deadline fires independently; blocks mix
    keys so TIMER rows fan out across lanes."""
    app = "@app:playback " + """
    define stream S (sym string, price float, kind int);
    partition with (sym of S) begin
    @info(name='q')
    from every e1=S[kind == 0] -> not S[kind == 1 and price > e1.price] for 1 sec
    select e1.price as p1 insert into Out;
    end;
    """

    def run(engine):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(
            f"@app:engine('{engine}') {app}" if engine else app)
        out = []
        rt.add_callback("Out", StreamCallback(
            lambda evs: out.extend(tuple(e.data) for e in evs)))
        rt.start()
        h = rt.get_input_handler("S")
        sends = [("a", 10.0, 0, 1000), ("b", 20.0, 0, 1300),
                 ("a", 50.0, 1, 1600),          # kills a's partial
                 ("c", 30.0, 0, 1900)]
        for sym, price, kind, ts in sends:
            h.send([sym, price, kind], timestamp=ts)
        rt.app_ctx.timestamp_generator.observe_event_time(4000)
        rt.app_ctx.scheduler.advance_to(4000)
        dev = any(pr.device_mode for pr in rt.partition_runtimes)
        rt.shutdown()
        return dev, sorted(out)

    dev_hit, dev = run(None)
    _, host = run("host")
    assert dev_hit and dev == host and len(host) == 2


# ------------------------------------------------------- sequence mode

def test_sequence_absent_compiles_to_device_and_exact():
    """SEQUENCE + absent compiles to the device since round 4 (the
    stabilize barrier clears absent pendings before every real event);
    the deadline fires in the event-free gap — device == host."""
    app = "@app:playback " + STREAMS + """
        @info(name='q')
        from e1=A[v > 20.0], not B[w > e1.v] for 1 sec
        select e1.v as v1 insert into Out;
    """
    b, _reason, out = run_app(
        app, [[A(1000, 25.0)], [("advance", 2000)][0:0] or
              [A(2000, 5.0)]], until=3000)
    assert b == "device"
    assert out == [(25.0,)]
