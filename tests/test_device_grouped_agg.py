"""Device grouped/running aggregation conformance (VERDICT r2 next #4+#8):
group-by finer than the partition key, no-window running aggregates,
minForever/maxForever, and EXACT INT/LONG sums on the device kernel
(ops/grouped_agg.py via plan/gagg_compiler.py) — byte-identical to the
host oracle through the public API.

Reference: query/selector/QuerySelector.java:44-224 (per-group aggregator
maps), GroupByKeyGenerator.java, SumAttributeAggregatorExecutor typed
variants."""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback

STREAM = "define stream S (sym string, user string, price float, " \
         "volume long);\n"


def run_app(app, sends, engine=None, batch=None):
    prefix = "@app:playback "
    if engine:
        prefix += f"@app:engine('{engine}') "
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(prefix + app)
    out = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: out.extend(tuple(e.data) for e in evs)))
    rt.start()
    if batch is not None:
        rt.get_input_handler("S").send_batch(batch[0], timestamps=batch[1])
    else:
        for row, ts in sends:
            rt.get_input_handler("S").send(row, timestamp=ts)
    backends = {n: q.backend for n, q in rt.query_runtimes.items()}
    prs = rt.partition_runtimes
    device = any(b == "device" for b in backends.values()) or \
        any(pr.device_mode for pr in prs)
    rt.shutdown()
    return device, out


def _norm(rows):
    """Float payloads compare through float32 (the conformance-corpus
    convention, tests/ref_harness._norm): the host accumulates float64,
    the device Kahan-compensated float32 — equal at f32 precision."""
    return [tuple(float(np.float32(v)) if isinstance(v, float) else v
                  for v in r) for r in rows]


def assert_parity(app, sends=None, batch=None, expect_device=True,
                  unordered=False):
    """unordered: host partition clones process a chunk's events grouped
    by key (an oracle chunking artifact — the reference routes per event,
    which is the order the device path preserves), so batch sends through
    partitions compare as multisets."""
    _, host = run_app(app, sends, engine="host", batch=batch)
    dev_hit, dev = run_app(app, sends, batch=batch)
    assert dev_hit == expect_device, f"device={dev_hit}"
    norm = (lambda x: sorted(_norm(x), key=repr)) if unordered else _norm
    assert norm(host) == norm(dev), \
        f"host={host[:6]}... dev={dev[:6]}..."
    assert len(host) > 0
    return host


def _rows(n=40, seed=2, n_sym=3, n_user=5, vol_max=1000):
    rng = np.random.default_rng(seed)
    sends = []
    for i in range(n):
        sends.append(([f"s{rng.integers(0, n_sym)}",
                       f"u{rng.integers(0, n_user)}",
                       float(np.float32(rng.uniform(1, 100))),
                       int(rng.integers(-vol_max, vol_max))],
                      1_000_000 + i * 100))
    return sends


def test_groupby_in_length_window():
    app = STREAM + """
        @info(name='q') from S#window.length(5)
        select sym, sum(price) as t, count() as c, avg(price) as a
        group by sym insert into Out;"""
    assert_parity(app, _rows())


def test_mixed_aggregate_arguments():
    """Distinct aggregate arguments — float AND int banks in one query."""
    app = STREAM + """
        @info(name='q') from S#window.length(4)
        select sym, sum(volume) as tv, avg(price) as ap,
               max(price) as mp, min(volume) as mv
        group by sym insert into Out;"""
    assert_parity(app, _rows(vol_max=2_000_000_000))


def test_groupby_two_keys():
    app = STREAM + """
        @info(name='q') from S#window.length(4)
        select sym, user, sum(price) as t group by sym, user
        insert into Out;"""
    assert_parity(app, _rows())


def test_running_aggregates_no_window():
    app = STREAM + """
        @info(name='q') from S[price > 10.0]
        select sym, sum(price) as t, min(price) as mn, max(price) as mx
        group by sym insert into Out;"""
    assert_parity(app, _rows())


def test_exact_int_sum_window_and_running():
    app = STREAM + """
        @info(name='q') from S#window.length(3)
        select sym, sum(volume) as tv, min(volume) as mn,
               max(volume) as mx
        group by sym insert into Out;"""
    host = assert_parity(app, _rows(vol_max=2_000_000_000))
    assert all(isinstance(r[1], (int, np.integer)) for r in host)

    app2 = STREAM + """
        @info(name='q') from S select sum(volume) as tv insert into Out;"""
    assert_parity(app2, _rows(vol_max=2_000_000_000))


def test_min_max_forever():
    app = STREAM + """
        @info(name='q') from S#window.length(2)
        select sym, maxForever(price) as mf, minForever(price) as nf
        group by sym insert into Out;"""
    assert_parity(app, _rows())


def test_partitioned_finer_groupby():
    """Partition by sym, group by user — the VERDICT #4 shape: lanes are
    partition keys, groups are finer."""
    app = """
    define stream S (sym string, user string, price float, volume long);
    partition with (sym of S) begin
    @info(name='q') from S#window.length(3)
    select sym, user, sum(price) as t, count() as c group by user
    insert into Out;
    end;"""
    sends = _rows(n=60)
    batch = ({"sym": np.asarray([r[0][0] for r in sends], object),
              "user": np.asarray([r[0][1] for r in sends], object),
              "price": np.asarray([r[0][2] for r in sends], np.float32),
              "volume": np.asarray([r[0][3] for r in sends], np.int64)},
             np.asarray([r[1] for r in sends], np.int64))
    host = assert_parity(app, batch=batch, unordered=True)
    assert len(host) == 60
    # per-event sends: exact order parity (no oracle chunking artifact)
    assert_parity(app, sends[:30])


def test_partitioned_running_int_sum():
    app = """
    define stream S (sym string, user string, price float, volume long);
    partition with (sym of S) begin
    @info(name='q') from S select user, sum(volume) as tv group by user
    insert into Out;
    end;"""
    assert_parity(app, _rows(n=50, vol_max=1_500_000_000))


def test_group_capacity_growth():
    """More groups than the initial slab capacity (G_START=8)."""
    app = STREAM + """
        @info(name='q') from S#window.length(3)
        select user, sum(price) as t group by user insert into Out;"""
    assert_parity(app, _rows(n=120, n_user=40))


def test_snapshot_restore_grouped():
    app = STREAM + """
        @info(name='q') from S#window.length(3)
        select sym, sum(volume) as tv group by sym insert into Out;"""
    sends = _rows(n=30, vol_max=1_000_000_000)

    def run(engine, restore_mid):
        m = SiddhiManager()
        pre = f"@app:playback @app:engine('{engine}') " if engine else \
            "@app:playback "
        rt = m.create_siddhi_app_runtime(pre + app)
        out = []
        cb = StreamCallback(lambda evs: out.extend(tuple(e.data)
                                                   for e in evs))
        rt.add_callback("Out", cb)
        rt.start()
        h = rt.get_input_handler("S")
        for i, (row, ts) in enumerate(sends):
            h.send(row, timestamp=ts)
            if restore_mid and i == 14:
                snap = rt.snapshot()
                rt.shutdown()
                rt = m.create_siddhi_app_runtime(pre + app)
                rt.restore(snap)
                rt.add_callback("Out", cb)
                rt.start()
                h = rt.get_input_handler("S")
        rt.shutdown()
        return out

    assert run("host", False) == run(None, True)


def test_oversized_int_value_is_data_error():
    """|v| >= 2^31 cannot ride i32 lanes: the chunk is a runtime data
    error routed through the junction's @OnError boundary (LOG mode drops
    it), never a silently wrong sum."""
    app = STREAM + """
        @info(name='q') from S select sum(volume) as tv insert into Out;"""
    dev_hit, out = run_app(
        app, [(["s0", "u0", 1.0, 100], 1_000_000),
              (["s0", "u0", 1.0, 3_000_000_000], 1_000_100),
              (["s0", "u0", 1.0, 50], 1_000_200)])
    assert dev_hit
    # first chunk aggregated; the oversized chunk dropped with a logged
    # error; stream keeps running
    assert out[0] == (100,) and out[-1][0] <= 150


def test_device_rejects_unsupported_to_host():
    """Selection shapes the egress select kernel cannot express fall
    back from the grouped-agg kernel with a recorded reason.  (having
    on a float-sum output used to be in this list wholesale; it now
    compiles into the device selection step — plan/select_compiler.py —
    as lengthBatch and stdDev moved off this list in earlier rounds.)"""
    for frag in (
            # exact int64 sums do not fit the two-float compare lanes
            "select sym, sum(volume) as t group by sym having t > 10",
            # avg needs float64 division at selection time
            "select sym, avg(price) as m group by sym having m > 1.0",):
        app = STREAM + f"@info(name='q') from S{'' if frag.startswith('s') else ''}" \
            + ("" if frag.startswith("#") else " ") + frag + \
            " insert into Out;"
        dev_hit, _ = run_app(app, _rows(n=10))
        assert not dev_hit, frag
    # limit over a sliding window shares selector slots with expired
    # rows: the dwin hybrid may still own the window buffer, but the
    # selection tail itself must report the host route
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "@app:playback " + STREAM + "@info(name='q') from "
        "S#window.length(4) select sym, sum(price) as t group by sym "
        "limit 2 insert into Out;")
    route = rt.query_runtimes["q"].selection_route
    assert route["backend"] == "host", route
    assert "expired" in route["reason"], route
    rt.shutdown()
    # burned-down shape: float-sum having now rides the device path
    app = STREAM + "@info(name='q') from S select sym, sum(price) as t " \
        "group by sym having t > 10.0 insert into Out;"
    dev_hit, _ = run_app(app, _rows(n=10))
    assert dev_hit, "float-sum having should ride the device select step"
    app = STREAM + "@info(name='q') from S#window.lengthBatch(3) " \
        "select sum(price) as t insert into Out;"
    dev_hit, _ = run_app(app, _rows(n=10))
    assert dev_hit, "lengthBatch should ride the device window path"


def test_int_minmax_only_has_no_count_bound():
    """Running min/max/count of ints need no exact-sum guard: groups can
    exceed 2^15 events (review finding: the INT_GROUP_MAX guard must key
    on sum/avg outputs, not on any int lane existing)."""
    app = STREAM + """
        @info(name='q') from S
        select min(volume) as mn, max(volume) as mx, count() as c
        insert into Out;"""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("@app:playback " + app)
    out = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: out.extend(tuple(e.data) for e in evs)))
    rt.start()
    h = rt.get_input_handler("S")
    n = (1 << 15) + 10
    rng = np.random.default_rng(0)
    vols = rng.integers(-1000, 1000, n)
    h.send_batch({"sym": np.full(n, "a", object),
                  "user": np.full(n, "u", object),
                  "price": np.ones(n, np.float32),
                  "volume": vols.astype(np.int64)},
                 timestamps=1_000_000 + np.arange(n, dtype=np.int64))
    assert rt.query_runtimes["q"].backend == "device"
    rt.shutdown()
    assert out[-1] == (int(vols.min()), int(vols.max()), n)


def test_infinite_float_values_propagate():
    """±inf inputs must reach min/max outputs (host parity), not clamp at
    ±F32_MAX (review finding: forever-lane sentinels)."""
    app = STREAM + """
        @info(name='q') from S
        select sym, min(price) as mn, maxForever(price) as mf
        group by sym insert into Out;"""
    sends = [(["a", "u", float("inf"), 1], 1_000_000),
             (["a", "u", 5.0, 1], 1_000_100),
             (["a", "u", float("-inf"), 1], 1_000_200)]
    assert_parity(app, sends)


def test_filtered_out_keys_allocate_no_groups():
    """Filter-rejected events must not grow the group slab (review
    finding: gid allocation ran before the ok mask)."""
    app = STREAM + """
        @info(name='q') from S[price > 1000.0]
        select user, sum(price) as t group by user insert into Out;"""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("@app:playback " + app)
    rt.start()
    h = rt.get_input_handler("S")
    for i in range(50):       # 50 distinct users, all filtered out
        h.send([f"s", f"u{i}", 1.0, 1], timestamp=1_000_000 + i * 100)
    qr = rt.query_runtimes["q"]
    assert qr.backend == "device"
    cga = qr.device_runtime.cga
    assert len(cga.gid_map) == 0 and cga.n_groups == 8, \
        (len(cga.gid_map), cga.n_groups)
    rt.shutdown()


def test_time_window_groupby_device():
    """Grouped sliding time windows on device: masked-expiry ring with a
    gid plane (ops/grouped_agg.build_grouped_time_step)."""
    app = STREAM + """
        @info(name='q') from S#window.time(1 sec)
        select sym, sum(price) as t, count() as c, min(price) as mn
        group by sym insert into Out;"""
    sends = []
    rng = np.random.default_rng(8)
    for i in range(40):
        sends.append(([f"s{rng.integers(0, 3)}", "u",
                       float(np.float32(rng.uniform(1, 100))), 1],
                      1_000_000 + i * 150))   # expiries interleave
    assert_parity(app, sends)


def test_external_time_window_groupby_int_sum_device():
    app = """
    define stream S (sym string, ets long, volume long);
    @info(name='q') from S#window.externalTime(ets, 1 sec)
    select sym, sum(volume) as tv, count() as c group by sym
    insert into Out;"""
    sends = []
    rng = np.random.default_rng(9)
    ets = 5_000_000
    for i in range(40):
        ets += int(rng.integers(50, 400))
        sends.append(([f"s{rng.integers(0, 3)}", ets,
                       int(rng.integers(-1_000_000_000, 1_000_000_000))],
                      1_000_000 + i * 100))
    assert_parity(app, sends)


def test_time_window_ring_growth_replay():
    """More in-window entries than the initial ring capacity (64): the
    grouped time ring must grow-and-replay, exactly."""
    app = STREAM + """
        @info(name='q') from S#window.time(10 sec)
        select sym, sum(price) as t, count() as c group by sym
        insert into Out;"""
    sends = []
    rng = np.random.default_rng(10)
    for i in range(200):                 # all within 10s of each other
        sends.append(([f"s{rng.integers(0, 2)}", "u",
                       float(np.float32(rng.uniform(1, 100))), 1],
                      1_000_000 + i * 40))
    host = assert_parity(app, sends)
    assert len(host) == 200


def test_partitioned_time_window_finer_groupby():
    app = """
    define stream S (sym string, user string, price float, volume long);
    partition with (sym of S) begin
    @info(name='q') from S#window.time(1 sec)
    select sym, user, sum(volume) as tv group by user insert into Out;
    end;"""
    assert_parity(app, _rows(n=50, vol_max=1_000_000_000))


def test_external_time_junk_ts_on_rejected_rows():
    """Filter-rejected rows carrying junk timestamps (ets=0 beside
    epoch-ms values) must not pin or blow the i32 time base (review:
    rebase must consider ACCEPTED rows only)."""
    app = """
    define stream S (sym string, ets long, volume long, kind int);
    @info(name='q') from S[kind == 1]#window.externalTime(ets, 1 sec)
    select sym, sum(volume) as tv group by sym insert into Out;"""
    epoch = 1_700_000_000_000
    sends = [(["a", epoch, 7, 1], 1_000_000),
             (["a", 0, 999, 0], 1_000_100),        # rejected, junk ets
             (["a", epoch + 500, 9, 1], 1_000_200)]
    out = assert_parity(app, sends)
    assert out == [("a", 7), ("a", 16)]


def test_group_count_bound_raises_with_consistent_state():
    """ADVICE r3: the >=2^15-events running-int-sum bound must restore
    the pre-block carry before raising, so @OnError continuation sees the
    offending chunk fully un-applied (not half-aggregated)."""
    from siddhi_tpu.ops.grouped_agg import INT_GROUP_MAX
    from siddhi_tpu.plan.gagg_compiler import CompiledGroupedAgg
    import siddhi_tpu.ops.grouped_agg as ga
    app = STREAM + """
        @info(name='q') from S select sum(volume) as tv insert into Out;"""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("@app:playback " + app)
    out = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: out.extend(tuple(e.data) for e in evs)))
    rt.start()
    qr = rt.query_runtimes["q"]
    assert qr.backend == "device"
    cga = qr.device_runtime.cga
    h = rt.get_input_handler("S")
    h.send(["a", "u", 1.0, 7], timestamp=1_000_000)
    carry_before = [np.asarray(a).copy() for a in cga.carry]
    # force the bound: pretend the group already accumulated 2^15 events
    cga.carry = type(cga.carry)(*[
        a if i != cga.carry._fields.index("gcnt")
        else np.full_like(np.asarray(a), INT_GROUP_MAX)
        for i, a in enumerate(cga.carry)])
    carry_forced = [np.asarray(a).copy() for a in cga.carry]
    h.send(["a", "u", 1.0, 9], timestamp=1_000_100)   # raises via @OnError
    after = [np.asarray(a) for a in cga.carry]
    # the offending chunk is fully un-applied: carry == pre-chunk carry
    assert all((x == y).all() for x, y in zip(carry_forced, after))
    rt.shutdown()
    assert out == [(7,)]


def test_stddev_randomized_parity():
    """stdDev lowers onto sum/sum-of-squares lanes (TwoSum pairs); device
    matches the host's float64 mean/meanSq formula at f32-normalized
    precision (the suite-wide float contract, _norm)."""
    app = STREAM + """
        @info(name='q') from S
        select sym, stdDev(price) as sd group by sym insert into Out;"""
    assert_parity(app, _rows(n=60))
    app2 = STREAM + """
        @info(name='q') from S#window.length(5)
        select stdDev(price) as sd insert into Out;"""
    assert_parity(app2, _rows(n=40))
