"""Management + trigger conformance ported from the reference corpus
(siddhi-core/src/test/java/io/siddhi/core/managment/ValidateTestCase,
StatisticsTestCase, PlaybackTestCase shapes; query/trigger/TriggerTestCase).
Behaviors mirrored; assertions are the reference tests' expectations."""
import time

import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.utils.errors import (DuplicateDefinitionError,
                                     SiddhiAppCreationError)


# ------------------------------------------------------ ValidateTestCase

def test_validate_ok():
    """validateTest1: a well-formed app validates without starting."""
    SiddhiManager().validate_siddhi_app("""
        @app:name('validateTest')
        define stream cseEventStream (symbol string, price float,
                                      volume long);
        @info(name='query1')
        from cseEventStream[symbol is null]
        select symbol, price insert into outputStream;""")


def test_validate_unknown_stream_raises():
    """validateTest2: querying an undefined stream fails validation."""
    with pytest.raises(SiddhiAppCreationError):
        SiddhiManager().validate_siddhi_app("""
            @app:name('validateTest')
            define stream cseEventStream (symbol string, price float,
                                          volume long);
            @info(name='query1')
            from cseEventStreamA[symbol is null]
            select symbol, price insert into outputStream;""")


# ------------------------------------------------------- TriggerTestCase

def test_trigger_duplicate_stream_id_raises():
    """testQuery3: a trigger whose id collides with a stream definition."""
    with pytest.raises(DuplicateDefinitionError):
        SiddhiManager().create_siddhi_app_runtime("""
            define stream StockStream (symbol string, price float,
                                       volume long);
            define trigger StockStream at 'start';""")


def test_trigger_at_start_fires_once():
    """testQuery5: `at 'start'` emits exactly one event on start()."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream cseEventStream (symbol string, price float,
                                      volume long);
        define trigger triggerStream at 'start';""")
    got = []
    rt.add_callback("triggerStream", StreamCallback(
        lambda evs: got.extend(list(e.data) for e in evs)))
    rt.start()
    rt.shutdown()
    assert len(got) == 1
    assert got[0][0] > 0          # triggered_time is the wall clock


def test_trigger_periodic_under_playback():
    """testQuery6 (deterministic): `at every 500 milliseconds` fires once
    per elapsed period of the virtual clock."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:playback
        define stream cseEventStream (symbol string);
        define trigger triggerStream at every 500 milliseconds;""")
    got = []
    rt.add_callback("triggerStream", StreamCallback(
        lambda evs: got.extend(list(e.data) for e in evs)))
    rt.start()
    rt.app_ctx.timestamp_generator.observe_event_time(1)
    rt.app_ctx.scheduler.advance_to(1101)
    rt.shutdown()
    assert len(got) == 2          # two full 500ms periods in ~1.1s


def test_trigger_cron_under_playback():
    """testQuery7 (deterministic): a cron trigger fires once per second."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:playback
        define stream cseEventStream (symbol string);
        define trigger triggerStream at '*/1 * * * * ?';""")
    got = []
    rt.add_callback("triggerStream", StreamCallback(
        lambda evs: got.extend(e.data[0] for e in evs)))
    rt.start()
    rt.app_ctx.timestamp_generator.observe_event_time(1_000)
    rt.app_ctx.scheduler.advance_to(3_500)
    rt.shutdown()
    assert len(got) >= 2
    diffs = [b - a for a, b in zip(got, got[1:])]
    assert all(d == 1000 for d in diffs), got


def test_trigger_feeds_query():
    """Trigger stream consumed by a normal query (reference trigger tests
    route triggerStream into downstream queries)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:playback
        define stream S (v int);
        define trigger tick at every 1 sec;
        @info(name='q')
        from tick select triggered_time insert into Out;""")
    got = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: got.extend(list(e.data) for e in evs)))
    rt.start()
    rt.app_ctx.timestamp_generator.observe_event_time(0)
    rt.app_ctx.scheduler.advance_to(2_500)
    rt.shutdown()
    assert len(got) == 2


# ---------------------------------------------------- StatisticsTestCase

def test_statistics_track_throughput_and_latency():
    """statisticsTest1 shape: @app:statistics tracks per-junction
    throughput and per-query latency for processed events."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:statistics(reporter='console', interval='60')
        define stream S (v int);
        @info(name='q') from S[v > 0] select v insert into Out;""")
    rt.start()
    h = rt.get_input_handler("S")
    for i in range(5):
        h.send([i + 1])
    snap = rt.app_ctx.statistics_manager.snapshot()
    rt.shutdown()
    text = str(snap)
    assert snap, "statistics snapshot empty"
    assert "S" in text or any("S" in str(k) for k in getattr(
        snap, "keys", lambda: [])()), snap


def test_statistics_runtime_toggle():
    """Statistics can be enabled at runtime (SiddhiAppRuntime.enableStats)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (v int);
        @info(name='q') from S select v insert into Out;""")
    rt.start()
    rt.enable_stats(True)
    rt.get_input_handler("S").send([1])
    assert rt.app_ctx.stats_enabled
    rt.enable_stats(False)
    rt.shutdown()


# ------------------------------------------------------ PlaybackTestCase

def test_playback_time_window_advances_on_event_time():
    """playbackTest1 shape: in @app:playback a time window expires by event
    timestamps, not wall clock."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:playback
        define stream cse (symbol string, price float, volume int);
        @info(name='query1')
        from cse#window.time(1 sec)
        select symbol, sum(volume) as total insert into outputStream;""")
    got = []
    rt.add_callback("outputStream", StreamCallback(
        lambda evs: got.extend(tuple(e.data) for e in evs)))
    rt.start()
    h = rt.get_input_handler("cse")
    h.send(["IBM", 1.0, 10], timestamp=1_000_000)
    h.send(["IBM", 1.0, 20], timestamp=1_000_100)
    # virtual clock jumps 2s: both events expire before the next arrival
    rt.app_ctx.timestamp_generator.observe_event_time(1_002_000)
    rt.app_ctx.scheduler.advance_to(1_002_000)
    h.send(["IBM", 1.0, 40], timestamp=1_002_100)
    rt.shutdown()
    assert got == [("IBM", 10), ("IBM", 30), ("IBM", 40)]


def test_playback_heartbeat_is_not_wall_clock():
    """No wall-clock leakage: without virtual-time advance a time window
    never expires, no matter how much real time passes."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:playback
        define stream cse (symbol string, volume int);
        @info(name='query1')
        from cse#window.time(10)
        select symbol, sum(volume) as total insert into outputStream;""")
    got = []
    rt.add_callback("outputStream", StreamCallback(
        lambda evs: got.extend(tuple(e.data) for e in evs)))
    rt.start()
    h = rt.get_input_handler("cse")
    h.send(["IBM", 10], timestamp=1_000_000)
    time.sleep(0.05)              # real time passes; virtual clock frozen
    h.send(["IBM", 20], timestamp=1_000_001)
    rt.shutdown()
    assert got == [("IBM", 10), ("IBM", 30)]
