"""Every SiddhiQL app shipped in samples/ must be clean under the
semantic analyzer: zero errors, and warnings only from the explicit
per-sample allowlist below.  A new sample that trips SA/SP warnings
either gets fixed or earns an allowlist entry with a justification —
silent hazard creep in the showcase code is a test failure."""
import ast
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from siddhi_tpu.analysis import analyze  # noqa: E402

SAMPLES_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "samples")

# sample file -> warning codes it is ALLOWED to emit (with why)
EXPECTED_WARNINGS = {
    # registers `custom:plus` via set_extension at runtime — the analyzer
    # cannot see runtime extension registration, SA007 is by design
    "quickstart_extension.py": {"SA007"},
    # the table-fill phase intentionally appends to a PK-less table to
    # measure raw insert throughput
    "tpu_join_performance.py": {"SA021"},
    "table_performance.py": {"SA021"},
}


def _apps_in(path):
    """Extract every SiddhiQL app string literal from a sample .py —
    plain strings verbatim; f-string placeholders tried as '0' (numeric
    slots like thresholds) and '' (optional-annotation slots), keeping
    whichever variant parses.  Short fragments without ';' are not apps."""
    tree = ast.parse(open(path).read())
    apps = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if "define stream" in node.value and ";" in node.value:
                apps.append([node.value])
        elif isinstance(node, ast.JoinedStr):
            variants = []
            for filler in ("0", ""):
                text = "".join(str(v.value) if isinstance(v, ast.Constant)
                               else filler for v in node.values)
                variants.append(text)
            if "define stream" in variants[0] and ";" in variants[0]:
                apps.append(variants)
    # drop fragments that are substrings of another extracted app
    return [v for v in apps
            if not any(v is not w and v[0] in w[0] for w in apps)]


def _sample_files():
    return sorted(f for f in os.listdir(SAMPLES_DIR) if f.endswith(".py"))


@pytest.mark.parametrize("fname", _sample_files())
def test_sample_apps_are_diagnostic_clean(fname):
    apps = _apps_in(os.path.join(SAMPLES_DIR, fname))
    assert apps, f"{fname}: no SiddhiQL app string found"
    allowed = EXPECTED_WARNINGS.get(fname, set())
    for i, variants in enumerate(apps):
        # pick the first placeholder variant that parses; if none does,
        # the first one's SA000 is reported below
        results = [analyze(v) for v in variants]
        r = next((x for x in results if "SA000" not in x.codes()),
                 results[0])
        assert not r.errors, (
            f"{fname} app #{i} has analyzer ERRORS:\n" +
            "\n".join(d.render(fname) for d in r.errors))
        unexpected = {d.code for d in r.warnings} - allowed
        assert not unexpected, (
            f"{fname} app #{i} emits warnings {sorted(unexpected)} not in "
            f"the expected-warning allowlist:\n" +
            "\n".join(d.render(fname) for d in r.warnings
                      if d.code in unexpected))
