"""Built-in function conformance matrix modeled on the reference executor
function tests (executor/function/* — cast, convert, coalesce, ifThenElse,
instanceOf×6, UUID, maximum/minimum, default, math:/str: namespaces —
and query/extension/ custom function registration).
"""
import pytest

from ref_harness import run_query

CSE = ("define stream cse (symbol string, price float, volume long, "
       "quantity int, available bool, ratio double);\n")
Q = "@info(name = 'query1') "
ROW = ("WSO2", 50.0, 100, 5, True, 2.25)


def _run_select(select_expr, expected_value):
    run_query(CSE + Q + f"""
        from cse select {select_expr} as v insert into out;""",
        [("cse", list(ROW))],
        [(expected_value,)])


SELECT_CASES = [
    ("coalesce(symbol, 'none')", "WSO2"),
    ("ifThenElse(price > 40.0, 'high', 'low')", "high"),
    ("ifThenElse(price < 40.0, 'high', 'low')", "low"),
    ("cast(quantity, 'long')", 5),
    ("cast(price, 'double')", 50.0),
    ("cast(volume, 'string')", "100"),
    ("convert(price, 'int')", 50),
    ("convert(quantity, 'float')", 5.0),
    ("maximum(price, ratio)", 50.0),
    ("minimum(price, ratio)", 2.25),
    ("maximum(quantity, volume)", 100),
    ("default(symbol, 'X')", "WSO2"),
    ("instanceOfInteger(quantity)", True),
    ("instanceOfInteger(price)", False),
    ("instanceOfLong(volume)", True),
    ("instanceOfFloat(price)", True),
    ("instanceOfDouble(ratio)", True),
    ("instanceOfBoolean(available)", True),
    ("instanceOfString(symbol)", True),
    ("instanceOfString(volume)", False),
    ("math:abs(0.0f - price)", 50.0),
    ("math:ceil(ratio)", 3.0),
    ("math:floor(ratio)", 2.0),
    ("math:sqrt(quantity)", 2.23606797749979),
    ("math:round(ratio)", 2.0),
    ("math:power(quantity, 2)", 25.0),
    ("str:concat(symbol, '-', 'X')", "WSO2-X"),
    ("str:length(symbol)", 4),
    ("str:upper(symbol)", "WSO2"),
    ("str:lower(symbol)", "wso2"),
    ("str:trim(' a ')", "a"),
    ("str:reverse(symbol)", "2OSW"),
    ("str:contains(symbol, 'SO')", True),
    ("quantity + volume * 2", 205),
    ("(quantity + volume) * 2", 210),
    ("volume % 30", 10),
]


@pytest.mark.parametrize("expr,expected", SELECT_CASES,
                         ids=[c[0] for c in SELECT_CASES])
def test_function_select(expr, expected):
    _run_select(expr, expected)


def test_uuid_is_unique_string():
    got = []
    from siddhi_tpu import SiddhiManager, StreamCallback
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(CSE + Q + """
        from cse select UUID() as u insert into out;""")
    rt.add_callback("out", StreamCallback(
        lambda evs: got.extend(e.data[0] for e in evs)))
    rt.start()
    h = rt.get_input_handler("cse")
    h.send(list(ROW))
    h.send(list(ROW))
    rt.shutdown()
    assert len(got) == 2 and got[0] != got[1]
    assert all(isinstance(u, str) and len(u) == 36 for u in got)


def test_event_timestamp():
    run_query(CSE + Q + """
        from cse select eventTimestamp() as ts insert into out;""",
        [("cse", list(ROW), 123456)],
        [(123456,)])


def test_is_null_condition():
    run_query("""
        define stream S (a string, b int);
        @info(name = 'query1')
        from S[not (a is null)] select a, b insert into out;""",
        [("S", [None, 1]), ("S", ["x", 2])],
        [("x", 2)])


def test_in_table_condition():
    run_query("""
        define stream Seed (s string);
        define stream S (s string);
        define table T (s string);
        from Seed select s insert into T;
        @info(name = 'query1')
        from S[S.s in T] select s insert into out;""",
        [("Seed", ["ok"]), ("S", ["ok"]), ("S", ["nope"])],
        [("ok",)])


def test_custom_function_extension():
    # ≙ reference query/extension CustomFunctionExtension via
    # siddhiManager.setExtension
    import numpy as np

    from siddhi_tpu import SiddhiManager, StreamCallback
    from siddhi_tpu.utils.extension import FunctionExtension

    class Tripple(FunctionExtension):
        def apply(self, vals):
            return np.asarray([None if v is None else v * 3
                               for v in np.asarray(vals, object)], object)

    m = SiddhiManager()
    m.set_extension("custom:tripple", Tripple)
    rt = m.create_siddhi_app_runtime("""
        define stream S (v int);
        from S select custom:tripple(v) as t insert into out;""")
    got = []
    rt.add_callback("out", StreamCallback(
        lambda evs: got.extend(e.data[0] for e in evs)))
    rt.start()
    rt.get_input_handler("S").send([7])
    rt.shutdown()
    assert got == [21]
