"""Flight recorder + incident bundles (core/flight.py, observability PR).

The always-on bounded ring of per-block records, the incident bus
(watchdog trips, circuit-breaker OPEN, quarantine bursts, buffer
overflow, junction exceptions, on-demand), bundle dump/retention, the
SIDDHI_TPU_FLIGHT kill switch, and the REST surface
(GET /incidents, GET /incidents/{id}/bundle,
POST /siddhi/apps/{app}/debug/bundle, GET /siddhi/apps/{app}/trace).
"""
import json
import os
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from siddhi_tpu import QueryCallback, SiddhiManager, StreamCallback  # noqa: E402
from siddhi_tpu.core.flight import (FlightRecorder, flight,  # noqa: E402
                                    flight_enabled)
from siddhi_tpu.core.resilience import InMemoryErrorStore  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_flight(tmp_path, monkeypatch):
    """The recorder is process-global; isolate each test and point the
    bundle directory at tmp so tests never litter the real one."""
    monkeypatch.setenv("SIDDHI_TPU_FLIGHT_DIR", str(tmp_path / "bundles"))
    flight().reset()
    yield
    flight().reset()
    from siddhi_tpu.core.profiling import profiler
    from siddhi_tpu.core.tracing import tracer
    profiler().disable()
    profiler().reset()
    tracer().disable()
    tracer().clear()


# -------------------------------------------------------------- the ring

def test_ring_records_ingest_blocks():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (v float);
        @info(name='q') from S[v > 1.0] select v insert into Out;
    """)
    rt.add_callback("Out", StreamCallback(lambda evs: None))
    rt.start()
    h = rt.get_input_handler("S")
    for i in range(5):
        h.send([float(i)])
    rt.flush()
    ring = flight().ring()
    rt.shutdown()
    # compile rows (round 16) interleave with ingest rows on the
    # same ring — filter to the ingest records for this stream
    recs = [r for r in ring if r.get("stream") == "S"]
    assert len(recs) == 5
    r = recs[-1]
    assert r["app"] == rt.name and r["batch"] == 1
    assert {"block", "t", "dispatches", "scan_ticks",
            "queue_depth", "saturation"} <= set(r)
    blocks = [r["block"] for r in recs]
    assert blocks == sorted(blocks)


def test_kill_switch_disables_ring_and_bus(monkeypatch):
    monkeypatch.setenv("SIDDHI_TPU_FLIGHT", "0")
    assert not flight_enabled()
    fl = flight()
    fl.record_block("a", stream="S", batch=1)
    assert fl.ring() == []
    assert fl.emit("on_demand", app="a") is None
    assert fl.incidents() == []


def test_ring_capacity_and_bundle_retention(tmp_path):
    fr = FlightRecorder(capacity=4, keep=2)
    for i in range(10):
        fr.record_block("a", stream="S", batch=i)
    assert len(fr.ring()) == 4
    assert [r["batch"] for r in fr.ring()] == [6, 7, 8, 9]
    ids = [fr.emit(f"k{i}", app="a")["id"] for i in range(3)]
    # all three incidents stay listed, only the newest 2 bundles retained
    assert [i["id"] for i in fr.incidents()] == ids
    assert fr.bundle(ids[0]) is None
    assert fr.bundle(ids[1]) is not None and fr.bundle(ids[2]) is not None
    d = os.environ["SIDDHI_TPU_FLIGHT_DIR"]
    kept = sorted(p for p in os.listdir(d) if p.endswith(".json"))
    assert len(kept) == 2


def test_errors_ride_the_ring():
    fl = flight()
    fl.note_error("a", "S", ValueError("boom"))
    fl.record_block("a", stream="S", batch=1)
    rec = fl.ring()[-1]
    assert rec["last_error"]["error"] == "ValueError: boom"
    assert rec["last_error"]["where"] == "S"


# ---------------------------------------------------------- incident bus

def test_watchdog_trip_emits_readable_bundle():
    """Forced SESSION_REARM_PATHOLOGY dispatch storm: the watchdog trip
    must land a 'watchdog_trip' bundle whose detail is the WD001
    incident and whose ring shows the blocks leading up to it."""
    import siddhi_tpu.plan.dwin_compiler as dwc
    cse = "define stream cse (symbol string, price float, volume long);\n"
    app = ("@app:playback " + cse +
           "@info(name='q') from cse#window.session(700, symbol) "
           "select symbol, price, volume insert all events into out;")
    dwc.SESSION_REARM_PATHOLOGY = True
    try:
        m = SiddhiManager()
        m.siddhi_context.error_store = InMemoryErrorStore()
        rt = m.create_siddhi_app_runtime(app)
        rt.add_callback("q", QueryCallback(lambda *a: None))
        rt.start()
        h = rt.get_input_handler("cse")

        def send(sym, ts):
            h.send_batch(
                {"symbol": np.asarray([sym], object),
                 "price": np.asarray([1.0], np.float32),
                 "volume": np.asarray([ts], np.int64)},
                np.asarray([ts], np.int64))

        send("A", 1000)
        send("C", 50_000)          # un-guarded: a ~49k-fire 1 ms crawl
        assert rt.watchdog.incidents, "storm did not trip the watchdog"
        incs = flight().incidents()
        assert any(i["kind"] == "watchdog_trip" for i in incs)
        bid = next(i["id"] for i in incs if i["kind"] == "watchdog_trip")
        bundle = flight().bundle(bid)
        assert bundle["detail"]["code"] == "WD001"
        assert bundle["app"] == rt.name
        assert any(r.get("stream") == "cse" for r in bundle["ring"])
        assert "env" in bundle and "config" in bundle
        json.dumps(bundle)         # fully JSON-serializable = readable
        d = os.environ["SIDDHI_TPU_FLIGHT_DIR"]
        assert json.load(open(os.path.join(d, f"{bid}.json")))["id"] == bid
        rt.shutdown()
        m.shutdown()
    finally:
        dwc.SESSION_REARM_PATHOLOGY = False


def test_circuit_open_emits_bundle():
    """A sink breaker's CLOSED -> OPEN transition is an incident."""
    import chaos
    chaos.reset()
    chaos.SCRIPTS["flightcb"] = chaos.FailureScript.fail_always()
    m = SiddhiManager()
    chaos.register(m)
    rt = m.create_siddhi_app_runtime("""
        @app:name('cbapp')
        define stream S (v int);
        @sink(type='chaos', chaos.id='flightcb', retry.max.attempts='1',
              retry.base.delay.ms='1', retry.jitter='0',
              circuit.failure.threshold='2', circuit.reset.ms='60000')
        define stream O (v int);
        @info(name='q') from S select v insert into O;
    """)
    rt.start()
    h = rt.get_input_handler("S")
    for i in range(6):
        h.send([i])
    assert chaos.INSTANCES["flightcb"].retry_join(30.0)
    incs = flight().incidents()
    assert any(i["kind"] == "circuit_open" and i["app"] == "cbapp"
               for i in incs), incs
    bid = next(i["id"] for i in incs if i["kind"] == "circuit_open")
    bundle = flight().bundle(bid)
    assert bundle["detail"]["sink"] == "O"
    assert bundle["detail"]["from"] == "closed"
    rt.shutdown()
    m.shutdown()


def test_quarantine_burst_emits_bundle(monkeypatch):
    """A single routing call rejecting >= the burst threshold is an
    incident (mass-poison feeds are a fault, not background noise)."""
    monkeypatch.setenv("SIDDHI_TPU_FLIGHT_QUARANTINE_BURST", "5")
    m = SiddhiManager()
    m.set_error_store(InMemoryErrorStore())
    rt = m.create_siddhi_app_runtime("""
        @quarantine(ts.slack.ms='1000')
        define stream In (symbol string, price float, volume long);
        @info(name='q') from In select symbol, price, volume
        insert into Out;
    """)
    rt.add_callback("Out", StreamCallback(lambda evs: None))
    rt.start()
    h = rt.get_input_handler("In")
    nan = float("nan")
    h.send_batch({"symbol": np.asarray(["A"] * 8, object),
                  "price": np.asarray([nan] * 8, np.float32),
                  "volume": np.arange(8, dtype=np.int64)},
                 timestamps=1_000_000 + np.arange(8, dtype=np.int64))
    rt.flush()
    incs = flight().incidents()
    assert any(i["kind"] == "quarantine_burst" for i in incs), incs
    bid = next(i["id"] for i in incs if i["kind"] == "quarantine_burst")
    bundle = flight().bundle(bid)
    assert bundle["detail"]["rejected"] >= 5
    assert bundle["detail"]["stream"] == "In"
    rt.shutdown()
    m.shutdown()


def test_junction_exception_emits_bundle():
    """An uncaught subscriber exception (OnError LOG path) lands a
    'junction_exception' bundle and notes the error for the ring."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (v int);
        @info(name='q') from S select v insert into Out;
    """)

    def boom(evs):
        raise RuntimeError("subscriber exploded")

    rt.add_callback("Out", StreamCallback(boom))
    rt.start()
    rt.get_input_handler("S").send([1])
    rt.flush()
    incs = flight().incidents()
    assert any(i["kind"] == "junction_exception" for i in incs), incs
    rt.shutdown()


# ------------------------------------------------------------------ REST

APP = """
@app:name('flightapp')
@app:statistics(reporter='console', interval='300', tracing='true',
                telemetry='true')
define stream S (sym string, price float);
@info(name='q')
from every e1=S[price > 10.0] -> e2=S[price > e1.price]
select e1.price as p1, e2.price as p2 insert into Out;
"""


def _req(method, url, payload=None):
    data = None
    if payload is not None:
        data = (payload if isinstance(payload, str)
                else json.dumps(payload)).encode()
    req = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read().decode())


def test_rest_incident_surface():
    from siddhi_tpu.service.rest import SiddhiService
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        _req("POST", f"{base}/siddhi/artifact/deploy", APP)
        rng = np.random.default_rng(0)
        _req("POST", f"{base}/siddhi/apps/flightapp/streams/S",
             [{"data": ["A", float(rng.uniform(5, 30))]}
              for _ in range(20)])
        svc.manager.get_siddhi_app_runtime("flightapp").flush()

        assert _req("GET", f"{base}/incidents") == {"incidents": []}

        out = _req("POST", f"{base}/siddhi/apps/flightapp/debug/bundle",
                   {"note": "operator snapshot"})
        assert out["kind"] == "on_demand"
        incs = _req("GET", f"{base}/incidents")["incidents"]
        assert [i["id"] for i in incs] == [out["id"]]

        bundle = _req("GET", f"{base}/incidents/{out['id']}/bundle")
        assert bundle["detail"]["note"] == "operator snapshot"
        # 20 ingest rows; compile rows (round 16) ride the same ring
        ingest = [r for r in bundle["ring"] if r.get("stream")]
        assert len(ingest) == 20
        assert any("compile" in r for r in bundle["ring"])
        assert any(ln.startswith("siddhi_kernel_")
                   for ln in bundle["metrics"])
        assert bundle["trace"]["traceEvents"]
        assert bundle["statistics"]["telemetry"]["nfa"]["q"]

        # unknown bundle id → 404
        try:
            _req("GET", f"{base}/incidents/inc-9999/bundle")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404

        # Chrome-trace parity route
        doc = _req("GET", f"{base}/siddhi/apps/flightapp/trace")
        assert doc["traceEvents"] and doc["displayTimeUnit"] == "ms"
        names = {e["name"] for e in doc["traceEvents"]}
        assert "ingest.chunk" in names
    finally:
        svc.stop()


def test_rest_bundle_409_when_disabled(monkeypatch):
    from siddhi_tpu.service.rest import SiddhiService
    monkeypatch.setenv("SIDDHI_TPU_FLIGHT", "0")
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        _req("POST", f"{base}/siddhi/artifact/deploy", APP)
        try:
            _req("POST", f"{base}/siddhi/apps/flightapp/debug/bundle", {})
            assert False, "expected 409"
        except urllib.error.HTTPError as e:
            assert e.code == 409
    finally:
        svc.stop()
