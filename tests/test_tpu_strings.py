"""Dictionary-encoded string attributes on the device NFA path: equality
conditions and cross-state string captures ride integer code lanes; any
other string usage falls back to the host cleanly (the regression this
guards: a string condition used to plan onto the device and then crash at
ingest, silently dropping events)."""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback

APP = """
define stream Trades (symbol string, price float);
@info(name='q')
from every e1=Trades[symbol == 'IBM' and price > 100.0]
    -> e2=Trades[symbol == e1.symbol and price > e1.price]
    within 10 sec
select e1.symbol as sym, e1.price as p1, e2.price as p2
insert into Alerts;
"""

SENDS = [("IBM", 101.0), ("WSO2", 150.0), ("IBM", 120.0),
         ("IBM", 90.0), ("IBM", 130.0), ("MSFT", 200.0)]


def run(app, sends, engine=None, out="Alerts", persist_mid=False):
    m = SiddhiManager()
    prefix = f"@app:engine('{engine}') " if engine else ""
    if persist_mid:
        from siddhi_tpu.core.snapshot import InMemoryPersistenceStore
        m.set_persistence_store(InMemoryPersistenceStore())
    rt = m.create_siddhi_app_runtime(prefix + app)
    got = []
    rt.add_callback(out, StreamCallback(
        lambda evs: got.extend(tuple(e.data) for e in evs)))
    rt.start()
    ts = 1_000_000
    mid = len(sends) // 2
    for i, (sym, price) in enumerate(sends):
        rt.get_input_handler("Trades").send([sym, price], timestamp=ts)
        ts += 100
        if persist_mid and i == mid:
            snap = rt.snapshot()
            rt.restore(snap)
    backend = rt.query_runtimes["q"].backend
    reason = rt.query_runtimes["q"].backend_reason
    rt.shutdown()
    return backend, reason, got


def test_string_equality_and_capture_parity():
    bh, _, host = run(APP, SENDS, engine="host")
    bd, reason, dev = run(APP, SENDS)
    assert bh == "host"
    assert bd == "device", reason
    assert host == dev
    assert host == [("IBM", 101.0, 120.0), ("IBM", 120.0, 130.0)]


def test_string_not_equal_parity():
    app = APP.replace("symbol == e1.symbol", "symbol != e1.symbol")
    bh, _, host = run(app, SENDS, engine="host")
    bd, reason, dev = run(app, SENDS)
    assert bd == "device", reason
    assert host == dev and len(host) > 0


def test_string_order_vs_constant_compiles_cross_state_falls_back():
    # round 4: order-vs-constant lowers onto a host-computed 0/1 lane
    app = APP.replace("symbol == 'IBM'", "symbol > 'A'")
    bd, _reason, dev = run(app, SENDS)
    bh, _r2, host = run(app, SENDS, engine="host")
    assert bd == "device" and bh == "host" and dev == host
    # cross-state string ORDER still has no lane form
    app2 = APP.replace("symbol == 'IBM'", "symbol > 'Z'").replace(
        "price > e1.price", "price > e1.price and symbol > e1.symbol")
    bd2, reason2, _ = run(app2, SENDS)
    assert bd2 == "host" and "ORDER" in (reason2 or "")


def test_string_function_falls_back():
    app = APP.replace("symbol == 'IBM'", "str:length(symbol) == 3")
    bd, _, _ = run(app, SENDS)
    assert bd == "host"


def test_string_events_are_not_silently_dropped():
    """The original bug: device-planned string condition crashed at ingest
    and the junction swallowed it — zero output while the host produced
    matches. Whatever the backend, output must equal the host's."""
    app = """
    define stream Trades (symbol string, price float);
    @info(name='q')
    from every e1=Trades[symbol == 'IBM' and price > 100.0]
        -> e2=Trades[price > e1.price] within 10 sec
    select e1.price as p1, e2.price as p2 insert into Alerts;
    """
    _, _, host = run(app, SENDS, engine="host")
    _, _, auto = run(app, SENDS)
    assert auto == host and len(host) > 0


def test_string_dictionary_survives_snapshot_restore():
    bh, _, host = run(APP, SENDS, engine="host")
    bd, _, dev = run(APP, SENDS, persist_mid=True)
    assert bd == "device"
    assert dev == host


def test_null_strings_never_match_like_host():
    """Host compare executors treat null operands as false; null codes (0)
    must behave identically on the device — null==null and null!='X' are
    both false."""
    sends = [(None, 101.0), (None, 120.0), ("IBM", 150.0),
             (None, 200.0), ("IBM", 250.0)]
    for app in (APP,
                APP.replace("symbol == e1.symbol",
                            "symbol != e1.symbol")):
        bh, _, host = run(app, sends, engine="host")
        bd, reason, dev = run(app, sends)
        assert bd == "device", reason
        assert host == dev, (app, host, dev)


def test_partitioned_string_pattern_parity():
    """String conditions inside a keyed partition (lanes + dictionary)."""
    app = """
    define stream Trades (acct int, symbol string, price float);
    partition with (acct of Trades) begin
    @info(name='q')
    from every e1=Trades[symbol == 'IBM'] ->
         e2=Trades[symbol == e1.symbol and price > e1.price]
        within 10 sec
    select e1.symbol as sym, e2.price as p2 insert into Alerts;
    end;
    """
    rng = np.random.default_rng(3)
    syms = ["IBM", "WSO2", "MSFT"]
    sends = []
    ts = 1_000_000
    rows = []
    for _ in range(60):
        rows.append([int(rng.integers(0, 4)),
                     syms[int(rng.integers(0, 3))],
                     float(np.round(rng.uniform(0, 100), 1))])

    def run_part(engine=None):
        m = SiddhiManager()
        prefix = (f"@app:engine('{engine}') " if engine else "")
        rt = m.create_siddhi_app_runtime(prefix + "@app:playback " + app)
        got = []
        rt.add_callback("Alerts", StreamCallback(
            lambda evs: got.extend(tuple(e.data) for e in evs)))
        rt.start()
        t = 1_000_000
        for r in rows:
            rt.get_input_handler("Trades").send(r, timestamp=t)
            t += 10
        dm = rt.partition_runtimes[0].device_mode
        rt.shutdown()
        return dm, got

    dm_h, host = run_part("host")
    dm_d, dev = run_part()
    assert not dm_h and dm_d
    assert sorted(host) == sorted(dev)
    assert len(host) > 0
