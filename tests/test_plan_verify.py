"""Plan-level verifier, liveness pruning and static cost model
(PR 3 tentpole): every PV/PC code fires at least once (asserted against
the catalog), pruning is proven match-output-identical on randomized
feeds, and the cost model's HBM predictions are byte-exact against the
real carries."""
import os
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from siddhi_tpu import SiddhiManager, StreamCallback  # noqa: E402
from siddhi_tpu.analysis import CATALOG  # noqa: E402
from siddhi_tpu.analysis.cost_model import (DEFAULT_FLOPS_WARN,  # noqa: E402
                                            bank_state_bytes,
                                            cost_diagnostics,
                                            nfa_flops_per_event, plan_cost,
                                            nfa_state_bytes)
from siddhi_tpu.analysis.plan_ir import (AutomatonIR, StateIR,  # noqa: E402
                                         automaton_ir_from_nfa,
                                         extract_plan)
from siddhi_tpu.analysis.plan_verify import (sanitize_step,  # noqa: E402
                                             verify_automaton, verify_plan)
from siddhi_tpu.plan.nfa_compiler import CompiledPatternNFA  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STREAM = "define stream S (price float, kind int);\n"


def _nfa(app, **kw):
    kw.setdefault("n_partitions", 2)
    kw.setdefault("mesh", None)
    return CompiledPatternNFA(STREAM + app, **kw)


def _feed(n=240, seed=0, parts=2):
    rng = np.random.default_rng(seed)
    pids = rng.integers(0, parts, n).astype(np.int64)
    cols = {"price": rng.uniform(0, 100, n).astype(np.float32),
            "kind": rng.integers(0, 3, n).astype(np.float32)}
    ts = 1_000_000 + np.cumsum(rng.integers(0, 800, n)).astype(np.int64)
    return pids, cols, ts


def _matches(nfa, feed):
    pids, cols, ts = feed
    return nfa.process_events(pids, cols, ts)


def _ir(**kw):
    """Minimal hand-built AutomatonIR for table-shape tests."""
    states = kw.pop("states")
    defaults = dict(query="q", transitions=[], start_states=(0,),
                    within_ms=None, n_partitions=1, n_slots=8,
                    n_rows=len(states), n_caps=1, n_attrs=2)
    defaults.update(kw)
    return AutomatonIR(states=states, **defaults)


def _codes(diags):
    return {d.code for d in diags}


# ================================================== automaton verification

def test_pv001_dangling_transition():
    a = _ir(states=[StateIR(0, "simple", ("S",), ("e1",))],
            transitions=[(0, "advance", 5)])
    codes = _codes(verify_automaton(a))
    assert codes == {"PV001"} and "PV001" in CATALOG


def test_pv002_accept_unreachable_graph():
    a = _ir(states=[StateIR(0, "simple", ("S",), ("e1",)),
                    StateIR(1, "simple", ("S",), ("e2",))],
            transitions=[(0, "stay", 0), (1, "accept", 2)])
    codes = _codes(verify_automaton(a))
    assert "PV002" in codes          # accept unreachable from start
    assert "PV003" in codes          # s1 unreachable


def test_pv005_within_starved_absent():
    a = _ir(states=[StateIR(0, "simple", ("S",), ("e1",)),
                    StateIR(1, "absent", ("S",), ("e2",),
                            waiting_ms=10_000)],
            transitions=[(0, "advance", 1), (1, "accept", 2)],
            within_ms=5_000)
    assert "PV005" in _codes(verify_automaton(a))


def test_pv005_from_real_app():
    # the absence needs 10s to confirm but every partial dies at 5s
    nfa = _nfa("from every e1=S[kind == 0] -> e2=S[kind == 1] -> "
               "not S[kind == 2] for 10 sec within 5 sec "
               "select e1.price as p1 insert into Out;")
    ir = automaton_ir_from_nfa(nfa, "q")
    assert "PV005" in _codes(verify_automaton(ir))


def test_clean_chain_no_pv_findings():
    nfa = _nfa("from every e1=S[kind == 0] -> e2=S[kind == 1] "
               "within 10 sec select e1.price as p1 insert into Out;")
    diags = verify_automaton(automaton_ir_from_nfa(nfa, "q"))
    assert not [d for d in diags if d.code.startswith("PV")]


def test_healthy_mid_chain_min0_kleene_not_flagged():
    # a LIVE min-0 kleene is epsilon-skipped but keeps appending — it
    # must be reachable in the derived table (no spurious PV003)
    nfa = _nfa("from e1=S[kind == 0] -> e2=S[kind == 2]<0:3> -> "
               "e3=S[kind == 1] "
               "select e1.price as p1, e3.price as p3 insert into Out;")
    assert nfa.prune_report["pruned_states"] == 0
    diags = verify_automaton(automaton_ir_from_nfa(nfa, "q"))
    assert not [d for d in diags if d.code.startswith("PV")], \
        [d.render() for d in diags]


# ================================================== liveness pruning

DEAD_APP = ("from e1=S[kind == 0 and 1 > 2] -> e2=S[kind == 1] "
            "select e1.price as p1 insert into Out;")
PRUNABLE_KLEENE = ("from e1=S[kind == 0] -> "
                   "e2=S[kind == 2 and 1 == 2]<0:3> -> e3=S[kind == 1] "
                   "select e1.price as p1, e3.price as p3 insert into Out;")
PRUNABLE_OR = ("from e1=S[kind == 0] -> "
               "e2=S[kind == 1] or e3=S[kind == 2 and 1 > 3] "
               "select e1.price as p1 insert into Out;")
SIMPLIFIABLE = ("from every e1=S[kind == 0 and 2 > 1] -> "
                "e2=S[kind == 1 and price > e1.price] within 20 sec "
                "select e1.price as p1, e2.price as p2 insert into Out;")


def test_dead_pattern_detected_and_step_skipped():
    nfa = _nfa(DEAD_APP)
    assert nfa.statically_dead and nfa.prune_report["dead"]
    assert _matches(nfa, _feed()) == []
    # PV002 rides the runtime's plan analysis
    ir = automaton_ir_from_nfa(nfa, "q")
    assert "PV002" in _codes(verify_automaton(ir))


def test_seq_dead_start_short_circuits():
    nfa = _nfa("from e1=S[kind == 0]<2:4>, e2=S[kind == 1] "
               "select e2.price as p2 insert into Out;")
    assert nfa.spec.dead_start and nfa.statically_dead
    assert _matches(nfa, _feed()) == []


@pytest.mark.parametrize("app,pruned", [
    (DEAD_APP, 0), (PRUNABLE_KLEENE, 1), (PRUNABLE_OR, 1),
    (SIMPLIFIABLE, 0)])
def test_pruned_vs_unpruned_identical_matches(app, pruned):
    """The equivalence proof: pruned and unpruned compiles of the same
    pattern produce identical match streams on randomized event feeds."""
    a = _nfa(app)
    b = _nfa(app, prune=False)
    assert a.prune_report["pruned_states"] == pruned
    assert b.prune_report["pruned_states"] == 0
    for seed in (0, 1, 2):
        feed = _feed(seed=seed)
        assert _matches(a, feed) == _matches(b, feed), \
            f"seed {seed}: pruned output diverged"


def test_prune_keeps_referenced_dead_capture():
    # the dead min-0 kleene's capture is selected -> must NOT be deleted
    # (its output column is always-null and must stay addressable)
    app = ("from e1=S[kind == 0] -> e2=S[kind == 2 and 1 == 2]<0:3> -> "
           "e3=S[kind == 1] "
           "select e1.price as p1, e2.price as p2, e3.price as p3 "
           "insert into Out;")
    a = _nfa(app)
    assert a.prune_report["pruned_states"] == 0
    b = _nfa(app, prune=False)
    for seed in (0, 3):
        feed = _feed(seed=seed)
        assert _matches(a, feed) == _matches(b, feed)


def test_prune_env_kill_switch(monkeypatch):
    monkeypatch.setenv("SIDDHI_TPU_NFA_PRUNE", "0")
    nfa = _nfa(PRUNABLE_KLEENE)
    assert not nfa.prune_enabled
    assert nfa.prune_report["pruned_states"] == 0


def test_pv004_and_pruned_counts_ride_rt_analysis():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        STREAM + "@info(name='q') " + PRUNABLE_KLEENE)
    try:
        assert "PV004" in rt.analysis.codes()
        assert rt.analysis.plan is not None
        assert rt.analysis.plan.pruned_states == 1
    finally:
        rt.shutdown()


def test_dead_pattern_through_engine_delivers_nothing():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        STREAM + "@info(name='q') " + DEAD_APP)
    try:
        assert "PV002" in rt.analysis.codes()
        got = []
        rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
        rt.start()
        pids, cols, ts = _feed(n=64)
        rt.get_input_handler("S").send_batch(
            {"price": cols["price"], "kind": cols["kind"].astype(np.int64)},
            timestamps=ts)
        rt.flush()
        assert got == []
    finally:
        rt.shutdown()


# ================================================== jaxpr kernel sanitizer

def test_pv010_host_callback():
    import jax
    import jax.numpy as jnp

    def fn(x):
        return jax.pure_callback(
            lambda v: np.asarray(v) * 2,
            jax.ShapeDtypeStruct(x.shape, x.dtype), x)
    diags = sanitize_step("k", fn, jnp.zeros((4,), jnp.float32))
    assert _codes(diags) == {"PV010"} and "PV010" in CATALOG


def test_pv011_float64_upcast():
    import jax
    import jax.numpy as jnp
    with jax.experimental.enable_x64():
        diags = sanitize_step(
            "k", lambda x: x * 2.0, jnp.zeros((4,), jnp.float64))
    assert "PV011" in _codes(diags)


def test_pv012_dynamic_shape():
    import jax.numpy as jnp

    def fn(x):
        return x[x > 0]          # boolean mask: data-dependent shape
    diags = sanitize_step("k", fn, jnp.arange(4, dtype=jnp.float32))
    assert _codes(diags) == {"PV012"}


def test_pv013_gather_in_elementwise_kernel():
    import jax.numpy as jnp

    def fn(x, idx):
        return x[idx]
    args = (jnp.arange(8, dtype=jnp.float32),
            jnp.zeros((4,), jnp.int32))
    assert "PV013" in _codes(sanitize_step("k", fn, *args,
                                           elementwise=True))
    # the same jaxpr is fine for a kernel that declares gather
    assert "PV013" not in _codes(sanitize_step("k", fn, *args))


def test_nfa_step_and_filter_program_sanitize_clean():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        STREAM +
        "@info(name='p') from every e1=S[kind == 0] -> e2=S[kind == 1] "
        "within 10 sec select e1.price as p1 insert into Out;\n"
        "@info(name='f') from S[price > 50] select price insert into F;")
    try:
        from siddhi_tpu.analysis.plan_verify import sanitize_runtime
        diags = sanitize_runtime(rt)
        assert not diags, [d.render() for d in diags]
    finally:
        rt.shutdown()


# ================================================== static cost model

def test_pc001_summary_on_device_plan():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        STREAM + "@info(name='q') from every e1=S[kind == 0] -> "
        "e2=S[kind == 1] within 10 sec "
        "select e1.price as p1 insert into Out;")
    try:
        assert "PC001" in rt.analysis.codes()
        cost = rt.analysis.plan.cost
        assert cost.total_hbm_bytes > 0
        assert cost.total_flops_per_event > 0
    finally:
        rt.shutdown()


def test_pc002_budget_gate():
    nfa = _nfa("from every e1=S[kind == 0] -> e2=S[kind == 1] "
               "within 10 sec select e1.price as p1 insert into Out;")
    plan = verify_plan(_plan_of(nfa), hbm_budget_mb=1e-6)
    assert "PC002" in _codes(plan.diagnostics)


def _plan_of(nfa):
    from siddhi_tpu.analysis.plan_ir import PlanIR
    return PlanIR(app_name="t", automata=[automaton_ir_from_nfa(nfa, "q")])


def test_pc003_flops_threshold():
    nfa = _nfa("from every e1=S[kind == 0] -> e2=S[kind == 1] "
               "within 10 sec select e1.price as p1 insert into Out;")
    report = plan_cost(_plan_of(nfa))
    assert "PC003" in _codes(cost_diagnostics(report, flops_warn=1))
    assert "PC003" not in _codes(
        cost_diagnostics(report, flops_warn=DEFAULT_FLOPS_WARN))


@pytest.mark.parametrize("app", [
    "from every e1=S[kind == 0] -> e2=S[kind == 1 and price > e1.price] "
    "within 10 sec select e1.price as p1 insert into Out;",
    "from e1=S[kind == 0] -> e2=S[kind == 1]<1:3> -> "
    "e3=S[kind == 0] -> not S[kind == 2] for 5 sec "
    "select e1.price as p1 insert into Out;",
    "from every e1=S[kind == 0], e2=S[kind == 1] "
    "select e1.price as p1 insert into Out;",
])
def test_hbm_prediction_byte_exact(app):
    nfa = _nfa(app, n_partitions=3)
    ir = automaton_ir_from_nfa(nfa, "q")
    predicted = sum(nfa_state_bytes(ir).values())
    actual = sum(int(np.asarray(v).nbytes) for v in nfa.carry.values())
    assert predicted == actual
    assert nfa_flops_per_event(ir) > 0


def test_bank_prediction_matches_live_bytes_gauge():
    from siddhi_tpu.core.profiling import profiler
    from siddhi_tpu.plan.nfa_compiler import CompiledPatternBank
    prof = profiler()
    was = prof.enabled
    prof.enable()
    try:
        apps = [STREAM + f"from every e1=S[kind == 0 and price > {t}] -> "
                "e2=S[kind == 1] within 10 sec "
                "select e1.price as p1 insert into Out;"
                for t in (10.0, 50.0)]
        bank = CompiledPatternBank(apps, n_partitions=4, n_slots=4,
                                   pattern_chunk=2)
        ir = automaton_ir_from_nfa(bank.nfa, "bank")
        predicted = bank_state_bytes(ir, 2, n_partitions=4)
        measured = prof.snapshot()["nfa.bank_step"]["live_bytes"]
        assert measured > 0
        # acceptance bound is 2x; the formulas are in fact byte-exact
        assert predicted == measured
    finally:
        if not was:
            prof.disable()


# ================================================== surfaces

def test_stats_json_embeds_plan_report():
    from siddhi_tpu.service.rest import SiddhiService
    svc = SiddhiService(port=0)
    try:
        rt = svc.manager.create_siddhi_app_runtime(
            "@app:statistics(enable='true') " + STREAM +
            "@info(name='q') from every e1=S[kind == 0] -> "
            "e2=S[kind == 1] within 10 sec "
            "select e1.price as p1 insert into Out;")
        doc = svc._stats_json()
        app_doc = doc["apps"][rt.name]
        assert "plan" in app_doc
        assert app_doc["plan"]["cost"]["total_hbm_bytes"] > 0
        assert app_doc["plan"]["plan"]["automata"][0]["n_states"] == 2
    finally:
        svc.manager.shutdown()


def test_analyze_cli_default_path_imports_no_jax(tmp_path):
    app = tmp_path / "a.siddhi"
    app.write_text(STREAM + "from S[price > 1] select price "
                   "insert into Out;")
    code = ("import sys\n"
            "from siddhi_tpu.analyze import main\n"
            f"rc = main([{str(app)!r}, '--json'])\n"
            "assert 'jax' not in sys.modules, 'jax leaked into the "
            "default analyze path'\n"
            "sys.exit(rc)\n")
    res = subprocess.run([sys.executable, "-c", code], cwd=ROOT,
                         capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stdout + res.stderr


def test_analyze_cli_plan_flag(tmp_path):
    app = tmp_path / "a.siddhi"
    app.write_text(
        STREAM + "@info(name='q') from every e1=S[kind == 0] -> "
        "e2=S[kind == 1] within 10 sec "
        "select e1.price as p1 insert into Out;")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    res = subprocess.run(
        [sys.executable, "-m", "siddhi_tpu.analyze", str(app),
         "--plan", "--json"],
        cwd=ROOT, capture_output=True, text=True, timeout=300, env=env)
    assert res.returncode == 0, res.stdout + res.stderr
    import json
    doc = json.loads(res.stdout)
    assert doc["plan"]["cost"]["total_hbm_bytes"] > 0
    codes = {d["code"] for d in doc["diagnostics"]}
    assert "PC001" in codes


def test_every_new_code_is_in_catalog_and_docs():
    new = {"PV001", "PV002", "PV003", "PV004", "PV005",
           "PV010", "PV011", "PV012", "PV013",
           "PC001", "PC002", "PC003"}
    assert new <= set(CATALOG)
    from siddhi_tpu.analysis import catalog_markdown
    md = catalog_markdown()
    for c in new:
        assert c in md
