"""Every SiddhiQL app shipped in samples/ must be clean under the
numeric-safety verifier at WARNING level (analysis/ranges.py) — the
NS-family twin of tests/test_samples_analysis.py.  A new sample that
trips an NS warning either declares its ranges/rates (or the
compensated-sum remediation), or earns an allowlist entry below with a
justification.  INFO-level findings (conservative-dtype provenance) are
the verifier's declared noise floor and stay out of this gate."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from siddhi_tpu.analysis.ranges import sample_numeric_counts  # noqa: E402

SAMPLES_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "samples")

# sample file -> NS codes it is ALLOWED to emit at warning level, each
# with a written justification (none today: the showcase apps are
# numerically clean — golden-pinned)
EXPECTED_NS = {}


def test_samples_are_numerically_clean():
    counts = sample_numeric_counts(SAMPLES_DIR)
    assert counts, "no samples analyzed"
    offenders = {}
    for fname, by_code in sorted(counts.items()):
        unexpected = set(by_code) - EXPECTED_NS.get(fname, set())
        if unexpected:
            offenders[fname] = {c: by_code[c] for c in sorted(unexpected)}
    assert not offenders, (
        "samples emit NS warnings not in the allowlist (declare "
        f"@attr:range/@app:rate or justify an entry): {offenders}")


def test_sample_counts_cover_every_sample_file():
    files = {f for f in os.listdir(SAMPLES_DIR) if f.endswith(".py")}
    counts = sample_numeric_counts(SAMPLES_DIR)
    assert set(counts) == files
