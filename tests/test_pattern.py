"""Pattern query behavioural tests.

Modeled on the reference conformance suites (siddhi-core
query/pattern/: PatternTestCase, EveryPatternTestCase, CountPatternTestCase,
LogicalPatternTestCase, WithinPatternTestCase, absent/*TestCase) — app string,
callbacks, send, assert exact match payloads and counts.
"""
import pytest

from siddhi_tpu import QueryCallback, SiddhiManager, StreamCallback


def make(app):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback("query1", QueryCallback(
        lambda ts, cur, exp: got.extend(e.data for e in (cur or []))))
    rt.start()
    return m, rt, got


STREAMS = """
define stream Stream1 (symbol string, price float, volume int);
define stream Stream2 (symbol string, price float, volume int);
"""


def test_simple_pattern_followed_by():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from e1=Stream1[price > 20] -> e2=Stream2[price > e1.price]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["WSO2", 55.6, 100])
    s2.send(["IBM", 55.7, 100])
    # non-every: only the first match fires
    s1.send(["GOOG", 56.0, 100])
    s2.send(["MSFT", 57.0, 100])
    rt.shutdown()
    assert got == [["WSO2", "IBM"]]


def test_pattern_ignores_non_matching_intermediates():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from e1=Stream1[price > 20] -> e2=Stream1[price > e1.price]
        select e1.price as p1, e2.price as p2
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s1.send(["A", 25.0, 1])
    s1.send(["B", 10.0, 1])   # does not match e2, pattern is non-strict
    s1.send(["C", 30.0, 1])
    rt.shutdown()
    assert got == [[25.0, 30.0]]


def test_every_pattern_restarts():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from every e1=Stream1[price > 20] -> e2=Stream2[price > e1.price]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["A1", 25.0, 1])
    s2.send(["B1", 26.0, 1])
    s1.send(["A2", 30.0, 1])
    s2.send(["B2", 31.0, 1])
    rt.shutdown()
    assert got == [["A1", "B1"], ["A2", "B2"]]


def test_every_overlapping_matches():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from every e1=Stream1[price > 20] -> e2=Stream2[price > 20]
        select e1.price as p1, e2.price as p2
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["A1", 21.0, 1])
    s1.send(["A2", 22.0, 1])
    s2.send(["B", 23.0, 1])   # completes both armed partials
    rt.shutdown()
    assert sorted(got) == [[21.0, 23.0], [22.0, 23.0]]


def test_logical_and_pattern():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from e1=Stream1[price > 20] and e2=Stream2[price > 30]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s2.send(["IBM", 35.0, 1])    # e2 first — AND is order-free
    s1.send(["WSO2", 25.0, 1])
    rt.shutdown()
    assert got == [["WSO2", "IBM"]]


def test_logical_or_pattern():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from e1=Stream1[price > 20] or e2=Stream2[price > 30]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;
    """)
    s2 = rt.get_input_handler("Stream2")
    s2.send(["IBM", 35.0, 1])
    rt.shutdown()
    assert got == [[None, "IBM"]]


def test_logical_and_then_next():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from e1=Stream1[price > 20] and e2=Stream2[price > 30] -> e3=Stream1[price > 40]
        select e1.price as p1, e2.price as p2, e3.price as p3
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["A", 25.0, 1])
    s2.send(["B", 35.0, 1])
    s1.send(["C", 45.0, 1])
    rt.shutdown()
    assert got == [[25.0, 35.0, 45.0]]


def test_count_pattern_min_reached():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from e1=Stream1[price > 20]<2:5> -> e2=Stream2[price > e1[0].price]
        select e1[0].price as p0, e1[1].price as p1, e2.price as p2
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["A", 25.0, 1])
    s1.send(["B", 30.0, 1])
    s1.send(["C", 35.0, 1])
    s2.send(["D", 45.0, 1])
    rt.shutdown()
    # all three Stream1 events accumulate into the same partial
    assert got == [[25.0, 30.0, 45.0]]


def test_count_pattern_exact_counts_accumulate():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from e1=Stream1[price > 20]<2:5> -> e2=Stream2[price > e1[0].price]
        select e1[0].price as p0, e1[2].price as p2x, e2.price as p2
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["A", 25.0, 1])
    s1.send(["B", 30.0, 1])
    s2.send(["D", 45.0, 1])
    rt.shutdown()
    # only two e1 events: e1[2] is null
    assert got == [[25.0, None, 45.0]]


def test_count_optional_zero():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from e1=Stream1[price > 100]<0:1> -> e2=Stream2[price > 20]
        select e1.price as p1, e2.price as p2
        insert into OutputStream;
    """)
    s2 = rt.get_input_handler("Stream2")
    s2.send(["B", 25.0, 1])
    rt.shutdown()
    assert got == [[None, 25.0]]


def test_within_expires_partials():
    m, rt, got = make("@app:playback " + STREAMS + """
        @info(name = 'query1')
        from every e1=Stream1[price > 20] -> e2=Stream2[price > e1.price]
            within 1 sec
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["A", 25.0, 1], timestamp=1000)
    s2.send(["B", 30.0, 1], timestamp=2500)   # > 1s later: expired, no match
    s1.send(["C", 25.0, 1], timestamp=3000)
    s2.send(["D", 30.0, 1], timestamp=3500)   # within 1s: match
    rt.shutdown()
    assert got == [["C", "D"]]


def test_pattern_group_by_output():
    m, rt, got = make(STREAMS + """
        @info(name = 'query1')
        from every e1=Stream1[price > 20] -> e2=Stream2[price > e1.price]
        select e1.symbol as symbol1, e2.price as price2
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["X", 21.0, 1])
    s2.send(["Y", 22.0, 1])
    rt.shutdown()
    assert got == [["X", 22.0]]


# --------------------------------------------------------------- absent (not)

def playback_make(app):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback("query1", QueryCallback(
        lambda ts, cur, exp: got.extend(e.data for e in (cur or []))))
    rt.start()
    return m, rt, got


def advance(rt, ts):
    """Advance playback virtual time so scheduler timers fire."""
    rt.app_ctx.timestamp_generator.observe_event_time(ts)
    rt.app_ctx.scheduler.advance_to(ts)


def test_absent_not_for_fires_after_wait():
    m, rt, got = playback_make("@app:playback " + STREAMS + """
        @info(name = 'query1')
        from e1=Stream1[price > 20] -> not Stream2[price > e1.price] for 1 sec
        select e1.symbol as symbol1
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s1.send(["WSO2", 25.0, 1], timestamp=1000)
    advance(rt, 2100)
    rt.shutdown()
    assert got == [["WSO2"]]


def test_absent_not_for_suppressed_by_arrival():
    m, rt, got = playback_make("@app:playback " + STREAMS + """
        @info(name = 'query1')
        from e1=Stream1[price > 20] -> not Stream2[price > e1.price] for 1 sec
        select e1.symbol as symbol1
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["WSO2", 25.0, 1], timestamp=1000)
    s2.send(["IBM", 30.0, 1], timestamp=1500)   # arrival kills the absence
    advance(rt, 2100)
    rt.shutdown()
    assert got == []


def test_absent_and_logical():
    m, rt, got = playback_make("@app:playback " + STREAMS + """
        @info(name = 'query1')
        from not Stream1[price > 20] and e2=Stream2[price > 30]
        select e2.symbol as symbol2
        insert into OutputStream;
    """)
    s2 = rt.get_input_handler("Stream2")
    s2.send(["IBM", 35.0, 1], timestamp=1000)
    rt.shutdown()
    assert got == [["IBM"]]


def test_absent_and_logical_poisoned():
    m, rt, got = playback_make("@app:playback " + STREAMS + """
        @info(name = 'query1')
        from not Stream1[price > 20] and e2=Stream2[price > 30]
        select e2.symbol as symbol2
        insert into OutputStream;
    """)
    s1 = rt.get_input_handler("Stream1")
    s2 = rt.get_input_handler("Stream2")
    s1.send(["BAD", 25.0, 1], timestamp=500)    # absence violated first
    s2.send(["IBM", 35.0, 1], timestamp=1000)
    rt.shutdown()
    assert got == []
