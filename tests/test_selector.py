"""Selector tests: group-by / having / order-by / limit + all 12 aggregators
(reference model: query/GroupByTestCase, OrderByLimitTestCase,
AggregationFunction tests)."""
import pytest

from siddhi_tpu import QueryCallback, SiddhiManager, StreamCallback


def collect(app, sends, stream="S", out="Out"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback(out, StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    h = rt.get_input_handler(stream)
    for s in sends:
        h.send(s)
    rt.shutdown()
    return got


def test_group_by_sum():
    got = collect("""
        define stream S (sym string, p double);
        from S select sym, sum(p) as t group by sym insert into Out;
    """, [["A", 1.0], ["B", 10.0], ["A", 2.0], ["B", 20.0]])
    assert [e.data for e in got] == [
        ["A", 1.0], ["B", 10.0], ["A", 3.0], ["B", 30.0]]


def test_avg_count_min_max():
    got = collect("""
        define stream S (p double);
        from S select avg(p) as a, count() as c, min(p) as mn, max(p) as mx
        insert into Out;
    """, [[4.0], [8.0], [6.0]])
    assert got[-1].data == [6.0, 3, 4.0, 8.0]


def test_distinct_count_stddev():
    got = collect("""
        define stream S (x int);
        from S select distinctCount(x) as dc, stdDev(x) as sd insert into Out;
    """, [[1], [1], [2]])
    assert got[-1].data[0] == 2
    assert got[-1].data[1] == pytest.approx(0.4714, abs=1e-3)


def test_minforever_maxforever():
    got = collect("""
        define stream S (x long);
        from S select minForever(x) as mn, maxForever(x) as mx insert into Out;
    """, [[5], [2], [9]])
    assert [e.data for e in got] == [[5, 5], [2, 5], [2, 9]]


def test_bool_and_or_aggregators():
    got = collect("""
        define stream S (ok bool);
        from S select and(ok) as allok, or(ok) as anyok insert into Out;
    """, [[True], [False], [True]])
    assert [e.data for e in got] == [[True, True], [False, True],
                                     [False, True]]


def test_having():
    got = collect("""
        define stream S (sym string, p double);
        from S select sym, sum(p) as t group by sym having t > 10.0
        insert into Out;
    """, [["A", 5.0], ["A", 7.0], ["B", 1.0]])
    assert [e.data for e in got] == [["A", 12.0]]


def test_order_by_limit_on_batch():
    got = collect("""
        define stream S (x int);
        from S#window.lengthBatch(4)
        select x order by x desc limit 2 insert into Out;
    """, [[3], [9], [1], [7]])
    assert [e.data[0] for e in got] == [9, 7]


def test_select_star():
    got = collect("""
        define stream S (a int, b string);
        from S select * insert into Out;
    """, [[1, "x"]])
    assert got[0].data == [1, "x"]


def test_unionset_and_sizeofset():
    got = collect("""
        define stream S (x int);
        from S select sizeOfSet(unionSet(createSet(x))) as n insert into Out;
    """, [[1], [2], [1]])
    assert [e.data[0] for e in got] == [1, 2, 2]


def test_output_rate_events():
    got = collect("""
        define stream S (x int);
        from S select x output every 3 events insert into Out;
    """, [[i] for i in range(7)])
    # flushed at 3 and 6 events
    assert [e.data[0] for e in got] == [0, 1, 2, 3, 4, 5]


def test_output_rate_last():
    got = collect("""
        define stream S (x int);
        from S select x output last every 3 events insert into Out;
    """, [[i] for i in range(6)])
    assert [e.data[0] for e in got] == [2, 5]


def test_eventtimestamp_function():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:playback
        define stream S (x int);
        from S select eventTimestamp() as ts insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    rt.get_input_handler("S").send([1], timestamp=12345)
    rt.shutdown()
    assert got[0].data == [12345]
