"""Deterministic chaos harness for the resilience suite.

Seeded fault injectors used by tests/test_resilience.py to prove the
no-event-loss contracts end-to-end.  Everything here is deterministic:
failure scripts are fixed sequences or seeded `random.Random` draws,
delivery scrambles are seeded permutations, and clocks are virtual —
no assertion in the suite depends on wall-clock sleeps.

Pieces:

  * ``FailureScript`` — per-call fail/succeed decisions (``fail_n``,
    ``fail_always``, ``fail_rate``).
  * ``ChaosSink`` / ``ChaosSource`` — engine-buildable transports
    (register via :func:`register`, then ``@sink(type='chaos',
    chaos.id='x')``) whose publish/connect consult a script; delivered
    payloads are recorded per ``chaos.id`` for assertions.
  * ``ChunkScrambler`` — junction receiver wrapper that buffers, then
    releases deliveries in a seeded order with seeded duplicates
    (delay/duplicate/reorder chaos without timers).
  * ``TearingStore`` — persistence-store wrapper that truncates/corrupts
    chosen saves, simulating torn writes; plus the raw :func:`tear`.
  * ``inject_fault`` — monkeypatch any bound method (e.g. a device-step
    wrapper) to raise per a script.
  * ``VirtualClock`` — manual monotonic clock for CircuitBreaker tests.
  * ``burst_feed`` / ``poison_feed`` / ``backwards_feed`` — seeded event
    generators for the overload/quarantine suite (tests/test_overload.py).
  * ``wraparound_feed`` — seeded stream-years feed crossing the ts32
    int32-ms horizon (device rebase under NUMGUARD,
    tests/test_numguard.py).
  * ``GatedReceiver`` — a junction subscriber whose delivery can be
    wedged (blocked on an Event) to exert real backpressure on @Async
    workers, then released.
"""
from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional

from siddhi_tpu.core.event import EventChunk
from siddhi_tpu.core.snapshot import PersistenceStore
from siddhi_tpu.core.source_sink import Sink, Source
from siddhi_tpu.utils.errors import ConnectionUnavailableError


class ChaosError(ConnectionUnavailableError):
    """Injected failure (subclasses ConnectionUnavailableError so the
    engine's retry machinery engages)."""


# ------------------------------------------------------------------ scripts


class FailureScript:
    """Decides, per call, whether to inject a failure.  Thread-safe;
    mutate ``self`` mid-test (e.g. ``script.heal()``) to model recovery."""

    def __init__(self, fail_first_n: int = 0, fail_forever: bool = False,
                 fail_rate: float = 0.0, seed: int = 0):
        self.fail_first_n = fail_first_n
        self.fail_forever = fail_forever
        self.fail_rate = fail_rate
        self._rng = random.Random(seed)
        self.calls = 0
        self.failures = 0
        self._lock = threading.Lock()

    @classmethod
    def fail_n(cls, n: int) -> "FailureScript":
        return cls(fail_first_n=n)

    @classmethod
    def fail_always(cls) -> "FailureScript":
        return cls(fail_forever=True)

    @classmethod
    def healthy(cls) -> "FailureScript":
        return cls()

    def heal(self):
        """Stop injecting failures from now on."""
        with self._lock:
            self.fail_first_n = 0
            self.fail_forever = False
            self.fail_rate = 0.0

    def check(self, what: str = "call"):
        """Raise ChaosError when the script says this call fails."""
        with self._lock:
            self.calls += 1
            fail = (self.fail_forever or self.calls <= self.fail_first_n
                    or (self.fail_rate > 0.0
                        and self._rng.random() < self.fail_rate))
            if fail:
                self.failures += 1
        if fail:
            raise ChaosError(f"chaos: injected {what} failure "
                             f"#{self.failures} (call {self.calls})")


# ------------------------------------------------------------------ transports

#: per-chaos.id state, shared between the engine-built instances and tests
SCRIPTS: Dict[str, FailureScript] = {}
DELIVERED: Dict[str, List] = {}
INSTANCES: Dict[str, Sink] = {}


def reset():
    SCRIPTS.clear()
    DELIVERED.clear()
    INSTANCES.clear()


def script_for(chaos_id: str) -> FailureScript:
    return SCRIPTS.setdefault(chaos_id, FailureScript.healthy())


def delivered(chaos_id: str) -> List:
    return DELIVERED.setdefault(chaos_id, [])


class ChaosSink(Sink):
    """``@sink(type='chaos', chaos.id='x', ...)`` — publish consults
    SCRIPTS['x']; successful payload events append to DELIVERED['x']."""

    def __init__(self, stream_def, options, mapper):
        super().__init__(stream_def, options, mapper)
        self.chaos_id = options.get("chaos.id", stream_def.id)
        INSTANCES[self.chaos_id] = self

    def publish(self, payload, event):
        script_for(self.chaos_id).check("publish")
        sink_log = delivered(self.chaos_id)
        if isinstance(payload, EventChunk):
            # columnar passthrough payload: record per-event for the
            # suite's no-loss assertions
            sink_log.extend(payload.to_events())
        elif isinstance(payload, list):
            sink_log.extend(payload)
        else:
            sink_log.append(payload)

    def retry_join(self, timeout: float = 30.0) -> bool:
        """Sleep-free rendezvous: wait until every queued retry for this
        sink has been resolved (delivered or exhausted)."""
        worker = self._retry_worker_inst
        return worker.join(timeout) if worker is not None else True


class ChaosSource(Source):
    """``@source(type='chaos', chaos.id='x')`` — connect consults the
    script; tests push events with ``emit``."""

    def __init__(self, stream_def, options, mapper, input_handler):
        super().__init__(stream_def, options, mapper, input_handler)
        self.chaos_id = options.get("chaos.id", stream_def.id)
        self.connect_attempts = 0
        INSTANCES[self.chaos_id] = self

    def connect(self):
        self.connect_attempts += 1
        script_for(self.chaos_id).check("connect")

    def emit(self, obj):
        self.deliver(obj)


def register(manager):
    """Make type='chaos' resolvable for @sink/@source on this manager."""
    manager.set_extension("sink:chaos", ChaosSink)
    manager.set_extension("source:chaos", ChaosSource)


# ------------------------------------------------------------------ delivery

class ChunkScrambler:
    """Junction receiver that buffers chunks, then ``release()``s them to
    the wrapped receiver in a seeded order with seeded duplicates —
    delay/duplicate/reorder chaos with zero timers."""

    def __init__(self, inner, seed: int = 0, duplicate_rate: float = 0.0,
                 reorder: bool = True):
        self.inner = inner
        self.rng = random.Random(seed)
        self.duplicate_rate = duplicate_rate
        self.reorder = reorder
        self.held: List = []
        self._lock = threading.Lock()

    def receive_chunk(self, chunk):
        with self._lock:
            self.held.append(chunk)

    def release(self):
        with self._lock:
            batch, self.held = self.held, []
        order = list(range(len(batch)))
        if self.reorder:
            self.rng.shuffle(order)
        for i in order:
            self.inner.receive_chunk(batch[i])
            if self.duplicate_rate > 0.0 and \
                    self.rng.random() < self.duplicate_rate:
                self.inner.receive_chunk(batch[i])


# ------------------------------------------------------------------ storage

def tear(blob: bytes, seed: int = 0, mode: str = "truncate") -> bytes:
    """Corrupt snapshot bytes deterministically: ``truncate`` keeps a
    seeded prefix (torn write), ``flip`` xors a few seeded bytes."""
    rng = random.Random(seed)
    if not blob:
        return blob
    if mode == "truncate":
        return blob[:rng.randrange(1, max(len(blob), 2))]
    out = bytearray(blob)
    for _ in range(3):
        i = rng.randrange(len(out))
        out[i] ^= 0xFF
    return bytes(out)


class TearingStore(PersistenceStore):
    """Wraps a real store; saves listed in ``tear_revisions`` (by 1-based
    save ordinal) write corrupted bytes — the pre-atomic-rename failure
    mode, reproduced deterministically."""

    def __init__(self, inner: PersistenceStore, tear_ordinals=(1,),
                 seed: int = 0, mode: str = "truncate"):
        self.inner = inner
        self.tear_ordinals = set(tear_ordinals)
        self.seed = seed
        self.mode = mode
        self.saves = 0

    def save(self, app_name, revision, snapshot):
        self.saves += 1
        if self.saves in self.tear_ordinals:
            snapshot = tear(snapshot, seed=self.seed + self.saves,
                            mode=self.mode)
        self.inner.save(app_name, revision, snapshot)

    def load(self, app_name, revision):
        return self.inner.load(app_name, revision)

    def last_revision(self, app_name):
        return self.inner.last_revision(app_name)

    def revisions(self, app_name):
        return self.inner.revisions(app_name)

    def clear_all_revisions(self, app_name):
        return self.inner.clear_all_revisions(app_name)


# ------------------------------------------------------------------ faults

def inject_fault(obj, attr: str, script: FailureScript,
                 error_cls=RuntimeError):
    """Wrap ``obj.attr`` so each call first consults ``script`` (raising
    ``error_cls``), e.g. a device-step wrapper.  Returns a restore()."""
    original = getattr(obj, attr)

    def wrapped(*a, **kw):
        try:
            script.check(attr)
        except ChaosError as e:
            raise error_cls(str(e)) from e
        return original(*a, **kw)

    setattr(obj, attr, wrapped)

    def restore():
        setattr(obj, attr, original)
    return restore


# ------------------------------------------------------------------ overload

def burst_feed(n_events: int, seed: int = 0, start_ts: int = 1_000_000,
               symbols=("A", "B", "C")):
    """Seeded burst of (symbol, price, volume, ts) rows with
    monotonically non-decreasing timestamps — offered faster than any
    consumer drains, for admission-control tests.  Returns a list of
    ``([symbol, price, volume], ts)`` tuples."""
    rng = random.Random(seed)
    ts = start_ts
    out = []
    for i in range(n_events):
        ts += rng.randrange(0, 3)          # dense: 0-2 ms apart
        out.append(([rng.choice(symbols), float(i), i], ts))
    return out


def poison_feed(n_events: int, seed: int = 0, start_ts: int = 1_000_000,
                poison_every: int = 5):
    """Seeded mixed feed: every ``poison_every``-th row is poisoned with
    a deterministic rotation of NaN price, Inf price, a non-coercible
    volume, or a timestamp far in the past.  Returns
    ``(rows, clean_rows)`` where rows is the full feed and clean_rows
    the healthy subset (for pre-filtered parity runs); each element is
    ``([symbol, price, volume], ts)``."""
    rng = random.Random(seed)
    ts = start_ts
    rows, clean = [], []
    kinds = ("nan", "inf", "type", "ts_regress")
    for i in range(n_events):
        ts += rng.randrange(1, 4)
        row = (["ABC", float(i), i], ts)
        if i and i % poison_every == 0:
            kind = kinds[(i // poison_every) % len(kinds)]
            if kind == "nan":
                row = (["ABC", float("nan"), i], ts)
            elif kind == "inf":
                row = (["ABC", float("inf"), i], ts)
            elif kind == "type":
                row = (["ABC", float(i), object()], ts)
            else:
                row = (["ABC", float(i), i], start_ts - 500_000)
            rows.append(row)
            continue
        rows.append(row)
        clean.append(row)
    return rows, clean


def backwards_feed(n_events: int, seed: int = 0,
                   start_ts: int = 1_000_000, jump_back_ms: int = 60_000,
                   every: int = 7):
    """Seeded feed where every ``every``-th timestamp regresses by
    ``jump_back_ms`` (beyond any sane slack) — the poisoned-clock
    upstream.  Returns ``([symbol, price, volume], ts)`` tuples."""
    rng = random.Random(seed)
    ts = start_ts
    out = []
    for i in range(n_events):
        ts += rng.randrange(1, 4)
        bad = i and i % every == 0
        out.append((["ABC", float(i), i],
                    ts - jump_back_ms if bad else ts))
    return out


def wraparound_feed(n_events: int, seed: int = 0,
                    start_ts: int = 1_000_000,
                    span_ms: int = 40 * 86_400_000,
                    symbols=("A", "B", "C")):
    """Seeded stream-years feed for the ts32 horizon (NS004 / ROADMAP
    item 5's scenario factory): ``n_events`` rows spread evenly across
    ``span_ms`` of stream time (default 40 days — past the ~24.8-day
    int32-ms horizon, forcing at least one device rebase) with seeded
    jitter.  Timestamps stay strictly increasing so window semantics
    are unambiguous for the host oracle.  Returns
    ``([symbol, price, volume], ts)`` tuples."""
    rng = random.Random(seed)
    stride = max(span_ms // max(n_events, 1), 2)
    out = []
    ts = start_ts
    for i in range(n_events):
        ts += stride + rng.randrange(0, max(stride // 2, 1))
        out.append(([rng.choice(symbols), float(i % 97), i % 89], ts))
    return out


class GatedReceiver:
    """Junction subscriber that blocks deliveries until ``open()`` —
    subscribe it directly on an @Async stream to wedge the worker and
    fill the bounded queue (downstream-of-a-query receivers are
    pipelined and return immediately, so they exert no backpressure)."""

    def __init__(self):
        self.gate = threading.Event()
        self.entered = threading.Event()   # a delivery reached the gate
        self.received: List = []
        self._lock = threading.Lock()

    def receive_chunk(self, chunk):
        self.entered.set()
        self.gate.wait()
        with self._lock:
            self.received.extend(chunk.timestamps.tolist())

    def open(self):
        self.gate.set()

    @property
    def count(self) -> int:
        with self._lock:
            return len(self.received)


# ------------------------------------------------------------------ clock

class VirtualClock:
    """Manual monotonic clock: inject as CircuitBreaker(clock=vc) and
    drive state transitions with ``advance`` — no sleeps."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> "VirtualClock":
        self.t += dt
        return self


# ------------------------------------------------------------------ locks

class LockOrderInversion:
    """Seeded lock-order inversion for the runtime lock-witness
    (core/lockwitness.py).

    Two witnessed locks, two phases, fully serialized by events so the
    scenario is deterministic and can never actually deadlock: thread 1
    takes A then B and completes; only after it has released both does
    thread 2 take B then A.  The interleaving that *would* deadlock
    never runs, but the acquisition-order history is exactly the LW001
    evidence — which is the point: the witness convicts on order, not
    on luck.
    """

    def __init__(self, witness, name_a: str = "chaos.A",
                 name_b: str = "chaos.B"):
        self.witness = witness
        self.lock_a = witness.wrap(threading.Lock(), name_a)
        self.lock_b = witness.wrap(threading.Lock(), name_b)

    def run(self, timeout: float = 5.0) -> None:
        phase1_done = threading.Event()

        def forward():            # A -> B
            with self.lock_a:
                with self.lock_b:
                    pass
            phase1_done.set()

        def backward():           # B -> A, strictly after phase 1
            if not phase1_done.wait(timeout):
                return
            with self.lock_b:
                with self.lock_a:
                    pass

        t1 = threading.Thread(target=forward, name="chaos-inv-fwd")
        t2 = threading.Thread(target=backward, name="chaos-inv-bwd")
        t1.start()
        t2.start()
        t1.join(timeout)
        t2.join(timeout)
