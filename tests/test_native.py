"""Native host data path: C++ bindings vs numpy fallbacks (native/
eventpack.cpp via siddhi_tpu/native_ext.py)."""
import threading

import numpy as np
import pytest

from siddhi_tpu import native_ext
from siddhi_tpu.native_ext import ColumnarRing, assign_rows, have_native


def test_assign_rows_matches_reference_loop():
    rng = np.random.default_rng(0)
    pids = rng.integers(0, 37, 5000).astype(np.int32)
    rows, counts, T = assign_rows(pids, 37)
    # reference semantics: running index per partition
    pos = np.zeros(37, np.int64)
    for i, p in enumerate(pids):
        assert rows[i] == pos[p]
        pos[p] += 1
    assert (counts == np.bincount(pids, minlength=37)).all()
    assert T == int(counts.max())


def test_ring_roundtrip_and_overflow():
    r = ColumnarRing(capacity=10, n_cols=3)
    v = np.arange(36.0).reshape(12, 3)
    pushed = r.push(v, np.arange(12), np.zeros(12, np.int32),
                    np.arange(12, dtype=np.int32))
    assert pushed == 10           # overflow → backpressure accounting
    assert r.dropped == 2
    assert len(r) == 10
    out_v, out_t, out_s, out_p = r.drain(6)
    assert out_v.shape == (6, 3)
    assert (out_v == v[:6]).all()
    assert len(r) == 4
    out_v2, *_ = r.drain(100)
    assert (out_v2 == v[6:10]).all()
    assert len(r) == 0


def test_ring_wraparound():
    r = ColumnarRing(capacity=4, n_cols=1)
    for k in range(5):   # repeatedly push 2 / drain 2 across the wrap point
        vals = np.asarray([[float(2 * k)], [float(2 * k + 1)]])
        assert r.push(vals, np.asarray([0, 0]), np.zeros(2, np.int32),
                      np.zeros(2, np.int32)) == 2
        out, *_ = r.drain(2)
        assert out.reshape(-1).tolist() == [2.0 * k, 2.0 * k + 1]


def test_ring_concurrent_producers():
    r = ColumnarRing(capacity=100_000, n_cols=1)

    def producer(tid):
        for _ in range(100):
            r.push(np.full((10, 1), float(tid)), np.arange(10),
                   np.zeros(10, np.int32), np.zeros(10, np.int32))
    threads = [threading.Thread(target=producer, args=(t,))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = 0
    while len(r):
        v, *_ = r.drain(1000)
        total += len(v)
    assert total + r.dropped == 4 * 100 * 10


@pytest.mark.skipif(not have_native(), reason="native .so not built")
def test_native_lib_is_loaded():
    assert native_ext.have_native()


def test_assign_rows_rejects_out_of_range_pids():
    with pytest.raises(ValueError):
        assign_rows(np.array([0, 5, 2], np.int32), 4)
    with pytest.raises(ValueError):
        assign_rows(np.array([0, -1, 2], np.int32), 4)
