"""Latency ledger, lag watermarks and the SLO engine (core/ledger.py).

Covers: nest-aware exclusive-time spans, the SIDDHI_TPU_LEDGER kill
switch, per-block folds into per-app histograms, event-time lag
watermarks, @app:slo parsing + burn-rate evaluation, the SLO001
incident bundle with waterfall evidence, the REST/statistics surfaces,
and the SA07x analyzer diagnostics.
"""
import json
import os
import sys
import time
import urllib.request

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from siddhi_tpu import SiddhiManager, StreamCallback  # noqa: E402
from siddhi_tpu.core.flight import flight  # noqa: E402
from siddhi_tpu.core.ledger import (LEDGER_ENV, STAGES,  # noqa: E402
                                    LatencyLedger, SloConfig, ledger,
                                    ledger_enabled)


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    """Ledger and flight recorder are process-global; isolate each test
    and point the bundle dir at tmp."""
    monkeypatch.setenv("SIDDHI_TPU_FLIGHT_DIR", str(tmp_path / "bundles"))
    ledger().reset()
    flight().reset()
    yield
    ledger().reset()
    flight().reset()


# ------------------------------------------------------------------ spans

class _VirtualClock:
    """Deterministic stand-in for perf_counter_ns — spans read whatever
    the test dialed in, so exclusive-time math asserts exact nanoseconds
    instead of racing the scheduler."""

    def __init__(self):
        self.ns = 0

    def __call__(self):
        return self.ns

    def tick(self, ms):
        self.ns += int(ms * 1_000_000)


def test_span_records_exclusive_time(monkeypatch):
    import siddhi_tpu.core.ledger as ledger_mod
    clock = _VirtualClock()
    monkeypatch.setattr(ledger_mod, "_pcns", clock)
    led = LatencyLedger()
    with led.span("dispatch"):
        clock.tick(2)
        with led.span("device"):
            clock.tick(5)
        clock.tick(2)
    ns = led.stage_ns()
    # device gets its own elapsed; dispatch gets only the surrounding
    # host time — NOT dispatch+device double counted
    assert ns["device"] == 5_000_000
    assert ns["dispatch"] == 4_000_000
    assert ns["dispatch"] + ns["device"] == 9_000_000


def test_span_nesting_three_deep(monkeypatch):
    import siddhi_tpu.core.ledger as ledger_mod
    clock = _VirtualClock()
    monkeypatch.setattr(ledger_mod, "_pcns", clock)
    led = LatencyLedger()
    with led.span("dispatch"):
        clock.tick(1)
        with led.span("decode"):
            with led.span("publish"):
                clock.tick(3)
            clock.tick(1)
    ns = led.stage_ns()
    # outer spans only carry their own exclusive time, not the child's
    assert ns["publish"] == 3_000_000
    assert ns["decode"] == 1_000_000
    assert ns["dispatch"] == 1_000_000


def test_kill_switch_disables_spans_and_blocks(monkeypatch):
    monkeypatch.setenv(LEDGER_ENV, "0")
    assert not ledger_enabled()
    led = LatencyLedger()
    with led.span("device"):
        time.sleep(0.001)
    assert led.stage_ns()["device"] == 0

    class Owner:
        pass

    assert led.note_block("a", Owner()) is None
    monkeypatch.setenv(LEDGER_ENV, "1")
    assert ledger_enabled()


def test_record_clamps_negative():
    led = LatencyLedger()
    led.record("queue", -50)
    assert led.stage_ns()["queue"] == 0


# ------------------------------------------------------------- note_block

class _Owner:
    pass


def test_note_block_folds_deltas_into_histograms():
    led = LatencyLedger()
    o = _Owner()
    assert led.note_block("app1", o) is None     # first call: baseline
    led.record("device", 3_000_000)
    led.record("ingress", 1_000_000)
    row = led.note_block("app1", o)
    assert row == {"device": 3.0, "ingress": 1.0}
    snap = led.snapshot(app="app1")
    stages = snap["apps"]["app1"]["stages_ms"]
    assert stages["device"]["count"] == 1
    assert stages["total"]["count"] == 1
    assert abs(stages["device"]["mean"] - 3.0) < 0.5
    assert snap["apps"]["app1"]["last_block_ms"]["device"] == 3.0


def test_note_block_row_skipped_when_not_wanted():
    led = LatencyLedger()
    o = _Owner()
    led.note_block("app1", o)
    led.record("device", 2_000_000)
    assert led.note_block("app1", o, want_row=False) is None
    # ... but the histogram fold still happened
    stages = led.snapshot(app="app1")["apps"]["app1"]["stages_ms"]
    assert stages["device"]["count"] == 1


def test_deferred_fold_drains_on_every_read_surface():
    led = LatencyLedger()
    o = _Owner()
    led.note_block("a", o)
    for _ in range(5):
        led.record("device", 1_000_000)
        led.note_block("a", o)
    # buffered, then folded lazily by prometheus_lines
    lines = led.prometheus_lines()
    assert any(l.startswith("siddhi_ledger_stage_latency_ms") and
               'app="a"' in l for l in lines)
    assert led.snapshot(app="a")["apps"]["a"]["stages_ms"][
        "device"]["count"] == 5


# ------------------------------------------------------- lag watermarks

def test_note_ingress_lag_watermark():
    led = LatencyLedger()
    led.note_ingress("app1", "S", event_ts_ms=1_000,
                     now_ms=1_750.0, dur_ns=10_000)
    snap = led.snapshot(app="app1")
    lag = snap["apps"]["app1"]["lag"]["S"]
    assert lag["lag_ms"] == 750.0
    assert lag["processing_lag_ms"] >= 0
    assert led.stage_ns()["ingress"] == 10_000
    lines = led.prometheus_lines()
    assert any(l.startswith("siddhi_event_time_lag_ms") and "750" in l
               for l in lines)
    assert any(l.startswith("siddhi_processing_lag_ms") for l in lines)


# ------------------------------------------------------------ SLO config

def test_slo_config_from_annotation():
    from siddhi_tpu.query_api.annotation import Annotation
    ann = (Annotation("app:slo")
           .element("latency.p99.ms", "250")
           .element("lag.ms", "1500")
           .element("window.blocks", "32")
           .element("breach.blocks", "5"))
    cfg = SloConfig.from_annotation(ann)
    assert cfg.latency_p99_ms == 250.0
    assert cfg.lag_ms == 1500.0
    assert cfg.window_blocks == 32
    assert cfg.breach_blocks == 5


def test_slo_config_tolerates_malformed_values():
    from siddhi_tpu.query_api.annotation import Annotation
    ann = (Annotation("app:slo")
           .element("latency.p99.ms", "fast")
           .element("window.blocks", "-3"))
    cfg = SloConfig.from_annotation(ann)
    assert cfg.latency_p99_ms is None          # malformed -> default
    assert cfg.window_blocks == 128
    assert cfg.breach_blocks == 3


def test_slo_breach_needs_consecutive_blocks():
    led = LatencyLedger()
    led.register_slo("a", SloConfig(latency_p99_ms=0.001,
                                    window_blocks=8, breach_blocks=3))
    o = _Owner()
    led.note_block("a", o)
    transitions = []
    for _ in range(8):
        led.record("device", 5_000_000)        # 5 ms >> 0.001 ms target
        st = led._slo["a"]
        before = st.breached
        led.note_block("a", o)
        if st.breached and not before:
            transitions.append(st.consecutive)
    assert led.slo_breached("a")
    assert len(transitions) == 1               # one transition, once
    st = led._slo["a"]
    assert st.breach_total == 1
    assert st.burn_latency > 1.0


def test_slo_recovery_clears_breach():
    led = LatencyLedger()
    led.register_slo("a", SloConfig(latency_p99_ms=1e9,
                                    window_blocks=8, breach_blocks=1))
    st = led._slo["a"]
    st.breached = True
    st.consecutive = 3
    assert st.observe(0.5, None) is False      # under target
    assert not st.breached
    assert st.consecutive == 0


def test_slo_breach_emits_slo001_bundle_with_waterfall():
    led = ledger()
    led.register_slo("appX", SloConfig(latency_p99_ms=0.000001,
                                       window_blocks=8, breach_blocks=2))
    o = _Owner()
    led.note_block("appX", o)
    for _ in range(8):
        led.record("device", 2_000_000)
        led.record("decode", 500_000)
        led.note_block("appX", o)
    assert led.slo_breached("appX")
    incs = [i for i in flight().incidents() if i["kind"] == "slo_breach"]
    assert len(incs) == 1
    bundle = flight().bundle(incs[0]["id"])
    det = bundle["detail"]
    assert det["code"] == "SLO001"
    assert det["slo"]["latency.p99.ms"] == 0.000001
    assert det["observed"]["breached"] is True
    # the breach ships its own waterfall evidence
    assert det["waterfall"]["device"] == 2.0
    assert det["waterfall"]["decode"] == 0.5
    assert det["stage_summary_ms"]["device"]["count"] >= 1


# -------------------------------------------------- runtime integration

def test_app_slo_annotation_registers_and_drops():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "@app:name('sloapp') "
        "@app:slo(latency.p99.ms='250', lag.ms='1500') "
        "define stream S (v float); "
        "@info(name='q') from S[v > 0.0] select v insert into Out;")
    assert rt.slo_config is not None
    assert rt.slo_config.latency_p99_ms == 250.0
    assert "sloapp" in ledger()._slo
    rt.start()
    rt.shutdown()
    assert "sloapp" not in ledger()._slo        # drop_app on shutdown


def test_engine_block_produces_full_waterfall():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "@app:name('wfapp') "
        "define stream S (sym string, price float); "
        "@info(name='q') from S[price > 0.0] "
        "select sym, price insert into Out;")
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.append(len(evs))))
    rt.start()
    h = rt.get_input_handler("S")
    cols = {"sym": np.asarray(["A"] * 16, object),
            "price": np.arange(1.0, 17.0)}
    for i in range(4):
        h.send_batch(cols, 1_000 + i * 16 + np.arange(16, dtype=np.int64))
    rt.flush()
    snap = rt.statistics
    lg = snap["ledger"]
    assert lg["enabled"]
    stages = lg["apps"]["wfapp"]["stages_ms"]
    # ingress + dispatch + device all saw blocks (first block is the
    # delta baseline, so count >= 2)
    for stage in ("ingress", "dispatch", "device", "total"):
        assert stages[stage]["count"] >= 2, (stage, stages)
    assert lg["apps"]["wfapp"]["lag"]["S"]["lag_ms"] is not None
    last = lg["apps"]["wfapp"]["last_block_ms"]
    assert last.get("device", 0) > 0
    # the flight ring rows carry the per-block waterfall
    rows = [r for r in flight().ring() if r.get("app") == "wfapp"
            and "ledger" in r]
    assert rows and rows[-1]["ledger"].get("device", 0) > 0
    rt.shutdown()


def test_ledger_kill_switch_end_to_end(monkeypatch):
    monkeypatch.setenv(LEDGER_ENV, "0")
    ledger().reset()
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "@app:name('offapp') "
        "define stream S (v float); "
        "@info(name='q') from S[v > 0.0] select v insert into Out;")
    rt.start()
    h = rt.get_input_handler("S")
    for i in range(3):
        h.send([float(i + 1)])
    rt.flush()
    snap = rt.statistics["ledger"]
    assert snap["enabled"] is False
    assert all(v == 0 for v in snap["stage_seconds"].values())
    assert "offapp" not in snap["apps"] or not snap["apps"]["offapp"].get(
        "stages_ms")
    rt.shutdown()


# ----------------------------------------------------------- REST + /slo

def _rest(method, url, payload=None):
    data = None
    if payload is not None:
        data = (payload if isinstance(payload, str)
                else json.dumps(payload)).encode()
    req = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read().decode())


def test_rest_slo_surface_and_health_degradation():
    from siddhi_tpu.service.rest import SiddhiService
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        _rest("POST", f"{base}/siddhi/artifact/deploy",
              "@app:name('slorest') "
              "@app:slo(latency.p99.ms='0.000001', window.blocks='8', "
              "breach.blocks='2') "
              "define stream S (v float); "
              "@info(name='q') from S[v > 0.0] select v insert into Out;")
        for i in range(12):
            _rest("POST", f"{base}/siddhi/apps/slorest/streams/S",
                  [{"data": [float(j + 1)]} for j in range(4)])
        svc.manager.get_siddhi_app_runtime("slorest").flush()
        slo = _rest("GET", f"{base}/slo")
        assert slo["enabled"]
        app_slo = slo["apps"]["slorest"]["slo"]
        assert app_slo["config"]["latency.p99.ms"] == 0.000001
        assert app_slo["breached"] is True
        assert app_slo["burn_rate"]["latency_p99"] > 1.0
        health = _rest("GET", f"{base}/health")
        assert health["apps"]["slorest"]["slo_breached"] is True
        assert health["status"] == "degraded"
        # burn-rate gauges ride /metrics
        req = urllib.request.Request(f"{base}/metrics")
        with urllib.request.urlopen(req, timeout=30) as r:
            text = r.read().decode()
        assert "siddhi_slo_burn_rate" in text
        assert 'siddhi_slo_breach_active{app="slorest"} 1' in text
        assert "siddhi_ledger_stage_seconds_total" in text
    finally:
        svc.stop()


# ------------------------------------------------------- SA07x analyzer

def test_analyzer_sa070_invalid_slo():
    from siddhi_tpu.analysis import analyze
    res = analyze(
        "@app:name('a') @app:slo(latency.p99.ms='fast') "
        "define stream S (v float); "
        "@info(name='q') from S[v > 0.0] select v insert into Out;")
    assert any(d.code == "SA070" for d in res.diagnostics)


def test_analyzer_sa071_unknown_option():
    from siddhi_tpu.analysis import analyze
    res = analyze(
        "@app:name('a') @app:slo(latency.p99.ms='250', latencyy='1') "
        "define stream S (v float); "
        "@info(name='q') from S[v > 0.0] select v insert into Out;")
    codes = [d.code for d in res.diagnostics]
    assert "SA071" in codes and "SA070" not in codes


def test_analyzer_sa072_no_targets():
    from siddhi_tpu.analysis import analyze
    res = analyze(
        "@app:name('a') @app:slo(window.blocks='16') "
        "define stream S (v float); "
        "@info(name='q') from S[v > 0.0] select v insert into Out;")
    assert any(d.code == "SA072" for d in res.diagnostics)


def test_analyzer_clean_slo_no_diagnostics():
    from siddhi_tpu.analysis import analyze
    res = analyze(
        "@app:name('a') @app:slo(latency.p99.ms='250', lag.ms='1000') "
        "define stream S (v float); "
        "@info(name='q') from S[v > 0.0] select v insert into Out;")
    assert not [d for d in res.diagnostics if d.code.startswith("SA07")]
