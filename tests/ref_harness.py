"""Harness for behavioral tests ported from the reference conformance
corpus (siddhi-core/src/test/java/io/siddhi/core/ — SURVEY.md §4 calls
those suites the de-facto conformance spec).

Each ported test supplies the SiddhiQL app, the event sends, and the
expected callback payloads from the reference test; `run_query` executes
them through the public API.  When the planner routes the query to the
device engine the same expectations apply — backend-identical output is
asserted by running both engines.
"""
import os
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from siddhi_tpu import QueryCallback, SiddhiManager, StreamCallback

# device-hit telemetry (VERDICT r2 next #6): every run_query records
# whether the planner actually executed the device engine, keyed by the
# running test.  conftest aggregates per suite at session end, regenerates
# the table in docs/conformance_map.md, and fails the run if a full-suite
# session regresses below tests/device_hit_floor.json.
TELEMETRY: List[Tuple[str, bool]] = []


def _norm(rows):
    """Reference float attrs are Java float (float32) — normalize both the
    engine output and expected literals through float32 for comparison."""
    out = []
    for r in rows:
        out.append(tuple(float(np.float32(v)) if isinstance(v, float) else v
                         for v in r))
    return out


def run_once(app: str, sends, callback_query: Optional[str],
             callback_stream: Optional[str], playback: bool,
             advance_to: Optional[int], engine: Optional[str]):
    prefix = ""
    if playback:
        prefix += "@app:playback "
    if engine:
        prefix += f"@app:engine('{engine}') "
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(prefix + app)
    got: List[tuple] = []
    removed: List[tuple] = []
    if callback_query:
        rt.add_callback(callback_query, QueryCallback(
            lambda ts, cur, exp: (
                got.extend(tuple(e.data) for e in (cur or [])),
                removed.extend(tuple(e.data) for e in (exp or [])))))
    else:
        rt.add_callback(callback_stream, StreamCallback(
            lambda evs: got.extend(tuple(e.data) for e in evs)))
    rt.start()
    ts = 1_000_000
    for send in sends:
        if len(send) == 3:
            sid, row, ts = send
        else:
            sid, row = send
            ts += 100
        if sid == "__advance__":
            # playback: advance virtual time so scheduler timers fire
            # between events (reference tests Thread.sleep here)
            rt.app_ctx.timestamp_generator.observe_event_time(ts)
            rt.app_ctx.scheduler.advance_to(ts)
            continue
        rt.get_input_handler(sid).send(list(row), timestamp=ts)
    if advance_to is not None:
        rt.app_ctx.timestamp_generator.observe_event_time(advance_to)
        rt.app_ctx.scheduler.advance_to(advance_to)
    backends = {name: q.backend for name, q in rt.query_runtimes.items()}
    # partitioned queries live in partition runtimes: keyed device mode
    # (device_query_runtimes) or host clones
    for pr in rt.partition_runtimes:
        for name, q in getattr(pr, "device_query_runtimes", {}).items():
            backends[name] = q.backend
    rt.shutdown()
    return got, removed, backends


def run_query(app: str, sends: Sequence, expected: Sequence,
              expected_removed: Optional[Sequence] = None,
              query: str = "query1", stream: Optional[str] = None,
              playback: bool = False, advance_to: Optional[int] = None,
              unordered: bool = False):
    """Run on the host engine, assert the reference expectations; if the
    planner compiles any query to the device, re-run on auto and assert
    backend-identical output."""
    cb_q = None if stream else query
    got, removed, _ = run_once(app, sends, cb_q, stream, playback,
                               advance_to, "host")
    norm = sorted if unordered else (lambda x: x)
    assert norm(_norm(got)) == norm(_norm(expected)), \
        f"host got {got!r}, expected {list(expected)!r}"
    if expected_removed is not None:
        assert norm(_norm(removed)) == norm(_norm(expected_removed)), \
            f"host removed {removed!r}, expected {list(expected_removed)!r}"
    got_d, removed_d, backends = run_once(app, sends, cb_q, stream,
                                          playback, advance_to, None)
    TELEMETRY.append((os.environ.get("PYTEST_CURRENT_TEST", "?"),
                      any(b == "device" for b in backends.values())))
    if any(b == "device" for b in backends.values()):
        assert norm(_norm(got_d)) == norm(_norm(got)), \
            f"device diverged: {got_d!r} vs host {got!r}"
        if expected_removed is not None:
            assert norm(_norm(removed_d)) == norm(_norm(removed))
    return backends
