"""Partition behavioural tests (reference model: siddhi-core
query/partition/PartitionTestCase1/2, PatternPartitionTestCase —
per-key isolated state, value and range partitions, inner streams)."""
import pytest

from siddhi_tpu import QueryCallback, SiddhiManager, StreamCallback


def make(app, cb_stream="Out"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback(cb_stream, StreamCallback(
        lambda evs: got.extend(e.data for e in evs)))
    rt.start()
    return m, rt, got


def test_value_partition_isolated_state():
    m, rt, got = make("""
        define stream S (symbol string, price float);
        partition with (symbol of S)
        begin
            from S select symbol, count() as c insert into Out;
        end;
    """)
    h = rt.get_input_handler("S")
    h.send(["IBM", 1.0])
    h.send(["WSO2", 2.0])
    h.send(["IBM", 3.0])      # IBM's counter independent of WSO2's
    rt.shutdown()
    assert got == [["IBM", 1], ["WSO2", 1], ["IBM", 2]]


def test_value_partition_windows_per_key():
    m, rt, got = make("""
        define stream S (symbol string, price float);
        partition with (symbol of S)
        begin
            from S#window.length(2) select symbol, sum(price) as total
            insert into Out;
        end;
    """)
    h = rt.get_input_handler("S")
    h.send(["A", 10.0])
    h.send(["B", 100.0])
    h.send(["A", 20.0])
    h.send(["A", 30.0])   # A's length-2 window slides: 20+30
    rt.shutdown()
    totals = [(g[0], g[1]) for g in got]
    assert totals[-1] == ("A", pytest.approx(50.0))
    assert ("B", pytest.approx(100.0)) in totals


def test_range_partition():
    m, rt, got = make("""
        define stream S (symbol string, volume int);
        partition with (volume < 100 as 'small' or volume >= 100 as 'large' of S)
        begin
            from S select symbol, count() as c insert into Out;
        end;
    """)
    h = rt.get_input_handler("S")
    h.send(["a", 50])
    h.send(["b", 500])
    h.send(["c", 70])     # same 'small' partition as a
    rt.shutdown()
    assert got == [["a", 1], ["b", 1], ["c", 2]]


def test_partition_inner_stream():
    m, rt, got = make("""
        define stream S (symbol string, price float);
        partition with (symbol of S)
        begin
            from S select symbol, price * 2.0 as doubled insert into #Mid;
            from #Mid select symbol, doubled + 1.0 as val insert into Out;
        end;
    """)
    h = rt.get_input_handler("S")
    h.send(["IBM", 10.0])
    rt.shutdown()
    assert got == [["IBM", pytest.approx(21.0)]]


def test_partition_query_callback():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, price float);
        partition with (symbol of S)
        begin
            @info(name='pq')
            from S select symbol, count() as c insert into Out;
        end;
    """)
    got = []
    rt.add_callback("pq", QueryCallback(
        lambda ts, cur, exp: got.extend(e.data for e in (cur or []))))
    rt.start()
    h = rt.get_input_handler("S")
    h.send(["X", 1.0])
    h.send(["X", 2.0])
    rt.shutdown()
    assert got == [["X", 1], ["X", 2]]


def test_partitioned_pattern():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, price float);
        partition with (symbol of S)
        begin
            @info(name='pq')
            from every e1=S[price > 20] -> e2=S[price > e1.price]
            select e1.symbol as symbol, e1.price as p1, e2.price as p2
            insert into Out;
        end;
    """)
    got = []
    rt.add_callback("pq", QueryCallback(
        lambda ts, cur, exp: got.extend(e.data for e in (cur or []))))
    rt.start()
    h = rt.get_input_handler("S")
    h.send(["A", 25.0])
    h.send(["B", 26.0])    # different key — must NOT complete A's pattern
    h.send(["A", 30.0])    # completes A's pattern
    h.send(["B", 40.0])    # completes B's pattern
    rt.shutdown()
    assert got == [["A", 25.0, 30.0], ["B", 26.0, 40.0]]


def test_partition_snapshot_restore():
    app = """
        define stream S (symbol string, price float);
        partition with (symbol of S)
        begin
            from S select symbol, count() as c insert into Out;
        end;
    """
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    rt.start()
    h = rt.get_input_handler("S")
    h.send(["IBM", 1.0])
    h.send(["IBM", 2.0])
    snap = rt.snapshot()
    rt.shutdown()

    m2 = SiddhiManager()
    rt2 = m2.create_siddhi_app_runtime(app)
    got = []
    rt2.add_callback("Out", StreamCallback(
        lambda evs: got.extend(e.data for e in evs)))
    rt2.restore(snap)
    rt2.start()
    rt2.get_input_handler("S").send(["IBM", 3.0])
    rt2.shutdown()
    assert got == [["IBM", 3]]
