"""Output rate-limiting conformance modeled on the reference suite
(query/ratelimit/ — first/last/all × events/time × group-by, snapshot;
reference query/output/ratelimit/** 19 limiter classes).
Time-based limiters run under @app:playback with explicit timestamps.
"""
from ref_harness import run_query

ADV = lambda ts: ("__advance__", None, ts)

S = "define stream S (symbol string, price float, volume int);\n"
Q = "@info(name = 'query1') "


def test_all_every_3_events():
    run_query(S + Q + """
        from S select symbol, price output every 3 events insert into out;""",
        [("S", ["A", 1.0, 1]), ("S", ["B", 2.0, 1]), ("S", ["C", 3.0, 1]),
         ("S", ["D", 4.0, 1])],
        [("A", 1.0), ("B", 2.0), ("C", 3.0)])


def test_first_every_3_events():
    run_query(S + Q + """
        from S select symbol output first every 3 events insert into out;""",
        [("S", ["A", 1.0, 1]), ("S", ["B", 2.0, 1]), ("S", ["C", 3.0, 1]),
         ("S", ["D", 4.0, 1]), ("S", ["E", 5.0, 1])],
        [("A",), ("D",)])


def test_last_every_3_events():
    run_query(S + Q + """
        from S select symbol output last every 3 events insert into out;""",
        [("S", ["A", 1.0, 1]), ("S", ["B", 2.0, 1]), ("S", ["C", 3.0, 1]),
         ("S", ["D", 4.0, 1])],
        [("C",)])


def test_all_every_time():
    run_query(S + Q + """
        from S select symbol output every 1 sec insert into out;""",
        [("S", ["A", 1.0, 1], 1000), ("S", ["B", 2.0, 1], 1400),
         ("S", ["C", 3.0, 1], 2100)],
        [("A",), ("B",), ("C",)], playback=True, advance_to=4000)


def test_first_every_time():
    run_query(S + Q + """
        from S select symbol output first every 1 sec insert into out;""",
        [("S", ["A", 1.0, 1], 1000), ("S", ["B", 2.0, 1], 1400),
         ADV(2050), ("S", ["C", 3.0, 1], 2100),
         ("S", ["D", 4.0, 1], 2200)],
        [("A",), ("C",)], playback=True, advance_to=4000)


def test_last_every_time():
    run_query(S + Q + """
        from S select symbol output last every 1 sec insert into out;""",
        [("S", ["A", 1.0, 1], 1000), ("S", ["B", 2.0, 1], 1400),
         ADV(2050), ("S", ["C", 3.0, 1], 2100)],
        [("B",), ("C",)], playback=True, advance_to=4000)


def test_first_per_group_every_events():
    run_query(S + Q + """
        from S select symbol, volume
        output first every 3 events insert into out;""",
        [("S", ["A", 1.0, 1]), ("S", ["A", 1.0, 2]), ("S", ["B", 2.0, 3]),
         ("S", ["B", 2.0, 4])],
        [("A", 1), ("B", 4)])


def test_snapshot_every_time_window_contents():
    run_query(S + Q + """
        from S#window.length(3) select symbol
        output snapshot every 1 sec insert into out;""",
        [("S", ["A", 1.0, 1], 1000), ("S", ["B", 2.0, 1], 1400)],
        [("A",), ("B",)], playback=True, advance_to=2100)


def test_rate_limit_with_aggregation():
    run_query(S + Q + """
        from S select sum(volume) as t output last every 2 events
        insert into out;""",
        [("S", ["A", 1.0, 10]), ("S", ["B", 1.0, 20]),
         ("S", ["C", 1.0, 30]), ("S", ["D", 1.0, 40])],
        [(30,), (100,)])


def test_rate_limit_group_by_aggregation():
    run_query(S + Q + """
        from S select symbol, sum(volume) as t group by symbol
        output last every 2 events insert into out;""",
        [("S", ["A", 1.0, 10]), ("S", ["A", 1.0, 20]),
         ("S", ["B", 1.0, 30]), ("S", ["B", 1.0, 40])],
        [(("A", 30)), ("B", 70)])
