"""Device slab-tensor incremental aggregation: conformance vs the host
bucket cascade (core/aggregation.py), routing, and state round-trips.

(reference model: aggregation/IncrementalExecutor.java:45-180 — here the
hot path is ops/incremental_agg.py segment reductions; see
plan/iagg_compiler.py.)"""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

APP = """
define stream S (symbol string, price double, volume long, ts long);
define aggregation Agg
from S
select symbol, avg(price) as avgPrice, sum(price) as total,
       count() as n, min(price) as lo, max(price) as hi
group by symbol
aggregate by ts every sec ... hour;
"""

Q = """
from Agg within 1496200000000, 1496400000000 per 'seconds'
select AGG_TIMESTAMP, symbol, avgPrice, total, n, lo, hi
"""


def run(engine, sends):
    prefix = f"@app:engine('{engine}') " if engine else ""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(prefix + APP)
    rt.start()
    h = rt.get_input_handler("S")
    for row in sends:
        h.send(list(row))
    events = rt.query(Q)
    rows = sorted([e.data for e in events], key=lambda r: (r[0], r[1]))
    agg = rt.aggregations["Agg"]
    rt.shutdown()
    return rows, agg


def gen(seed, n):
    rng = np.random.default_rng(seed)
    syms = ["A", "B", "C"]
    base = 1496289950000
    return [[syms[int(rng.integers(0, 3))],
             float(np.float32(rng.uniform(1.0, 100.0))),
             int(rng.integers(1, 5)),
             base + int(rng.integers(0, 120_000))]
            for _ in range(n)]


def test_device_routing_and_conformance():
    sends = gen(3, 400)
    host_rows, host_agg = run("host", sends)
    auto_rows, auto_agg = run(None, sends)
    from siddhi_tpu.plan.iagg_compiler import DeviceAggregationRuntime
    assert not isinstance(host_agg, DeviceAggregationRuntime)
    assert isinstance(auto_agg, DeviceAggregationRuntime)
    assert len(host_rows) == len(auto_rows) > 0
    for hr, ar in zip(host_rows, auto_rows):
        assert hr[0] == ar[0] and hr[1] == ar[1]      # bucket + group
        assert hr[4] == ar[4]                         # count exact
        for h, a in zip(hr[2:], ar[2:]):              # f32 lanes
            assert a == pytest.approx(h, rel=1e-5)


def test_device_agg_string_passthrough_falls_back_to_host():
    """A 'last'-of-string lane cannot ride float32 slabs → host runtime."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, note string, price double, ts long);
        define aggregation Agg
        from S select symbol, note, sum(price) as total
        group by symbol
        aggregate by ts every sec ... min;
    """)
    from siddhi_tpu.plan.iagg_compiler import DeviceAggregationRuntime
    agg = rt.aggregations["Agg"]
    assert not isinstance(agg, DeviceAggregationRuntime)
    rt.start()
    rt.get_input_handler("S").send(["A", "hello", 5.0, 1496289950000])
    events = rt.query("""
        from Agg within 1496200000000, 1496400000000 per 'seconds'
        select symbol, note, total""")
    rt.shutdown()
    assert [e.data for e in events] == [["A", "hello", 5.0]]
    # exactly one junction subscription survived the fallback
    junction = None
    for (sid, *_k), j in rt.junctions.items():
        if sid == "S":
            junction = j
    assert sum(1 for r in junction.receivers if isinstance(
        r, type(agg))) == 1


def test_device_agg_persist_restore_continuity():
    sends = gen(5, 120)
    m = SiddhiManager()
    from siddhi_tpu import InMemoryPersistenceStore
    m.set_persistence_store(InMemoryPersistenceStore())
    rt = m.create_siddhi_app_runtime(APP)
    rt.start()
    h = rt.get_input_handler("S")
    for row in sends[:60]:
        h.send(list(row))
    rev = rt.persist()
    rt.shutdown()

    rt2 = m.create_siddhi_app_runtime(APP)
    rt2.start()
    rt2.restore_revision(rev)
    h2 = rt2.get_input_handler("S")
    for row in sends[60:]:
        h2.send(list(row))
    got = sorted([e.data for e in rt2.query(Q)],
                 key=lambda r: (r[0], r[1]))
    rt2.shutdown()

    # reference run: everything through one uninterrupted runtime
    want, _ = run(None, sends)
    assert len(got) == len(want) > 0
    for g, w in zip(got, want):
        assert g[0] == w[0] and g[1] == w[1] and g[4] == w[4]
        for a, b in zip(g[2:], w[2:]):
            assert a == pytest.approx(b, rel=1e-5)


def test_device_agg_purge_matches_host():
    """Purging drops old buckets identically on both runtimes."""
    sends = gen(7, 100)
    for engine in ("host", None):
        prefix = f"@app:engine('{engine}') " if engine else ""
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(prefix + APP)
        rt.start()
        h = rt.get_input_handler("S")
        for row in sends:
            h.send(list(row))
        agg = rt.aggregations["Agg"]
        if hasattr(agg, "_sync"):
            agg._sync()
        newest = max(b for b, _ in agg.buckets["sec"].keys())
        agg.purge(newest + 10_000_000_000)
        if hasattr(agg, "_sync"):
            agg._sync()
        left = {d: len(agg.buckets[d]) for d in agg.durations}
        if engine == "host":
            host_left = left
        else:
            dev_left = left
        rt.shutdown()
    assert host_left == dev_left
