"""Ingest-armor contract tests (overload control, poison quarantine,
dispatch-storm watchdog — siddhi_tpu/core/overload.py):

  * SHED_OLDEST keeps the engine alive at 10x+ offered load with exact
    shed accounting (admitted == delivered + shed, to the event) and a
    bounded ingest p99 — no send ever wedges on a saturated buffer;
  * BLOCK bounds the formerly infinite ``Queue.put()`` with a timeout +
    typed BufferOverflowError routed through @OnError(action='STORE');
  * poison quarantine: a mixed poison feed produces results
    bit-identical to the pre-filtered feed; rejects land in the error
    store (origin='ingest') and a replay RE-validates (still-poison
    events return to the store: at-least-once, never silently dropped);
  * ts32 timestamp-slack edges: within-slack regressions admitted
    bit-identically, beyond-slack and would-wrap stamps quarantined;
  * wedged @Async stop(): drain bounded by drain.timeout.ms, leftovers
    counted as shed reason='drain_timeout';
  * dispatch-storm watchdog: the round-5 session re-arm crawl
    (re-introduced behind dwin_compiler.SESSION_REARM_PATHOLOGY) trips
    in < 500 dispatches, disarms the timer, records a WD001 incident
    (error store origin='watchdog'), and the app keeps running;
  * SA06x analyzer diagnostics, /health degraded + /metrics series, and
    the SIDDHI_TPU_INGEST_GUARD=0 kill switch.

All feeds come from the seeded generators in tests/chaos.py; no
assertion depends on a wall-clock sleep (rendezvous use junction.flush
and gated receivers).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import chaos  # noqa: E402  (tests/ is on sys.path via conftest)
from siddhi_tpu import SiddhiManager, StreamCallback  # noqa: E402
from siddhi_tpu.analysis import analyze  # noqa: E402
from siddhi_tpu.core.resilience import InMemoryErrorStore  # noqa: E402
from siddhi_tpu.ops.ts32 import safe_max  # noqa: E402


def _mk(app, error_store=None):
    m = SiddhiManager()
    if error_store is not None:
        m.set_error_store(error_store)
    return m, m.create_siddhi_app_runtime(app)


def _capture(rt, stream="Out"):
    got = []
    rt.add_callback(stream, StreamCallback(
        lambda evs: got.extend((e.timestamp, tuple(e.data)) for e in evs)))
    return got


S = "define stream In (symbol string, price float, volume long);\n"
PASS_Q = "@info(name='q') from In select symbol, price, volume " \
         "insert into Out;\n"


# ============================================================= admission

def test_shed_oldest_survives_overload_exact_accounting():
    """10x+ offered load against a wedged consumer: the engine stays
    alive, every send returns fast, and admitted == delivered + shed
    exactly (no event unaccounted)."""
    app = ("@Async(buffer.size='8', batch.size.max='1', "
           "overload='SHED_OLDEST', overload.high='0.75', "
           "overload.low='0.25') " + S + PASS_Q)
    m, rt = _mk(app)
    gate = chaos.GatedReceiver()
    rt.junctions["In"].subscribe(gate)
    rt.start()
    h = rt.get_input_handler("In")
    feed = chaos.burst_feed(400, seed=11)     # 50x the 8-chunk buffer
    lat = []
    for row, ts in feed:
        t0 = time.perf_counter()
        h.send(row, ts)
        lat.append(time.perf_counter() - t0)
    lat.sort()
    p99 = lat[int(len(lat) * 0.99) - 1]
    assert p99 < 0.25, f"ingest p99 {p99 * 1e3:.1f} ms — a send wedged"
    gate.open()
    rt.junctions["In"].flush()                # barrier: queue fully drained
    im = rt.ingest_metrics
    admitted = im.ingest_admitted_total.value(stream="In")
    shed = im.ingest_shed_total.value(stream="In", reason="shed_oldest")
    assert admitted == len(feed)              # SHED_OLDEST admits every send
    assert shed > 0                           # and genuinely shed under load
    assert admitted == gate.count + shed      # exact accounting
    assert im.ingest_overflow_total.value(stream="In") == 0
    rt.shutdown()
    m.shutdown()


def test_block_policy_bounded_timeout_routes_to_error_store():
    """BLOCK + full buffer: put() is bounded by block.timeout.ms and the
    overflow surfaces as a typed BufferOverflowError through
    @OnError(action='STORE') — the pre-armor code blocked forever."""
    app = ("@OnError(action='STORE') "
           "@Async(buffer.size='4', batch.size.max='1', overload='BLOCK', "
           "block.timeout.ms='200') " + S + PASS_Q)
    m, rt = _mk(app, error_store=InMemoryErrorStore())
    gate = chaos.GatedReceiver()
    rt.junctions["In"].subscribe(gate)
    rt.start()
    h = rt.get_input_handler("In")
    for row, ts in chaos.burst_feed(5, seed=3):   # fills buffer + in-hand
        h.send(row, ts)
    t0 = time.perf_counter()
    for row, ts in chaos.burst_feed(3, seed=4, start_ts=2_000_000):
        h.send(row, ts)                            # each: 200 ms then typed
    elapsed = time.perf_counter() - t0
    assert 0.55 <= elapsed < 5.0, f"timeout not bounded: {elapsed:.2f}s"
    entries = rt.error_store.list(app_name=rt.name)
    assert [e.origin for e in entries] == ["stream"] * 3
    assert all("BufferOverflowError" in e.error for e in entries)
    im = rt.ingest_metrics
    assert im.ingest_overflow_total.value(stream="In") == 3
    gate.open()
    rt.junctions["In"].flush()
    rt.shutdown()
    m.shutdown()


def test_store_policy_spills_to_error_store():
    """STORE: above the high watermark new chunks divert to the error
    store (origin='overload') instead of shedding, and a replay
    re-ingests them once the consumer recovers."""
    app = ("@Async(buffer.size='4', batch.size.max='1', overload='STORE', "
           "overload.high='0.75') " + S + PASS_Q)
    m, rt = _mk(app, error_store=InMemoryErrorStore())
    gate = chaos.GatedReceiver()
    rt.junctions["In"].subscribe(gate)
    rt.start()
    h = rt.get_input_handler("In")
    feed = chaos.burst_feed(40, seed=5)
    for row, ts in feed:
        h.send(row, ts)
    entries = rt.error_store.list(app_name=rt.name)
    assert entries and all(e.origin == "overload" for e in entries)
    stored = sum(len(e.events) for e in entries)
    im = rt.ingest_metrics
    assert im.ingest_shed_total.value(stream="In", reason="stored") == stored
    assert im.ingest_admitted_total.value(stream="In") + stored == len(feed)
    gate.open()
    rt.junctions["In"].flush()
    before = gate.count
    assert rt.replay_errors() == stored
    # admission still applies during replay: a replayed burst that
    # refills the buffer re-diverts to the store (no loss, no dup) —
    # drain in bounded rounds until the store is empty
    for _ in range(50):
        rt.junctions["In"].flush()
        if rt.error_store.count(rt.name) == 0:
            break
        rt.replay_errors()
    assert rt.error_store.count(rt.name) == 0
    assert gate.count == before + stored      # recovered, none lost
    rt.shutdown()
    m.shutdown()


def test_wedged_async_stop_drain_is_bounded():
    """A receiver wedged forever must not wedge shutdown: the drain is
    bounded by @Async(drain.timeout.ms) and leftovers are counted as
    shed reason='drain_timeout'."""
    app = ("@Async(buffer.size='8', batch.size.max='1', "
           "drain.timeout.ms='500') " + S + PASS_Q)
    m, rt = _mk(app)
    gate = chaos.GatedReceiver()                  # never opened pre-stop
    rt.junctions["In"].subscribe(gate)
    rt.start()
    h = rt.get_input_handler("In")
    for row, ts in chaos.burst_feed(6, seed=7):
        h.send(row, ts)
    im = rt.ingest_metrics
    t0 = time.perf_counter()
    rt.shutdown()
    elapsed = time.perf_counter() - t0
    assert elapsed < 10.0, f"shutdown wedged for {elapsed:.1f}s"
    assert im.ingest_shed_total.value(stream="In",
                                      reason="drain_timeout") > 0
    gate.open()                                   # release the dead worker
    m.shutdown()


# ============================================================ quarantine

QUAR = "@quarantine(ts.slack.ms='1000') " + S


def test_poison_feed_parity_and_replay_revalidates():
    """A mixed poison feed (NaN/Inf prices, a non-coercible volume,
    wildly regressed stamps) through a quarantined stream produces
    output BIT-IDENTICAL to the pre-filtered feed through an unguarded
    stream; every reject is stored (origin='ingest') and a replay
    re-validates — still-poison events return to the store."""
    rows, clean = chaos.poison_feed(60, seed=13, poison_every=5)
    store = InMemoryErrorStore()
    m, rt = _mk(QUAR + PASS_Q, error_store=store)
    got = _capture(rt)
    rt.start()
    h = rt.get_input_handler("In")
    for row, ts in rows:
        h.send(row, ts)
    rt.flush()

    m2, rt2 = _mk(S + PASS_Q)                     # unguarded reference
    want = _capture(rt2)
    rt2.start()
    h2 = rt2.get_input_handler("In")
    for row, ts in clean:
        h2.send(row, ts)
    rt2.flush()
    assert got == want, "quarantined run diverged from pre-filtered run"

    n_poison = len(rows) - len(clean)
    entries = store.list(app_name=rt.name)
    assert sum(len(e.events) for e in entries) == n_poison
    assert all(e.origin == "ingest" for e in entries)
    reasons = {r for e in entries
               for r in ("nan", "type", "ts_regress") if r in e.error}
    assert reasons == {"nan", "type", "ts_regress"}
    im = rt.ingest_metrics
    quarantined = sum(im.ingest_quarantined_total.series().values())
    assert quarantined == n_poison

    # replay re-validates: poison is still poison, back in the store
    rt.replay_errors()
    rt.flush()
    entries = store.list(app_name=rt.name)
    assert sum(len(e.events) for e in entries) == n_poison
    assert got == want, "replay must not leak poison into results"
    rt.shutdown()
    m.shutdown()
    rt2.shutdown()
    m2.shutdown()


def test_backwards_timestamp_feed_quarantined():
    """Every beyond-slack regression from the seeded backwards feed is
    quarantined; the admitted remainder flows through untouched."""
    feed = chaos.backwards_feed(50, seed=17, jump_back_ms=60_000, every=7)
    store = InMemoryErrorStore()
    m, rt = _mk(QUAR + PASS_Q, error_store=store)
    got = _capture(rt)
    rt.start()
    h = rt.get_input_handler("In")
    for row, ts in feed:
        h.send(row, ts)
    rt.flush()
    n_bad = sum(1 for i in range(50) if i and i % 7 == 0)
    im = rt.ingest_metrics
    assert im.ingest_quarantined_total.value(
        stream="In", reason="ts_regress") == n_bad
    assert len(got) == len(feed) - n_bad
    assert all("ts_regress" in e.error
               for e in store.list(app_name=rt.name))
    rt.shutdown()
    m.shutdown()


def test_ts32_slack_edges():
    """ts32 admissibility edges: a regression of exactly the slack is
    admitted bit-identically, one ms beyond is quarantined, an offset
    past safe_max(slack) would wrap the ts32 window math and is
    quarantined WITHOUT advancing the high-water mark."""
    slack = 1000
    base = 1_000_000
    m, rt = _mk(QUAR + PASS_Q, error_store=InMemoryErrorStore())
    got = _capture(rt)
    rt.start()
    h = rt.get_input_handler("In")
    h.send(["A", 1.0, 1], base)                   # hwm = base
    h.send(["B", 2.0, 2], base - slack)           # exactly slack: admitted
    h.send(["C", 3.0, 3], base - slack - 1)       # beyond: quarantined
    h.send(["D", 4.0, 4], base + safe_max(slack) + 1)   # would wrap
    h.send(["E", 5.0, 5], base + 10)              # hwm didn't move: admitted
    rt.flush()
    assert [(ts, d[0]) for ts, d in got] == \
        [(base, "A"), (base - slack, "B"), (base + 10, "E")]
    im = rt.ingest_metrics
    assert im.ingest_quarantined_total.value(
        stream="In", reason="ts_regress") == 1
    assert im.ingest_quarantined_total.value(
        stream="In", reason="ts_wrap") == 1

    # parity: the admitted subset through an unguarded engine is
    # bit-identical (the validator must not perturb admitted events)
    m2, rt2 = _mk(S + PASS_Q)
    want = _capture(rt2)
    rt2.start()
    h2 = rt2.get_input_handler("In")
    for row, ts in [(["A", 1.0, 1], base), (["B", 2.0, 2], base - slack),
                    (["E", 5.0, 5], base + 10)]:
        h2.send(row, ts)
    rt2.flush()
    assert got == want
    rt.shutdown()
    m.shutdown()
    rt2.shutdown()
    m2.shutdown()


# ============================================================== watchdog

def test_dispatch_storm_watchdog_trips_and_disarms():
    """Regression for the session-timer dispatch storm: with the
    round-5 re-arm pathology re-introduced (a 1 ms timer crawl with
    zero ingest progress), the watchdog must trip in < 500 dispatches,
    force-disarm the timer, record a WD001 incident (and an error-store
    entry, origin='watchdog'), and the app must keep running."""
    import numpy as np

    import siddhi_tpu.plan.dwin_compiler as dwc
    from siddhi_tpu import QueryCallback

    cse = "define stream cse (symbol string, price float, volume long);\n"
    app = ("@app:playback " + cse +
           "@info(name='q') from cse#window.session(700, symbol) "
           "select symbol, price, volume insert all events into out;")
    fired = [0]
    orig = dwc.DeviceWindowProcessor._on_timer

    def counted(self, now):
        fired[0] += 1
        return orig(self, now)

    dwc.SESSION_REARM_PATHOLOGY = True
    dwc.DeviceWindowProcessor._on_timer = counted
    try:
        m, rt = _mk(app, error_store=InMemoryErrorStore())
        rt.add_callback("q", QueryCallback(lambda *a: None))
        rt.start()
        h = rt.get_input_handler("cse")

        def send(sym, ts):
            h.send_batch(
                {"symbol": np.asarray([sym], object),
                 "price": np.asarray([1.0], np.float32),
                 "volume": np.asarray([ts], np.int64)},
                np.asarray([ts], np.int64))

        send("A", 1000)
        send("C", 50_000)      # un-guarded: a ~49k-fire 1 ms crawl
        wd = rt.watchdog
        assert wd.incidents, "watchdog did not trip on the storm"
        inc = wd.incidents[0]
        assert inc["code"] == "WD001"
        assert inc["fires"] < 500
        assert fired[0] < 500, f"storm ran {fired[0]} dispatches"
        assert inc["target"].endswith(".counted")   # the timer target
        entries = rt.error_store.list(app_name=rt.name)
        assert any(e.origin == "watchdog" for e in entries)
        assert rt.ingest_metrics.watchdog_trips_total.series()
        send("D", 60_000)      # timer disarmed; the app still ingests
        rt.flush()
        rt.shutdown()
        m.shutdown()
    finally:
        dwc.SESSION_REARM_PATHOLOGY = False
        dwc.DeviceWindowProcessor._on_timer = orig


# ============================================================== analyzer

def test_analyzer_sa06x_diagnostics():
    ok = ("@Async(buffer.size='64', overload='SHED_OLDEST', "
          "overload.high='0.8', overload.low='0.5') " + S + PASS_Q)
    assert not {"SA060", "SA061", "SA062", "SA063"} & \
        set(analyze(ok).codes())
    bad_policy = "@Async(overload='DROP_EVERYTHING') " + S + PASS_Q
    assert "SA060" in analyze(bad_policy).codes()
    bad_marks = ("@Async(overload='SHED_NEW', overload.high='0.2', "
                 "overload.low='0.9') " + S + PASS_Q)
    assert "SA061" in analyze(bad_marks).codes()
    store_no_store = "@Async(overload='STORE') " + S + PASS_Q
    assert "SA062" in analyze(store_no_store).codes()
    bad_quar = "@quarantine(nan='maybe') " + S + PASS_Q
    assert "SA063" in analyze(bad_quar).codes()
    bad_slack = "@quarantine(ts.slack.ms='-5') " + S + PASS_Q
    assert "SA063" in analyze(bad_slack).codes()


# ============================================================== service

def test_service_health_degraded_and_ingest_metrics():
    """REST surface: a saturated @Async buffer flips /health to
    'degraded' with the stream listed, /metrics exposes the
    siddhi_ingest_* series, and recovery returns /health to 'up'."""
    import json
    import urllib.request

    from siddhi_tpu.service import SiddhiService

    def req(method, url, body=None):
        data = json.dumps(body).encode() if isinstance(body, (dict, list)) \
            else (body.encode() if isinstance(body, str) else None)
        r = urllib.request.Request(url, data=data, method=method)
        with urllib.request.urlopen(r) as resp:
            return resp.status, resp.read().decode()

    app = ("@app:name('armored') "
           "@Async(buffer.size='4', batch.size.max='1', "
           "overload='SHED_NEW', overload.high='0.25', "
           "overload.low='0.1', drain.timeout.ms='500') "
           "define stream S (symbol string, price float); "
           "@info(name='q') from S select symbol, price insert into Out;")
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    gate = chaos.GatedReceiver()
    try:
        req("POST", f"{base}/siddhi/artifact/deploy", app)
        rt = svc.manager.runtimes["armored"]
        rt.junctions["S"].subscribe(gate)
        # wedge the worker on one delivery first, then burst: the queue
        # then sits pinned at the high watermark (high_chunks=1)
        req("POST", f"{base}/siddhi/apps/armored/streams/S",
            [{"data": ["A", 0.0]}])
        assert gate.entered.wait(10.0)
        req("POST", f"{base}/siddhi/apps/armored/streams/S",
            [{"data": ["A", float(i)]} for i in range(12)])
        _, body = req("GET", f"{base}/health")
        health = json.loads(body)
        assert health["status"] == "degraded"
        assert health["apps"]["armored"]["saturated_streams"] == ["S"]
        _, text = req("GET", f"{base}/metrics")
        assert "# TYPE siddhi_ingest_admitted_total counter" in text
        assert 'siddhi_ingest_admitted_total{app="armored",stream="S"}' \
            in text
        assert 'siddhi_ingest_shed_total{app="armored",' in text
        assert 'siddhi_ingest_saturation{app="armored",stream="S"}' in text
        gate.open()
        rt.junctions["S"].flush()
        _, body = req("GET", f"{base}/health")
        assert json.loads(body)["status"] == "up"
    finally:
        gate.open()            # never leave a wedged worker for stop()
        svc.stop()


# ============================================================ kill switch

def test_kill_switch_disables_ingest_guard(monkeypatch):
    """SIDDHI_TPU_INGEST_GUARD=0: no admission control, no validator, no
    watchdog — the legacy unbounded path, bit-for-bit."""
    monkeypatch.setenv("SIDDHI_TPU_INGEST_GUARD", "0")
    app = ("@Async(buffer.size='8', overload='SHED_OLDEST') " + QUAR +
           PASS_Q)
    m, rt = _mk(app)
    got = _capture(rt)
    rt.start()
    j = rt.junctions["In"]
    assert j.overload is None
    assert j.validator is None
    assert rt.watchdog is None
    h = rt.get_input_handler("In")
    h.send(["A", float("nan"), 1], 1000)      # poison flows through
    rt.flush()
    assert len(got) == 1
    im = rt.ingest_metrics
    assert sum(im.ingest_admitted_total.series().values()) == 0
    rt.shutdown()
    m.shutdown()
