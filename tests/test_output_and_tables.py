"""Rate limiting, table CRUD, triggers, statistics, store queries and
distributed sinks (reference models: query/ratelimit/, query/table/,
trigger tests, managment/StatisticsTestCase, store/,
transport/MultiClientDistributedSinkTestCase)."""
import pytest

from siddhi_tpu import QueryCallback, SiddhiManager, StreamCallback
from siddhi_tpu.core.source_sink import InMemoryBroker


def make(app, cb="Out"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback(cb, StreamCallback(
        lambda evs: got.extend(e.data for e in evs)))
    rt.start()
    return m, rt, got


# ---------------------------------------------------------------- rate limit

def test_output_every_n_events():
    m, rt, got = make("""
        define stream S (v int);
        from S select v output every 3 events insert into Out;
    """)
    h = rt.get_input_handler("S")
    for i in range(7):
        h.send([i])
    rt.shutdown()
    # batches flushed at every 3rd event
    assert [g[0] for g in got] == [0, 1, 2, 3, 4, 5]


def test_output_first_every_n_events():
    m, rt, got = make("""
        define stream S (v int);
        from S select v output first every 3 events insert into Out;
    """)
    h = rt.get_input_handler("S")
    for i in range(7):
        h.send([i])
    rt.shutdown()
    assert [g[0] for g in got] == [0, 3, 6]


def test_output_last_every_n_events():
    m, rt, got = make("""
        define stream S (v int);
        from S select v output last every 3 events insert into Out;
    """)
    h = rt.get_input_handler("S")
    for i in range(6):
        h.send([i])
    rt.shutdown()
    assert [g[0] for g in got] == [2, 5]


def test_output_snapshot_every_time():
    m, rt, got = make("""
        @app:playback
        define stream S (v int);
        from S#window.length(5) select sum(v) as total
        output snapshot every 1 sec insert into Out;
    """)
    h = rt.get_input_handler("S")
    h.send([10], timestamp=1000)
    h.send([20], timestamp=1200)
    rt.app_ctx.timestamp_generator.observe_event_time(2100)
    rt.app_ctx.scheduler.advance_to(2100)
    rt.shutdown()
    assert got and got[-1][0] == 30


# ---------------------------------------------------------------- tables

TABLE_APP = """
define stream Add (symbol string, price float);
define stream Del (symbol string);
define stream Upd (symbol string, price float);
define stream Check (symbol string);
define table T (symbol string, price float);
from Add insert into T;
from Del delete T on T.symbol == Del.symbol;
from Upd update T set T.price = Upd.price on T.symbol == Upd.symbol;
@info(name='q') from Check[Check.symbol in T] select symbol insert into Out;
"""


def test_table_insert_delete_update_in():
    m, rt, got = make(TABLE_APP)
    add = rt.get_input_handler("Add")
    add.send(["IBM", 10.0])
    add.send(["WSO2", 20.0])
    rt.get_input_handler("Check").send(["IBM"])          # present
    rt.get_input_handler("Del").send(["IBM"])
    rt.get_input_handler("Check").send(["IBM"])          # deleted
    rt.get_input_handler("Upd").send(["WSO2", 99.0])
    events = rt.query("from T select symbol, price")
    rt.shutdown()
    assert got == [["IBM"]]
    assert [e.data for e in events] == [["WSO2", 99.0]]


def test_table_update_or_insert():
    m, rt, got = make("""
        define stream S (symbol string, price float);
        define table T (symbol string, price float);
        from S update or insert into T set T.price = S.price
            on T.symbol == S.symbol;
    """, cb=None) if False else (None, None, None)
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, price float);
        define table T (symbol string, price float);
        from S update or insert into T set T.price = S.price
            on T.symbol == S.symbol;
    """)
    rt.start()
    h = rt.get_input_handler("S")
    h.send(["IBM", 1.0])
    h.send(["IBM", 2.0])     # updates, not duplicates
    h.send(["WSO2", 3.0])
    events = rt.query("from T select symbol, price")
    rt.shutdown()
    assert sorted(e.data for e in events) == [["IBM", 2.0], ["WSO2", 3.0]]


def test_primary_key_table_store_query():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, price float);
        @PrimaryKey('symbol')
        define table T (symbol string, price float);
        from S insert into T;
    """)
    rt.start()
    h = rt.get_input_handler("S")
    h.send(["A", 1.0])
    h.send(["B", 2.0])
    events = rt.query("from T on T.symbol == 'B' select symbol, price")
    rt.shutdown()
    assert [e.data for e in events] == [["B", 2.0]]


def test_store_query_cache_is_lru():
    """The store-query runtime cache evicts least-recently-used entries one
    at a time, not wholesale (reference SiddhiAppRuntime.java:280-316)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, price float);
        define table T (symbol string, price float);
        from S insert into T;
    """)
    rt.start()
    rt.get_input_handler("S").send(["A", 1.0])
    rt._store_query_cache_size = 4
    for i in range(6):
        rt.query(f"from T select symbol, price limit {i + 1}")
    q0 = "from T select symbol, price limit 1"
    assert q0 not in rt._store_query_cache          # evicted (LRU)
    assert len(rt._store_query_cache) == 4
    # touching an entry protects it from the next eviction
    q3 = "from T select symbol, price limit 3"
    rt.query(q3)
    rt.query("from T select symbol")                # evicts limit-4, not q3
    assert q3 in rt._store_query_cache
    rt.shutdown()


def test_secondary_index_probe_used_and_correct():
    """@Index conditions must consult the hash index (not full-scan) and
    stay correct across updates/deletes/PK-overwrites (reference:
    IndexEventHolder secondary indexes)."""
    import numpy as np

    from siddhi_tpu.core.table import InMemoryTable
    from siddhi_tpu.query_api.definition import (Attribute, AttrType,
                                                 StreamDefinition)

    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, grp string, price float);
        define stream Del (grp string);
        define stream Upd (sym string, grp string);
        @PrimaryKey('sym') @Index('grp')
        define table T (sym string, grp string, price float);
        from S insert into T;
        from Del delete T on T.grp == Del.grp;
        from Upd update T set T.grp = Upd.grp on T.sym == Upd.sym;
    """)
    rt.start()
    h = rt.get_input_handler("S")
    for i in range(50):
        h.send([f"s{i}", f"g{i % 5}", float(i)])

    table = rt.tables["T"]
    # the compiled store-query condition picks the index probe
    sdef = StreamDefinition("q", [Attribute("g", AttrType.STRING)])
    from siddhi_tpu.plan.expr_compiler import ExprCompiler
    from siddhi_tpu.compiler.parser import parse_expression
    cond = table.compile_condition(
        parse_expression("T.grp == 'g2' and price > 10.0"), None,
        lambda scope: ExprCompiler(scope, np))
    assert cond.index_probe is not None and cond.index_probe[0] == "grp"
    rows = table.find(cond)
    assert sorted(rows.columns["sym"].tolist()) == \
        sorted(f"s{i}" for i in range(50) if i % 5 == 2 and i > 10)

    # update moves a row between buckets; delete drops a bucket
    rt.get_input_handler("Upd").send(["s2", "g0"])
    rows = table.find(cond)
    assert "s2" not in rows.columns["sym"].tolist()
    rt.get_input_handler("Del").send(["g2"])
    assert len(table.find(cond)) == 0
    # PK overwrite re-buckets (insert with clashing key rewrites the row)
    h.send(["s0", "g2", 999.0])
    rows = table.find(cond)
    assert rows.columns["sym"].tolist() == ["s0"]
    rt.shutdown()


def test_secondary_index_beats_full_scan():
    """Probe cost must scale with bucket size, not table size."""
    import time as _time

    import numpy as np

    from siddhi_tpu.compiler.parser import parse_expression
    from siddhi_tpu.plan.expr_compiler import ExprCompiler

    def build(n_rows, indexed):
        m = SiddhiManager()
        ann = "@Index('grp')" if indexed else ""
        rt = m.create_siddhi_app_runtime(f"""
            define stream S (sym string, grp string, price float);
            {ann}
            define table T (sym string, grp string, price float);
            from S insert into T;
        """)
        rt.start()
        cols = {"sym": np.asarray([f"s{i}" for i in range(n_rows)], object),
                "grp": np.asarray([f"g{i}" for i in range(n_rows)], object),
                "price": np.arange(n_rows, dtype=np.float32)}
        rt.get_input_handler("S").send_batch(cols)
        return rt

    def probe_time(rt, reps=60):
        table = rt.tables["T"]
        cond = table.compile_condition(
            parse_expression("T.grp == 'g7'"), None,
            lambda scope: ExprCompiler(scope, np))
        table.find(cond)       # warm the column cache
        t0 = _time.perf_counter()
        for _ in range(reps):
            table.find(cond)
        return (_time.perf_counter() - t0) / reps

    rt_small = build(200, indexed=True)
    rt_big = build(20_000, indexed=True)
    rt_big_scan = build(20_000, indexed=False)
    t_small, t_big = probe_time(rt_small), probe_time(rt_big)
    t_scan = probe_time(rt_big_scan)
    for rt in (rt_small, rt_big, rt_big_scan):
        rt.shutdown()
    # indexed probe ~O(bucket): 100× more rows must NOT cost 10× more;
    # unindexed full scan over 20k rows must be clearly slower
    assert t_big < t_small * 10, (t_small, t_big)
    assert t_scan > t_big * 3, (t_big, t_scan)


# ---------------------------------------------------------------- triggers

def test_periodic_trigger_playback():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:playback
        define trigger T at every 1 sec;
        from T select triggered_time insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: got.extend(e.data for e in evs)))
    rt.start()
    rt.app_ctx.timestamp_generator.observe_event_time(3500)
    rt.app_ctx.scheduler.advance_to(3500)
    rt.shutdown()
    assert len(got) >= 2


def test_start_trigger():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define trigger T at 'start';
        from T select triggered_time insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: got.extend(e.data for e in evs)))
    rt.start()
    rt.shutdown()
    assert len(got) == 1


# ---------------------------------------------------------------- statistics

def test_statistics_counters():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:statistics(reporter='console', interval='300')
        define stream S (v int);
        @info(name='q') from S[v > 0] select v insert into Out;
    """)
    rt.start()
    h = rt.get_input_handler("S")
    for i in range(5):
        h.send([i + 1])
    snap = rt.statistics
    rt.shutdown()
    flat = str(snap)
    assert "S" in flat
    # throughput tracker saw the 5 events
    assert any("5" in str(v) for v in str(snap).split())


# ---------------------------------------------------------------- dist sinks

def test_round_robin_distributed_sink():
    class Collect:
        def __init__(self, topic):
            self.topic = topic
            self.items = []

        def on_message(self, msg):
            self.items.append(msg)

    c1, c2 = Collect("d1"), Collect("d2")
    InMemoryBroker.subscribe(c1)
    InMemoryBroker.subscribe(c2)
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (v int);
        @sink(type='inMemory', @map(type='passThrough'),
              @distribution(strategy='roundRobin',
                            @destination(topic='d1'),
                            @destination(topic='d2')))
        define stream Out (v int);
        from S select v insert into Out;
    """)
    rt.start()
    h = rt.get_input_handler("S")
    for i in range(4):
        h.send([i])
    rt.shutdown()
    InMemoryBroker.unsubscribe(c1)
    InMemoryBroker.unsubscribe(c2)
    assert len(c1.items) == 2 and len(c2.items) == 2


def test_broadcast_distributed_sink():
    class Collect:
        def __init__(self, topic):
            self.topic = topic
            self.items = []

        def on_message(self, msg):
            self.items.append(msg)

    c1, c2 = Collect("b1"), Collect("b2")
    InMemoryBroker.subscribe(c1)
    InMemoryBroker.subscribe(c2)
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (v int);
        @sink(type='inMemory', @map(type='passThrough'),
              @distribution(strategy='broadcast',
                            @destination(topic='b1'),
                            @destination(topic='b2')))
        define stream Out (v int);
        from S select v insert into Out;
    """)
    rt.start()
    rt.get_input_handler("S").send([7])
    rt.shutdown()
    InMemoryBroker.unsubscribe(c1)
    InMemoryBroker.unsubscribe(c2)
    assert len(c1.items) == 1 and len(c2.items) == 1
