"""Filter conformance matrix modeled on the reference filter suites
(query/FilterTestCase1.java 81 @Tests + FilterTestCase2.java 41 @Tests):
every comparison operator against every numeric literal suffix
(int / 50L / 50f / 50d) and attribute type, plus bool/string equality,
and/or/not combinations, arithmetic in conditions, and literal-first
orderings.  Each case runs on the host engine and — numeric shapes —
re-runs compiled on the device engine with identical output asserted.
"""
import pytest

from ref_harness import run_query

CSE = ("define stream cse (symbol string, price float, volume long, "
       "quantity int, available bool, ratio double);\n")
Q = "@info(name = 'query1') "

ROWS = [
    ("WSO2", 50.0, 100, 5, True, 8.5),
    ("IBM", 72.5, 40, 2, False, 1.25),
    ("ORACLE", 35.0, 200, 9, True, 0.5),
]


def _run_filter(cond, expected_symbols):
    run_query(CSE + Q + f"""
        from cse[{cond}] select symbol, volume insert into out;""",
        [("cse", list(r)) for r in ROWS],
        [(r[0], r[2]) for r in ROWS if r[0] in expected_symbols])


# op × literal-suffix matrix over a long attribute (reference testFilterQuery
# 4-30: volume > 50L / 50f / 50d / 45 …)
CMP_CASES = [
    ("volume > 50", {"WSO2", "ORACLE"}),
    ("volume > 50L", {"WSO2", "ORACLE"}),
    ("volume > 50f", {"WSO2", "ORACLE"}),
    ("volume > 50d", {"WSO2", "ORACLE"}),
    ("volume >= 100", {"WSO2", "ORACLE"}),
    ("volume >= 100L", {"WSO2", "ORACLE"}),
    ("volume >= 200f", {"ORACLE"}),
    ("volume >= 200d", {"ORACLE"}),
    ("volume < 100", {"IBM"}),
    ("volume < 100L", {"IBM"}),
    ("volume < 100.0f", {"IBM"}),
    ("volume < 100d", {"IBM"}),
    ("volume <= 100", {"WSO2", "IBM"}),
    ("volume <= 100L", {"WSO2", "IBM"}),
    ("volume <= 40f", {"IBM"}),
    ("volume <= 40d", {"IBM"}),
    ("volume == 100", {"WSO2"}),
    ("volume == 100L", {"WSO2"}),
    ("volume == 40f", {"IBM"}),
    ("volume == 200d", {"ORACLE"}),
    ("volume != 100", {"IBM", "ORACLE"}),
    ("volume != 100L", {"IBM", "ORACLE"}),
    ("volume != 40f", {"WSO2", "ORACLE"}),
    ("volume != 200d", {"WSO2", "IBM"}),
    # literal-first orderings (reference: `70 > price`, `150 > volume`)
    ("70 > price", {"WSO2", "ORACLE"}),
    ("150 > volume", {"WSO2", "IBM"}),
    ("100 == volume", {"WSO2"}),
    ("100 != volume", {"IBM", "ORACLE"}),
    ("40 <= volume", {"WSO2", "IBM", "ORACLE"}),
    ("200 <= volume", {"ORACLE"}),
]


@pytest.mark.parametrize("cond,expected", CMP_CASES,
                         ids=[c[0] for c in CMP_CASES])
def test_filter_long_matrix(cond, expected):
    _run_filter(cond, expected)


# float attribute vs every suffix (reference testFilterQuery 31-55)
FLOAT_CASES = [
    ("price > 50", {"IBM"}),
    ("price > 50L", {"IBM"}),
    ("price > 50f", {"IBM"}),
    ("price > 50d", {"IBM"}),
    ("price >= 50.0", {"WSO2", "IBM"}),
    ("price < 50", {"ORACLE"}),
    ("price <= 50", {"WSO2", "ORACLE"}),
    ("price == 50.0", {"WSO2"}),
    ("price == 50", {"WSO2"}),
    ("price != 50.0", {"IBM", "ORACLE"}),
    ("price != 35L", {"WSO2", "IBM"}),
]


@pytest.mark.parametrize("cond,expected", FLOAT_CASES,
                         ids=[c[0] for c in FLOAT_CASES])
def test_filter_float_matrix(cond, expected):
    _run_filter(cond, expected)


# int attribute matrix (quantity)
INT_CASES = [
    ("quantity > 4", {"WSO2", "ORACLE"}),
    ("quantity > 4L", {"WSO2", "ORACLE"}),
    ("quantity > 4f", {"WSO2", "ORACLE"}),
    ("quantity > 4d", {"WSO2", "ORACLE"}),
    ("quantity == 2", {"IBM"}),
    ("quantity != 2", {"WSO2", "ORACLE"}),
    ("quantity <= 5", {"WSO2", "IBM"}),
]


@pytest.mark.parametrize("cond,expected", INT_CASES,
                         ids=[c[0] for c in INT_CASES])
def test_filter_int_matrix(cond, expected):
    _run_filter(cond, expected)


# double attribute matrix (ratio)
DOUBLE_CASES = [
    ("ratio > 1.0", {"WSO2", "IBM"}),
    ("ratio > 1", {"WSO2", "IBM"}),
    ("ratio > 1L", {"WSO2", "IBM"}),
    ("ratio > 1.0f", {"WSO2", "IBM"}),
    ("ratio < 1.0d", {"ORACLE"}),
    ("ratio == 0.5", {"ORACLE"}),
    ("ratio != 0.5", {"WSO2", "IBM"}),
]


@pytest.mark.parametrize("cond,expected", DOUBLE_CASES,
                         ids=[c[0] for c in DOUBLE_CASES])
def test_filter_double_matrix(cond, expected):
    _run_filter(cond, expected)


# bool + string (reference: `available != true`, symbol comparisons)
BOOL_STR_CASES = [
    ("available == true", {"WSO2", "ORACLE"}),
    ("available != true", {"IBM"}),
    ("available == false", {"IBM"}),
    ("symbol == 'WSO2'", {"WSO2"}),
    ("symbol != 'WSO2'", {"IBM", "ORACLE"}),
    ("'IBM' == symbol", {"IBM"}),
]


@pytest.mark.parametrize("cond,expected", BOOL_STR_CASES,
                         ids=[c[0] for c in BOOL_STR_CASES])
def test_filter_bool_string_matrix(cond, expected):
    _run_filter(cond, expected)


# logical combinations (reference testFilterQuery 23, 56-81)
LOGIC_CASES = [
    ("volume > 12L and price < 56", {"WSO2", "ORACLE"}),
    ("symbol != 'WSO2' and volume != 55L and price != 72.5f", {"ORACLE"}),
    ("volume != 100 and volume != 70d", {"IBM", "ORACLE"}),
    ("price != 53.6d or price != 87", {"WSO2", "IBM", "ORACLE"}),
    ("volume != 40f and volume != 400", {"WSO2", "ORACLE"}),
    ("price > 40 or volume > 150", {"WSO2", "IBM", "ORACLE"}),
    ("not (price > 40)", {"ORACLE"}),
    ("not (price > 40) and volume > 100", {"ORACLE"}),
    ("volume > 50 and (price > 40 or quantity > 8)", {"WSO2", "ORACLE"}),
    ("true", {"WSO2", "IBM", "ORACLE"}),
    ("false", set()),
]


@pytest.mark.parametrize("cond,expected", LOGIC_CASES,
                         ids=[str(i) for i in range(len(LOGIC_CASES))])
def test_filter_logical_matrix(cond, expected):
    _run_filter(cond, expected)


# arithmetic inside conditions (reference FilterTestCase2: add/sub/mul/div/mod
# per type)
MATH_CASES = [
    ("price + 10 > 80", {"IBM"}),
    ("price - 10 < 30", {"ORACLE"}),
    ("price * 2 > 120", {"IBM"}),
    ("price / 2 < 20", {"ORACLE"}),
    ("volume % 3 == 1", {"WSO2", "IBM"}),
    ("volume + quantity > 150", {"ORACLE"}),
    ("volume * quantity >= 500", {"WSO2", "ORACLE"}),
    ("price + ratio > 58", {"WSO2", "IBM"}),
    ("quantity - 1 == 1", {"IBM"}),
    ("volume / 2 == 50", {"WSO2"}),
]


@pytest.mark.parametrize("cond,expected", MATH_CASES,
                         ids=[c[0] for c in MATH_CASES])
def test_filter_math_matrix(cond, expected):
    _run_filter(cond, expected)


def test_filter_select_projection_math():
    run_query(CSE + Q + """
        from cse[volume >= 100]
        select symbol, price * 2 as doubled, volume + quantity as vq
        insert into out;""",
        [("cse", list(r)) for r in ROWS],
        [("WSO2", 100.0, 105), ("ORACLE", 70.0, 209)])


def test_filter_no_condition_passthrough():
    run_query(CSE + Q + """
        from cse select symbol insert into out;""",
        [("cse", list(r)) for r in ROWS],
        [("WSO2",), ("IBM",), ("ORACLE",)])
