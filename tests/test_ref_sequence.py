"""Port of the reference sequence conformance suite
(siddhi-core/src/test/java/io/siddhi/core/query/sequence/SequenceTestCase.java,
32 @Test methods; testQuery17 does not exist upstream).  Expected payloads
are the reference's own assertions.  ref_harness additionally re-runs each
app with engine auto and asserts backend-identical output whenever the
planner compiles it to the device.
"""
from ref_harness import run_query

S12 = """
define stream Stream1 (symbol string, price float, volume int);
define stream Stream2 (symbol string, price float, volume int);
"""
S123 = S12 + "define stream Stream3 (symbol string, price float, volume int);\n"
STOCK_TWITTER = """
define stream StockStream (symbol string, price float, volume int);
define stream TwitterStream (symbol string, count int);
"""
SS12 = """
define stream StockStream1 (symbol string, price float, volume int);
define stream StockStream2 (symbol string, price float, volume int);
"""

Q = "@info(name = 'query1') "


def test_seq_1_basic():
    run_query(S12 + Q + """
        from e1=Stream1[price>20],e2=Stream2[price>e1.price]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream2", ["IBM", 55.7, 100])],
        [("WSO2", "IBM")])


def test_seq_2_every_restart():
    run_query(S12 + Q + """
        from every e1=Stream1[price>20], e2=Stream2[price>e1.price]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream1", ["GOOG", 57.6, 100]),
         ("Stream2", ["IBM", 65.7, 100])],
        [("GOOG", "IBM")])


def test_seq_3_trailing_star():
    run_query(S12 + Q + """
        from every e1=Stream1[price>20], e2=Stream2[price>e1.price]*
        select e1.symbol as symbol1, e2[0].symbol as symbol2,
               e2[1].symbol as symbol3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream1", ["IBM", 55.7, 100])],
        [("WSO2", None, None), ("IBM", None, None)])


def test_seq_4_leading_star_two_collected():
    run_query(S12 + Q + """
        from every e1=Stream2[price>20]*, e2=Stream1[price>e1[0].price]
        select e1[0].price as price1, e1[1].price as price2,
               e2.price as price3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 59.6, 100]), ("Stream2", ["WSO2", 55.6, 100]),
         ("Stream2", ["IBM", 55.7, 100]), ("Stream1", ["WSO2", 57.6, 100])],
        [(55.6, 55.7, 57.6)])


def test_seq_5_leading_star_descending_second():
    run_query(S12 + Q + """
        from every e1=Stream2[price>20]*, e2=Stream1[price>e1[0].price]
        select e1[0].price as price1, e1[1].price as price2,
               e2.price as price3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 59.6, 100]), ("Stream2", ["WSO2", 55.6, 100]),
         ("Stream2", ["IBM", 55.0, 100]), ("Stream1", ["WSO2", 57.6, 100])],
        [(55.6, 55.0, 57.6)])


def test_seq_6_leading_optional():
    run_query(S12 + Q + """
        from every e1=Stream2[price>20]?, e2=Stream1[price>e1[0].price]
        select e1[0].price as price1, e2.price as price3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 59.6, 100]), ("Stream2", ["WSO2", 55.6, 100]),
         ("Stream2", ["IBM", 55.7, 100]), ("Stream1", ["WSO2", 57.6, 100])],
        [(55.7, 57.6)])


def test_seq_7_or_second():
    run_query(S12 + Q + """
        from every e1=Stream2[price>20],
             e2=Stream2[price>e1.price] or e3=Stream2[symbol=='IBM']
        select e1.price as price1, e2.price as price2, e3.price as price3
        insert into OutputStream;""",
        [("Stream2", ["WSO2", 59.6, 100]), ("Stream2", ["WSO2", 55.6, 100]),
         ("Stream2", ["IBM", 55.7, 100]), ("Stream2", ["WSO2", 57.6, 100])],
        [(55.6, 55.7, None), (55.7, 57.6, None)])


def test_seq_8_or_ibm_side():
    run_query(S12 + Q + """
        from every e1=Stream2[price>20],
             e2=Stream2[price>e1.price] or e3=Stream2[symbol=='IBM']
        select e1.price as price1, e2.price as price2, e3.price as price3
        insert into OutputStream;""",
        [("Stream2", ["WSO2", 59.6, 100]), ("Stream2", ["WSO2", 55.6, 100]),
         ("Stream2", ["IBM", 55.0, 100]), ("Stream2", ["WSO2", 57.6, 100])],
        [(55.6, None, 55.0), (55.0, 57.6, None)])


def test_seq_9_or_both_orders():
    run_query(S12 + Q + """
        from every e1=Stream2[price>20],
             e2=Stream2[price>e1.price] or e3=Stream2[symbol=='IBM']
        select e1.price as price1, e2.price as price2, e3.price as price3
        insert into OutputStream;""",
        [("Stream2", ["WSO2", 59.6, 100]), ("Stream2", ["WSO2", 55.6, 100]),
         ("Stream2", ["WSO2", 57.6, 100]), ("Stream2", ["IBM", 55.7, 100])],
        [(55.6, 57.6, None), (57.6, None, 55.7)])


def test_seq_10_leading_plus_single():
    run_query(S12 + Q + """
        from every e1=Stream2[price>20]+, e2=Stream1[price>e1[0].price]
        select e1[0].price as price1, e1[1].price as price2,
               e2.price as price3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 59.6, 100]), ("Stream2", ["WSO2", 55.6, 100]),
         ("Stream1", ["WSO2", 57.6, 100])],
        [(55.6, None, 57.6)])


_RISING_PLUS = S12 + Q + """
    from every e1=Stream1[price>20],
         e2=Stream1[(e2[last].price is null and price>=e1.price) or
                    ((not (e2[last].price is null)) and
                     price>=e2[last].price)]+,
         e3=Stream1[price<e2[last].price]
    select e1.price as price1, e2[0].price as price2, e2[1].price as price3,
           e3.price as price4
    insert into OutputStream;"""


def test_seq_11_rising_run_then_drop():
    run_query(_RISING_PLUS,
        [("Stream1", ["WSO2", 29.6, 100]), ("Stream1", ["WSO2", 35.6, 100]),
         ("Stream1", ["WSO2", 57.6, 100]), ("Stream1", ["IBM", 47.6, 100])],
        [(29.6, 35.6, 57.6, 47.6)])


def test_seq_12_and_filter_two_streams():
    run_query(STOCK_TWITTER + Q + """
        from every e1=StockStream[ price >= 50 and volume > 100 ],
             e2=TwitterStream[count > 10]
        select e1.price as price, e1.symbol as symbol, e2.count as count
        insert into OutputStream;""",
        [("StockStream", ["GOOG", 51.0, 101]),
         ("StockStream", ["IBM", 76.6, 111]),
         ("TwitterStream", ["IBM", 20]),
         ("StockStream", ["WSO2", 45.6, 100]),
         ("TwitterStream", ["GOOG", 20])],
        [(76.6, "IBM", 20)])


def test_seq_13_mid_star_zero_len():
    run_query(STOCK_TWITTER + Q + """
        from every e1=StockStream[ price >= 50 and volume > 100 ],
             e2=StockStream[price <= 40]*, e3=StockStream[volume <= 70]
        select e1.symbol as symbol1, e2[0].symbol as symbol2,
               e3.symbol as symbol3
        insert into OutputStream;""",
        [("StockStream", ["IBM", 75.6, 105]),
         ("StockStream", ["GOOG", 21.0, 81]),
         ("StockStream", ["WSO2", 176.6, 65])],
        [("IBM", "GOOG", "WSO2")])


def test_seq_14_two_streams_star_three_matches():
    run_query(SS12 + Q + """
        from every e1=StockStream1[ price >= 50 and volume > 100 ],
             e2=StockStream2[price <= 40]*, e3=StockStream2[volume <= 70]
        select e3.symbol as symbol1, e2[0].symbol as symbol2,
               e3.volume as volume
        insert into OutputStream;""",
        [("StockStream1", ["IBM", 75.6, 105]),
         ("StockStream2", ["GOOG", 21.0, 81]),
         ("StockStream2", ["WSO2", 21.0, 65]),
         ("StockStream1", ["IBM", 78.6, 106]),
         ("StockStream2", ["DDD", 23.0, 181]),
         ("StockStream2", ["WSO2", 21.0, 60]),
         ("StockStream1", ["BIRT", 87.6, 123]),
         ("StockStream2", ["DOX", 25.0, 25])],
        [("WSO2", "GOOG", 65), ("WSO2", "DDD", 60), ("DOX", None, 25)])


def test_seq_15_star_filter_on_e1_capture():
    run_query(SS12 + Q + """
        from every e1=StockStream1[ price >= 50 and volume > 100 ],
             e2=StockStream2[e1.symbol != 'AMBA']*,
             e3=StockStream2[volume <= 70]
        select e3.symbol as symbol1, e2[0].symbol as symbol2,
               e3.volume as volume
        insert into OutputStream;""",
        [("StockStream1", ["IBM", 75.6, 105]),
         ("StockStream2", ["GOOG", 21.0, 81]),
         ("StockStream2", ["WSO2", 21.0, 65]),
         ("StockStream1", ["AMBA", 78.6, 106]),
         ("StockStream2", ["DDD", 23.0, 181]),
         ("StockStream2", ["WSO2", 21.0, 60]),
         ("StockStream1", ["BIRT", 87.6, 123]),
         ("StockStream2", ["DOX", 25.0, 25])],
        [("WSO2", "GOOG", 65), ("DOX", None, 25)])


def test_seq_16_filterless_first():
    run_query(SS12 + Q + """
        from every e1=StockStream1, e2=StockStream2[e1.symbol != 'AMBA']*,
             e3=StockStream2[volume <= 70]
        select e3.symbol as symbol1, e2[0].symbol as symbol2,
               e3.volume as volume
        insert into OutputStream;""",
        [("StockStream1", ["IBM", 75.6, 105]),
         ("StockStream2", ["GOOG", 21.0, 81]),
         ("StockStream2", ["WSO2", 21.0, 65]),
         ("StockStream1", ["AMBA", 78.6, 106]),
         ("StockStream2", ["DDD", 23.0, 181]),
         ("StockStream2", ["WSO2", 21.0, 60]),
         ("StockStream1", ["BIRT", 87.6, 123]),
         ("StockStream2", ["DOX", 25.0, 25])],
        [("WSO2", "GOOG", 65), ("DOX", None, 25)])


def test_seq_18_rising_run_skips_low_start():
    run_query(_RISING_PLUS,
        [("Stream1", ["WSO2", 29.6, 100]), ("Stream1", ["WSO2", 25.0, 100]),
         ("Stream1", ["WSO2", 35.6, 100]), ("Stream1", ["WSO2", 57.6, 100]),
         ("Stream1", ["IBM", 47.6, 100])],
        [(25.0, 35.6, 57.6, 47.6)])


def test_seq_19_rising_two_step():
    run_query(_RISING_PLUS,
        [("Stream1", ["WSO2", 25.0, 100]), ("Stream1", ["WSO2", 40.0, 100]),
         ("Stream1", ["WSO2", 35.0, 100])],
        [(25.0, 40.0, None, 35.0)])


def test_seq_20_rising_three_matches():
    run_query(_RISING_PLUS,
        [("Stream1", ["WSO2", 29.6, 100]), ("Stream1", ["WSO2", 25.0, 100]),
         ("Stream1", ["WSO2", 35.6, 100]), ("Stream1", ["WSO2", 25.5, 100]),
         ("Stream1", ["WSO2", 57.6, 100]), ("Stream1", ["WSO2", 58.6, 100]),
         ("Stream1", ["IBM", 47.6, 100]), ("Stream1", ["IBM", 27.6, 100]),
         ("Stream1", ["IBM", 49.6, 100]), ("Stream1", ["IBM", 45.6, 100])],
        [(25.0, 35.6, None, 25.5), (25.5, 57.6, 58.6, 47.6),
         (27.6, 49.6, None, 45.6)])


_RISING_LAST_IDX = S12 + Q + """
    from every e1=Stream1[price>20],
         e2=Stream1[((e2[last].price is null) and price>=e1.price) or
                    ((not (e2[last].price is null)) and
                     price>=e2[last].price)]+,
         e3=Stream1[price<e2[last].price]
    select e1.price as price1, e2[0].price as price2,
           e2[last-2].price as price3, e2[last-1].price as price4,
           e2[last].price as price5, e3.price as price6,
           e2[last-20].price as price7
    insert into OutputStream;"""


def test_seq_21_last_minus_indexing():
    run_query(_RISING_LAST_IDX,
        [("Stream1", ["WSO2", 29.6, 100]), ("Stream1", ["WSO2", 25.0, 100]),
         ("Stream1", ["WSO2", 35.6, 100]), ("Stream1", ["WSO2", 45.5, 100]),
         ("Stream1", ["WSO2", 57.6, 100]), ("Stream1", ["WSO2", 58.6, 100]),
         ("Stream1", ["IBM", 47.6, 100]), ("Stream1", ["IBM", 45.6, 100])],
        [(25.0, 35.6, 45.5, 57.6, 58.6, 47.6, None)])


def test_seq_23_last_minus_two_matches():
    run_query(S12 + Q + """
        from every e1=Stream1[price>20],
             e2=Stream1[price>=e2[last].price or price>=e1.price ]+,
             e3=Stream1[price<e2[last].price]
        select e1.price as price1, e2[0].price as price2,
               e2[last-2].price as price3, e2[last-1].price as price4,
               e2[last].price as price5, e3.price as price6
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 29.6, 100]), ("Stream1", ["WSO2", 25.0, 100]),
         ("Stream1", ["WSO2", 35.6, 100]), ("Stream1", ["WSO2", 29.5, 100]),
         ("Stream1", ["WSO2", 57.6, 100]), ("Stream1", ["WSO2", 58.6, 100]),
         ("Stream1", ["IBM", 57.7, 100]), ("Stream1", ["IBM", 45.6, 100])],
        [(25.0, 35.6, None, None, 35.6, 29.5),
         (29.5, 57.6, None, 57.6, 58.6, 57.7)])


def test_seq_25_and_pair_second():
    run_query(S123 + Q + """
        from e1=Stream1[price >20],
             e2=Stream2['IBM' == symbol] and e3=Stream3['WSO2' == symbol]
        select e1.price as price1, e2.price as price2, e3.price as price3
        insert into OutputStream;""",
        [("Stream1", ["IBM", 25.5, 100]), ("Stream2", ["IBM", 45.5, 100]),
         ("Stream3", ["WSO2", 46.56, 100])],
        [(25.5, 45.5, 46.56)])


def test_seq_27_or_pair_second():
    run_query(S123 + Q + """
        from e1=Stream1[price >20],
             e2=Stream2['IBM' == symbol] or e3=Stream3['WSO2' == symbol]
        select e1.price as price1, e2.price as price2, e3.price as price3
        insert into OutputStream;""",
        [("Stream1", ["IBM", 59.65, 100]), ("Stream2", ["IBM", 45.5, 100])],
        [(59.65, 45.5, None)])


def test_seq_28_and_pair_higher_prices():
    run_query(S123 + Q + """
        from e1=Stream1[price >20],
             e2=Stream2['IBM' == symbol] and e3=Stream3['WSO2' == symbol]
        select e1.price as price1, e2.price as price2, e3.price as price3
        insert into OutputStream;""",
        [("Stream1", ["IBM", 59.65, 100]), ("Stream2", ["IBM", 45.5, 100]),
         ("Stream3", ["WSO2", 46.56, 100])],
        [(59.65, 45.5, 46.56)])


def test_seq_29_single_shot_no_second_match():
    run_query(S12 + Q + """
        from e1=Stream1[price>20],e2=Stream2[price>e1.price]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream2", ["IBM", 55.7, 100]),
         ("Stream1", ["ORACLE", 55.6, 100]),
         ("Stream2", ["GOOGLE", 55.7, 100])],
        [("WSO2", "IBM")])


def test_seq_30_every_two_matches():
    run_query(S12 + Q + """
        from every e1=Stream1[price>20],e2=Stream2[price>e1.price]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream2", ["IBM", 55.7, 100]),
         ("Stream1", ["ORACLE", 55.6, 100]),
         ("Stream1", ["MICROSOFT", 55.8, 100]),
         ("Stream2", ["GOOGLE", 55.9, 100])],
        [("WSO2", "IBM"), ("MICROSOFT", "GOOGLE")])


def test_seq_31_broken_contiguity_no_match():
    run_query(S12 + Q + """
        from e1=Stream1[price>20], e2=Stream2[price>e1.price]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100]), ("Stream1", ["GOOG", 57.6, 100]),
         ("Stream2", ["IBM", 65.7, 100])],
        [])


def test_seq_32_leading_and_pair():
    run_query(S123 + Q + """
        from e1=Stream1[price >20] and e2=Stream2['IBM' == symbol],
             e3=Stream3['WSO2' == symbol]
        select e1.price as price1, e2.price as price2, e3.price as price3
        insert into OutputStream;""",
        [("Stream1", ["IBM", 25.5, 100]), ("Stream2", ["IBM", 45.5, 100]),
         ("Stream3", ["WSO2", 46.56, 100])],
        [(25.5, 45.5, 46.56)])
