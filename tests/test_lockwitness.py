"""Runtime lock-witness (core/lockwitness.py) + PR 13 defect regressions.

Covers: inversion detection (observed-order and against the static
graph), LW002 long holds, reentrancy, the off-by-default zero-wrap
contract, the seeded chaos inversion round-tripping through
GET /incidents as an LW001 bundle, the armed-witness overhead smoke
bound, and regression tests for the three auditor-surfaced defects
fixed in this PR (sink retry sleep, heartbeat re-arm race, flight env
read on the hot path).
"""
import json
import os
import sys
import threading
import time
import urllib.request

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from siddhi_tpu import SiddhiManager, StreamCallback  # noqa: E402
from siddhi_tpu.core import lockwitness  # noqa: E402
from siddhi_tpu.core.flight import flight, flight_enabled  # noqa: E402
from siddhi_tpu.core.lockwitness import (LockWitness,  # noqa: E402
                                         maybe_wrap)
from siddhi_tpu.core.source_sink import Sink  # noqa: E402
from siddhi_tpu.core.timestamp import TimestampGenerator  # noqa: E402
from siddhi_tpu.utils.errors import ConnectionUnavailableError  # noqa: E402

from chaos import LockOrderInversion  # noqa: E402


@pytest.fixture(autouse=True)
def _witness_isolation():
    """The module-global witness must stay disarmed and clean around
    every test here; seeded scenarios use private instances."""
    lockwitness.disarm()
    lockwitness.witness().reset()
    yield
    lockwitness.disarm()
    lockwitness.witness().reset()


# ------------------------------------------------------------- detection


def test_inversion_detected_across_threads():
    w = LockWitness(emit_incidents=False)
    w.arm()
    inv = LockOrderInversion(w)
    inv.run()
    found = w.inversions()
    assert len(found) == 1
    assert found[0]["code"] == "LW001"
    assert sorted(found[0]["first"] + found[0]["second"]) == sorted(
        ["chaos.A", "chaos.B", "chaos.B", "chaos.A"])
    assert found[0]["other_thread"] == "chaos-inv-fwd"


def test_inversion_against_static_graph_single_thread():
    """The witness convicts against the *static* graph too: one runtime
    B->A acquisition is enough when the source proves A->B elsewhere."""
    w = LockWitness(emit_incidents=False,
                    static_edges={("s.A", "s.B")})
    w.arm()
    a = w.wrap(threading.Lock(), "s.A")
    b = w.wrap(threading.Lock(), "s.B")
    with b:
        with a:
            pass
    found = w.inversions()
    assert len(found) == 1
    assert found[0]["static"] is True


def test_consistent_order_is_clean():
    w = LockWitness(emit_incidents=False)
    w.arm()
    a = w.wrap(threading.Lock(), "c.A")
    b = w.wrap(threading.Lock(), "c.B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert w.inversions() == []
    assert ("c.A", "c.B") in w.edges()


def test_long_hold_reports_lw002():
    w = LockWitness(hold_ms=5.0, emit_incidents=False)
    w.arm()
    lock = w.wrap(threading.Lock(), "h.L")
    with lock:
        time.sleep(0.03)
    holds = w.holds()
    assert holds and holds[0]["code"] == "LW002"
    assert holds[0]["lock"] == "h.L"
    assert holds[0]["held_ms"] >= 5.0


def test_rlock_reentrancy_single_report():
    w = LockWitness(emit_incidents=False)
    w.arm()
    rl = w.wrap(threading.RLock(), "r.L")
    with rl:
        with rl:      # reentrant: no self-edge, no imbalance
            pass
    assert w.edges() == {}
    assert w.inversions() == []
    # still usable afterwards (balanced depth)
    with rl:
        pass


# ------------------------------------------------------------ off switch


def test_maybe_wrap_is_identity_when_disarmed(monkeypatch):
    monkeypatch.delenv("SIDDHI_TPU_LOCKWITNESS", raising=False)
    lock = threading.Lock()
    assert maybe_wrap(lock, "x.L") is lock


def test_maybe_wrap_env_knob_arms(monkeypatch):
    monkeypatch.setenv("SIDDHI_TPU_LOCKWITNESS", "1")
    lock = threading.Lock()
    wrapped = maybe_wrap(lock, "x.L")
    assert wrapped is not lock
    assert wrapped.name == "x.L"
    with wrapped:       # protocol intact
        pass


def test_engine_locks_plain_by_default(monkeypatch):
    monkeypatch.delenv("SIDDHI_TPU_LOCKWITNESS", raising=False)
    from siddhi_tpu.core.resilience import CircuitBreaker
    assert isinstance(CircuitBreaker()._lock, type(threading.Lock()))


# ------------------------------------------------- LW001 incident bundle


def _req(url):
    with urllib.request.urlopen(url) as r:
        return json.loads(r.read().decode())


def test_seeded_inversion_round_trips_through_rest(tmp_path, monkeypatch):
    monkeypatch.setenv("SIDDHI_TPU_FLIGHT_DIR", str(tmp_path / "bundles"))
    flight().reset()
    w = LockWitness()                 # emit_incidents=True: the real bus
    w.arm()
    LockOrderInversion(w).run()
    assert w.inversions(), "seeded inversion not observed"

    from siddhi_tpu.service.rest import SiddhiService
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        incs = _req(f"{base}/incidents")["incidents"]
        ids = [i["id"] for i in incs if i["kind"] == "lock_inversion"]
        assert ids, f"no lock_inversion incident on the bus: {incs}"
        bundle = _req(f"{base}/incidents/{ids[0]}/bundle")
        assert bundle["detail"]["code"] == "LW001"
        assert bundle["detail"]["first"] == ["chaos.A", "chaos.B"]
        assert bundle["detail"]["second"] == ["chaos.B", "chaos.A"]
    finally:
        svc.stop()
        flight().reset()


# ------------------------------------------------------- overhead smoke


def test_witness_overhead_smoke():
    """bench --smoke style: identical ingest work with witnessed (armed)
    vs plain engine locks, alternated per round, GC off, medians.  The
    armed bound here is deliberately generous for CI jitter; the
    measured number (~1-2%) is documented in docs/robustness.md."""
    import gc
    import statistics as stats

    app = """
        define stream S (v float);
        @info(name='q') from S[v > 0.5] select v insert into Out;
    """

    def build(armed):
        if armed:
            lockwitness.arm(hold_ms=60_000.0)
        else:
            lockwitness.disarm()
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(app)
        rt.add_callback("Out", StreamCallback(lambda evs: None))
        rt.start()
        return m, rt, rt.get_input_handler("S")

    m_wit, rt_wit, h_wit = build(True)      # locks wrapped at construction
    m_pl, rt_pl, h_pl = build(False)        # plain locks
    lockwitness.witness().arm()             # armed during the timed phase

    batch = [[float(i % 7)] for i in range(64)]

    def round_time(h):
        t0 = time.perf_counter()
        for row in batch:
            h.send(row)
        return time.perf_counter() - t0

    try:
        for row in batch:                   # warmup / trace both
            h_wit.send(row)
            h_pl.send(row)
        wit_times, plain_times = [], []
        gc.disable()
        try:
            for _ in range(7):              # block-paired alternation
                plain_times.append(round_time(h_pl))
                wit_times.append(round_time(h_wit))
        finally:
            gc.enable()
        wit, plain = stats.median(wit_times), stats.median(plain_times)
        assert wit < plain * 1.5, (
            f"armed lock-witness overhead too high: witnessed {wit:.6f}s "
            f"vs plain {plain:.6f}s per 64-event round")
        assert lockwitness.witness().inversions() == []
    finally:
        lockwitness.disarm()
        lockwitness.witness().reset()
        rt_wit.shutdown()
        rt_pl.shutdown()
        m_wit.shutdown()
        m_pl.shutdown()


# ------------------------------------- regressions for PR 13 fixed defects


def test_sink_connect_retry_is_interruptible():
    """CE003's one real engine hit: Sink.connect_with_retry slept out
    its whole backoff ladder through shutdown().  Now the backoff rides
    an Event and shutdown returns promptly mid-ladder."""

    class NeverUpSink(Sink):
        def connect(self):
            raise ConnectionUnavailableError("endpoint down")

    s = NeverUpSink(stream_def=None,
                    options={"retry.max.attempts": "6",
                             "retry.base.delay.ms": "400",
                             "retry.max.delay.ms": "400"},
                    mapper=None)
    t = threading.Thread(target=s.connect_with_retry,
                         name="test-connect-retry")
    t0 = time.perf_counter()
    t.start()
    time.sleep(0.15)                 # let it enter the backoff ladder
    s.shutdown()
    t.join(timeout=2.0)
    elapsed = time.perf_counter() - t0
    assert not t.is_alive(), "connect_with_retry ignored shutdown"
    assert elapsed < 1.5, (
        f"shutdown waited out the backoff ladder: {elapsed:.2f}s")
    assert not s.connected


def test_heartbeat_stops_after_shutdown():
    """Pre-fix, a tick in flight across shutdown() re-armed the
    playback heartbeat forever (the round-5 timer re-arm spin class)."""
    g = TimestampGenerator()
    ticks = []
    g.add_time_change_listener(ticks.append)
    g.enable_playback(idle_time_ms=10, increment_ms=5)
    g.observe_event_time(1_000)
    deadline = time.monotonic() + 2.0
    while not ticks and time.monotonic() < deadline:
        time.sleep(0.01)
    assert ticks, "heartbeat never ticked"
    g.shutdown()
    time.sleep(0.05)                 # drain any tick already in flight
    seen = len(ticks)
    time.sleep(0.08)                 # several would-be intervals
    assert len(ticks) == seen, "heartbeat re-armed after shutdown"
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline:
        if not [t for t in threading.enumerate()
                if t.name == "siddhi-heartbeat"]:
            break
        time.sleep(0.02)
    assert not [t for t in threading.enumerate()
                if t.name == "siddhi-heartbeat"], "heartbeat timer leaked"


def test_heartbeat_concurrent_observe_no_orphan_timers():
    """Pre-fix, racing observe_event_time callers cancel/replaced the
    timer unguarded and could orphan a live timer."""
    g = TimestampGenerator()
    g.enable_playback(idle_time_ms=25, increment_ms=1)

    def hammer(base):
        for i in range(300):
            g.observe_event_time(base + i)

    threads = [threading.Thread(target=hammer, args=(k * 10_000,),
                                name=f"test-observe-{k}")
               for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    g.shutdown()
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline:
        alive = [t for t in threading.enumerate()
                 if t.name == "siddhi-heartbeat"]
        if not alive:
            break
        time.sleep(0.02)
    assert not alive, f"orphaned heartbeat timers: {alive}"


def test_flight_enabled_fast_path_still_flippable(monkeypatch):
    """CE101's engine hit: flight_enabled paid the ~0.9 us
    os.environ.get on every record_block.  The fast _data read must
    keep the runtime-flip contract."""
    monkeypatch.delenv("SIDDHI_TPU_FLIGHT", raising=False)
    assert flight_enabled() is True
    monkeypatch.setenv("SIDDHI_TPU_FLIGHT", "0")
    assert flight_enabled() is False
    monkeypatch.setenv("SIDDHI_TPU_FLIGHT", "off")
    assert flight_enabled() is False
    monkeypatch.setenv("SIDDHI_TPU_FLIGHT", "1")
    assert flight_enabled() is True
    monkeypatch.delenv("SIDDHI_TPU_FLIGHT")
    assert flight_enabled() is True
