"""Dispatch consolidation (round 7): stacked-vs-sequential equivalence.

The CompiledPatternBank restructuring (chunk stacking into one vmapped
super-dispatch, gated by SIDDHI_TPU_NFA_STACK; carry donation; fused
per-app egress, gated by SIDDHI_TPU_EGRESS_FUSE) must be BIT-IDENTICAL
in match semantics: randomized feeds produce identical counts, decoded
ring payloads and `dropped` counters vs the chunk-sequential legacy
path, for B in {1, 4} and through a forced grow-and-replay — the same
proof style as tests/test_nfa_batch.py.

Plus the structural claims: a C-chunk bank REALLY pays one device
dispatch per block (profiler dispatch_count) from ONE compiled
executable (compile_count), the donated input carry is REALLY deleted
after the step, the stacked [C, N, ...] carry is byte-identical to C
separate chunk carries (asserted against cost_model), the default chunk
sizing matches cost_model.default_pattern_chunk, and an app with two
device query runtimes performs exactly ONE egress D2H per ingest block.
Runs on the conftest-forced virtual 8-device CPU mesh.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from siddhi_tpu.ops.nfa import (STACK_ENV, pack_blocks,  # noqa: E402
                                resolve_stack)
from siddhi_tpu.plan.nfa_compiler import CompiledPatternBank  # noqa: E402
from siddhi_tpu.core.profiling import profiler  # noqa: E402

STREAM = "define stream S (partition int, price float, kind int);\n"
P = 16          # partitions
T = 12          # events per lane per block
BASE = 1_000_000
GAP = 1_000     # per-lane inter-arrival ms


def _apps(n, within_ms=9_000):
    """n structurally-identical alert patterns, thresholds as the only
    difference (parameter lanes → homogeneous chunks by construction)."""
    thrs = np.linspace(5.0, 95.0, n)
    return [STREAM +
            f"from every e1=S[kind == 0 and price > {thr}] -> "
            f"e2=S[kind == 1 and price > e1.price] "
            f"within {within_ms} milliseconds "
            "select e1.price as p1, e2.price as p2 insert into Out;"
            for thr in thrs]


def _bank(n_apps, chunk, stack, ring=8, n_slots=4, batch_b=None,
          replayable=False):
    bank = CompiledPatternBank(_apps(n_apps), n_partitions=P,
                               n_slots=n_slots, pattern_chunk=chunk,
                               ring=ring, batch_b=batch_b, stack=stack,
                               replayable=replayable)
    bank.base_ts = BASE
    return bank


def _block(rng, t0):
    """One dense [P, T] block, every lane active, globally time-ordered."""
    n = P * T
    pids = np.tile(np.arange(P, dtype=np.int64), T)
    j = np.repeat(np.arange(T, dtype=np.int64), P)
    ts = t0 + j * GAP + pids * (GAP // P)
    cols = {"partition": pids.astype(np.float32),
            "price": rng.uniform(0, 100, n).astype(np.float32),
            "kind": rng.integers(0, 2, n).astype(np.float32)}
    return pack_blocks(pids, cols, ts, np.zeros(n, np.int32), P,
                       base_ts=BASE)


def _feed(bank, seed, n_blocks=3, replayed=False):
    """Run n_blocks through the bank; → (counts [N], sorted payload rows,
    dropped)."""
    rng = np.random.default_rng(seed)
    counts = np.zeros(bank.n_patterns, np.int64)
    rows = []
    t0 = BASE
    for _ in range(n_blocks):
        block = _block(rng, t0)
        t0 += T * GAP
        out = (bank.process_block_replayed(block) if replayed
               else bank.process_block(block))
        counts += np.asarray(out[0], np.int64)
        dec = bank.decode_ring(*out[1:])
        rows.append(sorted(zip(*(np.asarray(v).tolist()
                                 for v in dec.values()))))
    return counts, rows, bank.total_dropped()


@pytest.mark.parametrize("B", [1, 4])
def test_stacked_matches_sequential(B):
    """4 patterns x chunk 2 = C=2: the one-super-dispatch bank and the
    legacy chunk loop must agree exactly on counts, decoded ring
    payloads and dropped, across randomized feeds."""
    total = 0
    for seed in (0, 1, 2):
        seq = _bank(4, 2, stack=False, batch_b=B)
        stk = _bank(4, 2, stack=True, batch_b=B)
        assert not seq.stacked and stk.stacked and stk.n_chunks == 2
        c_seq, r_seq, d_seq = _feed(seq, seed)
        c_stk, r_stk, d_stk = _feed(stk, seed)
        assert (c_seq == c_stk).all(), \
            f"B={B} seed={seed}: counts diverged {c_seq} vs {c_stk}"
        assert r_seq == r_stk, f"B={B} seed={seed}: payloads diverged"
        assert d_seq == d_stk
        total += int(c_seq.sum())
    assert total > 0, "degenerate parity grid (0 matches)"


def test_grow_and_replay_parity():
    """Forced slot overflow (K=1 ring): both paths rewind, double K and
    replay at their own granularity, and still agree exactly."""
    seq = _bank(4, 2, stack=False, n_slots=1, replayable=True)
    stk = _bank(4, 2, stack=True, n_slots=1, replayable=True)
    c_seq, r_seq, d_seq = _feed(seq, 5, replayed=True)
    c_stk, r_stk, d_stk = _feed(stk, 5, replayed=True)
    assert d_seq == 0 and d_stk == 0, "replay left evicted partials"
    assert seq.nfa.spec.n_slots > 1 and stk.nfa.spec.n_slots > 1, \
        "feed never overflowed K=1 — the replay path was not exercised"
    assert (c_seq == c_stk).all() and c_seq.sum() > 0
    assert r_seq == r_stk


def test_dispatch_count_drops_c_to_1():
    """The profiler's dispatch_count sees C device executions per block
    on the sequential path and exactly ONE on the stacked path, and the
    stacked bank compiles ONE executable for any number of blocks."""
    prof = profiler()
    was = prof.enabled
    prof.enable()
    try:
        rng = np.random.default_rng(0)
        seq = _bank(8, 2, stack=False)
        stk = _bank(8, 2, stack=True)
        assert seq.n_chunks == 4 and stk.n_chunks == 4

        def dispatches(bank, block):
            d0 = prof.total_dispatches()
            np.asarray(bank.process_block(block)[0])
            return prof.total_dispatches() - d0

        b1, b2 = _block(rng, BASE), _block(rng, BASE + T * GAP)
        assert dispatches(seq, b1) == 4
        assert dispatches(seq, b2) == 4
        c0 = prof.stats("nfa.bank_step").compile_count
        assert dispatches(stk, b1) == 1
        assert dispatches(stk, b2) == 1
        # one executable covers every block of this shape: the only
        # compile is the first stacked step's
        assert prof.stats("nfa.bank_step").compile_count - c0 == 1
    finally:
        if not was:
            prof.disable()


def test_donated_carry_is_deleted():
    """Default (non-replayable) banks donate the carry: after one step
    the INPUT buffers are deleted (XLA aliased them in place).  A
    replayable bank must NOT donate — the rewind snapshot survives."""
    rng = np.random.default_rng(1)
    stk = _bank(4, 2, stack=True)
    leaf = stk._stack_carry["slot_state"]
    stk.process_block(_block(rng, BASE))
    assert leaf.is_deleted(), \
        "stacked step did not donate its input carry"
    rep = _bank(4, 2, stack=True, replayable=True)
    leaf = rep._stack_carry["slot_state"]
    rep.process_block(_block(rng, BASE))
    assert not leaf.is_deleted(), \
        "replayable step donated the carry its rewind depends on"


def test_env_kill_switch(monkeypatch):
    monkeypatch.setenv(STACK_ENV, "0")
    assert resolve_stack() is False
    legacy = _bank(4, 2, stack=None)
    assert not legacy.stacked and legacy._carries is not None
    monkeypatch.delenv(STACK_ENV)
    assert resolve_stack() is True
    assert resolve_stack(False) is False
    on = _bank(4, 2, stack=None)
    assert on.stacked


def test_stacked_carry_bytes_identical_to_sequential():
    """[C, N, ...] holds exactly the elements of C separate [N, ...]
    carries — stacking changes dispatch count, never bytes — and the
    cost model's stacked_bank_state_bytes prices it identically."""
    from siddhi_tpu.analysis.cost_model import (bank_state_bytes,
                                                stacked_bank_state_bytes)
    from siddhi_tpu.analysis.plan_ir import automaton_ir_from_nfa
    seq = _bank(4, 2, stack=False)
    stk = _bank(4, 2, stack=True)
    seq_bytes = sum(int(v.nbytes) for c in seq._carries
                    for v in c.values())
    stk_bytes = sum(int(v.nbytes) for v in stk._stack_carry.values())
    assert stk_bytes == seq_bytes
    a = automaton_ir_from_nfa(stk.nfa, "q")
    assert stacked_bank_state_bytes(a, stk.n_chunks, stk.chunk, P) == \
        stk.n_chunks * bank_state_bytes(a, stk.chunk, P)


def test_default_chunk_matches_cost_model():
    """The bank's auto chunk sizing IS the cost model's formula — with
    the round-6 B-batching fusion growth (~3.2x per B-doubling) priced
    in, so defaults don't spill at SIDDHI_TPU_NFA_BATCH=4."""
    from siddhi_tpu.analysis import cost_model as cm
    bank = CompiledPatternBank(_apps(4), n_partitions=P, n_slots=4,
                               ring=8)        # pattern_chunk=None → auto
    spec = bank.nfa.spec
    want = cm.default_pattern_chunk(
        4, P, spec.n_slots, spec.n_rows, spec.n_caps,
        batch_b=max(bank.nfa.batch_b, 1), ring=True)
    assert bank.chunk == want
    # the growth factor really bites: at B=4 (two doublings) the modeled
    # per-pattern step footprint grows ~3.2^2 over B=1
    b1 = cm.bank_chunk_bytes_per_pattern(10_000, 8, 2, 1, batch_b=1)
    b4 = cm.bank_chunk_bytes_per_pattern(10_000, 8, 2, 1, batch_b=4)
    assert b4 == int(b1 * cm.BATCH_FUSION_GROWTH ** 2)
    # and a budget that only fits the B=1 footprint must pick a smaller
    # divisor chunk at B=4
    budget = cm.bank_chunk_bytes_per_pattern(10_000, 8, 2, 1,
                                             batch_b=1) * 200
    c1 = cm.default_pattern_chunk(1000, 10_000, 8, 2, 1, batch_b=1,
                                  budget=budget)
    c4 = cm.default_pattern_chunk(1000, 10_000, 8, 2, 1, batch_b=4,
                                  budget=budget)
    assert c4 < c1


def test_plan_ir_surfaces_stacking():
    from siddhi_tpu.analysis.plan_ir import automaton_ir_from_nfa
    stk = _bank(4, 2, stack=True)
    a = automaton_ir_from_nfa(stk.nfa, "q")
    assert a.stacked and a.dispatches_per_block == 1
    assert a.as_dict()["stacked"] is True
    seq = _bank(4, 2, stack=False)
    a2 = automaton_ir_from_nfa(seq.nfa, "q")
    assert not a2.stacked and a2.dispatches_per_block == 2


# ---------------------------------------------------------------- egress fuse

FUSE_APP = """
    @app:playback @app:pipeline('2')
    define stream S (k int, v float);
    @info(name='q1')
    from every e1=S[k == 0] -> e2=S[k == 1 and v > e1.v]
    select e1.v as a, e2.v as b insert into Out1;
    @info(name='q2')
    from every e1=S[k == 1] -> e2=S[k == 0 and v > e1.v]
    select e1.v as c, e2.v as d insert into Out2;
"""


def _run_fuse_app(n_blocks=4, block_n=48):
    from siddhi_tpu import SiddhiManager, StreamCallback
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(FUSE_APP)
    out = {"Out1": [], "Out2": []}
    for sid in out:
        rt.add_callback(sid, StreamCallback(
            lambda evs, _s=sid: out[_s].extend(
                tuple(e.data) for e in evs)))
    rt.start()
    h = rt.get_input_handler("S")
    rng = np.random.default_rng(9)
    t0 = 1_000_000
    for _ in range(n_blocks):
        h.send_batch(
            {"k": rng.integers(0, 2, block_n).astype(np.int64),
             "v": rng.uniform(0, 100, block_n).astype(np.float32)},
            timestamps=t0 + np.arange(block_n, dtype=np.int64) * 7)
        t0 += block_n * 7
    rt.flush()
    fusers = {qr.device_runtime.nfa.egress_fuser
              for qr in rt.query_runtimes.values()}
    rt.shutdown()
    return out, fusers


def test_fused_egress_one_d2h_per_block(monkeypatch):
    """An app with TWO device pattern runtimes pays exactly ONE egress
    D2H per ingest block (both runtimes' compacted buffers ride one
    slab), and decodes to the same matches as the unfused legacy path
    (SIDDHI_TPU_EGRESS_FUSE=0)."""
    n_blocks = 4
    monkeypatch.delenv("SIDDHI_TPU_EGRESS_FUSE", raising=False)
    fused_out, fusers = _run_fuse_app(n_blocks)
    assert len(fusers) == 1, "runtimes did not share the app fuser"
    fuser = fusers.pop()
    assert fuser is not None
    # every ingest block formed one group, read back with one D2H
    assert fuser.d2h_count == n_blocks, \
        f"expected {n_blocks} fused D2H reads, got {fuser.d2h_count}"

    monkeypatch.setenv("SIDDHI_TPU_EGRESS_FUSE", "0")
    legacy_out, legacy_fusers = _run_fuse_app(n_blocks)
    assert legacy_fusers == {None}
    assert sum(len(v) for v in fused_out.values()) > 0, \
        "degenerate fuse feed (0 matches)"
    for sid in fused_out:
        assert fused_out[sid] == legacy_out[sid], \
            f"{sid}: fused egress decoded different matches"


def test_app_dispatches_per_block_gauge():
    """The per-app dispatches/block gauge ticks from real ingest deltas
    and exports on /metrics."""
    prof = profiler()
    was = prof.enabled
    prof.enable()
    try:
        _run_fuse_app(2)
        apps = [a for a in prof.app_blocks if prof.app_blocks[a][1] > 0]
        assert apps, "no app recorded ingest-block dispatch deltas"
        assert any(prof.dispatches_per_block(a) > 0 for a in apps)
        lines = "\n".join(prof.prometheus_lines())
        assert "siddhi_app_dispatches_per_block" in lines
        assert "siddhi_kernel_dispatches_total" in lines
    finally:
        if not was:
            prof.disable()
