"""Numeric-safety verifier (analysis/ranges.py): interval-lattice
property tests, @attr:range / @app:rate seeding (SA09x), every NS0xx
static verdict positive AND negative, provenance triage, the jax-free
`analyze --numeric` CLI, and the plan-grounded runtime attach.

The lattice tests are randomized-but-seeded brute-force enumerations:
every abstract op is checked sound (the result hull covers every
concrete pairing) over small integer domains, and widening is checked
to terminate in <= 2 steps (the jump-to-bounds contract the module
docstring promises)."""
import json
import math
import os
import random
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from siddhi_tpu.analysis.diagnostics import CATALOG, Severity  # noqa: E402
from siddhi_tpu.analysis.ranges import (Interval,  # noqa: E402
                                        analyze_numeric, ts32_safe_max)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -------------------------------------------------- lattice: soundness

def _rand_interval(rng, span=6):
    a = rng.randint(-span, span)
    b = rng.randint(-span, span)
    return Interval(min(a, b), max(a, b), declared=True)


def _points(iv):
    return range(int(iv.lo), int(iv.hi) + 1)


def test_lattice_binary_ops_sound_and_exact_vs_enumeration():
    """add/sub/mul hulls equal the exact min/max over every concrete
    pair; join covers both operands."""
    rng = random.Random(42)
    for _ in range(300):
        x, y = _rand_interval(rng), _rand_interval(rng)
        for name, op, conc in (
                ("add", x.add(y), lambda a, b: a + b),
                ("sub", x.sub(y), lambda a, b: a - b),
                ("mul", x.mul(y), lambda a, b: a * b)):
            vals = [conc(a, b) for a in _points(x) for b in _points(y)]
            assert op.lo == min(vals) and op.hi == max(vals), \
                f"{name}({x}, {y}) -> {op} vs exact " \
                f"[{min(vals)}, {max(vals)}]"
        j = x.join(y)
        for v in list(_points(x)) + list(_points(y)):
            assert j.contains(v)


def test_lattice_unary_ops_exact_vs_enumeration():
    rng = random.Random(7)
    for _ in range(200):
        x = _rand_interval(rng)
        for op, conc in ((x.neg(), lambda a: -a),
                         (x.abs_(), abs)):
            vals = [conc(a) for a in _points(x)]
            assert op.lo == min(vals) and op.hi == max(vals)


def test_lattice_div_sound_when_divisor_excludes_zero():
    rng = random.Random(13)
    for _ in range(200):
        x = _rand_interval(rng)
        d = _rand_interval(rng)
        if d.contains_zero:
            # zero-crossing divisor is the NS002 path: div degrades to
            # top rather than raising
            t = x.div(d)
            assert t.lo == -math.inf and t.hi == math.inf
            continue
        q = x.div(d)
        for a in _points(x):
            for b in _points(d):
                assert q.lo <= a / b <= q.hi, (x, d, q, a, b)


def test_lattice_mod_sound():
    rng = random.Random(99)
    for _ in range(200):
        x = _rand_interval(rng)
        d = _rand_interval(rng)
        m = x.mod(d)
        for a in _points(x):
            for b in _points(d):
                if b == 0:
                    continue
                assert m.contains(math.fmod(a, b)), (x, d, m, a, b)


def test_lattice_scale_covers_window_accumulation():
    rng = random.Random(5)
    for _ in range(200):
        x = _rand_interval(rng)
        n = rng.randint(0, 50)
        s = x.scale(n)
        # a sum of n terms each within x lands within n*x (plus the
        # empty-accumulator 0 the engine's identity rows hold)
        for _ in range(20):
            total = sum(rng.randint(int(x.lo), int(x.hi))
                        for _ in range(n))
            assert s.contains(total), (x, n, s, total)
        assert s.contains(0)


def test_widening_terminates_in_two_steps():
    """Jump-to-bounds widening: iterating widen over ANY ascending
    chain reaches a fixpoint in at most 2 applications."""
    rng = random.Random(21)
    bounds = Interval(-(1 << 31), (1 << 31) - 1)
    for _ in range(300):
        cur = _rand_interval(rng)
        steps = 0
        while True:
            grow = cur.join(_rand_interval(rng, span=40))
            nxt = cur.widen(grow, bounds)
            if nxt == cur:
                break
            cur = nxt
            steps += 1
            assert steps <= 2, f"widening chain did not stabilise: {cur}"
        assert bounds.lo <= cur.lo <= cur.hi <= bounds.hi


def test_interval_invariants_and_provenance():
    with pytest.raises(ValueError):
        Interval(3, 1)
    a = Interval(0, 5, declared=True)
    b = Interval(1, 2, declared=False)
    assert not a.add(b).declared        # provenance is AND over leaves
    assert a.add(Interval(1, 2, declared=True)).declared
    assert Interval.top().contains(1e300)
    assert a.as_list() == [0, 5]
    assert Interval.top().as_list() == [None, None]   # JSON-safe inf


def test_ts32_safe_max_mirrors_device_kernel():
    """ranges.py is jax-free so it MIRRORS ops/ts32.safe_max; the two
    formulas must never drift."""
    from siddhi_tpu.ops.ts32 import safe_max
    for slack in (0, 1, 1000, 86_400_000, (1 << 30)):
        assert ts32_safe_max(slack) == safe_max(slack), slack


# ------------------------------------------- @attr:range seeding (SA09x)

def _codes(app, engine=None):
    rep = analyze_numeric(app, engine)
    return rep, {d.code for d in rep.findings}


def test_sa090_malformed_range_annotation():
    rep, codes = _codes("""
        @attr:range('no_such_attr', 0, 1)
        define stream S (v int);
        from S select v as v insert into Out;
    """)
    assert "SA090" in codes
    d = next(d for d in rep.findings if d.code == "SA090")
    assert d.severity == Severity.ERROR
    assert d.line >= 1                      # position threaded through


def test_sa090_non_numeric_bounds():
    _, codes = _codes("""
        @attr:range('v', 'abc', 10)
        define stream S (v int);
        from S select v as v insert into Out;
    """)
    assert "SA090" in codes


def test_sa091_inverted_bounds():
    rep, codes = _codes("""
        @attr:range('v', 10, -10)
        define stream S (v int);
        from S select v as v insert into Out;
    """)
    assert "SA091" in codes
    assert next(d for d in rep.findings
                if d.code == "SA091").severity == Severity.ERROR


def test_sa092_bounds_wider_than_dtype():
    _, codes = _codes("""
        @attr:range('w', 0, 99999999999)
        define stream S (w int);
        from S select w as w insert into Out;
    """)
    assert "SA092" in codes


def test_well_formed_declarations_are_silent():
    rep, codes = _codes("""
        @attr:range('v', -500, 500)
        define stream S (v int);
        from S select v as v insert into Out;
    """)
    assert not codes & {"SA090", "SA091", "SA092"}
    assert rep.ok
    assert rep.declared_ranges.get("S.v") == [-500, 500]


# --------------------------------------------------- NS verdicts pos/neg

def test_ns001_int_overflow_positive():
    rep, codes = _codes("""
        @attr:range('a', 0, 2000000000)
        define stream S (a int);
        from S select a + a as b insert into Out;
    """)
    assert "NS001" in codes
    d = next(d for d in rep.findings if d.code == "NS001")
    assert d.severity == Severity.WARNING   # declared range arms it


def test_ns001_negative_bounded_arithmetic():
    _, codes = _codes("""
        @attr:range('a', 0, 1000)
        define stream S (a int);
        from S select a + a as b insert into Out;
    """)
    assert "NS001" not in codes


def test_ns002_division_by_zero_crossing_divisor():
    rep, codes = _codes("""
        @attr:range('d', -5, 5)
        define stream S (v double, d double);
        from S select v / d as q insert into Out;
    """)
    assert "NS002" in codes
    assert next(d for d in rep.findings
                if d.code == "NS002").severity == Severity.WARNING


def test_ns002_negative_divisor_excludes_zero():
    _, codes = _codes("""
        @attr:range('d', 1, 5)
        define stream S (v double, d double);
        from S select v / d as q insert into Out;
    """)
    assert "NS002" not in codes


def test_ns003_naive_slab_past_precision_budget():
    app = """
        @app:rate(10000)
        @attr:range('price', 0, 100000)
        define stream S (price double, symbol string);
        define aggregation agg
        from S
        select symbol, sum(price) as total
        group by symbol
        aggregate every sec ... day;
    """
    rep, codes = _codes(app)
    assert "NS003" in codes
    assert next(d for d in rep.findings
                if d.code == "NS003").severity == Severity.WARNING


@pytest.mark.parametrize("mode", ["compensated", "kahan"])
def test_ns003_negative_compensated_remediation(mode):
    """@numeric(sum='compensated') is the documented per-query
    remediation — it must clear the verdict."""
    _, codes = _codes(f"""
        @app:rate(10000)
        @attr:range('price', 0, 100000)
        define stream S (price double, symbol string);
        @numeric(sum='{mode}')
        define aggregation agg
        from S
        select symbol, sum(price) as total
        group by symbol
        aggregate every sec ... day;
    """)
    assert "NS003" not in codes


def test_ns003_negative_host_engine():
    """The host cascade accumulates arbitrary-precision — no finding."""
    _, codes = _codes("""
        @app:rate(10000)
        @attr:range('price', 0, 100000)
        define stream S (price double, symbol string);
        define aggregation agg
        from S
        select symbol, sum(price) as total
        group by symbol
        aggregate every sec ... day;
    """, engine="host")
    assert "NS003" not in codes


def test_ns004_within_past_ts32_horizon():
    rep, codes = _codes("""
        define stream A (x int); define stream B (x int);
        from every e1=A -> e2=B within 1728000000 millisec
        select e1.x as x insert into Out;
    """)
    assert "NS004" in codes
    d = next(d for d in rep.findings if d.code == "NS004")
    assert d.severity == Severity.WARNING


def test_ns004_negative_short_within():
    _, codes = _codes("""
        define stream A (x int); define stream B (x int);
        from every e1=A -> e2=B within 10 sec
        select e1.x as x insert into Out;
    """)
    assert "NS004" not in codes


def test_ns004_time_window_span():
    _, codes = _codes("""
        define stream S (v double);
        from S#window.time(30 days) select v as v insert into Out;
    """)
    assert "NS004" in codes


def test_ns005_count_lane_saturation():
    rep, codes = _codes("""
        @app:rate(1000000)
        define stream S (v double);
        from S#window.time(5000 sec) select count() as n insert into Out;
    """)
    assert "NS005" in codes
    assert next(d for d in rep.findings
                if d.code == "NS005").severity == Severity.WARNING


def test_ns005_negative_bounded_window():
    _, codes = _codes("""
        @app:rate(100)
        define stream S (v double);
        from S#window.time(10 sec) select count() as n insert into Out;
    """)
    assert "NS005" not in codes


def test_ns006_lossy_egress_demotion():
    rep, codes = _codes("""
        @app:engine('tpu')
        @attr:range('v', 0, 100000000)
        define stream S (v long);
        from S select v as v insert into Out;
    """)
    assert "NS006" in codes
    assert next(d for d in rep.findings
                if d.code == "NS006").severity == Severity.WARNING


def test_ns006_negative_on_host_engine_and_small_range():
    _, codes = _codes("""
        @app:engine('host')
        @attr:range('v', 0, 100000000)
        define stream S (v long);
        from S select v as v insert into Out;
    """)
    assert "NS006" not in codes
    _, codes = _codes("""
        @app:engine('tpu')
        @attr:range('v', 0, 1000)
        define stream S (v long);
        from S select v as v insert into Out;
    """)
    assert "NS006" not in codes


def test_catalog_has_every_ns_code():
    for code in ("NS001", "NS002", "NS003", "NS004", "NS005", "NS006",
                 "NS101", "SA090", "SA091", "SA092"):
        assert code in CATALOG, code


# -------------------------------------------------- provenance triage

def test_undeclared_bounds_downgrade_to_info():
    """The same escape without @attr:range rests only on conservative
    dtype bounds: INFO, and the report stays gate-clean (ok)."""
    rep = analyze_numeric("""
        define stream S (a int);
        from S select a + a as b insert into Out;
    """)
    infos = [d for d in rep.findings if d.code == "NS001"]
    assert infos and all(d.severity == Severity.INFO for d in infos)
    assert rep.ok
    assert "conservative dtype bounds" in infos[0].message


def test_report_surfaces():
    rep = analyze_numeric("""
        @app:rate(1000000)
        define stream S (v double);
        from S#window.time(5000 sec) select count() as n insert into Out;
    """)
    doc = rep.as_dict()
    assert doc["source"] == "static"
    assert doc["rate_eps"] == 1000000
    assert doc["rate_declared"] is True
    assert any(f["code"] == "NS005" for f in doc["findings"])
    json.dumps(doc)                        # REST-safe (no inf/dataclass)
    text = rep.dump()
    assert "NS005" in text
    assert not rep.ok
    assert rep.counts().get("NS005", 0) >= 1


# ----------------------------------------------------- jax-free CLI

def _cli(tmp_path, text, *flags):
    f = tmp_path / "app.siddhi"
    f.write_text(text)
    return subprocess.run(
        [sys.executable, "-m", "siddhi_tpu.analyze", str(f), "--numeric",
         *flags],
        capture_output=True, text=True, cwd=ROOT, timeout=120)


DIRTY = """
@app:rate(1000000)
define stream S (v double);
from S#window.time(5000 sec) select count() as n insert into Out;
"""

CLEAN = """
@attr:range('v', 0, 100)
define stream S (v double);
from S#window.length(10) select v as v insert into Out;
"""


def test_cli_numeric_exit_codes(tmp_path):
    res = _cli(tmp_path, DIRTY)
    assert res.returncode == 1, res.stdout + res.stderr
    assert "NS005" in res.stdout
    res = _cli(tmp_path, CLEAN)
    assert res.returncode == 0, res.stdout + res.stderr


def test_cli_numeric_json_and_jax_free(tmp_path):
    f = tmp_path / "app.siddhi"
    f.write_text(DIRTY)
    probe = (
        "import sys, runpy\n"
        f"sys.argv = ['analyze', {str(f)!r}, '--numeric', '--json']\n"
        "try:\n"
        "    runpy.run_module('siddhi_tpu.analyze', run_name='__main__')\n"
        "except SystemExit as e:\n"
        "    assert e.code == 1, e.code\n"
        "assert 'jax' not in sys.modules, 'the --numeric path must stay "
        "jax-free'\n")
    res = subprocess.run([sys.executable, "-c", probe],
                         capture_output=True, text=True, cwd=ROOT,
                         timeout=120)
    assert res.returncode == 0, res.stdout + res.stderr
    doc = json.loads(res.stdout)
    assert any(fi["code"] == "NS005" for fi in doc["findings"])


# ------------------------------------------- plan-grounded runtime half

def test_runtime_attach_produces_plan_report():
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.analysis.ranges import attach_numeric_analysis
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:rate(1000000)
        define stream S (sym string, v double);
        @info(name='q') from S#window.time(5000 sec)
        select sym, count() as n group by sym insert into Out;
    """)
    try:
        rep = rt.analysis.numeric
        assert rep is not None and rep.source == "plan"
        assert rt.numeric_report is rep
        assert any(d.code == "NS005" for d in rep.findings)
        # NS findings were merged into the app-level diagnostics exactly
        # once (no dup between the source pass and the plan re-ground)
        ns_keys = [(d.code, d.message) for d in rt.analysis.diagnostics
                   if d.code.startswith("NS")]
        assert len(ns_keys) == len(set(ns_keys))
        # re-attach is idempotent
        before = [(d.code, d.message) for d in rt.analysis.diagnostics]
        attach_numeric_analysis(rt)
        after = [(d.code, d.message) for d in rt.analysis.diagnostics]
        assert before == after
    finally:
        rt.shutdown()


def test_runtime_attach_strict_raises():
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.analysis.ranges import attach_numeric_analysis
    from siddhi_tpu.utils.errors import SiddhiAppValidationException
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:rate(1000000)
        define stream S (sym string, v double);
        @info(name='q') from S#window.time(5000 sec)
        select sym, count() as n group by sym insert into Out;
    """)
    try:
        with pytest.raises(SiddhiAppValidationException):
            attach_numeric_analysis(rt, strict=True)
    finally:
        rt.shutdown()


def test_stats_endpoint_carries_numeric_section():
    import urllib.request
    from siddhi_tpu.service.rest import SiddhiService
    svc = SiddhiService(port=0).start()
    try:
        base = f"http://127.0.0.1:{svc.port}"
        app = ("@app:name('nstat') "
               "@app:statistics(reporter='console', interval='300') "
               "@app:rate(1000000) "
               "define stream S (sym string, v double); "
               "@info(name='q') from S#window.time(5000 sec) "
               "select sym, count() as n group by sym insert into Out;")
        req = urllib.request.Request(
            f"{base}/siddhi/artifact/deploy", data=app.encode(),
            method="POST")
        with urllib.request.urlopen(req, timeout=30):
            pass
        with urllib.request.urlopen(f"{base}/stats", timeout=30) as r:
            doc = json.loads(r.read().decode())
        num = doc["apps"]["nstat"].get("numeric")
        assert num, f"/stats has no numeric section: {doc['apps']}"
        assert num["source"] == "plan"
        assert any(f["code"] == "NS005" for f in num["findings"])
    finally:
        svc.stop()
