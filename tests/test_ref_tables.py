"""Table conformance tests ported from the reference corpus
(siddhi-core/src/test/java/io/siddhi/core/query/table/ — IndexTableTestCase,
PrimaryKeyTableTestCase, JoinTableTestCase, LogicalTableTestCase,
DeleteFromTableTestCase, UpdateFromTableTestCase, UpdateOrInsertTableTestCase,
InsertIntoTableTestCase).  Behaviors mirrored; assertions are the reference
tests' expected payloads."""
from ref_harness import run_query

STOCKS = """
define stream StockStream (symbol string, price float, volume long);
define stream CheckStockStream (symbol string, volume long);
define stream UpdateStockStream (symbol string, price float, volume long);
define stream DeleteStockStream (symbol string);
"""


def T(ann=""):
    return f"{ann} define table StockTable " \
           "(symbol string, price float, volume long);\n"


FILL = [("StockStream", ["WSO2", 55.6, 100]),
        ("StockStream", ["IBM", 75.6, 10]),
        ("StockStream", ["MSFT", 57.6, 200])]


# ------------------------------------------------- IndexTableTestCase

def test_index_join_eq():
    """indexTableTest1: join on the indexed attribute."""
    run_query(STOCKS + T("@Index('symbol')") + """
        from StockStream insert into StockTable;
        @info(name='query1')
        from CheckStockStream join StockTable
            on CheckStockStream.symbol == StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;""",
        [("StockStream", ["WSO2", 55.6, 100]),
         ("StockStream", ["IBM", 55.6, 100]),
         ("CheckStockStream", ["IBM", 100]),
         ("CheckStockStream", ["WSO2", 100])],
        [("IBM", 100), ("WSO2", 100)])


def test_index_join_lt_const():
    """indexTableTest2 family: non-eq condition over the indexed attr falls
    back to scan but stays correct."""
    run_query(STOCKS + T("@Index('volume')") + """
        from StockStream insert into StockTable;
        @info(name='query1')
        from CheckStockStream join StockTable
            on StockTable.volume < 150
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;""",
        FILL + [("CheckStockStream", ["BP", 100])],
        [("BP", 100), ("BP", 10)], unordered=True)


def test_index_delete_on_indexed():
    run_query(STOCKS + T("@Index('symbol')") + """
        from StockStream insert into StockTable;
        from DeleteStockStream delete StockTable
            on StockTable.symbol == DeleteStockStream.symbol;
        @info(name='query1')
        from CheckStockStream join StockTable
            on CheckStockStream.symbol == StockTable.symbol
        select StockTable.symbol, StockTable.volume
        insert into OutStream;""",
        FILL + [("DeleteStockStream", ["IBM"]),
                ("CheckStockStream", ["IBM", 0]),
                ("CheckStockStream", ["WSO2", 0])],
        [("WSO2", 100)])


def test_index_update_on_indexed():
    run_query(STOCKS + T("@Index('symbol')") + """
        from StockStream insert into StockTable;
        from UpdateStockStream update StockTable
            set StockTable.volume = UpdateStockStream.volume
            on StockTable.symbol == UpdateStockStream.symbol;
        @info(name='query1')
        from CheckStockStream join StockTable
            on CheckStockStream.symbol == StockTable.symbol
        select StockTable.symbol, StockTable.volume
        insert into OutStream;""",
        FILL + [("UpdateStockStream", ["IBM", 77.6, 999]),
                ("CheckStockStream", ["IBM", 0])],
        [("IBM", 999)])


def test_index_condition_and_residual():
    """Indexed eq AND residual non-indexed conjunct."""
    run_query(STOCKS + T("@Index('symbol')") + """
        from StockStream insert into StockTable;
        @info(name='query1')
        from CheckStockStream join StockTable
            on CheckStockStream.symbol == StockTable.symbol
               and StockTable.volume > 50
        select StockTable.symbol, StockTable.volume
        insert into OutStream;""",
        FILL + [("CheckStockStream", ["IBM", 0]),     # vol 10 → filtered
                ("CheckStockStream", ["WSO2", 0])],
        [("WSO2", 100)])


# ------------------------------------------------- PrimaryKeyTableTestCase

def test_pk_join_eq():
    """primaryKeyTableTest1: probe on the PK."""
    run_query(STOCKS + T("@PrimaryKey('symbol')") + """
        from StockStream insert into StockTable;
        @info(name='query1')
        from CheckStockStream join StockTable
            on CheckStockStream.symbol == StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;""",
        [("StockStream", ["WSO2", 55.6, 100]),
         ("StockStream", ["IBM", 55.6, 100]),
         ("CheckStockStream", ["IBM", 100]),
         ("CheckStockStream", ["WSO2", 100])],
        [("IBM", 100), ("WSO2", 100)])


def test_pk_overwrite_on_duplicate_insert():
    """PK clash keeps ONE row (latest values win on this engine)."""
    run_query(STOCKS + T("@PrimaryKey('symbol')") + """
        from StockStream insert into StockTable;
        @info(name='query1')
        from CheckStockStream join StockTable
            on CheckStockStream.symbol == StockTable.symbol
        select StockTable.symbol, StockTable.price, StockTable.volume
        insert into OutStream;""",
        [("StockStream", ["IBM", 10.0, 1]),
         ("StockStream", ["IBM", 20.0, 2]),
         ("CheckStockStream", ["IBM", 0])],
        [("IBM", 20.0, 2)])


def test_pk_delete():
    """primaryKeyTableTest: delete by PK condition."""
    run_query(STOCKS + T("@PrimaryKey('symbol')") + """
        from StockStream insert into StockTable;
        from DeleteStockStream delete StockTable
            on StockTable.symbol == DeleteStockStream.symbol;
        @info(name='query1')
        from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;""",
        FILL + [("DeleteStockStream", ["WSO2"]),
                ("CheckStockStream", ["X", 0])],
        [("IBM", 10), ("MSFT", 200)], unordered=True)


def test_pk_int_key():
    run_query("""
        define stream S (id int, name string);
        define stream Q (id int);
        @PrimaryKey('id') define table T (id int, name string);
        from S insert into T;
        @info(name='query1')
        from Q join T on T.id == Q.id
        select T.id, T.name insert into OutStream;""",
        [("S", [1, "a"]), ("S", [2, "b"]), ("S", [3, "c"]),
         ("Q", [2])],
        [(2, "b")])


# ------------------------------------------------- LogicalTableTestCase

def test_logical_and_condition():
    run_query(STOCKS + T() + """
        from StockStream insert into StockTable;
        @info(name='query1')
        from CheckStockStream join StockTable
            on StockTable.symbol == 'IBM' and StockTable.volume == 10
        select StockTable.symbol, StockTable.volume
        insert into OutStream;""",
        FILL + [("CheckStockStream", ["X", 0])],
        [("IBM", 10)])


def test_logical_or_condition():
    run_query(STOCKS + T() + """
        from StockStream insert into StockTable;
        @info(name='query1')
        from CheckStockStream join StockTable
            on StockTable.symbol == 'IBM' or StockTable.volume == 200
        select StockTable.symbol insert into OutStream;""",
        FILL + [("CheckStockStream", ["X", 0])],
        [("IBM",), ("MSFT",)], unordered=True)


def test_logical_not_condition():
    run_query(STOCKS + T() + """
        from StockStream insert into StockTable;
        @info(name='query1')
        from CheckStockStream join StockTable
            on not (StockTable.symbol == 'IBM')
        select StockTable.symbol insert into OutStream;""",
        FILL + [("CheckStockStream", ["X", 0])],
        [("WSO2",), ("MSFT",)], unordered=True)


# ------------------------------------------------- Delete/Update/UpsertTestCase

def test_delete_with_compound_condition():
    run_query(STOCKS + T() + """
        from StockStream insert into StockTable;
        from DeleteStockStream delete StockTable
            on StockTable.symbol == DeleteStockStream.symbol
               and StockTable.volume < 50;
        @info(name='query1')
        from CheckStockStream join StockTable
        select StockTable.symbol insert into OutStream;""",
        FILL + [("DeleteStockStream", ["IBM"]),     # vol 10 < 50 → deleted
                ("DeleteStockStream", ["WSO2"]),    # vol 100 → kept
                ("CheckStockStream", ["X", 0])],
        [("WSO2",), ("MSFT",)], unordered=True)


def test_update_multiple_rows():
    """update hits every matching row."""
    run_query("""
        define stream S (symbol string, price float);
        define stream U (tag string);
        define stream C (x int);
        define table T (symbol string, price float);
        from S insert into T;
        from U update T set T.price = 0.0 on T.price > 50.0;
        @info(name='query1')
        from C join T select T.symbol, T.price insert into OutStream;""",
        [("S", ["A", 55.0]), ("S", ["B", 45.0]), ("S", ["C", 65.0]),
         ("U", ["go"]), ("C", [1])],
        [("A", 0.0), ("B", 45.0), ("C", 0.0)], unordered=True)


def test_update_or_insert_inserts_then_updates():
    run_query("""
        define stream S (symbol string, price float);
        define stream C (x int);
        define table T (symbol string, price float);
        from S update or insert into T set T.price = S.price
            on T.symbol == S.symbol;
        @info(name='query1')
        from C join T select T.symbol, T.price insert into OutStream;""",
        [("S", ["A", 1.0]), ("S", ["B", 2.0]), ("S", ["A", 3.0]),
         ("C", [1])],
        [("A", 3.0), ("B", 2.0)], unordered=True)


# ------------------------------------------------- JoinTableTestCase

def test_table_join_with_stream_filter():
    run_query(STOCKS + T() + """
        from StockStream insert into StockTable;
        @info(name='query1')
        from CheckStockStream[volume > 50] join StockTable
            on CheckStockStream.symbol == StockTable.symbol
        select CheckStockStream.symbol, StockTable.price
        insert into OutStream;""",
        FILL + [("CheckStockStream", ["IBM", 10]),    # filtered out
                ("CheckStockStream", ["IBM", 100])],
        [("IBM", 75.6)])


def test_table_join_select_star_arity():
    run_query("""
        define stream S (a int);
        define stream F (b int);
        define table T (b int);
        from F insert into T;
        @info(name='query1')
        from S join T on T.b == S.a
        select S.a, T.b insert into OutStream;""",
        [("F", [1]), ("F", [2]), ("S", [2])],
        [(2, 2)])


def test_in_table_membership():
    """`in Table` membership operator
    (reference condition/InConditionExpressionExecutor)."""
    run_query("""
        define stream S (symbol string, price float);
        define stream F (symbol string);
        @PrimaryKey('symbol') define table T (symbol string);
        from F insert into T;
        @info(name='query1')
        from S[symbol in T] select symbol, price insert into OutStream;""",
        [("F", ["IBM"]), ("S", ["IBM", 1.0]), ("S", ["WSO2", 2.0]),
         ("S", ["IBM", 3.0])],
        [("IBM", 1.0), ("IBM", 3.0)])


def test_table_window_join():
    """Stream window join against a table stays windowed on the stream
    side (JoinTableTestCase window variants)."""
    run_query("""
        define stream S (symbol string, v long);
        define stream F (symbol string, m long);
        define table T (symbol string, m long);
        from F insert into T;
        @info(name='query1')
        from S#window.length(1) join T on T.symbol == S.symbol
        select S.symbol, S.v, T.m insert into OutStream;""",
        [("F", ["A", 7]), ("S", ["A", 1]), ("S", ["A", 2])],
        [("A", 1, 7), ("A", 2, 7)])
