"""Lint step: `ruff check` over the engine package, configured by
ruff.toml at the repo root.

The container image bakes its toolchain (nothing may be pip-installed),
so when ruff is absent the ruff test SKIPS — but a pure-AST fallback
still enforces the highest-signal rules so lint rot is caught even
without the binary: F401 unused imports, unused exception bindings (the
common F841 case), and — since ruff.toml widened to the B (bugbear) and
SIM (simplify) families — B006 mutable argument defaults, B023 loop-
variable capture in closures, B904 raise-without-from inside except,
SIM118 `in dict.keys()`, and SIM201/202 negated ==/!= comparisons."""
import ast
import os
import re
import shutil
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, "siddhi_tpu")


def test_ruff_clean():
    ruff = shutil.which("ruff")
    if ruff is None:
        pytest.skip("ruff not installed in this image (no pip installs "
                    "allowed); AST fallback below still runs")
    res = subprocess.run([ruff, "check", "siddhi_tpu", "tests", "bench.py"],
                        cwd=ROOT, capture_output=True, text=True)
    assert res.returncode == 0, f"ruff violations:\n{res.stdout}{res.stderr}"


def test_engine_lint_strict():
    """The CE/LW engine self-audit rides the lint step: `analyze
    --engine --strict` must exit 0 (clean modulo the justified
    allowlist in analysis/engine/__init__.py).  Runs as a subprocess so
    it also re-proves the no-jax guarantee of the analyze CLI."""
    res = subprocess.run(
        [sys.executable, "-m", "siddhi_tpu.analyze", "--engine", "--strict"],
        cwd=ROOT, capture_output=True, text=True)
    assert res.returncode == 0, (
        f"engine audit not clean:\n{res.stdout}{res.stderr}")


def _py_files():
    for dirpath, dirnames, filenames in os.walk(PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for f in filenames:
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def test_no_unused_imports_f401_fallback():
    bad = []
    for path in _py_files():
        if os.path.basename(path) == "__init__.py":
            continue        # facades re-export (per-file-ignore in ruff.toml)
        src = open(path).read()
        lines = src.splitlines()
        tree = ast.parse(src)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            if isinstance(node, ast.ImportFrom) and \
                    node.module == "__future__":
                continue
            if "noqa" in lines[node.lineno - 1]:
                continue
            rest = "\n".join(
                ln for i, ln in enumerate(lines, 1)
                if not (node.lineno <= i <= node.end_lineno))
            for a in node.names:
                if a.name == "*":
                    continue
                nm = (a.asname or a.name).split(".")[0]
                if not re.search(r"\b%s\b" % re.escape(nm), rest):
                    rel = os.path.relpath(path, ROOT)
                    bad.append(f"{rel}:{node.lineno}: unused import '{nm}'")
    assert not bad, "F401 (unused imports):\n" + "\n".join(bad)


def test_no_unused_exception_bindings_f841_fallback():
    bad = []
    for path in _py_files():
        tree = ast.parse(open(path).read())
        for node in ast.walk(tree):
            if isinstance(node, ast.ExceptHandler) and node.name:
                body = ast.unparse(ast.Module(body=node.body,
                                              type_ignores=[]))
                if not re.search(r"\b%s\b" % node.name, body):
                    rel = os.path.relpath(path, ROOT)
                    bad.append(f"{rel}:{node.lineno}: unused exception "
                               f"binding '{node.name}'")
    assert not bad, "F841 (unused `except as` bindings):\n" + "\n".join(bad)


def test_no_mutable_default_args_b006_fallback():
    """B006: list/dict/set literals (or constructor calls) as argument
    defaults are shared across calls — a classic aliasing bug."""
    bad = []
    for path in _py_files():
        tree = ast.parse(open(path).read())
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            defaults = list(node.args.defaults) + \
                [d for d in node.args.kw_defaults if d is not None]
            for d in defaults:
                mutable = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(d, ast.Call) and
                    isinstance(d.func, ast.Name) and
                    d.func.id in ("list", "dict", "set"))
                if mutable:
                    rel = os.path.relpath(path, ROOT)
                    bad.append(f"{rel}:{d.lineno}: mutable default in "
                               f"'{node.name}'")
    assert not bad, "B006 (mutable argument defaults):\n" + "\n".join(bad)


def test_no_loop_variable_capture_b023_fallback():
    """B023: a closure defined inside a loop that reads the loop
    variable binds the VARIABLE, not the iteration's value — freeze it
    via a default argument (`def f(..., _x=x)`), the repo idiom."""
    bad = []
    for path in _py_files():
        tree = ast.parse(open(path).read())
        for loop in ast.walk(tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor)):
                continue
            targets = {t.id for t in ast.walk(loop.target)
                       if isinstance(t, ast.Name)}
            for sub in ast.walk(ast.Module(body=loop.body + loop.orelse,
                                           type_ignores=[])):
                if not isinstance(sub, (ast.FunctionDef, ast.Lambda)):
                    continue
                bound = {a.arg for a in (sub.args.args +
                                         sub.args.kwonlyargs)}
                body = sub.body if isinstance(sub.body, list) \
                    else [ast.Expr(sub.body)]
                names = {n.id for s in body for n in ast.walk(s)
                         if isinstance(n, ast.Name)}
                captured = sorted((targets & names) - bound)
                if captured:
                    rel = os.path.relpath(path, ROOT)
                    bad.append(f"{rel}:{sub.lineno}: closure captures "
                               f"loop variable(s) {captured}")
    assert not bad, "B023 (loop-variable capture):\n" + "\n".join(bad)


def test_raise_from_in_except_b904_fallback():
    """B904: `raise X(...)` inside an except block without `from err` /
    `from None` hides the causal chain."""
    bad = []
    for path in _py_files():
        tree = ast.parse(open(path).read())
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            for n in ast.walk(ast.Module(body=node.body, type_ignores=[])):
                if isinstance(n, ast.Raise) and n.exc is not None and \
                        n.cause is None:
                    rel = os.path.relpath(path, ROOT)
                    bad.append(f"{rel}:{n.lineno}: raise without "
                               f"`from` inside except")
    assert not bad, "B904 (raise-without-from):\n" + "\n".join(bad)


def test_no_sim118_or_negated_compares_fallback():
    """SIM118 (`k in d.keys()` -> `k in d`) and SIM201/202
    (`not a == b` -> `a != b`)."""
    bad = []
    for path in _py_files():
        tree = ast.parse(open(path).read())
        rel = os.path.relpath(path, ROOT)
        for node in ast.walk(tree):
            if isinstance(node, ast.Compare) and len(node.ops) == 1 and \
                    isinstance(node.ops[0], (ast.In, ast.NotIn)):
                c = node.comparators[0]
                if isinstance(c, ast.Call) and \
                        isinstance(c.func, ast.Attribute) and \
                        c.func.attr == "keys" and not c.args:
                    bad.append(f"{rel}:{node.lineno}: `in d.keys()`")
            if isinstance(node, (ast.For, ast.AsyncFor)):
                it = node.iter
                if isinstance(it, ast.Call) and \
                        isinstance(it.func, ast.Attribute) and \
                        it.func.attr == "keys" and not it.args:
                    bad.append(f"{rel}:{node.lineno}: `for ... in "
                               f"d.keys()`")
            if isinstance(node, ast.UnaryOp) and \
                    isinstance(node.op, ast.Not) and \
                    isinstance(node.operand, ast.Compare) and \
                    len(node.operand.ops) == 1 and \
                    isinstance(node.operand.ops[0], (ast.Eq, ast.NotEq)):
                bad.append(f"{rel}:{node.lineno}: negated ==/!= compare")
    assert not bad, "SIM118/SIM201/SIM202:\n" + "\n".join(bad)


def test_no_syntax_or_undefined_star_imports():
    """E9-class guard: every module compiles; no `import *` outside
    facades (star imports defeat pyflakes' undefined-name analysis)."""
    for path in _py_files():
        src = open(path).read()
        compile(src, path, "exec")      # E9: syntax/indentation errors
        if os.path.basename(path) != "__init__.py":
            tree = ast.parse(src)
            for node in ast.walk(tree):
                if isinstance(node, ast.ImportFrom):
                    assert not any(a.name == "*" for a in node.names), \
                        f"{path}:{node.lineno}: star import"