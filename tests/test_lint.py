"""Lint step: `ruff check` over the engine package, configured by
ruff.toml at the repo root.

The container image bakes its toolchain (nothing may be pip-installed),
so when ruff is absent the ruff test SKIPS — but a pure-AST fallback
still enforces the highest-signal pyflakes rule (F401 unused imports)
plus unused exception bindings (the common F841 case) so lint rot is
caught even without the binary."""
import ast
import os
import re
import shutil
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, "siddhi_tpu")


def test_ruff_clean():
    ruff = shutil.which("ruff")
    if ruff is None:
        pytest.skip("ruff not installed in this image (no pip installs "
                    "allowed); AST fallback below still runs")
    res = subprocess.run([ruff, "check", "siddhi_tpu", "tests", "bench.py"],
                        cwd=ROOT, capture_output=True, text=True)
    assert res.returncode == 0, f"ruff violations:\n{res.stdout}{res.stderr}"


def _py_files():
    for dirpath, dirnames, filenames in os.walk(PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for f in filenames:
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def test_no_unused_imports_f401_fallback():
    bad = []
    for path in _py_files():
        if os.path.basename(path) == "__init__.py":
            continue        # facades re-export (per-file-ignore in ruff.toml)
        src = open(path).read()
        lines = src.splitlines()
        tree = ast.parse(src)
        for node in ast.walk(tree):
            if not isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            if isinstance(node, ast.ImportFrom) and \
                    node.module == "__future__":
                continue
            if "noqa" in lines[node.lineno - 1]:
                continue
            rest = "\n".join(
                ln for i, ln in enumerate(lines, 1)
                if not (node.lineno <= i <= node.end_lineno))
            for a in node.names:
                if a.name == "*":
                    continue
                nm = (a.asname or a.name).split(".")[0]
                if not re.search(r"\b%s\b" % re.escape(nm), rest):
                    rel = os.path.relpath(path, ROOT)
                    bad.append(f"{rel}:{node.lineno}: unused import '{nm}'")
    assert not bad, "F401 (unused imports):\n" + "\n".join(bad)


def test_no_unused_exception_bindings_f841_fallback():
    bad = []
    for path in _py_files():
        tree = ast.parse(open(path).read())
        for node in ast.walk(tree):
            if isinstance(node, ast.ExceptHandler) and node.name:
                body = ast.unparse(ast.Module(body=node.body,
                                              type_ignores=[]))
                if not re.search(r"\b%s\b" % node.name, body):
                    rel = os.path.relpath(path, ROOT)
                    bad.append(f"{rel}:{node.lineno}: unused exception "
                               f"binding '{node.name}'")
    assert not bad, "F841 (unused `except as` bindings):\n" + "\n".join(bad)


def test_no_syntax_or_undefined_star_imports():
    """E9-class guard: every module compiles; no `import *` outside
    facades (star imports defeat pyflakes' undefined-name analysis)."""
    for path in _py_files():
        src = open(path).read()
        compile(src, path, "exec")      # E9: syntax/indentation errors
        if os.path.basename(path) != "__init__.py":
            tree = ast.parse(src)
            for node in ast.walk(tree):
                if isinstance(node, ast.ImportFrom):
                    assert not any(a.name == "*" for a in node.names), \
                        f"{path}:{node.lineno}: star import"