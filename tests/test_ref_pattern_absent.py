"""Port of the reference absent-pattern conformance suite
(query/pattern/absent/AbsentPatternTestCase.java, 43 @Tests — the 24
distinct shapes; the remainder are timing permutations of these).
Reference Thread.sleep timings become explicit playback timestamps with
`__advance__` rows firing the scheduler between events.
"""
from ref_harness import run_query

S12 = """
define stream Stream1 (symbol string, price float, volume int);
define stream Stream2 (symbol string, price float, volume int);
"""
S123 = S12 + "define stream Stream3 (symbol string, price float, volume int);\n"
S1234 = S123 + "define stream Stream4 (symbol string, price float, volume int);\n"
Q = "@info(name = 'query1') "

ADV = lambda ts: ("__advance__", None, ts)


def pq(app, sends, expected, advance_to=None):
    run_query(app, sends, expected, playback=True, advance_to=advance_to)


def test_absent_1_fires_after_wait():
    pq(S12 + Q + """
        from e1=Stream1[price>20] -> not Stream2[price>e1.price] for 1 sec
        select e1.symbol as symbol1 insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100], 1000)],
        [("WSO2",)], advance_to=2200)


def test_absent_2_arrival_after_wait_is_fine():
    pq(S12 + Q + """
        from e1=Stream1[price>20] -> not Stream2[price>e1.price] for 1 sec
        select e1.symbol as symbol1 insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100], 1000), ADV(2100),
         ("Stream2", ["IBM", 58.7, 100], 2150)],
        [("WSO2",)], advance_to=2200)


def test_absent_3_arrival_within_wait_suppresses():
    pq(S12 + Q + """
        from e1=Stream1[price>20] -> not Stream2[price>e1.price] for 1 sec
        select e1.symbol as symbol1 insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100], 1000),
         ("Stream2", ["IBM", 58.7, 100], 1100)],
        [], advance_to=2200)


def test_absent_4_arrival_below_filter_ignored():
    pq(S12 + Q + """
        from e1=Stream1[price>20] -> not Stream2[price>e1.price] for 1 sec
        select e1.symbol as symbol1 insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100], 1000),
         ("Stream2", ["IBM", 50.7, 100], 1100)],
        [("WSO2",)], advance_to=2200)


def test_absent_5_leading_quiet_then_match():
    pq(S12 + Q + """
        from not Stream1[price>20] for 1 sec -> e2=Stream2[price>30]
        select e2.symbol as symbol insert into OutputStream;""",
        [ADV(1200), ("Stream2", ["IBM", 58.7, 100], 1250)],
        [("IBM",)], advance_to=2000)


def test_absent_6_leading_reset_by_arrival():
    pq(S12 + Q + """
        from not Stream1[price>20] for 1 sec -> e2=Stream2[price>30]
        select e2.symbol as symbol insert into OutputStream;""",
        [("Stream1", ["WSO2", 59.6, 100], 100), ADV(2200),
         ("Stream2", ["IBM", 58.7, 100], 2250)],
        [("IBM",)], advance_to=3000)


def test_absent_7_leading_arrival_below_filter_then_quick_e2():
    pq(S12 + Q + """
        from not Stream1[price>20] for 1 sec -> e2=Stream2[price>30]
        select e2.symbol as symbol insert into OutputStream;""",
        [("Stream1", ["WSO2", 5.6, 100], 100),
         ("Stream2", ["IBM", 58.7, 100], 200)],
        [], advance_to=2000)


def test_absent_8_leading_arrival_then_quick_e2():
    pq(S12 + Q + """
        from not Stream1[price>20] for 1 sec -> e2=Stream2[price>30]
        select e2.symbol as symbol insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100], 100),
         ("Stream2", ["IBM", 58.7, 100], 200)],
        [], advance_to=2000)


def test_absent_9_trailing_suppressed():
    pq(S123 + Q + """
        from e1=Stream1[price>10] -> e2=Stream2[price>20]
             -> not Stream3[price>30] for 1 sec
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 15.6, 100], 1000),
         ("Stream2", ["IBM", 28.7, 100], 1100),
         ("Stream3", ["GOOGLE", 55.7, 100], 1200)],
        [], advance_to=2500)


def test_absent_10_trailing_below_filter():
    pq(S123 + Q + """
        from e1=Stream1[price>10] -> e2=Stream2[price>20]
             -> not Stream3[price>30] for 1 sec
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 15.6, 100], 1000),
         ("Stream2", ["IBM", 28.7, 100], 1100),
         ("Stream3", ["GOOGLE", 25.7, 100], 1200)],
        [("WSO2", "IBM")], advance_to=2500)


def test_absent_11_trailing_fires():
    pq(S123 + Q + """
        from e1=Stream1[price>10] -> e2=Stream2[price>20]
             -> not Stream3[price>30] for 1 sec
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 15.6, 100], 1000),
         ("Stream2", ["IBM", 28.7, 100], 1100)],
        [("WSO2", "IBM")], advance_to=2500)


def test_absent_12_middle_fires_then_next():
    pq(S123 + Q + """
        from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec
             -> e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 15.6, 100], 1000), ADV(2200),
         ("Stream3", ["GOOGLE", 55.7, 100], 2250)],
        [("WSO2", "GOOGLE")], advance_to=3000)


def test_absent_13_middle_below_filter_arrival():
    pq(S123 + Q + """
        from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec
             -> e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 15.6, 100], 1000),
         ("Stream2", ["IBM", 8.7, 100], 1100), ADV(2300),
         ("Stream3", ["GOOGLE", 55.7, 100], 2350)],
        [("WSO2", "GOOGLE")], advance_to=3000)


def test_absent_14_middle_arrival_suppresses():
    pq(S123 + Q + """
        from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec
             -> e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 15.6, 100], 1000),
         ("Stream2", ["IBM", 28.7, 100], 1100),
         ("Stream3", ["GOOGLE", 55.7, 100], 1200)],
        [], advance_to=2500)


def test_absent_15_leading_not_confirmed_before_e2():
    pq(S123 + Q + """
        from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20]
             -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 15.6, 100], 100),
         ("Stream2", ["IBM", 28.7, 100], 200),
         ("Stream3", ["GOOGLE", 55.7, 100], 300)],
        [], advance_to=2000)


def test_absent_16_leading_quiet_then_chain():
    pq(S123 + Q + """
        from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20]
             -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3
        insert into OutputStream;""",
        [ADV(2200), ("Stream2", ["IBM", 28.7, 100], 2250),
         ("Stream3", ["GOOGLE", 55.7, 100], 2350)],
        [("IBM", "GOOGLE")], advance_to=3000)


def test_absent_17_leading_below_filter_then_chain():
    pq(S123 + Q + """
        from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20]
             -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 5.6, 100], 600), ADV(1200),
         ("Stream2", ["IBM", 28.7, 100], 1250),
         ("Stream3", ["GOOGLE", 55.7, 100], 1350)],
        [("IBM", "GOOGLE")], advance_to=3000)


def test_absent_18_leading_rearmed_after_arrival():
    pq(S123 + Q + """
        from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20]
             -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 25.6, 100], 100), ADV(1300),
         ("Stream2", ["IBM", 28.7, 100], 1350),
         ("Stream3", ["GOOGLE", 55.7, 100], 1450)],
        [("IBM", "GOOGLE")], advance_to=3000)


def test_absent_19_trailing_after_three():
    pq(S1234 + Q + """
        from e1=Stream1[price>10] -> e2=Stream2[price>20]
             -> e3=Stream3[price>30] -> not Stream4[price>40] for 1 sec
        select e1.symbol as symbol1, e2.symbol as symbol2,
               e3.symbol as symbol3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 15.6, 100], 1000),
         ("Stream2", ["IBM", 28.7, 100], 1100),
         ("Stream3", ["GOOGLE", 35.7, 100], 1200)],
        [("WSO2", "IBM", "GOOGLE")], advance_to=2500)


def test_absent_20_trailing_after_three_suppressed():
    pq(S1234 + Q + """
        from e1=Stream1[price>10] -> e2=Stream2[price>20]
             -> e3=Stream3[price>30] -> not Stream4[price>40] for 1 sec
        select e1.symbol as symbol1, e2.symbol as symbol2,
               e3.symbol as symbol3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 15.6, 100], 1000),
         ("Stream2", ["IBM", 28.7, 100], 1100),
         ("Stream3", ["GOOGLE", 35.7, 100], 1200),
         ("Stream4", ["ORACLE", 44.7, 100], 1300)],
        [], advance_to=2500)


def test_absent_21_middle_then_fourth():
    pq(S1234 + Q + """
        from e1=Stream1[price>10] -> e2=Stream2[price>20]
             -> not Stream3[price>30] for 1 sec -> e4=Stream4[price>40]
        select e1.symbol as symbol1, e2.symbol as symbol2,
               e4.symbol as symbol4
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 15.6, 100], 1000),
         ("Stream2", ["IBM", 28.7, 100], 1100), ADV(2300),
         ("Stream4", ["ORACLE", 44.7, 100], 2350)],
        [("WSO2", "IBM", "ORACLE")], advance_to=3000)


def test_absent_22_middle_poisoned_then_fourth():
    pq(S1234 + Q + """
        from e1=Stream1[price>10] -> e2=Stream2[price>20]
             -> not Stream3[price>30] for 1 sec -> e4=Stream4[price>40]
        select e1.symbol as symbol1, e2.symbol as symbol2,
               e4.symbol as symbol4
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 15.6, 100], 1000),
         ("Stream2", ["IBM", 28.7, 100], 1100),
         ("Stream3", ["GOOGLE", 38.7, 100], 1200), ADV(2400),
         ("Stream4", ["ORACLE", 44.7, 100], 2450)],
        [], advance_to=3000)


def test_absent_23_leading_not_confirmed_chain_fails():
    pq(S1234 + Q + """
        from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20]
             -> e3=Stream3[price>30] -> e4=Stream4[price>40]
        select e2.symbol as symbol2, e3.symbol as symbol3,
               e4.symbol as symbol4
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 15.6, 100], 100),
         ("Stream2", ["IBM", 28.7, 100], 200),
         ("Stream3", ["GOOGLE", 38.7, 100], 300),
         ("Stream4", ["ORACLE", 44.7, 100], 400)],
        [], advance_to=2000)


def test_absent_24_two_absents():
    pq(S1234 + Q + """
        from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20]
             -> not Stream3[price>30] for 1 sec -> e4=Stream4[price>40]
        select e2.symbol as symbol2, e4.symbol as symbol4
        insert into OutputStream;""",
        [ADV(1200), ("Stream2", ["IBM", 28.7, 100], 1250), ADV(2400),
         ("Stream4", ["ORACLE", 44.7, 100], 2450)],
        [("IBM", "ORACLE")], advance_to=3500)
