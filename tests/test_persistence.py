"""Persistence tests (reference model: managment/PersistenceTestCase and
IncrementalPersistenceTestCase — persist → new runtime → restore → state
continues)."""
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.snapshot import (FileSystemPersistenceStore,
                                      InMemoryPersistenceStore)

APP = """
define stream S (symbol string, price float);
from S select symbol, sum(price) as total group by symbol insert into Out;
"""


def _fresh(store):
    m = SiddhiManager()
    m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime(APP)
    got = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: got.extend(e.data for e in evs)))
    rt.start()
    return m, rt, got


def test_full_persist_restore_roundtrip():
    store = InMemoryPersistenceStore()
    m, rt, _ = _fresh(store)
    rt.get_input_handler("S").send(["IBM", 10.0])
    rt.get_input_handler("S").send(["IBM", 15.0])
    rev = rt.persist()
    assert rev.endswith("_full")
    rt.shutdown()

    m2, rt2, got = _fresh(store)
    rt2.restore_last_revision()
    rt2.get_input_handler("S").send(["IBM", 5.0])
    rt2.shutdown()
    assert got == [["IBM", pytest.approx(30.0)]]


def test_incremental_chain_restore(tmp_path):
    store = FileSystemPersistenceStore(str(tmp_path))
    m, rt, _ = _fresh(store)
    h = rt.get_input_handler("S")
    h.send(["IBM", 10.0])
    base = rt.persist()                      # full base
    h.send(["IBM", 5.0])
    inc1 = rt.persist(incremental=True)
    assert inc1.endswith("_inc")
    h.send(["WSO2", 7.0])
    inc2 = rt.persist(incremental=True)
    rt.shutdown()

    m2, rt2, got = _fresh(store)
    rt2.restore_last_revision()              # base + inc1 + inc2 replay
    rt2.get_input_handler("S").send(["IBM", 1.0])
    rt2.get_input_handler("S").send(["WSO2", 1.0])
    rt2.shutdown()
    assert got == [["IBM", pytest.approx(16.0)],
                   ["WSO2", pytest.approx(8.0)]]


def test_incremental_skips_unchanged_elements():
    store = InMemoryPersistenceStore()
    m, rt, _ = _fresh(store)
    rt.get_input_handler("S").send(["IBM", 10.0])
    rt.persist()
    rev = rt.persist(incremental=True)       # nothing changed since full
    import pickle
    payload = pickle.loads(store.load(rt.name, rev))
    assert payload["__incremental__"] is True
    assert payload["state"] == {}
    rt.shutdown()
