"""Round-4 NFA algebra: mid-chain `every` (clone forking), leading min-0
kleene (epsilon start), absent-in-sequence — randomized conformance vs
the host oracle, including fork floods that stress slot allocation and
grow-and-replay.  (Reference semantics: StateInputStreamParser.java:272-
273 every-state clones; CountPreStateProcessor min-0 epsilon;
AbsentStreamPreStateProcessor in SEQUENCE chains.)"""
import numpy as np
import pytest

from siddhi_tpu import QueryCallback, SiddhiManager


def run(app, rows, engine=None, expect_backend=None):
    m = SiddhiManager()
    pre = "@app:playback " + (f"@app:engine('{engine}') " if engine else "")
    rt = m.create_siddhi_app_runtime(pre + app)
    got = []
    rt.add_callback("q", QueryCallback(
        lambda ts, cur, exp: got.extend(
            (ts, tuple(e.data)) for e in (cur or []))))
    rt.start()
    h = rt.get_input_handler("A")
    for row, ts in rows:
        h.send(row, timestamp=ts)
    backend = rt.query_runtimes["q"].backend
    if expect_backend:
        assert backend == expect_backend, rt.query_runtimes["q"].backend_reason
    rt.shutdown()
    return got


def parity(app, rows):
    dev = run(app, rows, expect_backend="device")
    host = run(app, rows, engine="host", expect_backend="host")
    assert dev == host, f"device {dev[:6]}... vs host {host[:6]}..."
    return dev


A = "define stream A (v float, w float);\n"


def gen(seed, n=80, vmax=10.0, step=200):
    rng = np.random.default_rng(seed)
    ts = 1_000_000
    rows = []
    for _ in range(n):
        ts += int(rng.integers(1, step))
        rows.append(([float(np.float32(rng.uniform(0, vmax))),
                      float(np.float32(rng.uniform(0, vmax)))], ts))
    return rows


# ------------------------------------------------------------ mid-chain every

def test_mid_every_basic_fork():
    app = A + """@info(name='q')
    from e1=A[v < 1.0] -> every e2=A[v > 5.0] -> e3=A[v > 8.0]
    select e1.v as a, e2.v as b, e3.v as c insert into Out;"""
    out = parity(app, gen(1, n=60))
    assert out        # the shape must actually produce matches


@pytest.mark.parametrize("seed", [2, 3, 4])
def test_mid_every_fork_flood(seed):
    """Every qualifying event forks a clone: dozens of live partials per
    lane force slot-ring growth through grow-and-replay."""
    app = A + """@info(name='q')
    from e1=A[v < 2.0] -> every e2=A[v > 2.0] -> e3=A[v > 9.0]
    select e1.v as a, e2.v as b, e3.v as c insert into Out;"""
    out = parity(app, gen(seed, n=120))
    assert out


def test_mid_every_with_within():
    app = A + """@info(name='q')
    from e1=A[v < 2.0] -> every e2=A[v > 4.0] -> e3=A[v > 8.0]
    within 3 sec
    select e1.v as a, e2.v as b, e3.v as c insert into Out;"""
    parity(app, gen(5, n=100, step=800))


def test_mid_every_logical_group():
    app = A + """@info(name='q')
    from e1=A[v < 2.0] -> every (e2=A[v > 4.0] and e3=A[w > 4.0])
        -> e4=A[v > 8.0]
    select e1.v as a, e2.v as b, e4.v as c insert into Out;"""
    parity(app, gen(6, n=100))


def test_mid_every_group_of_two():
    app = A + """@info(name='q')
    from e1=A[v < 2.0] -> every (e2=A[v > 3.0] -> e3=A[w > 3.0])
        -> e4=A[v > 9.0]
    select e1.v as a, e2.v as b, e3.w as c, e4.v as d insert into Out;"""
    parity(app, gen(7, n=100))


def test_leading_and_mid_every():
    app = A + """@info(name='q')
    from every e1=A[v < 2.0] -> every e2=A[v > 6.0] -> e3=A[v > 9.0]
    select e1.v as a, e2.v as b, e3.v as c insert into Out;"""
    parity(app, gen(8, n=90))


# ------------------------------------------------------------ leading min-0

def test_leading_min0_pattern_every():
    # every-leading-count shares one accumulator chain (arm_once — the
    # reference's shared StateEvent), so matches are sparse; parity with
    # the oracle is the contract
    app = A + """@info(name='q')
    from every e1=A[v < 3.0]<0:3> -> e2=A[v > 7.0]
    select e1[0].v as a, e2.v as b insert into Out;"""
    assert parity(app, gen(10, n=80))


def test_leading_min0_single_shot():
    app = A + """@info(name='q')
    from e1=A[v < 3.0]<0:2> -> e2=A[v > 7.0]
    select e1[last].v as a, e2.v as b insert into Out;"""
    parity(app, gen(11, n=40))


def test_leading_min0_empty_match():
    """The empty-kleene (epsilon) path: the successor can match with zero
    kleene occurrences and the capture decodes as None."""
    app = A + """@info(name='q')
    from e1=A[v < 3.0]<0:2> -> e2=A[v > 7.0]
    select e1[0].v as a, e2.v as b insert into Out;"""
    out = parity(app, [([8.1, 0.0], 1000), ([2.0, 0.0], 1400)])
    assert out == [(1000, (None, pytest.approx(8.1)))]


def test_leading_min0_sequence_nonevery_compiles():
    """Round 5: the SEQUENCE leading-kleene family compiles (r4 pin
    retired) — non-every min-0 is a single virgin that dies forever on
    its first unproductive event."""
    app = A + """@info(name='q')
    from e1=A[v < 3.0]<0:2>, e2=A[v > 5.0]
    select e1[0].v as a, e2.v as b insert into Out;"""
    parity(app, gen(12, n=40))


def test_leading_min0_every_sequence_compiles():
    """Round 5: every + SEQUENCE + leading min-0 on device — the virgin
    closer-block after a freeze and the same-event close+append seed
    (oracle every-clone) are modeled in-kernel."""
    app = A + """@info(name='q')
    from every e1=A[v < 3.0]<0:2>, e2=A[v > 5.0]
    select e1[0].v as a, e2.v as b insert into Out;"""
    parity(app, gen(12, n=60))


def test_leading_min0_within():
    app = A + """@info(name='q')
    from every e1=A[v < 3.0]<0:3> -> e2=A[v > 8.0] within 2 sec
    select e1[0].v as a, e2.v as b insert into Out;"""
    parity(app, gen(13, n=100, step=900))


# ------------------------------------------------------------ absent in seq

def test_absent_in_sequence():
    app = A + """@info(name='q')
    from every e1=A[v > 7.0], not A[v < 1.0] for 1 sec
    select e1.v as a insert into Out;"""
    parity(app, gen(20, n=70, step=600))


def test_absent_mid_sequence():
    app = A + """@info(name='q')
    from every e1=A[v > 7.0], not A[v < 1.0] for 1 sec, e3=A[v > 5.0]
    select e1.v as a, e3.v as b insert into Out;"""
    parity(app, gen(21, n=70, step=600))


# ---------------------------------------------------------------- pins

def test_within_expiry_self_forward_dies_not_crashes():
    """ADVICE r3 pin: when a within-expired partial's every-group head is
    the expiring unit ITSELF (`A -> every B within t`), the reference
    would re-arm into the pending list it is iterating and throw
    ConcurrentModificationException — broken upstream.  Our chosen
    semantics: the partial silently dies (firing stops `within` after the
    chain start), identically on host and device.  This test pins that
    choice so a future reference upgrade that fixes the CME is noticed."""
    app = A + """@info(name='q')
    from (e1=A[v < 2.0] -> every e2=A[v > 5.0]) within 1 sec
    select e1.v as a, e2.v as b insert into Out;"""
    rows = [([1.0, 0.0], 1000), ([6.0, 0.0], 1400), ([7.0, 0.0], 1900),
            # past within (2100 > 1000+1000): the re-arm must be dead,
            # not crash — and never fire again
            ([8.0, 0.0], 2400), ([9.0, 0.0], 2900)]
    dev = run(app, rows, expect_backend="device")
    host = run(app, rows, engine="host", expect_backend="host")
    expect = [(1400, (1.0, 6.0)), (1900, (1.0, 7.0))]
    assert [(t, (round(a, 2), round(b, 2))) for t, (a, b) in dev] == expect
    assert dev == host


def test_string_order_vs_constant_compiles():
    """Round 4: `s > 'A'` lowers onto a host-computed 0/1 lane the device
    condition reads — order-vs-constant string predicates compile."""
    app = """define stream A (s string, v float);
    @info(name='q')
    from every e1=A[s > 'bbb'] -> e2=A[v > e1.v and s <= 'bbb']
    select e1.s as a, e1.v as x, e2.s as b insert into Out;"""
    import numpy as np
    rng = np.random.default_rng(3)
    words = ["aaa", "abc", "bbb", "bcd", "ccc", "zzz"]
    rows = []
    ts = 1_000_000
    for _ in range(60):
        ts += int(rng.integers(1, 300))
        rows.append(([words[int(rng.integers(0, len(words)))],
                      float(np.float32(rng.uniform(0, 10)))], ts))
    m_rows = [([r[0], r[1]], t) for (r, t) in rows]
    from siddhi_tpu import QueryCallback, SiddhiManager

    def go(engine):
        m = SiddhiManager()
        pre = "@app:playback " + (f"@app:engine('{engine}') " if engine
                                  else "")
        rt = m.create_siddhi_app_runtime(pre + app)
        got = []
        rt.add_callback("q", QueryCallback(
            lambda _ts, cur, exp: got.extend(tuple(e.data)
                                             for e in (cur or []))))
        rt.start()
        h = rt.get_input_handler("A")
        for row, t in m_rows:
            h.send(row, timestamp=t)
        b = rt.query_runtimes["q"].backend
        rt.shutdown()
        return b, got
    bd, dev = go(None)
    bh, host = go("host")
    assert bd == "device" and bh == "host"
    assert dev == host and dev


def test_indexed_kleene_selects():
    """Round 4: e[k] / e[last-k] SELECT indexing rides dedicated capture
    banks (absolute banks written at chain length k+1; last-k banks shift
    behind the last bank) — parity incl. out-of-range None decode."""
    app = A + """@info(name='q')
    from every e1=A[v < 5.0]<2:6> -> e2=A[v > 8.0]
    select e1[0].v as a, e1[1].v as b, e1[3].v as c, e1[last].v as d,
           e1[last-1].v as e, e1[last-2].v as f, e2.v as g
    insert into Out;"""
    parity(app, gen(30, n=80))


def test_leading_kleene_sequence_device_parity():
    """Round 5 (r4 pin retired): min>=2 leading kleene in a SEQUENCE is a
    DEAD shape — the per-event barrier kills sub-min accumulators before
    CountPost can re-add them, so neither engine ever matches; the device
    compiles it to a never-arming chain (NfaSpec.dead_start)."""
    for head in ("every e1=A[v < 9.0]<2:6>", "e1=A[v < 9.0]<2:6>"):
        app = A + f"""@info(name='q')
        from {head}, e2=A[v > 8.0]
        select e1[1].v as b, e2.v as g insert into Out;"""
        for seed in (13, 29):
            rows = gen(seed, n=80)
            assert parity(app, rows) == []


def test_leading_kleene_sequence_overlapping_conditions():
    """Adversarial single-stream shapes where one event can both append
    and close — the reversed per-event unit order (closer first) and the
    every-clone seed must match the oracle."""
    for head in ("every e1=A[v < 6.0]*", "every e1=A[v < 6.0]+",
                 "every e1=A[v < 6.0]<0:1>", "e1=A[v < 6.0]?"):
        app = A + f"""@info(name='q')
        from {head}, e2=A[v > 4.0]
        select e1[0].v as a, e1[1].v as b, e2.v as g insert into Out;"""
        for seed in (13, 29):
            parity(app, gen(seed, n=60))
