"""Port of the reference pattern conformance suites
query/pattern/CountPatternTestCase.java (15 @Tests) and
query/pattern/WithinPatternTestCase.java (7 @Tests).
Sleep-based reference timings become explicit event timestamps.
"""
from ref_harness import run_query

S12 = """
define stream Stream1 (symbol string, price float, volume int);
define stream Stream2 (symbol string, price float, volume int);
"""
EV = "define stream EventStream (symbol string, price float, volume int);\n"
S1 = "define stream Stream1 (symbol string, price float, volume int);\n"
Q = "@info(name = 'query1') "

_CNT25 = S12 + Q + """
    from e1=Stream1[price>20] <2:5> -> e2=Stream2[price>20]
    select e1[0].price as price1_0, e1[1].price as price1_1,
           e1[2].price as price1_2, e1[3].price as price1_3,
           e2.price as price2
    insert into OutputStream;"""


def test_count_1_gap_in_run():
    run_query(_CNT25,
        [("Stream1", ["WSO2", 25.6, 100]), ("Stream1", ["GOOG", 47.6, 100]),
         ("Stream1", ["GOOG", 13.7, 100]), ("Stream1", ["GOOG", 47.8, 100]),
         ("Stream2", ["IBM", 45.7, 100]), ("Stream2", ["IBM", 55.7, 100])],
        [(25.6, 47.6, 47.8, None, 45.7)])


def test_count_2_closes_at_min():
    run_query(_CNT25,
        [("Stream1", ["WSO2", 25.6, 100]), ("Stream1", ["GOOG", 47.6, 100]),
         ("Stream1", ["GOOG", 13.7, 100]), ("Stream2", ["IBM", 45.7, 100]),
         ("Stream1", ["GOOG", 47.8, 100]), ("Stream2", ["IBM", 55.7, 100])],
        [(25.6, 47.6, None, None, 45.7)])


def test_count_3_min_reached_after_first_close_attempt():
    run_query(_CNT25,
        [("Stream1", ["WSO2", 25.6, 100]), ("Stream2", ["IBM", 45.7, 100]),
         ("Stream1", ["GOOG", 47.8, 100]), ("Stream2", ["IBM", 55.7, 100])],
        [(25.6, 47.8, None, None, 55.7)])


def test_count_4_below_min_no_match():
    run_query(_CNT25,
        [("Stream1", ["WSO2", 25.6, 100]), ("Stream2", ["IBM", 45.7, 100])],
        [])


def test_count_5_max_stops_absorbing():
    run_query(_CNT25,
        [("Stream1", ["WSO2", 25.6, 100]), ("Stream1", ["GOOG", 47.6, 100]),
         ("Stream1", ["GOOG", 23.7, 100]), ("Stream1", ["GOOG", 24.7, 100]),
         ("Stream1", ["GOOG", 25.7, 100]), ("Stream1", ["WSO2", 27.6, 100]),
         ("Stream2", ["IBM", 45.7, 100]), ("Stream1", ["GOOG", 47.8, 100]),
         ("Stream2", ["IBM", 55.7, 100])],
        [(25.6, 47.6, 23.7, 24.7, 45.7)])


def test_count_6_next_filter_on_indexed_capture():
    run_query(S12 + Q + """
        from e1=Stream1[price>20] <2:5> -> e2=Stream2[price>e1[1].price]
        select e1[0].price as price1_0, e1[1].price as price1_1,
               e2.price as price2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 25.6, 100]), ("Stream1", ["GOOG", 47.6, 100]),
         ("Stream2", ["IBM", 45.7, 100]), ("Stream2", ["IBM", 55.7, 100])],
        [(25.6, 47.6, 55.7)])


def test_count_7_zero_min_immediate():
    run_query(S12 + Q + """
        from e1=Stream1[price>20] <0:5> -> e2=Stream2[price>20]
        select e1[0].price as price1_0, e1[1].price as price1_1,
               e2.price as price2
        insert into OutputStream;""",
        [("Stream2", ["IBM", 45.7, 100])],
        [(None, None, 45.7)])


def test_count_8_zero_min_with_events():
    run_query(S12 + Q + """
        from e1=Stream1[price>20] <0:5> -> e2=Stream2[price>e1[0].price]
        select e1[0].price as price1_0, e1[1].price as price1_1,
               e2.price as price2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 25.6, 100]), ("Stream1", ["GOOG", 7.6, 100]),
         ("Stream2", ["IBM", 45.7, 100])],
        [(25.6, None, 45.7)])


def test_count_9_star_mid_chain():
    run_query(EV + Q + """
        from e1 = EventStream [price >= 50 and volume > 100]
             -> e2 = EventStream [price <= 40] <0:5>
             -> e3 = EventStream [volume <= 70]
        select e1.symbol as symbol1, e2[0].symbol as symbol2,
               e3.symbol as symbol3
        insert into StockQuote;""",
        [("EventStream", ["IBM", 75.6, 105]),
         ("EventStream", ["GOOG", 21.0, 81]),
         ("EventStream", ["WSO2", 176.6, 65])],
        [("IBM", "GOOG", "WSO2")], stream="StockQuote")


def test_count_10_max_only_first_closes():
    run_query(EV + Q + """
        from e1 = EventStream [price >= 50 and volume > 100]
             -> e2 = EventStream [price <= 40] <:5>
             -> e3 = EventStream [volume <= 70]
        select e1.symbol as symbol1, e2[0].symbol as symbol2,
               e3.symbol as symbol3
        insert into StockQuote;""",
        [("EventStream", ["IBM", 75.6, 105]),
         ("EventStream", ["GOOG", 21.0, 61]),
         ("EventStream", ["WSO2", 21.0, 61])],
        [("IBM", None, "GOOG")], stream="StockQuote")


def test_count_11_max_only_last_index():
    run_query(EV + Q + """
        from e1 = EventStream [price >= 50 and volume > 100]
             -> e2 = EventStream [price <= 40] <:5>
             -> e3 = EventStream [volume <= 70]
        select e1.symbol as symbol1, e2[last].symbol as symbol2,
               e3.symbol as symbol3
        insert into StockQuote;""",
        [("EventStream", ["IBM", 75.6, 105]),
         ("EventStream", ["GOOG", 21.0, 61]),
         ("EventStream", ["WSO2", 21.0, 61])],
        [("IBM", None, "GOOG")], stream="StockQuote")


def test_count_12_last_index_filled():
    run_query(EV + Q + """
        from e1 = EventStream [price >= 50 and volume > 100]
             -> e2 = EventStream [price <= 40] <:5>
             -> e3 = EventStream [volume <= 70]
        select e1.symbol as symbol1, e2[last].symbol as symbol2,
               e3.symbol as symbol3
        insert into StockQuote;""",
        [("EventStream", ["IBM", 75.6, 105]),
         ("EventStream", ["GOOG", 21.0, 91]),
         ("EventStream", ["FB", 21.0, 81]),
         ("EventStream", ["WSO2", 21.0, 61])],
        [("IBM", "FB", "WSO2")], stream="StockQuote")


def test_count_13_self_symbol_match_sliding():
    run_query(EV + Q + """
        from every e1 = EventStream
             -> e2 = EventStream [e1.symbol==e2.symbol]<4:6>
        select e1.volume as volume1, e2[0].volume as volume2,
               e2[1].volume as volume3, e2[2].volume as volume4,
               e2[3].volume as volume5, e2[4].volume as volume6,
               e2[5].volume as volume7
        insert into StockQuote;""",
        [("EventStream", ["IBM", 75.6, 100]),
         ("EventStream", ["IBM", 75.6, 200]),
         ("EventStream", ["IBM", 75.6, 300]),
         ("EventStream", ["GOOG", 21.0, 91]),
         ("EventStream", ["IBM", 75.6, 400]),
         ("EventStream", ["IBM", 75.6, 500]),
         ("EventStream", ["GOOG", 21.0, 91]),
         ("EventStream", ["IBM", 75.6, 600]),
         ("EventStream", ["IBM", 75.6, 700]),
         ("EventStream", ["IBM", 75.6, 800]),
         ("EventStream", ["GOOG", 21.0, 91]),
         ("EventStream", ["IBM", 75.6, 900])],
        [(100, 200, 300, 400, 500, None, None),
         (200, 300, 400, 500, 600, None, None),
         (300, 400, 500, 600, 700, None, None),
         (400, 500, 600, 700, 800, None, None),
         (500, 600, 700, 800, 900, None, None)], stream="StockQuote")


def test_count_14_zero_min_two_collected():
    run_query(S12 + Q + """
        from e1=Stream1[price>20] <0:5> -> e2=Stream2[price>e1[0].price]
        select e1[0].price as price1_0, e1[1].price as price1_1,
               e1[2].price as price1_2, e2.price as price2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 25.6, 100]), ("Stream1", ["WSO2", 23.6, 100]),
         ("Stream1", ["GOOG", 7.6, 100]), ("Stream2", ["IBM", 45.7, 100])],
        [(25.6, 23.6, None, 45.7)])


def test_count_15_exact_count_then_absent_and():
    run_query(S12 + Q + """
        from every e1=Stream1[price>20] -> e2=Stream1[price>20]<2>
             -> not Stream1[price>20] and e3=Stream2
        select e1.price as price1_0, e2[0].price as price2_0,
               e2[1].price as price2_1, e2[2].price as price2_2,
               e3.price as price2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 25.6, 100]), ("Stream1", ["WSO2", 23.6, 100]),
         ("Stream1", ["WSO2", 23.6, 100]), ("Stream1", ["GOOG", 27.6, 100]),
         ("Stream1", ["GOOG", 28.6, 100]), ("Stream2", ["IBM", 45.7, 100])],
        [(23.6, 27.6, 28.6, None, 45.7)])


# ---------------------------------------------- WithinPatternTestCase

def test_within_1_first_partial_expires():
    run_query(S12 + Q + """
        from every e1=Stream1[price>20] -> e2=Stream2[price>e1.price]
            within 1 sec
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100], 1000),
         ("Stream1", ["GOOG", 54.0, 100], 2500),
         ("Stream2", ["IBM", 55.7, 100], 2600)],
        [("GOOG", "IBM")])


def test_within_2_group_syntax():
    run_query(S12 + Q + """
        from (every e1=Stream1[price>20] -> e2=Stream2[price>e1.price])
            within 1 sec
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100], 1000),
         ("Stream1", ["GOOG", 54.0, 100], 2500),
         ("Stream2", ["IBM", 55.7, 100], 2600)],
        [("GOOG", "IBM")])


def test_within_3_nested_group():
    run_query(S12 + Q + """
        from (every (e1=Stream1[price>20] -> e3=Stream1[price>20])
              -> e2=Stream2[price>e1.price]) within 2 sec
        select e1.price as price1, e3.price as price3, e2.price as price2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100], 1000),
         ("Stream1", ["GOOG", 54.0, 100], 1600),
         ("Stream1", ["WSO2", 53.6, 100], 2200),
         ("Stream1", ["GOOG", 53.0, 100], 3100),
         ("Stream2", ["IBM", 57.7, 100], 3700)],
        [(53.6, 53.0, 57.7)])


def test_within_4_expired_restart():
    run_query(S1 + Q + """
        from every (e1=Stream1 -> e2=Stream1[symbol == e1.symbol])
            within 5 sec
        select e1.symbol as symbol1, e1.volume as volume1,
               e2.symbol as symbol2, e2.volume as volume2
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100], 1000),
         ("Stream1", ["WSO2", 55.7, 150], 7500),
         ("Stream1", ["WSO2", 58.7, 200], 8100),
         ("Stream1", ["WSO2", 58.7, 250], 8200)],
        [("WSO2", 150, "WSO2", 200)])


def test_within_5_three_state_group():
    run_query(S1 + Q + """
        from every (e1=Stream1 -> e2=Stream1[symbol == e1.symbol]
             -> e3=Stream1[symbol == e2.symbol]) within 5 sec
        select e1.symbol as symbol1, e1.volume as volume1,
               e2.symbol as symbol2, e2.volume as volume2,
               e3.symbol as symbol3, e3.volume as volume3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100], 1000),
         ("Stream1", ["WSO2", 56.6, 150], 1100),
         ("Stream1", ["WSO2", 57.7, 200], 7500),
         ("Stream1", ["WSO2", 58.7, 250], 8100),
         ("Stream1", ["WSO2", 57.7, 300], 8200),
         ("Stream1", ["WSO2", 59.7, 350], 8300)],
        [("WSO2", 200, "WSO2", 250, "WSO2", 300)])


def test_within_6_two_rounds():
    run_query(S1 + Q + """
        from every (e1=Stream1 -> e2=Stream1[symbol == e1.symbol]
             -> e3=Stream1[symbol == e2.symbol]) within 5 sec
        select e1.symbol as symbol1, e1.volume as volume1,
               e2.symbol as symbol2, e2.volume as volume2,
               e3.symbol as symbol3, e3.volume as volume3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100], 1000),
         ("Stream1", ["WSO2", 55.7, 150], 1100),
         ("Stream1", ["WSO2", 58.7, 200], 1200),
         ("Stream1", ["WSO2", 58.7, 210], 1300),
         ("Stream1", ["WSO2", 58.7, 250], 1900),
         ("Stream1", ["WSO2", 58.7, 260], 2000),
         ("Stream1", ["WSO2", 58.7, 270], 2100)],
        [("WSO2", 100, "WSO2", 150, "WSO2", 200),
         ("WSO2", 210, "WSO2", 250, "WSO2", 260)])


def test_within_7_expiry_then_chain():
    run_query(S1 + Q + """
        from every (e1=Stream1 -> e2=Stream1[symbol == e1.symbol]
             -> e3=Stream1[symbol == e2.symbol]) within 5 sec
        select e1.symbol as symbol1, e1.volume as volume1,
               e2.symbol as symbol2, e2.volume as volume2,
               e3.symbol as symbol3, e3.volume as volume3
        insert into OutputStream;""",
        [("Stream1", ["WSO2", 55.6, 100], 1000),
         ("Stream1", ["WSO2", 56.6, 150], 7500),
         ("Stream1", ["WSO2", 57.7, 200], 7600),
         ("Stream1", ["WSO2", 58.7, 250], 8200)],
        [("WSO2", 150, "WSO2", 200, "WSO2", 250)])
