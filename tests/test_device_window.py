"""Device window path (plan/dwin_compiler + ops/dwin): randomized
multi-chunk parity against the host window processors, ring growth, and
snapshot round-trips.  The per-kind emission algebra itself is pinned by
tests/test_ref_windows.py; this suite stresses chunking boundaries and
state mechanics the conformance vectors cannot reach."""
import zlib

import numpy as np
import pytest

from siddhi_tpu import (InMemoryPersistenceStore, QueryCallback,
                        SiddhiManager)

CSE = "define stream cse (symbol string, price float, volume long);\n"

KIND_QUERIES = {
    "length": "#window.length(5)",
    "lengthBatch": "#window.lengthBatch(4)",
    "time": "#window.time(1 sec)",
    "timeBatch": "#window.timeBatch(1 sec)",
    "externalTime": "#window.externalTime(volume, 500)",
    "externalTimeBatch": "#window.externalTimeBatch(volume, 500)",
    "timeLength": "#window.timeLength(1 sec, 4)",
    "delay": "#window.delay(300)",
    "batch": "#window.batch()",
    # round 5: device sort (multi-key incl. LONG hi/lo lex + desc) and
    # per-key gap sessions
    "sort": "#window.sort(3, price)",
    "sort_desc_multi": "#window.sort(4, volume, 'desc', price)",
    "session": "#window.session(700)",
    "session_keyed": "#window.session(700, symbol)",
}


def _run(app, chunks, engine=None):
    m = SiddhiManager()
    m.set_persistence_store(InMemoryPersistenceStore())
    pre = "@app:playback " + (f"@app:engine('{engine}') " if engine else "")
    rt = m.create_siddhi_app_runtime(pre + app)
    log = []
    rt.add_callback("q", QueryCallback(
        lambda ts, cur, exp: log.append(
            (ts, [(e.timestamp, tuple(e.data)) for e in (cur or [])],
             [(e.timestamp, tuple(e.data)) for e in (exp or [])]))))
    rt.start()
    h = rt.get_input_handler("cse")
    for cols, ts in chunks:
        h.send_batch(cols, timestamps=ts)
    backend = rt.query_runtimes["q"].backend
    rt.shutdown()
    return backend, log


def _random_chunks(seed, n_events=60):
    rng = np.random.default_rng(seed)
    ts, t = [], 1_000_000
    for _ in range(n_events):
        t += int(rng.integers(1, 400))
        ts.append(t)
    ts = np.asarray(ts, np.int64)
    syms = rng.choice(np.asarray(["A", "B", "C"], object), n_events)
    price = rng.uniform(0, 10, n_events).astype(np.float32)
    vol = ts - 999_000          # monotone (externalTime attr)
    chunks, i = [], 0
    while i < n_events:
        k = int(rng.integers(1, 7))
        sl = slice(i, min(i + k, n_events))
        chunks.append(({"symbol": syms[sl], "price": price[sl],
                        "volume": vol[sl]}, ts[sl]))
        i += k
    return chunks


@pytest.mark.parametrize("kind", sorted(KIND_QUERIES))
def test_randomized_chunked_parity(kind):
    app = CSE + f"@info(name='q') from cse{KIND_QUERIES[kind]} " \
        "select symbol, price, volume insert all events into out;"
    chunks = _random_chunks(seed=zlib.crc32(kind.encode()))
    bd, dev = _run(app, chunks)
    bh, host = _run(app, chunks, engine="host")
    assert bd == "device" and bh == "host"
    assert dev == host


def test_session_timer_dispatch_bounded():
    """Regression: the session gap timer must not re-arm at an instant
    <= the one it just processed.  A min-live re-arm at exactly
    min+gap — where the kernel evicts nothing — made playback
    advance_to() fire the same virtual ms forever (300k+ device
    dispatches on this 60-event stream before the fix).  Bound the
    MEASURED dispatch count, not wall time."""
    from siddhi_tpu.core.profiling import profiler
    app = CSE + f"@info(name='q') from cse{KIND_QUERIES['session']} " \
        "select symbol, price, volume insert all events into out;"
    chunks = _random_chunks(seed=zlib.crc32(b"session"))
    prof = profiler()
    was = prof.enabled
    prof.enable()
    try:
        d0 = prof.total_dispatches()
        bd, _ = _run(app, chunks)
        n_steps = prof.total_dispatches() - d0
    finally:
        if not was:
            prof.disable()
    assert bd == "device"
    # 18 chunks + one timer per chunk-end+gap instant, plus compile-time
    # warmup steps: orders of magnitude below the runaway regime
    assert 0 < n_steps < 500, n_steps


def test_ring_growth_preserves_contents():
    """Start capacity is 16; a 200-deep length window must grow the ring
    slabs without losing or reordering entries."""
    app = CSE + "@info(name='q') from cse#window.length(200) " \
        "select symbol, price, volume insert all events into out;"
    chunks = _random_chunks(seed=7, n_events=300)
    bd, dev = _run(app, chunks)
    _, host = _run(app, chunks, engine="host")
    assert bd == "device" and dev == host


def test_snapshot_roundtrip_device_ring():
    app = CSE + "@info(name='q') from cse#window.lengthBatch(4) " \
        "select symbol, sum(price) as t insert all events into out;"
    chunks = _random_chunks(seed=11, n_events=30)
    mid = len(chunks) // 2

    m = SiddhiManager()
    m.set_persistence_store(InMemoryPersistenceStore())
    rt = m.create_siddhi_app_runtime("@app:playback " + app)
    log = []
    rt.add_callback("q", QueryCallback(
        lambda ts, cur, exp: log.append(
            (ts, [(e.timestamp, tuple(e.data)) for e in (cur or [])],
             [(e.timestamp, tuple(e.data)) for e in (exp or [])]))))
    rt.start()
    h = rt.get_input_handler("cse")
    for cols, ts in chunks[:mid]:
        h.send_batch(cols, timestamps=ts)
    rev = rt.persist()
    rt.shutdown()

    rt2 = m.create_siddhi_app_runtime("@app:playback " + app)
    log2 = []
    rt2.add_callback("q", QueryCallback(
        lambda ts, cur, exp: log2.append(
            (ts, [(e.timestamp, tuple(e.data)) for e in (cur or [])],
             [(e.timestamp, tuple(e.data)) for e in (exp or [])]))))
    rt2.start()
    rt2.restore_revision(rev)
    h2 = rt2.get_input_handler("cse")
    for cols, ts in chunks[mid:]:
        h2.send_batch(cols, timestamps=ts)
    rt2.shutdown()

    # a fresh run over the whole stream defines the expected tail
    _, full = _run(app, chunks)
    assert log2 == full[len(log):]
