"""Observability layer tests: histogram metrics, reporter lifecycle,
kernel profiling, span tracing, Prometheus exposition, and the
no-overhead-when-disabled contract.

(reference shapes: managment/StatisticsTestCase — here extended to the
full observability PR surface: core/statistics.py, core/profiling.py,
core/tracing.py, service/rest.py /metrics + /stats.)"""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.profiling import profiler
from siddhi_tpu.core.statistics import (BufferedEventsTracker, Counter,
                                        Gauge, Histogram, LatencyTracker,
                                        StatisticsManager, ThroughputTracker,
                                        prometheus_text)
from siddhi_tpu.core.tracing import tracer


@pytest.fixture(autouse=True)
def _clean_globals():
    """The profiler and tracer are process-global; isolate each test."""
    profiler().disable()
    profiler().reset()
    tracer().disable()
    tracer().clear()
    yield
    profiler().disable()
    profiler().reset()
    tracer().disable()
    tracer().clear()


# ---------------------------------------------------------------- histogram

def test_histogram_percentiles_match_numpy():
    """Log-bucketed percentiles within the bucket resolution (~6%) of
    numpy's exact answer on a known heavy-tailed distribution."""
    rng = np.random.default_rng(42)
    vals = rng.lognormal(mean=10.0, sigma=1.5, size=20_000).astype(np.int64)
    h = Histogram()
    for v in vals:
        h.record(int(v))
    for q in (50, 95, 99):
        est = h.percentile(q)
        ref = float(np.percentile(vals, q))
        assert abs(est - ref) / ref < 0.07, (q, est, ref)
    assert h.count == len(vals)
    assert h.max == int(vals.max())
    assert abs(h.mean() - vals.mean()) / vals.mean() < 0.01


def test_histogram_small_values_exact():
    h = Histogram()
    for v in (0, 1, 2, 5, 31):
        h.record(v)
    assert h.count == 5 and h.min == 0 and h.max == 31
    # values < 32 land in exact unit buckets
    assert h.percentile(1) == 0.0
    assert [b for b, _ in h.buckets()] == [1, 2, 3, 6, 32]


# ---------------------------------------------------------------- trackers

def test_latency_tracker_nests_and_keeps_zero_marks():
    t = LatencyTracker("t")
    t.mark_in()          # outer
    t.mark_in()          # nested (query feeding a query on one thread)
    t.mark_out()
    t.mark_out()
    assert t.count == 2
    # unmatched mark_out is a no-op, not a corruption
    t.mark_out()
    assert t.count == 2
    # a 0-ns duration is recorded (the old `if self._mark:` dropped it)
    t2 = LatencyTracker("t2")
    t2._tls.marks = [time.perf_counter_ns()]
    t2.mark_out()
    assert t2.count == 1


def test_latency_tracker_threads_do_not_corrupt_each_other():
    t = LatencyTracker("t")
    errs = []

    def worker():
        try:
            for _ in range(200):
                t.mark_in()
                t.mark_out()
        except Exception as e:  # noqa: BLE001
            errs.append(e)
    threads = [threading.Thread(target=worker) for _ in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errs
    assert t.hist.count == t.count


def test_throughput_windowed_rate_resets_between_reads():
    t = ThroughputTracker("t")
    t.event_in(100)
    assert t.windowed_rate() > 0
    time.sleep(0.01)
    # no new events since the snapshot → windowed rate is 0, lifetime isn't
    assert t.windowed_rate() == 0.0
    assert t.rate() > 0


def test_counter_and_gauge_labels():
    c = Counter("c")
    c.inc(3, stream="S")
    c.inc(2, stream="S")
    c.inc(7, stream="T")
    assert c.value(stream="S") == 5 and c.value(stream="T") == 7
    g = Gauge("g")
    g.set(1.5, host="a")
    g.set_fn(lambda: 2.5, host="b")
    assert g.value(host="a") == 1.5 and g.value(host="b") == 2.5


def test_buffered_tracker_sums_suppliers():
    b = BufferedEventsTracker("b")
    b.register(lambda: 3)
    b.register(lambda: 4)
    assert b.buffered == 7


# ------------------------------------------------------------- reporter

def test_reporter_lifecycle_joins_thread_and_never_doubles():
    sm = StatisticsManager("app", reporter="json", interval_s=1)
    sm.start_reporting()
    t1 = sm._thread
    assert t1 is not None and t1.is_alive()
    sm.start_reporting()                 # idempotent: same thread
    assert sm._thread is t1
    sm.stop_reporting()
    assert sm._thread is None
    assert not t1.is_alive()             # joined, not abandoned
    sm.start_reporting()                 # restart after stop works
    t2 = sm._thread
    assert t2 is not None and t2.is_alive() and t2 is not t1
    sm.stop_reporting()
    assert not t2.is_alive()


def test_statistics_annotation_parsing_and_snapshot_shape():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:statistics(reporter='json', interval='1')
        define stream S (v int);
        @info(name='q') from S[v > 0] select v insert into Out;
    """)
    sm = rt.app_ctx.statistics_manager
    assert sm.reporter == "json" and sm.interval_s == 1
    assert rt.app_ctx.stats_enabled
    rt.start()
    assert sm._thread is not None and sm._thread.is_alive()
    h = rt.get_input_handler("S")
    for i in range(5):
        h.send([i + 1])
    snap = rt.statistics
    rt.shutdown()
    assert sm._thread is None            # stop_reporting joined it
    # snapshot shape: windowed rates + histogram percentiles + kernels
    assert set(snap) >= {"throughput", "latency_ms", "memory_bytes",
                         "buffered", "counters", "gauges", "kernels"}
    (tkey, tstats), = [(k, v) for k, v in snap["throughput"].items()
                       if k.endswith(".Streams.S")]
    assert tkey.startswith("io.siddhi.SiddhiApps.")
    assert tstats["count"] == 5
    assert "rate_windowed_eps" in tstats
    lat = next(iter(snap["latency_ms"].values()))
    assert set(lat) >= {"avg_ms", "count", "p50_ms", "p95_ms", "p99_ms",
                        "max_ms"}
    assert lat["count"] == 5


def test_stats_disabled_registers_zero_trackers():
    """No @app:statistics → no trackers, no profiler enablement: the hot
    path carries zero observability overhead."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (v int);
        @info(name='q') from S[v > 0] select v insert into Out;
    """)
    rt.start()
    h = rt.get_input_handler("S")
    for i in range(3):
        h.send([i + 1])
    sm = rt.app_ctx.statistics_manager
    rt.shutdown()
    assert sm.throughput == {} and sm.latency == {} and sm.buffered == {}
    assert not profiler().enabled
    assert all(j.throughput_tracker is None
               for j in rt.junctions.values())


# ------------------------------------------------------------- profiling

def test_kernel_profiler_counts_calls_and_compiles():
    import jax
    import jax.numpy as jnp
    from siddhi_tpu.core.profiling import wrap_kernel
    profiler().enable()
    fn = wrap_kernel("test.kernel", jax.jit(lambda x: x + 1),
                     batch_of=lambda x: int(x.size))
    fn(jnp.zeros(8))
    fn(jnp.zeros(8))
    fn(jnp.zeros(16))        # retrace: new shape
    st = profiler().stats("test.kernel")
    assert st.calls == 3
    assert st.compile_count == 2
    assert st.batch_events == 32 and st.max_batch == 16
    snap = profiler().snapshot()["test.kernel"]
    assert snap["compile_count"] == 2 and snap["calls"] == 3


def test_kernel_profiler_disabled_is_passthrough():
    import jax
    import jax.numpy as jnp
    from siddhi_tpu.core.profiling import wrap_kernel
    fn = wrap_kernel("test.off", jax.jit(lambda x: x * 2))
    out = fn(jnp.ones(4))
    assert float(out.sum()) == 8.0
    assert profiler().snapshot()["test.off"]["calls"] == 0


def test_engine_device_path_profiles_kernels():
    """@app:statistics turns kernel profiling on; the device filter
    program shows up with calls + a compile count."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:statistics(reporter='console', interval='300')
        define stream S (v float);
        @info(name='q') from S[v > 1.0] select v insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    h = rt.get_input_handler("S")
    h.send_batch({"v": np.asarray([0.5, 2.0, 3.0], np.float32)})
    rt.flush()
    snap = rt.statistics["kernels"]
    rt.shutdown()
    assert len(got) == 2
    assert "filter.program" in snap, snap
    k = snap["filter.program"]
    assert k["calls"] >= 1 and k["compile_count"] >= 1


# --------------------------------------------------------------- tracing

def test_dump_trace_is_valid_chrome_trace_json(tmp_path):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:statistics(reporter='console', interval='300', tracing='true')
        define stream S (v int);
        @info(name='q') from S[v > 0] select v insert into Out;
    """)
    rt.start()
    h = rt.get_input_handler("S")
    for i in range(3):
        h.send([i + 1])
    rt.flush()
    path = str(tmp_path / "trace.json")
    rt.dump_trace(path)
    rt.shutdown()
    doc = json.load(open(path))
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and evs
    names = {e["name"] for e in evs}
    assert "ingest.chunk" in names
    for e in evs:                         # perfetto-required fields
        assert {"name", "ph", "ts", "pid", "tid"} <= set(e)
        if e["ph"] == "X":
            assert isinstance(e["dur"], float) and e["dur"] >= 0


def test_tracing_disabled_records_nothing():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (v int);
        @info(name='q') from S select v insert into Out;
    """)
    rt.start()
    rt.get_input_handler("S").send([1])
    rt.shutdown()
    assert tracer().to_dict()["traceEvents"] == []


# ----------------------------------------------------------- async depth

def test_async_junction_queue_depth_wired_to_buffered_tracker():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:statistics(reporter='console', interval='300')
        @Async(buffer.size='64')
        define stream S (v int);
        @info(name='q') from S[v > 0] select v insert into Out;
    """)
    rt.start()
    sm = rt.app_ctx.statistics_manager
    (bkey, bt), = sm.buffered.items()
    assert bkey.endswith(".Streams.S")
    h = rt.get_input_handler("S")
    for i in range(10):
        h.send([i + 1])
    assert bt.buffered >= 0               # live supplier, not the dead field
    rt.flush()
    assert bt.buffered == 0               # drained
    snap = rt.statistics
    rt.shutdown()
    assert bkey in snap["buffered"]


# ------------------------------------------------------------ exposition

def _scrape(url):
    with urllib.request.urlopen(url) as r:
        return r.headers.get("Content-Type", ""), r.read().decode()


def test_metrics_endpoint_serves_prometheus_text():
    from siddhi_tpu.service import SiddhiService
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        req = urllib.request.Request(
            f"{base}/siddhi/artifact/deploy", data=b"""
            @app:name('obsapp')
            @app:statistics(reporter='console', interval='300')
            define stream S (v float);
            @info(name='q') from S[v > 1.0] select v insert into Out;
            """, method="POST")
        urllib.request.urlopen(req).read()
        rt = svc.manager.get_siddhi_app_runtime("obsapp")
        h = rt.get_input_handler("S")
        for _ in range(4):
            h.send_batch({"v": np.asarray([0.5, 2.0, 3.0], np.float32)})
        rt.flush()
        ctype, text = _scrape(f"{base}/metrics")
        assert "text/plain" in ctype
        lines = [ln for ln in text.splitlines() if ln]
        # valid exposition: every sample line is `name{labels} value`
        for ln in lines:
            if ln.startswith("#"):
                continue
            metric, _, value = ln.rpartition(" ")
            assert metric and (value == "+Inf" or float(value) is not None)
        assert any(ln.startswith("siddhi_latency_seconds_bucket{")
                   for ln in lines)
        assert any(ln.startswith("siddhi_latency_seconds_sum{")
                   for ln in lines)
        assert any(ln.startswith("siddhi_latency_seconds_count{")
                   for ln in lines)
        assert any(ln.startswith("siddhi_throughput_events_total{")
                   for ln in lines)
        # per-kernel gauges from the device filter program
        assert any("siddhi_kernel_compile_count{" in ln for ln in lines)
        assert any("siddhi_kernel_device_time_seconds_total{" in ln
                   for ln in lines)
        # histogram bucket invariants: cumulative, count == +Inf bucket
        buckets = [ln for ln in lines
                   if ln.startswith("siddhi_latency_seconds_bucket{")
                   and 'name="q"' in ln]
        counts = [int(ln.rpartition(" ")[2]) for ln in buckets]
        assert counts == sorted(counts)
        count_line = next(ln for ln in lines if ln.startswith(
            "siddhi_latency_seconds_count{") and 'name="q"' in ln)
        assert counts[-1] == int(count_line.rpartition(" ")[2])

        ctype, stats = _scrape(f"{base}/stats")
        doc = json.loads(stats)
        assert "obsapp" in doc["apps"]
        assert "filter.program" in doc["kernels"]
    finally:
        svc.stop()


def test_prometheus_text_escapes_label_values():
    sm = StatisticsManager('we"ird\napp')
    sm.throughput_tracker("Streams", "S").event_in(2)
    txt = prometheus_text([sm])
    assert '\\"' in txt and "\\n" in txt


# ------------------------------------------------------------ multihost

def test_multihost_global_statistics_single_process():
    from siddhi_tpu.parallel.multihost import MultiHostAppRuntime
    rt = MultiHostAppRuntime("""
        @app:statistics(reporter='console', interval='300')
        define stream S (sym string, v float);
        partition with (sym of S) begin
        @info(name='q') from S[v > 0.0] select sym, v insert into Out;
        end;
    """)
    rt.start()
    n = rt.send_batch("S", {"sym": np.asarray(["a", "b"], object),
                            "v": np.asarray([1.0, 2.0], np.float32)},
                      np.asarray([1000, 1001], np.int64))
    rt.flush()
    stats = rt.global_statistics()
    rt.shutdown()
    assert n == 2
    skey = next(k for k in stats if ".Streams.S.count" in k)
    assert stats[skey] == 2
