"""bench.py is part of the tested surface (round 6).

BENCH_r05 was a raw rc=1 `RuntimeError: Unable to initialize backend`
stack trace — the bench script itself had no tier-1 coverage, so a
bench-only regression could sit undetected until the next device round.
Two subprocess checks close that:

  * `bench.py --smoke` (CPU-pinned, one tiny block per phase, seconds)
    must exit 0 and emit valid JSON with the per-phase fields, including
    the NFA B-sweep with equal match counts across B;
  * with an unreachable backend, bench.py must emit a structured
    `{"skipped": "backend unavailable", ...}` line and exit 0 instead of
    crashing.
"""
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(ROOT, "bench.py")


def _run(args, env_extra=None, timeout=560):
    env = dict(os.environ)
    env.update(env_extra or {})
    return subprocess.run([sys.executable, BENCH] + args,
                          capture_output=True, text=True, timeout=timeout,
                          env=env, cwd=ROOT)


def test_bench_smoke_runs_clean():
    res = _run(["--smoke"])
    assert res.returncode == 0, res.stdout + res.stderr
    out = json.loads(res.stdout.strip().splitlines()[-1])
    assert out["smoke"] is True and out["platform"] == "cpu"
    assert out["gate_matches"] > 0
    assert out["gate_dropped"] == 0
    assert out["engine_matches_delivered"] > 0
    sweep = out["b_sweep"]
    assert [r["batch_b"] for r in sweep] == [1, 2, 4]
    # bit-identical match semantics across B, asserted inside the sweep
    # and visible here
    assert len({r["matches_counted"] for r in sweep}) == 1
    # ticks really drop T -> ceil(T/B)
    for r in sweep:
        assert r["scan_ticks_per_block"] == -(-8 // r["batch_b"])
    # dispatch consolidation (round 7): the tiny C=2-chunk bank really
    # drops to ONE measured device dispatch per block, at equal matches
    dsm = out["d_sweep_smoke"]
    assert dsm["sequential"]["dispatches_per_block"] == 2
    assert dsm["stacked"]["dispatches_per_block"] == 1
    assert dsm["stacked"]["matches"] == dsm["sequential"]["matches"] > 0
    # cross-tenant super-dispatch (round 14): 2 heterogeneous tenant
    # apps share one bucket and one gang launch per ingest wall — fewer
    # dispatches than the SIDDHI_TPU_XTENANT=0 run, bit-identical
    # matches asserted inside bench_mtenant itself
    msm = out["mtenant_smoke"]
    assert msm["n_apps"] == 2 and msm["tenants"] == 2
    assert msm["buckets"] >= 1
    assert msm["matches"] > 0
    assert msm["packed_dispatches_per_block"] < \
        msm["unpacked_dispatches_per_block"]
    # partition-axis shard-out (round 15): 1/2/4-shard fans over the
    # same keyed feed emit bit-identical rows (parity asserted inside
    # bench_shardscale), every key owned by exactly one shard, FNV
    # ownership balanced
    ssm = out["shardscale_smoke"]
    assert ssm["keys"] == 512
    assert ssm["parity_rows"] > 0
    assert len(ssm["shard_keys"]) == 4
    assert sum(ssm["shard_keys"]) == 512
    assert 1.0 <= ssm["max_imbalance"] < 1.5
    # ingest armor (round 9): SHED_OLDEST under a wedged consumer, with
    # exact accounting asserted inside the smoke and visible here
    osm = out["overload_smoke"]
    assert osm["admitted"] == 200
    assert osm["shed"] > 0
    assert osm["admitted"] == osm["delivered"] + osm["shed"]
    # host rim (round 11): the columnar ingest -> match -> inMemory-sink
    # run materialized ZERO per-event Event objects, while the legacy
    # per-event callback run over the same feed did materialize — both
    # asserted inside the smoke and visible here
    rsm = out["rim_smoke"]
    assert rsm["sink_rows"] > 0
    assert rsm["columnar_materialized"] == 0
    assert rsm["legacy_materialized"] > 0
    prof = out["kernel_profile"]
    assert prof["nfa.bank_step"]["scan_ticks"] > 0
    assert prof["nfa.bank_step"]["dispatch_count"] > 0
    # flight recorder + device telemetry (round 10): ring populated by
    # the smoke's own ingest, on-demand bundle round-tripped through
    # REST, and the always-on recorder's per-block overhead bounded
    # (asserted < 5% inside the smoke itself)
    fsm = out["flight_smoke"]
    assert fsm["ring_blocks"] > 0
    assert fsm["bundle_id"].startswith("inc-")
    assert fsm["bundle_ring_blocks"] > 0
    assert fsm["telemetry_gate_pass"] > 0
    assert 0.0 <= fsm["overhead_pct"] < 5.0
    # latency ledger (round 12): waterfall stage-sum reconciles against
    # the independent e2e wall clock, a forced @app:slo breach round-trips
    # an SLO001 bundle with waterfall evidence, and the always-on ledger's
    # per-block overhead stays bounded (asserted < 5% inside the smoke)
    lsm = out["ledger_smoke"]
    assert 0.3 <= lsm["waterfall_coverage_p50"] <= 2.5
    assert lsm["waterfall_attributed_p50_ms"] > 0
    assert lsm["slo_bundle_id"].startswith("inc-")
    assert lsm["slo_bundle_code"] == "SLO001"
    assert lsm["slo_waterfall_stages"] > 0
    assert 0.0 <= lsm["overhead_pct"] < 5.0
    # compile observatory (round 16): a subprocess restart against the
    # same persistent cache dir hits instead of recompiling, the shape-
    # class signatures derived in both processes are identical, and the
    # match payloads are bit-identical (parity asserted inside the smoke)
    csm = out["coldstart_smoke"]
    assert csm["cold_ttfm_s"] > csm["warm_ttfm_s"] > 0
    assert csm["warm_cache_hits"] > 0
    assert csm["cold_cache_misses"] > 0
    assert csm["signatures"]
    assert any(s.startswith("filter.program[") for s in csm["signatures"])
    assert csm["parity_digest"]
    # numeric safety (round 18): the static verifier fired on the
    # constructed overflow app, samples/ are NS-clean, the armed
    # NUMGUARD run tripped the device sentinel plane at bit-identical
    # outputs, and the sentinel ingest overhead stays bounded (the < 5%
    # / 50 ms noise-floor bound is asserted inside the smoke itself)
    nsm = out["numeric_smoke"]
    assert "NS005" in nsm["static_codes"]
    assert nsm["sample_findings_total"] == 0
    assert nsm["sentinel_trips"] > 0
    assert nsm["overhead_pct"] >= 0.0
    # device selection tail (round 19): having + order-by + limit
    # compiled into the egress kernel — row parity vs the host
    # QuerySelector and the device routing are asserted inside
    # bench_select itself; here we pin the artifact shape
    ssel = out["select_smoke"]
    assert ssel["rows"] > 0
    assert ssel["events_per_sec"] > 0
    assert ssel["host_events_per_sec"] > 0
    assert ssel["route_sig"].startswith("h1o1l4")


def test_fail_on_p99_gate():
    """--fail-on-p99 on the waterfall phase: an impossible threshold
    must exit 1 with the FAIL line; a generous one must pass rc 0."""
    args = ["--phase", "waterfall", "--wf-blocks", "6",
            "--wf-chunk", "512"]
    env = {"JAX_PLATFORMS": "cpu"}
    res = _run(args + ["--fail-on-p99", "0.000001"], env_extra=env)
    assert res.returncode == 1, res.stdout + res.stderr
    assert "[bench] FAIL" in res.stderr
    assert "--fail-on-p99" in res.stderr
    # the phase still printed its JSON before the gate tripped
    wf = json.loads(res.stdout.strip().splitlines()[-1])
    assert wf["e2e_p99_ms"] > 0

    res = _run(args + ["--fail-on-p99", "1e9"], env_extra=env)
    assert res.returncode == 0, res.stdout + res.stderr
    wf = json.loads(res.stdout.strip().splitlines()[-1])
    assert wf["waterfall"] and wf["coverage_p50"] > 0


def test_fail_on_imbalance_gate():
    """--fail-on-imbalance on the shardscale phase: the max/mean key
    ratio is >= 1 by construction, so a sub-1 threshold must exit 1
    with the FAIL line; a generous one must pass rc 0."""
    args = ["--phase", "shardscale", "--sc-keys", "1024",
            "--sc-shards", "1,4"]
    env = {"JAX_PLATFORMS": "cpu", "SIDDHI_TPU_MESH": "off"}
    res = _run(args + ["--fail-on-imbalance", "0.99"], env_extra=env)
    assert res.returncode == 1, res.stdout + res.stderr
    assert "[bench] FAIL" in res.stderr
    assert "--fail-on-imbalance" in res.stderr
    # the phase still printed its JSON before the gate tripped
    sc = json.loads(res.stdout.strip().splitlines()[-1])
    assert sc["shardscale_max_imbalance"] >= 1.0

    res = _run(args + ["--fail-on-imbalance", "10.0"], env_extra=env)
    assert res.returncode == 0, res.stdout + res.stderr
    sc = json.loads(res.stdout.strip().splitlines()[-1])
    row4 = next(r for r in sc["shardscale"] if r["shards"] == 4)
    assert len(row4["shard_keys"]) == 4
    assert sum(row4["shard_keys"]) == 1024


def test_fail_on_numeric_gate():
    """--fail-on-numeric: jax-free samples/ NS sweep — the shipped
    samples are clean (0 warnings), so limit 0 passes rc 0 and the
    only way to force the failure arm without dirtying samples/ is an
    impossible limit of -1."""
    env = {"JAX_PLATFORMS": "cpu"}
    res = _run(["--fail-on-numeric", "-1"], env_extra=env, timeout=120)
    assert res.returncode == 1, res.stdout + res.stderr
    assert "[bench] FAIL" in res.stderr
    assert "--fail-on-numeric" in res.stderr
    # the sweep still printed its JSON before the gate tripped
    ns = json.loads(res.stdout.strip().splitlines()[-1])
    assert ns["unit"] == "warnings" and ns["value"] == 0

    res = _run(["--fail-on-numeric", "0"], env_extra=env, timeout=120)
    assert res.returncode == 0, res.stdout + res.stderr
    ns = json.loads(res.stdout.strip().splitlines()[-1])
    assert ns["value"] == 0 and ns["per_file"] == {}


def test_bench_skips_on_unreachable_backend():
    # a platform name jax cannot initialize reproduces the BENCH_r05
    # failure mode; bench must report a structured skip and exit 0
    res = _run([], env_extra={"JAX_PLATFORMS": "no_such_backend"},
               timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    out = json.loads(res.stdout.strip().splitlines()[-1])
    assert out["skipped"] == "backend unavailable"
    assert out["error"]
