"""Worker for the PUBLIC-API multi-host test: one process of a
jax.distributed cluster running a partitioned @app:engine('device')
SiddhiManager app through parallel.multihost.MultiHostAppRuntime.

Each process generates the SAME deterministic global stream; the wrapper
routes each event to its key's owning process, so the planner-built
KEYED device runtime (key→lane slab + @Async pipelined ingest + flush
barriers + grow-and-replay) executes with jax.process_count() > 1 over
this host's local devices.  Writes local match payloads + the DCN-
reduced global stats as JSON.

Usage: multihost_engine_worker.py <coordinator> <num_procs> <pid> <out>
"""
import json
import os
import sys

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=4")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np  # noqa: E402

from siddhi_tpu import StreamCallback  # noqa: E402
from siddhi_tpu.parallel.multihost import MultiHostAppRuntime  # noqa: E402

APP = """@app:playback
@Async(buffer.size='64', batch.size.max='4096')
define stream S (sym string, price float, kind int);
partition with (sym of S) begin
@info(name='q')
from every e1=S[kind == 0] -> e2=S[kind == 1 and price > e1.price]
    within 10 sec
select e1.price as p1, e2.price as p2 insert into Out;
end;
"""

N_KEYS = 48          # > the slab's starting lane count → forces growth
CHUNK = 1024
CHUNKS = 3


def global_chunk(ci: int):
    rng = np.random.default_rng(777 + ci)
    syms = np.asarray([f"k{i % N_KEYS}" for i in range(CHUNK)], object)
    cols = {"sym": syms,
            "price": rng.uniform(0, 100, CHUNK).astype(np.float32),
            "kind": rng.integers(0, 2, CHUNK).astype(np.int64)}
    ts = 1_000_000 + ci * CHUNK * 3 + np.arange(CHUNK, dtype=np.int64) * 3
    return cols, ts


def main():
    coord, nproc, pid, out_path = sys.argv[1:5]
    mh = MultiHostAppRuntime(APP, coord, int(nproc), int(pid))
    assert jax.process_count() == int(nproc), jax.process_count()
    got = []
    cb = StreamCallback(lambda evs: got.extend(
        (round(float(e.data[0]), 3), round(float(e.data[1]), 3))
        for e in evs))
    mh.add_callback("Out", cb)
    mh.start()
    sent = 0
    for ci in range(CHUNKS):
        cols, ts = global_chunk(ci)
        sent += mh.send_batch("S", cols, ts)
    mh.flush()
    stats = mh.global_stats(matches=len(got), ingested=sent)
    backend = None
    for pr in mh.runtime.partition_runtimes:
        for qr in getattr(pr, "device_query_runtimes", {}).values():
            backend = qr.backend
    mh.shutdown()
    with open(out_path, "w") as f:
        json.dump({"pid": int(pid), "local_matches": sorted(got),
                   "ingested": sent, "stats": stats,
                   "backend": backend}, f)


if __name__ == "__main__":
    main()
