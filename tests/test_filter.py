"""Filter/projection behavioural tests (reference model:
siddhi-core query/FilterTestCase1/2 — build app, attach callbacks, send,
assert payloads)."""
import pytest

from siddhi_tpu import QueryCallback, SiddhiManager, StreamCallback


def run_app(app, sends, stream="S", callback_on="Out"):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback(callback_on, StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    h = rt.get_input_handler(stream)
    for s in sends:
        h.send(s)
    rt.shutdown()
    return got


def test_simple_filter():
    got = run_app("""
        define stream S (symbol string, price float, volume long);
        from S[price > 100.0] select symbol, price insert into Out;
    """, [["IBM", 150.0, 10], ["X", 50.0, 1], ["GOOG", 700.5, 2]])
    assert [e.data for e in got] == [["IBM", 150.0], ["GOOG", 700.5]]


def test_filter_and_or_not():
    got = run_app("""
        define stream S (a int, b int);
        from S[(a > 1 and b < 10) or not (a == 5)]
        select a, b insert into Out;
    """, [[2, 5], [5, 50], [1, 3]])
    assert [e.data for e in got] == [[2, 5], [1, 3]]


def test_math_in_select():
    got = run_app("""
        define stream S (a int, b int);
        from S select a + b as s, a * b as p, a - b as d, a / b as q,
                      a % b as m
        insert into Out;
    """, [[7, 2]])
    assert got[0].data == [9, 14, 5, 3, 1]


def test_string_compare():
    got = run_app("""
        define stream S (sym string, p int);
        from S[sym == 'IBM'] select sym insert into Out;
    """, [["IBM", 1], ["X", 2], ["IBM", 3]])
    assert len(got) == 2


def test_bool_and_constants():
    got = run_app("""
        define stream S (ok bool, x int);
        from S[ok == true and x > 0] select x insert into Out;
    """, [[True, 5], [False, 6], [True, -1]])
    assert [e.data for e in got] == [[5]]


def test_chained_queries():
    """Output of one query feeds the next (junction recirculation)."""
    got = run_app("""
        define stream S (x int);
        from S[x > 0] select x * 2 as x insert into Mid;
        from Mid[x > 10] select x insert into Out;
    """, [[3], [6], [-1]])
    assert [e.data for e in got] == [[12]]


def test_ifthenelse_and_functions():
    got = run_app("""
        define stream S (x int);
        from S select ifThenElse(x > 0, 'pos', 'neg') as sign,
                      coalesce(x, 0) as cx,
                      math:abs(0 - x) as ax
        insert into Out;
    """, [[5], [-3]])
    assert got[0].data[0] == "pos" and got[1].data[0] == "neg"
    assert got[1].data[2] == 3


def test_query_callback_split():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (x int);
        @info(name='q')
        from S select x insert into Out;
    """)
    rows = []
    rt.add_callback("q", QueryCallback(
        lambda ts, cur, exp: rows.append((cur, exp))))
    rt.start()
    rt.get_input_handler("S").send([42])
    rt.shutdown()
    assert rows[0][0][0].data == [42]
    assert rows[0][1] is None


def test_send_event_batch():
    import numpy as np
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (x int, y double);
        from S[x % 2 == 0] select y insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    rt.get_input_handler("S").send_batch(
        {"x": np.arange(10, dtype=np.int32),
         "y": np.arange(10, dtype=np.float64) * 1.5})
    rt.shutdown()
    assert len(got) == 5
    assert got[2].data == [6.0]


def test_script_function():
    got = run_app("""
        define function tripler[python] return long { data[0] * 3 };
        define stream S (x long);
        from S select tripler(x) as t insert into Out;
    """, [[7]])
    assert got[0].data == [21]


def test_cast_convert():
    got = run_app("""
        define stream S (x int);
        from S select convert(x, 'double') as d, cast(x, 'string') as s
        insert into Out;
    """, [[3]])
    assert got[0].data == [3.0, "3"]


def test_string_lane_filter_randomized_parity():
    """Round 4: string predicates on the device filter path ride per-chunk
    order-preserving code lanes (plan/str_lanes.py) — randomized parity
    vs host across ==/!=/order/is-null, nulls included."""
    import numpy as np
    from siddhi_tpu import SiddhiManager, StreamCallback

    apps = {
        "eq":   "s == 'mm'",
        "neq":  "s != 'mm'",
        "gt":   "s > 'mm'",
        "lte":  "s <= 'mm'",
        "vv":   "s < t",
        "null": "s is null",
        "mix":  "(s > 'aa' and s < 'zz') or t == 'mm'",
    }
    rng = np.random.default_rng(5)
    words = np.asarray(["aa", "mm", "zz", "ab", "ya", None], object)
    n = 200
    scol = words[rng.integers(0, len(words), n)]
    tcol = words[rng.integers(0, len(words), n)]
    vcol = rng.uniform(0, 10, n).astype(np.float32)
    ts = 1_000_000 + np.arange(n, dtype=np.int64) * 10

    for name, cond in apps.items():
        app = (f"define stream S (s string, t string, v float);\n"
               f"@info(name='q') from S[{cond}] select v insert into O;")

        def run(engine):
            m = SiddhiManager()
            pre = "@app:playback " + (
                f"@app:engine('{engine}') " if engine else "")
            rt = m.create_siddhi_app_runtime(pre + app)
            got = []
            rt.add_callback("O", StreamCallback(
                lambda evs: got.extend(tuple(e.data) for e in evs)))
            rt.start()
            rt.get_input_handler("S").send_batch(
                {"s": scol, "t": tcol, "v": vcol}, timestamps=ts)
            b = rt.query_runtimes["q"].backend
            rt.shutdown()
            return b, got
        bd, dev = run(None)
        bh, host = run("host")
        assert bd == "device" and bh == "host", (name, bd)
        assert dev == host, (name, len(dev), len(host))
