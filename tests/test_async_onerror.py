"""@Async junctions and @OnError fault streams (reference models:
managment/AsyncTestCase, stream/junction OnError tests)."""
import time

from siddhi_tpu import SiddhiManager, StreamCallback


def test_async_junction_delivers_all_events():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @Async(buffer.size='256', workers='2', batch.size.max='32')
        define stream S (v int);
        from S[v >= 0] select v insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    h = rt.get_input_handler("S")
    for i in range(300):
        h.send([i])
    deadline = time.time() + 5
    while len(got) < 300 and time.time() < deadline:
        time.sleep(0.01)
    rt.shutdown()
    assert sorted(e.data[0] for e in got) == list(range(300))


def test_onerror_stream_routes_failures():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream A (v int);
        @OnError(action='STREAM')
        define stream S (v int);
        define function boom[python] return int { data[0] if data[0] < 3 else (_ for _ in ()).throw(ValueError('kaboom')) };
        from A select v insert into S;
        from S select boom(v) as v insert into Out;
        from !S select v, _error insert into FaultOut;
    """)
    ok, fault = [], []
    rt.add_callback("Out", StreamCallback(lambda evs: ok.extend(evs)))
    rt.add_callback("FaultOut", StreamCallback(lambda evs: fault.extend(evs)))
    rt.start()
    h = rt.get_input_handler("A")
    h.send([1])
    h.send([5])     # boom() raises → routed to !S
    rt.shutdown()
    assert [e.data[0] for e in ok] == [1]
    assert len(fault) == 1 and fault[0].data[0] == 5
    assert "kaboom" in str(fault[0].data[1])


def test_onerror_log_default_swallows():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (v int);
        define function boom2[python] return int { (_ for _ in ()).throw(ValueError('x')) };
        from S select boom2(v) as v insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    rt.get_input_handler("S").send([1])   # error logged, app alive
    rt.get_input_handler("S").send([2])
    rt.shutdown()
    assert got == []
