"""Compile-time observatory (plan/shapes.py).

Covers: shape-class signature stability, the single-choke-point rule
(``jax.jit`` appears nowhere outside the registry + a short allowlist),
compile attribution + trigger tallies, the CC001 ingest-blocking-compile
incident, /metrics exposition (one HELP/TYPE header per series, process
gauges), prewarm ladder behaviour on grow, and — via subprocesses — the
persistent compile cache surviving a process restart with bit-identical
results and identical shape-class signatures.
"""
import ast
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from siddhi_tpu import SiddhiManager, StreamCallback  # noqa: E402
from siddhi_tpu.core.flight import flight  # noqa: E402
from siddhi_tpu.plan.shapes import (COMPILE_CACHE_ENV,  # noqa: E402
                                    LADDER_RUNGS, PREWARM_ENV, SHAPES_TYPES,
                                    _AotHandoff, compile_cache_dir,
                                    nfa_shape_dims, prewarm_enabled,
                                    shape_registry, shape_signature)


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    """Registry and flight recorder are process-global; isolate each
    test and point incident bundles at tmp."""
    monkeypatch.setenv("SIDDHI_TPU_FLIGHT_DIR", str(tmp_path / "bundles"))
    shape_registry().reset()
    flight().reset()
    yield
    shape_registry().reset()
    flight().reset()


# ------------------------------------------------------------ signatures

def test_signature_sorted_stable_and_hashable():
    sig = shape_signature("nfa.step", {"K": 8, "B": 4, "donate": True,
                                       "caps": (16, 32)})
    assert sig == "nfa.step[B=4,K=8,caps=16x32,donate=1]"
    # order of insertion must not matter
    assert sig == shape_signature(
        "nfa.step", {"caps": [16, 32], "donate": True, "B": 4, "K": 8})
    hash(sig)


def test_signature_bools_render_as_ints():
    assert shape_signature("t", {"a": False, "b": True}) == "t[a=0,b=1]"


def test_nfa_shape_dims_contract():
    class Spec:
        units = [1, 2, 3]
        n_slots = 16
        n_rows = 2
        n_caps = 0
        telemetry = False

    d = nfa_shape_dims(Spec(), 4, 8, donate=True, ring=3)
    assert d == {"S": 3, "K": 16, "P": 4, "B": 8, "R": 2, "C": 1,
                 "telem": False, "donate": True, "ring": 3}
    assert shape_signature("nfa.bank_step", d) == (
        "nfa.bank_step[B=8,C=1,K=16,P=4,R=2,S=3,donate=1,ring=3,telem=0]")


def test_cache_env_kill_switch(monkeypatch):
    for off in ("0", "off", "false", ""):
        monkeypatch.setenv(COMPILE_CACHE_ENV, off)
        assert compile_cache_dir() is None
    monkeypatch.setenv(COMPILE_CACHE_ENV, "/tmp/ccache")
    assert compile_cache_dir() == "/tmp/ccache"
    monkeypatch.setenv(PREWARM_ENV, "0")
    assert not prewarm_enabled()
    assert not shape_registry().prewarm_submit("t", {"n": 1}, lambda: None)


# ------------------------------------------------------- the choke point

#: The only files allowed to spell ``jax.jit`` — everything else must go
#: through shape_registry().jit()/adopt() so compiles stay attributable.
_JIT_ALLOWLIST = {
    "plan/shapes.py",         # the registry itself
    "parallel/mesh.py",       # sharded step built here, adopt()ed by the
                              # NFA compiler as nfa.mesh_step
    "parallel/multihost.py",  # cross-host stats reduction helper
    "ops/incremental_agg.py",  # standalone op-level kernels (no engine
                              # entry point routes through them)
}


def test_jax_jit_routed_through_registry_everywhere():
    root = os.path.join(REPO, "siddhi_tpu")
    offenders = []
    for dirpath, _dirs, files in os.walk(root):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=rel)
            for node in ast.walk(tree):
                hit = (isinstance(node, ast.Attribute)
                       and node.attr == "jit"
                       and isinstance(node.value, ast.Name)
                       and node.value.id == "jax")
                hit = hit or (isinstance(node, ast.ImportFrom)
                              and node.module == "jax"
                              and any(a.name == "jit" for a in node.names))
                if hit and rel not in _JIT_ALLOWLIST:
                    offenders.append(f"{rel}:{node.lineno}")
    assert not offenders, (
        "jax.jit outside the shape registry (route through "
        f"shape_registry().jit/adopt or extend the allowlist): {offenders}")


# ------------------------------------------------------------ attribution

def test_registry_jit_attributes_compile_and_calls():
    import jax.numpy as jnp
    reg = shape_registry()
    rj = reg.jit("test.kernel", {"n": 7}, lambda x: x * 2 + 1)
    out = rj(jnp.arange(8))
    assert int(out[1]) == 3
    rj(jnp.arange(8))                     # second call: no new compile
    e = rj.entry
    assert e.signature == "test.kernel[n=7]"
    assert e.calls == 2
    assert e.compiles >= 1
    assert e.compile_seconds > 0          # monitoring listener credited us
    assert e.blocked_seconds > 0
    assert e.triggers == {"build": 1}
    tot = reg.totals()
    assert tot["shape_classes"] >= 1
    assert tot["compiles"] >= 1
    snap = reg.snapshot()
    assert any(d["signature"] == "test.kernel[n=7]"
               for d in snap["entries"])
    assert snap["recent_compiles"][-1]["signature"] == "test.kernel[n=7]"
    lines = reg.prometheus_lines()
    assert any(l.startswith("siddhi_compile_seconds_total")
               and 'signature="test.kernel[n=7]"' in l for l in lines)


def test_adopt_tallies_triggers_per_rebuild():
    import jax
    reg = shape_registry()
    jitted = jax.jit(lambda x: x + 1)
    reg.adopt("test.adopted", {"k": 1}, jitted, trigger="build")
    rj = reg.adopt("test.adopted", {"k": 1}, jitted, trigger="grow")
    assert rj.entry.triggers == {"build": 1, "grow": 1}
    assert rj.entry.last_trigger == "grow"


def test_blocking_compile_stall_emits_cc001():
    reg = shape_registry()
    e = reg.entry("test.stall", {"K": 64})
    # 5s blocked on a grow-triggered compile >> the 2s default threshold
    reg._note_compile(e, "grow", 1, 5.0)
    incs = [i for i in flight().incidents() if i["kind"] == "compile_stall"]
    assert len(incs) == 1
    det = flight().bundle(incs[0]["id"])["detail"]
    assert det["code"] == "CC001"
    assert det["signature"] == "test.stall[K=64]"
    assert det["trigger"] == "grow"
    assert det["blocked_ms"] == 5000.0
    # the compile row itself rides the flight ring alongside blocks
    rows = [r for r in flight().ring() if "compile" in r]
    assert rows and rows[-1]["compile"] == "test.stall[K=64]"


def test_build_trigger_never_emits_cc001():
    reg = shape_registry()
    reg._note_compile(reg.entry("test.cold", {"K": 8}), "build", 1, 30.0)
    assert not [i for i in flight().incidents()
                if i["kind"] == "compile_stall"]


# ------------------------------------------------------------ exposition

def test_metrics_single_header_per_series_and_process_gauges():
    from siddhi_tpu.core.statistics import PROCESS_TYPES, prometheus_text
    import jax.numpy as jnp
    rj = shape_registry().jit("test.metrics", {"n": 1}, lambda x: x - 1)
    rj(jnp.arange(4))
    text = prometheus_text([])
    for name, typ, _help in list(SHAPES_TYPES) + list(PROCESS_TYPES):
        assert text.count(f"# TYPE {name} ") == 1, name
        assert text.count(f"# HELP {name} ") == 1, name
        assert f"# TYPE {name} {typ}\n" in text, name
    assert 'siddhi_compile_total{kind="test.metrics"' in text
    # process series carry live values
    rss = [l for l in text.splitlines()
           if l.startswith("siddhi_process_rss_bytes ")]
    assert rss and float(rss[0].split()[1]) > 0
    up = [l for l in text.splitlines()
          if l.startswith("siddhi_process_uptime_seconds ")]
    assert up and float(up[0].split()[1]) >= 0
    assert 'siddhi_gc_collections_total{generation="0"}' in text


def test_runtime_statistics_carry_shape_snapshot(monkeypatch):
    monkeypatch.setenv("SIDDHI_TPU_XTENANT", "0")
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "@app:name('shapestats') "
        "define stream S (v float); "
        "@info(name='q') from S[v > 0.0] select v insert into Out;")
    rt.start()
    rt.get_input_handler("S").send([1.0])
    rt.get_input_handler("S").send([2.0])
    rt.flush()
    snap = rt.statistics["shapes"]
    assert snap["cache"]["configured"] is True
    sigs = [e["signature"] for e in snap["entries"]]
    assert any(s.startswith("filter.program[") for s in sigs)
    assert snap["totals"]["compiles"] >= 1
    rt.shutdown()


# ------------------------------------------------------- prewarm ladder

def test_grow_ladder_prewarms_next_rungs(monkeypatch):
    monkeypatch.setenv("SIDDHI_TPU_XTENANT", "0")
    monkeypatch.setenv("SIDDHI_TPU_MESH", "off")   # ladder rides the
    monkeypatch.setenv(PREWARM_ENV, "1")           # per-NFA step path
    reg = shape_registry()
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "@app:name('ladder') "
        "define stream S (sym string, price float); "
        "@info(name='pat') from every e1=S[price > 10] "
        "-> e2=S[price > e1.price] "
        "select e1.sym as s1, e2.price as p2 insert into Out;")
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.append(len(evs))))
    rt.start()
    h = rt.get_input_handler("S")
    h.send_batch({"sym": np.asarray(["A"] * 8, object),
                  "price": 11.0 + np.arange(8.0)},
                 1_000 + np.arange(8, dtype=np.int64))
    rt.flush()                      # first step call arms the ladder hook
    assert reg.prewarm_join(timeout=300)

    nfa = rt.query_runtimes["pat"].device_runtime.nfa
    k0 = nfa.spec.n_slots
    base_sig = shape_signature(
        "nfa.step", nfa_shape_dims(nfa.spec, nfa.n_partitions, nfa.batch_b,
                                   donate=nfa._effective_donate()))
    by_sig = {e["signature"]: e for e in reg.snapshot()["entries"]}
    assert by_sig[base_sig]["triggers"].get("build") == 1
    # every ladder rung is a DIFFERENT shape class, compiled ahead of need
    for mlt in LADDER_RUNGS:
        spec = nfa.spec
        rung_sig = shape_signature("nfa.step", dict(
            nfa_shape_dims(spec, nfa.n_partitions, nfa.batch_b,
                           donate=nfa._effective_donate()), K=k0 * mlt))
        assert rung_sig != base_sig
        assert by_sig[rung_sig]["compiles"] >= 1, rung_sig
        assert by_sig[rung_sig]["last_trigger"] == "prewarm"
    snap = reg.snapshot()["prewarm"]
    assert snap["compiled"] >= len(LADDER_RUNGS)
    assert snap["errors"] == 0

    # the grown-K rebuild lands on the exact shape class the ladder
    # already compiled, tallied under its own "grow" trigger
    nfa.grow_slots(k0 * LADDER_RUNGS[0])
    grown_sig = shape_signature(
        "nfa.step", nfa_shape_dims(nfa.spec, nfa.n_partitions, nfa.batch_b,
                                   donate=nfa._effective_donate()))
    assert grown_sig != base_sig
    e = {e["signature"]: e for e in reg.snapshot()["entries"]}[grown_sig]
    assert e["triggers"].get("prewarm") == 1
    assert e["triggers"].get("grow") == 1
    # ...and takes over the ladder's AOT executable outright (the
    # owner-gated handoff): no re-trace, no re-compile at grow time
    assert e["triggers"].get("prewarm-handoff") == 1
    assert e["prewarmed"] is True
    assert reg.snapshot()["prewarm"]["handoffs"] >= 1

    # the handed-over executable really runs: same block shape as the
    # ladder's abstract snapshot, so the AOT path serves the call and
    # the shape class never compiles again
    before = len(got)
    h.send_batch({"sym": np.asarray(["A"] * 8, object),
                  "price": 111.0 + np.arange(8.0)},
                 9_000 + np.arange(8, dtype=np.int64))
    rt.flush()
    assert len(got) > before
    e = {e["signature"]: e for e in reg.snapshot()["entries"]}[grown_sig]
    assert e["compiles"] == 1       # the prewarm compile — nothing since
    assert e["calls"] >= 1
    rt.shutdown()
    reg.prewarm_join(timeout=60)    # grow re-arms the ladder; drain it


def test_prewarm_handoff_is_owner_gated():
    """A shape-class signature pins array shapes, not the constants an
    owner baked into its HLO — a rebuild may only take over a prewarmed
    executable queued by the SAME owner token."""
    import jax
    import jax.numpy as jnp
    os.environ[PREWARM_ENV] = "1"
    try:
        reg = shape_registry()
        dims = {"n": 8}
        build = lambda: (lambda x: x * 3, # noqa: E731
                         (jax.ShapeDtypeStruct((8,), jnp.float32),), {})
        assert reg.prewarm_submit("hand.off", dims, build, owner="me")
        assert reg.prewarm_join(timeout=60)
        x = jnp.arange(8, dtype=jnp.float32)

        stranger = reg.jit("hand.off", dims, lambda x: x * 3,
                           prewarm_owner="not-me")
        assert not isinstance(stranger._jitted, _AotHandoff)
        mine = reg.jit("hand.off", dims, lambda x: x * 3,
                       prewarm_owner="me")
        assert isinstance(mine._jitted, _AotHandoff)
        np.testing.assert_array_equal(np.asarray(mine(x)),
                                      np.asarray(x) * 3)
        # aval mismatch falls back to the plain jit (which retraces)
        y = jnp.arange(16, dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(mine(y)),
                                      np.asarray(y) * 3)
        assert reg.snapshot()["prewarm"]["handoffs"] == 1
    finally:
        os.environ.pop(PREWARM_ENV, None)


# ------------------------------------------- cache across process restart

def _run_cachestab_worker(cache_dir, extra_env=None):
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", SIDDHI_TPU_XTENANT="0",
               SIDDHI_TPU_PREWARM="0")
    env[COMPILE_CACHE_ENV] = cache_dir
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--coldstart-worker", "--cs-tiny"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_compile_cache_survives_process_restart(tmp_path):
    cache = str(tmp_path / "ccache")
    cold = _run_cachestab_worker(cache)
    assert cold["cache_misses"] > 0
    assert os.listdir(cache), "persistent cache wrote no artifacts"
    warm = _run_cachestab_worker(cache)
    # the restarted process derives the SAME shape-class signatures ...
    assert cold["signatures"] == warm["signatures"]
    assert any(s.startswith("filter.program[") for s in warm["signatures"])
    # ... hits the cache instead of recompiling ...
    assert warm["cache_hits"] > 0
    assert warm["cache_misses"] == 0
    # ... and produces bit-identical matches (cache introduces zero drift)
    assert cold["digest"] == warm["digest"]
    assert cold["matches"] == warm["matches"] > 0
    # parity against a cache-disabled process: same events, same matches
    off = _run_cachestab_worker("0")
    assert off["digest"] == cold["digest"]
    assert off["cache"]["enabled"] is False
