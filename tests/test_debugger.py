"""Debugger tests (reference model: siddhi-core debugger/TestDebugger)."""
from siddhi_tpu import SiddhiManager, StreamCallback

APP = """
define stream S (symbol string, price float);
@info(name='q1') from S[price > 10] select symbol, price insert into Out;
"""


def test_breakpoint_in_and_out_and_state():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP)
    hits = []

    def cb(events, query, terminal, dbg):
        hits.append((query, terminal, [e.data for e in events]))
        dbg.play()  # synchronous resume

    dbg = rt.debug()
    dbg.set_debugger_callback(cb)
    dbg.acquire_break_point("q1", dbg.IN)
    dbg.acquire_break_point("q1", dbg.OUT)
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.get_input_handler("S").send(["IBM", 50.0])
    rt.shutdown()
    assert ("q1", "IN", [["IBM", 50.0]]) in hits
    assert ("q1", "OUT", [["IBM", 50.0]]) in hits
    assert len(got) == 1


def test_next_steps_to_following_terminal():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP)
    hits = []

    def cb(events, query, terminal, dbg):
        hits.append(terminal)
        if len(hits) == 1:
            dbg.next()     # step: must stop again at OUT
        else:
            dbg.play()

    dbg = rt.debug()
    dbg.set_debugger_callback(cb)
    dbg.acquire_break_point("q1", dbg.IN)
    rt.get_input_handler("S").send(["IBM", 50.0])
    rt.shutdown()
    assert hits == ["IN", "OUT"]


def test_get_query_state():
    app = """
        define stream S (symbol string, price float);
        @info(name='q1') from S select symbol, sum(price) as t
        group by symbol insert into Out;
    """
    # host engine: selector aggregate state is introspectable
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("@app:engine('host') " + app)
    dbg = rt.debug()
    rt.get_input_handler("S").send(["IBM", 5.0])
    state = dbg.get_query_state("q1")
    assert any("selector" in k for k in state)
    rt.shutdown()
    # device engine (grouped-agg kernel): the device state is exposed
    m2 = SiddhiManager()
    rt2 = m2.create_siddhi_app_runtime(app)
    dbg2 = rt2.debug()
    rt2.get_input_handler("S").send(["IBM", 5.0])
    state2 = dbg2.get_query_state("q1")
    assert rt2.query_runtimes["q1"].backend == "device"
    assert state2 and all(v is not None for v in state2.values())
    rt2.shutdown()
