"""Named-window (`define window`) conformance tests ported from the
reference corpus (siddhi-core/src/test/java/io/siddhi/core/window/ —
LengthWindowTestCase, LengthBatchWindowTestCase, TimeWindowTestCase,
TimeBatchWindowTestCase, SortWindowTestCase, DelayWindowTestCase,
CustomJoinWindowTestCase).  Behaviors mirrored with this repo's sends;
assertions are the reference tests' expected semantics: shared window
definitions feed many queries, `output all events` exposes expiry,
joins run against the shared buffer."""
from ref_harness import run_query

CSE = "define stream cse (symbol string, price float, volume int);\n"


# ------------------------------------------------- LengthWindowTestCase

def test_named_length_window_under_capacity():
    """testLengthWindow1: fewer events than the window size — only
    CURRENT events, in arrival order."""
    run_query(CSE + """
        define window cseWindow (symbol string, price float, volume int)
            length(4) output all events;
        @info(name='query1') from cse select symbol, price, volume
            insert into cseWindow;
        @info(name='query2') from cseWindow insert into outputStream;""",
        [("cse", ["IBM", 700.0, 0]), ("cse", ["WSO2", 60.5, 1])],
        [("IBM", 700.0, 0), ("WSO2", 60.5, 1)], stream="outputStream",
        playback=True)


def test_named_length_window_expiry_interleaves():
    """testLengthWindow2: past capacity, each arrival expires the oldest —
    `insert all events` interleaves CURRENT and EXPIRED rows."""
    run_query(CSE + """
        define window cseWindow (symbol string, price float, volume int)
            length(4) output all events;
        @info(name='query1') from cse select symbol, price, volume
            insert into cseWindow;
        @info(name='query2') from cseWindow insert all events into
            outputStream;""",
        [("cse", ["IBM", 700.0, i]) for i in range(6)],
        [("IBM", 700.0, 0), ("IBM", 700.0, 1), ("IBM", 700.0, 2),
         ("IBM", 700.0, 3),
         ("IBM", 700.0, 0), ("IBM", 700.0, 4),      # 0 expires as 4 arrives
         ("IBM", 700.0, 1), ("IBM", 700.0, 5)],     # 1 expires as 5 arrives
        stream="outputStream", playback=True)


def test_named_window_aggregate_query():
    """Aggregates over a shared window buffer (LengthWindowTestCase
    aggregation variants): sum tracks the live window contents."""
    run_query(CSE + """
        define window cseWindow (symbol string, price float, volume int)
            length(2) output all events;
        @info(name='query1') from cse select symbol, price, volume
            insert into cseWindow;
        @info(name='query2') from cseWindow select sum(volume) as total
            insert into outputStream;""",
        [("cse", ["IBM", 1.0, 10]), ("cse", ["IBM", 1.0, 20]),
         ("cse", ["IBM", 1.0, 30])],
        [(10,), (30,), (50,)],          # 10, 10+20, 20+30 (10 expired)
        stream="outputStream", playback=True)


# --------------------------------------------- LengthBatchWindowTestCase

def test_named_length_batch_window():
    """Batch named window emits only on full batches."""
    run_query(CSE + """
        define window cseWindow (symbol string, price float, volume int)
            lengthBatch(2) output all events;
        @info(name='query1') from cse select symbol, price, volume
            insert into cseWindow;
        @info(name='query2') from cseWindow insert into outputStream;""",
        [("cse", ["A", 1.0, 1]), ("cse", ["B", 1.0, 2]),
         ("cse", ["C", 1.0, 3]), ("cse", ["D", 1.0, 4]),
         ("cse", ["E", 1.0, 5])],
        [("A", 1.0, 1), ("B", 1.0, 2), ("C", 1.0, 3), ("D", 1.0, 4)],
        stream="outputStream", playback=True)


# ------------------------------------------------- TimeWindowTestCase

def test_named_time_window_expiry():
    """Time-based named window expires by virtual clock."""
    run_query(CSE + """
        define window cseWindow (symbol string, price float, volume int)
            time(1 sec) output all events;
        @info(name='query1') from cse select symbol, price, volume
            insert into cseWindow;
        @info(name='query2') from cseWindow select sum(volume) as total
            insert into outputStream;""",
        [("cse", ["A", 1.0, 10], 1_000_000),
         ("cse", ["B", 1.0, 20], 1_000_100),
         ("__advance__", None, 1_002_000),
         ("cse", ["C", 1.0, 40], 1_002_100)],
        [(10,), (30,), (40,)],        # A+B expired by the clock advance
        stream="outputStream", playback=True)


# --------------------------------------------- TimeBatchWindowTestCase

def test_named_time_batch_window():
    run_query(CSE + """
        define window cseWindow (symbol string, price float, volume int)
            timeBatch(1 sec) output all events;
        @info(name='query1') from cse select symbol, price, volume
            insert into cseWindow;
        @info(name='query2') from cseWindow insert into outputStream;""",
        [("cse", ["A", 1.0, 1], 1_000_000),
         ("cse", ["B", 1.0, 2], 1_000_200),
         ("__advance__", None, 1_001_100),
         ("cse", ["C", 1.0, 3], 1_001_200),
         ("__advance__", None, 1_002_200)],
        [("A", 1.0, 1), ("B", 1.0, 2), ("C", 1.0, 3)],
        stream="outputStream", playback=True)


# ------------------------------------------------- SortWindowTestCase

def test_named_sort_window():
    """sort(2, volume) keeps the two smallest volumes; larger rows expire
    immediately."""
    run_query(CSE + """
        define window cseWindow (symbol string, price float, volume int)
            sort(2, volume) output all events;
        @info(name='query1') from cse select symbol, price, volume
            insert into cseWindow;
        @info(name='query2') from cseWindow insert expired events into
            outputStream;""",
        [("cse", ["A", 1.0, 50]), ("cse", ["B", 1.0, 20]),
         ("cse", ["C", 1.0, 40]), ("cse", ["D", 1.0, 10])],
        [("A", 1.0, 50), ("C", 1.0, 40)],
        stream="outputStream", playback=True)


# ------------------------------------------------- DelayWindowTestCase

def test_named_delay_window():
    """delay(1 sec): events surface only after the delay elapses."""
    run_query(CSE + """
        define window cseWindow (symbol string, price float, volume int)
            delay(1 sec);
        @info(name='query1') from cse select symbol, price, volume
            insert into cseWindow;
        @info(name='query2') from cseWindow insert into outputStream;""",
        [("cse", ["A", 1.0, 1], 1_000_000),
         ("__advance__", None, 1_000_500),
         ("cse", ["B", 1.0, 2], 1_000_600),
         ("__advance__", None, 1_001_100)],
        [("A", 1.0, 1)],               # only A's delay has elapsed
        stream="outputStream", playback=True)


# --------------------------------------------- CustomJoinWindowTestCase

def test_join_named_window_with_table():
    """testJoinWindowWithTable: a length(1) check window joined against a
    table — expected single (WSO2, WSO2, 100) row."""
    run_query("""
        define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string);
        define window CheckStockWindow (symbol string) length(1)
            output all events;
        define table StockTable (symbol string, price float, volume long);
        @info(name='query0') from StockStream insert into StockTable;
        @info(name='query1') from CheckStockStream insert into
            CheckStockWindow;
        @info(name='query2')
        from CheckStockWindow join StockTable
            on CheckStockWindow.symbol == StockTable.symbol
        select CheckStockWindow.symbol as checkSymbol,
               StockTable.symbol as symbol, StockTable.volume as volume
        insert into OutputStream;""",
        [("StockStream", ["WSO2", 55.6, 100]),
         ("StockStream", ["IBM", 75.6, 10]),
         ("CheckStockStream", ["WSO2"])],
        [("WSO2", "WSO2", 100)], stream="OutputStream", playback=True)


def test_join_two_named_windows():
    """testJoinWindowWithWindow: filtered inserts feed two shared windows;
    the join fires per matching regulator arrival (rooms 4 and 5)."""
    run_query("""
        define stream TempStream (deviceID long, roomNo int, temp double);
        define stream RegulatorStream (deviceID long, roomNo int, isOn bool);
        define window TempWindow (deviceID long, roomNo int, temp double)
            time(1 min);
        define window RegulatorWindow (deviceID long, roomNo int, isOn bool)
            length(1);
        @info(name='query1') from TempStream[temp > 30.0]
            insert into TempWindow;
        @info(name='query2') from RegulatorStream[isOn == false]
            insert into RegulatorWindow;
        @info(name='query3')
        from TempWindow join RegulatorWindow
            on TempWindow.roomNo == RegulatorWindow.roomNo
        select TempWindow.roomNo, RegulatorWindow.deviceID,
               'start' as action
        insert into RegulatorActionStream;""",
        [("TempStream", [100, 1, 20.0]), ("TempStream", [100, 2, 25.0]),
         ("TempStream", [100, 3, 30.0]), ("TempStream", [100, 4, 35.0]),
         ("TempStream", [100, 5, 40.0]),
         ("RegulatorStream", [100, 1, False]),
         ("RegulatorStream", [100, 2, False]),
         ("RegulatorStream", [100, 3, False]),
         ("RegulatorStream", [100, 4, False]),
         ("RegulatorStream", [100, 5, False])],
        [(4, 100, "start"), (5, 100, "start")],
        stream="RegulatorActionStream", playback=True)


def test_many_streams_one_named_window():
    """testWindowWithMultipleStreams shape: five source streams feed one
    shared window; the window sees the union."""
    streams = "\n".join(
        f"define stream Stream{i} (symbol string, price float, volume long);"
        for i in range(5))
    inserts = "\n".join(
        f"@info(name='insert{i}') from Stream{i} insert into AllWindow;"
        for i in range(5))
    run_query(streams + """
        define window AllWindow (symbol string, price float, volume long)
            length(10) output all events;
        """ + inserts + """
        @info(name='query1') from AllWindow select symbol, volume
            insert into OutputStream;""",
        [(f"Stream{i}", ["WSO2", i * 10.0, 1]) for i in range(5)],
        [("WSO2", 1)] * 5, stream="OutputStream", playback=True)


def test_filter_on_named_window_query():
    """testWindowFilter shape: `from W[cond]` filters the shared buffer's
    output stream."""
    run_query("""
        define stream StockIn (symbol string, price float, volume long);
        define window StockWindow (symbol string, price float, volume long)
            length(10) output all events;
        @info(name='query1') from StockIn insert into StockWindow;
        @info(name='query2') from StockWindow[volume > 6]
            select symbol, volume insert into OutputStream;""",
        [("StockIn", ["WSO2", 84.0, 20]), ("StockIn", ["IBM", 90.0, 1]),
         ("StockIn", ["WSO2", 55.0, 5]), ("StockIn", ["IBM", 70.0, 8])],
        [("WSO2", 20), ("IBM", 8)], stream="OutputStream", playback=True)


def test_named_window_unidirectional_join_stream():
    """Stream joined to a named window (only stream side triggers)."""
    run_query("""
        define stream Probe (symbol string);
        define stream StockIn (symbol string, volume long);
        define window StockWindow (symbol string, volume long) length(5);
        @info(name='query1') from StockIn insert into StockWindow;
        @info(name='query2')
        from Probe unidirectional join StockWindow
            on Probe.symbol == StockWindow.symbol
        select Probe.symbol, StockWindow.volume
        insert into OutputStream;""",
        [("StockIn", ["IBM", 10]), ("StockIn", ["WSO2", 20]),
         ("Probe", ["IBM"]), ("StockIn", ["IBM", 30]),
         ("Probe", ["IBM"])],
        [("IBM", 10), ("IBM", 10), ("IBM", 30)],
        stream="OutputStream", playback=True, unordered=True)
