"""A REAL 2-process jax.distributed run (VERDICT r3 #5): two OS
processes coordinate over localhost (the DCN path), each with 4 virtual
CPU devices, jointly executing the mesh-sharded NFA step over a global
8-device mesh via DistributedPatternBank.step_local.  Asserts global
match parity with a single-process run over the same stream and that
egress is host-local (each process sees only its own partition range).

This is the first artifact where the cross-host assembly
(make_array_from_process_local_data), the SPMD step, the fused stats
all-reduce, and host-local shard readback execute with
jax.process_count() > 1."""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "multihost_worker.py")
ENGINE_WORKER = os.path.join(HERE, "multihost_engine_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _scrubbed_env():
    env = dict(os.environ)
    # fresh subprocesses must not register the axon TPU plugin, and must
    # not inherit the parent's forced device count
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = ""
    return env


_PROBE_SRC = """
import sys
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(sys.argv[1], num_processes=2,
                           process_id=int(sys.argv[2]))
assert jax.process_count() == 2
# rendezvous alone is not enough: some builds accept the handshake but
# reject any multiprocess computation ("Multiprocess computations
# aren't implemented on the CPU backend") — run one tiny SPMD step
mesh = Mesh(np.array(jax.devices()), ("d",))
sh = NamedSharding(mesh, P("d"))
arr = jax.make_array_from_process_local_data(
    sh, np.ones((jax.local_device_count(),), np.float32),
    (jax.device_count(),))
out = jax.jit(lambda a: a * 2, out_shardings=sh)(arr)
assert all(float(np.asarray(s.data)[0]) == 2.0
           for s in out.addressable_shards)
print("OK")
"""

_probe_result = None


def _two_proc_available() -> bool:
    """Cached preflight: can two localhost jax.distributed processes
    rendezvous AND execute a multiprocess computation here?  On hosts
    where they cannot, the full tests either burned their whole
    240-300 s communicate() timeout or failed after long partial runs —
    this 60 s probe lets them skip fast instead."""
    global _probe_result
    if _probe_result is None:
        coord = f"127.0.0.1:{_free_port()}"
        env = _scrubbed_env()
        procs = [subprocess.Popen(
            [sys.executable, "-c", _PROBE_SRC, coord, str(i)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
            for i in range(2)]
        ok = True
        for p in procs:
            try:
                out, _ = p.communicate(timeout=60)
                ok = ok and p.returncode == 0 and b"OK" in out
            except subprocess.TimeoutExpired:
                ok = False
        if not ok:
            for p in procs:
                p.kill()
        _probe_result = ok
    return _probe_result


def _require_two_proc():
    if not _two_proc_available():
        pytest.skip("2-process jax.distributed rendezvous unavailable "
                    "on this host (preflight probe failed/timed out)")


def test_two_process_distributed_matches_single_process(tmp_path):
    _require_two_proc()
    coord = f"127.0.0.1:{_free_port()}"
    outs = [str(tmp_path / f"proc{i}.json") for i in range(2)]
    env = _scrubbed_env()
    procs = [subprocess.Popen(
        [sys.executable, WORKER, coord, "2", str(i), outs[i]],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for i in range(2)]
    logs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("2-process run timed out")
        logs.append((p.returncode, out.decode()[-2000:],
                     err.decode()[-2000:]))
    assert all(rc == 0 for rc, _o, _e in logs), logs

    r0 = json.load(open(outs[0]))
    r1 = json.load(open(outs[1]))
    # disjoint halves of the partition space
    assert r0["range"] == [0, 8] and r1["range"] == [8, 16]

    # single-process reference over the SAME deterministic stream
    single = str(tmp_path / "single.json")
    p = subprocess.run(
        [sys.executable, WORKER, f"127.0.0.1:{_free_port()}", "1", "0",
         single], env=env, capture_output=True, timeout=240)
    assert p.returncode == 0, p.stderr.decode()[-2000:]
    rs = json.load(open(single))
    assert rs["range"] == [0, 16]

    for b in range(len(rs["blocks"])):
        b0, b1, bs = (r0["blocks"][b], r1["blocks"][b], rs["blocks"][b])
        # the fused stats psum is GLOBAL and identical on both hosts
        assert b0["stats"] == b1["stats"] == bs["stats"]
        # the two hosts' local matches partition the global set exactly
        assert b0["local_matches"] + b1["local_matches"] == \
            bs["stats"]["matches"] == bs["local_matches"]
        # per-partition counts line up with the single-process run
        assert b0["per_partition"] + b1["per_partition"] == \
            bs["per_partition"]
    # the workload actually matched something
    assert sum(b["stats"]["matches"] for b in rs["blocks"]) > 0


def test_single_device_absent_semantics(tmp_path):
    """The conftest mesh can mask single-device NFA bugs (round 4: a
    leading-absent TIMER re-arm chained confirmations only when mesh is
    None — the real-TPU flavor).  Run the leading-absent conformance
    shapes in a fresh 1-device CPU process."""
    code = """
import sys
sys.path.insert(0, {repo!r}); sys.path.insert(0, {tests!r})
import jax
jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 1
import test_ref_pattern_absent as t
t.test_absent_5_leading_quiet_then_match()
t.test_absent_6_leading_reset_by_arrival()
t.test_absent_8_leading_arrival_then_quick_e2()
t.test_absent_18_leading_rearmed_after_arrival()
t.test_absent_24_two_absents()
print("OK")
""".format(repo=os.path.dirname(HERE), tests=HERE)
    env = _scrubbed_env()
    p = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, timeout=240)
    assert p.returncode == 0 and b"OK" in p.stdout, \
        p.stderr.decode()[-2000:]


def test_two_process_siddhi_manager_engine(tmp_path):
    """Round 5 (VERDICT r4 #5): the PUBLIC SiddhiManager engine runs
    multi-host — each process builds the same @app:engine-eligible
    partitioned app, the multihost router (parallel/multihost.py) shards
    the key space, and the union of the processes' match payloads equals
    a single-process run.  The keyed device runtime (key→lane mapping,
    @Async flush barriers, pipelined ingest, slab growth past the
    starting lane count) executes with jax.process_count() == 2; the
    global stats ride one DCN all-reduce."""
    _require_two_proc()
    coord = f"127.0.0.1:{_free_port()}"
    outs = [str(tmp_path / f"eng{i}.json") for i in range(2)]
    env = _scrubbed_env()
    procs = [subprocess.Popen(
        [sys.executable, ENGINE_WORKER, coord, "2", str(i), outs[i]],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for i in range(2)]
    logs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("2-process engine run timed out")
        logs.append((p.returncode, out.decode()[-2000:],
                     err.decode()[-2000:]))
    assert all(rc == 0 for rc, _o, _e in logs), logs
    r0, r1 = (json.load(open(o)) for o in outs)

    single = str(tmp_path / "eng_single.json")
    p = subprocess.run(
        [sys.executable, ENGINE_WORKER, f"127.0.0.1:{_free_port()}", "1",
         "0", single], env=env, capture_output=True, timeout=300)
    assert p.returncode == 0, p.stderr.decode()[-2000:]
    rs = json.load(open(single))

    # both processes ran the planner-built KEYED device runtime
    assert r0["backend"] == r1["backend"] == rs["backend"] == "device"
    # the key space was actually split
    assert r0["ingested"] > 0 and r1["ingested"] > 0
    assert r0["ingested"] + r1["ingested"] == rs["ingested"]
    # cross-host payload parity: the union of local match payloads equals
    # the single-process run (multiset compare)
    union = sorted(map(tuple, r0["local_matches"] +
                       r1["local_matches"]))
    assert union == sorted(map(tuple, rs["local_matches"]))
    assert union, "workload must actually match"
    # the DCN-reduced stats are global and identical on both hosts
    assert r0["stats"] == r1["stats"]
    assert r0["stats"]["matches"] == len(union)
    assert r0["stats"]["ingested"] == rs["ingested"]
