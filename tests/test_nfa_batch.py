"""Fatter scan ticks (round 6): batched-vs-legacy NFA equivalence.

The ops/nfa restructuring (condition hoisting + B-event micro-batching,
gated by SIDDHI_TPU_NFA_BATCH) must be BIT-IDENTICAL in match semantics:
for every B in {1, 2, 4, 8} and every pattern family the kernel supports
(every/sequence, kleene counts, within expiry, absent deadlines, leading
min-0 kleene), randomized feeds produce identical matches, payloads and
`dropped` counters vs the B=1 legacy one-event-tick path — the same way
liveness pruning was proven in tests/test_plan_verify.py.

Plus the structural claims: the jaxpr scan length genuinely drops
T -> ceil(T/B), and the KernelProfiler records scan_ticks/batch_b.
Runs on the conftest-forced virtual 8-device CPU mesh.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from siddhi_tpu.ops.nfa import (BATCH_ENV, DEFAULT_BATCH_B,  # noqa: E402
                                build_block_step, resolve_batch_b)
from siddhi_tpu.plan.nfa_compiler import CompiledPatternNFA  # noqa: E402

STREAM = "define stream S (price float, kind int);\n"

#: the B x shape parity grid — one app per supported pattern family
SHAPES = {
    "every_within":
        "from every e1=S[kind == 0] -> "
        "e2=S[kind == 1 and price > e1.price] within 3 sec "
        "select e1.price as p1, e2.price as p2 insert into Out;",
    "count":
        # self e[last] ref: a capture-READING condition that must stay
        # in-scan while the other conditions hoist (mixed mode); the
        # not() keeps the EMPTY chain appendable (null compares false)
        "from every e1=S[kind == 0] -> "
        "e2=S[kind == 1 and not (price < e2[last].price)]<1:3> -> "
        "e3=S[kind == 0] "
        "select e1.price as p1, e3.price as p3 insert into Out;",
    "kleene0_within":
        "from e1=S[kind == 0] -> e2=S[kind == 2]<0:3> -> "
        "e3=S[kind == 1] within 4 sec "
        "select e1.price as p1, e2.price as p2, e3.price as p3 "
        "insert into Out;",
    "absent":
        "from every e1=S[kind == 0 and price > 60.0] -> "
        "not S[kind == 1 and price > e1.price] for 2 sec "
        "select e1.price as p1 insert into Out;",
    "sequence":
        "from every e1=S[kind == 0], e2=S[kind == 1] "
        "select e1.price as p1, e2.price as p2 insert into Out;",
}


def _feed(n=220, seed=0, parts=2):
    rng = np.random.default_rng(seed)
    pids = rng.integers(0, parts, n).astype(np.int64)
    cols = {"price": rng.uniform(0, 100, n).astype(np.float32),
            "kind": rng.integers(0, 3, n).astype(np.float32)}
    ts = 1_000_000 + np.cumsum(rng.integers(0, 900, n)).astype(np.int64)
    return pids, cols, ts


def _run(nfa, feed, timer_to=None):
    pids, cols, ts = feed
    out = list(nfa.process_events(pids, cols, ts))
    dropped = [int(nfa.last_dropped_total)]
    if timer_to is not None:
        out += list(nfa.process_timer(timer_to))
        dropped.append(int(nfa.last_dropped_total))
    return out, dropped


_LEGACY_CACHE = {}


def _legacy(shape):
    """One B=1 compile per shape, shared across the B parametrization."""
    if shape not in _LEGACY_CACHE:
        _LEGACY_CACHE[shape] = CompiledPatternNFA(
            STREAM + SHAPES[shape], n_partitions=2, n_slots=4,
            mesh=None, batch_b=1)
    return _LEGACY_CACHE[shape]


@pytest.mark.parametrize("shape", sorted(SHAPES))
@pytest.mark.parametrize("B", [1, 2, 4, 8])
def test_batched_matches_legacy(shape, B):
    batched = CompiledPatternNFA(STREAM + SHAPES[shape], n_partitions=2,
                                 n_slots=4, mesh=None, batch_b=B)
    legacy = _legacy(shape)
    assert batched.spec.batch_b == B and legacy.spec.batch_b == 1
    timer_to = 1_000_000 + 600_000 if shape == "absent" else None
    total = 0
    for seed in (0, 1, 2):
        feed = _feed(seed=seed)
        got, gdrop = _run(batched, feed, timer_to)
        want, wdrop = _run(legacy, feed, timer_to)
        assert got == want, \
            f"{shape} B={B} seed={seed}: batched diverged " \
            f"({len(got)} vs {len(want)} matches)"
        assert gdrop == wdrop, \
            f"{shape} B={B} seed={seed}: dropped counters diverged"
        total += len(want)
        # fresh state per seed: both kernels rebuild their carries
        from siddhi_tpu.ops.nfa import make_carry
        batched.carry = batched._place_carry(
            make_carry(batched.spec, batched.n_partitions))
        batched.base_ts = None
        legacy.carry = legacy._place_carry(
            make_carry(legacy.spec, legacy.n_partitions))
        legacy.base_ts = None
    assert total > 0, f"{shape}: degenerate grid cell (0 matches)"


def test_batched_matches_legacy_on_mesh():
    """Default auto mesh = the virtual 8-device CPU mesh: the sharded
    engine step runs the same restructured kernel."""
    app = STREAM + SHAPES["every_within"]
    a = CompiledPatternNFA(app, n_partitions=8, batch_b=4)
    b = CompiledPatternNFA(app, n_partitions=8, batch_b=1)
    assert a.mesh is not None and a.mesh.devices.size == 8
    feed = _feed(n=300, parts=8)
    got, _ = _run(a, feed)
    want, _ = _run(b, feed)
    assert got == want and len(want) > 0


def _scan_lengths(jaxpr, acc):
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            acc.add(int(eqn.params.get("length", -1)))
        for p in eqn.params.values():
            sub = getattr(p, "jaxpr", None)
            if sub is not None:
                _scan_lengths(sub, acc)
            elif isinstance(p, (list, tuple)):
                for x in p:
                    sub = getattr(x, "jaxpr", None)
                    if sub is not None:
                        _scan_lengths(sub, acc)
    return acc


def test_jaxpr_tick_count_drops():
    """The sequential chain REALLY shrinks: with B=4 and T=10 events the
    outer scan runs ceil(10/4)=3 ticks (a fully-unrolled length-4 inner
    scan per tick); the legacy jaxpr scans all 10."""
    import jax
    nfa = CompiledPatternNFA(STREAM + SHAPES["every_within"],
                             n_partitions=2, mesh=None, batch_b=4)
    T = 10
    block = {a: np.zeros((2, T), np.float32)
             for a in nfa.spec.attr_names}
    block["__ts"] = np.arange(T, dtype=np.int32)[None].repeat(2, 0)
    block["__stream"] = np.zeros((2, T), np.int32)
    block["__valid"] = np.ones((2, T), bool)
    batched = jax.make_jaxpr(build_block_step(nfa.spec))(nfa.carry, block)
    lens = _scan_lengths(batched.jaxpr, set())
    assert 3 in lens, f"expected a ceil(T/B)=3-tick scan, got {lens}"
    assert T not in lens, f"a T={T}-tick chain survived batching: {lens}"
    legacy = jax.make_jaxpr(
        build_block_step(nfa.spec, batch_b=1))(nfa.carry, block)
    lens1 = _scan_lengths(legacy.jaxpr, set())
    assert T in lens1


def test_profiler_records_scan_ticks_and_batch_b():
    from siddhi_tpu.core.profiling import profiler
    prof = profiler()
    was = prof.enabled
    prof.enable()
    try:
        prof.stats("nfa.step").scan_ticks = 0
        nfa = CompiledPatternNFA(STREAM + SHAPES["every_within"],
                                 n_partitions=2, mesh=None, batch_b=4)
        pids = np.zeros(10, np.int64)      # one lane -> T = 10
        cols = {"price": np.linspace(1, 99, 10).astype(np.float32),
                "kind": np.tile([0.0, 1.0], 5).astype(np.float32)}
        ts = 1_000_000 + np.arange(10, dtype=np.int64) * 100
        nfa.process_events(pids, cols, ts)
        st = prof.snapshot()["nfa.step"]
        assert st["batch_b"] == 4
        assert st["scan_ticks"] == -(-10 // 4)      # ceil(T/B) = 3
        assert "scan_ticks" in st and "batch_b" in st
    finally:
        if not was:
            prof.disable()


def test_env_kill_switch(monkeypatch):
    monkeypatch.setenv(BATCH_ENV, "1")
    assert resolve_batch_b() == 1
    nfa = CompiledPatternNFA(STREAM + SHAPES["sequence"],
                             n_partitions=2, mesh=None)
    assert nfa.batch_b == 1 and nfa.spec.batch_b == 1
    monkeypatch.delenv(BATCH_ENV)
    assert resolve_batch_b() == DEFAULT_BATCH_B
    assert resolve_batch_b(8) == 8
    monkeypatch.setenv(BATCH_ENV, "garbage")
    assert resolve_batch_b() == DEFAULT_BATCH_B


def test_cond_free_classification():
    """Capture-free conditions hoist; capture-reading ones must not."""
    nfa = CompiledPatternNFA(STREAM + SHAPES["every_within"],
                             n_partitions=2, mesh=None, batch_b=4)
    # e1: event-only -> free; e2 reads e1.price -> pinned in-scan
    assert nfa.spec.cond_free == (True, False)
    k = CompiledPatternNFA(STREAM + SHAPES["count"], n_partitions=2,
                           mesh=None, batch_b=4)
    # e2's self e[last] ref reads its own capture bank -> not free
    free = dict(zip(("e1", "e2", "e3"), k.spec.cond_free))
    assert free["e1"] and not free["e2"] and free["e3"]
