"""Regression tests for the round-4 advisor findings (ADVICE.md):

1. LONG payloads beyond ±2^62 (or whose hi word collides with the null
   sentinel) must raise a data error on the device window path, not
   silently wrap / decode as null.
2. String ORDER comparisons follow Java String.compareTo (UTF-16 code
   unit order), which diverges from Python/numpy code-point order when
   supplementary-plane characters are present — device and host must
   agree with each other AND with the reference order.
3. Concurrent StreamJunction.flush() calls must not interleave barrier
   copies across workers (each used to stall ~600 s); persist() from a
   junction worker's own callback must not deadlock.
"""
import threading

import numpy as np
import pytest

from siddhi_tpu import (InMemoryPersistenceStore, QueryCallback,
                        SiddhiManager, StreamCallback)

CSE = "define stream cse (symbol string, price float, volume long);\n"


def _collect(rt, qname="q"):
    log = []
    rt.add_callback(qname, QueryCallback(
        lambda ts, cur, exp: log.extend(
            tuple(e.data) for e in (cur or []))))
    return log


# ---------------------------------------------------------------- LONG guard

@pytest.mark.parametrize("bad", [2 ** 62, -(2 ** 62), 2 ** 63 - 1,
                                 -(2 ** 62) + (2 ** 31) - 1])
def test_dwin_long_out_of_range_raises(bad):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "@app:playback @app:engine('device') " + CSE +
        "@info(name='q') from cse#window.length(3) "
        "select symbol, volume insert into out;")
    errors = []
    rt.app_ctx.exception_listeners.append(errors.append)
    log = _collect(rt)
    rt.start()
    h = rt.get_input_handler("cse")
    h.send_batch({"symbol": np.asarray(["A"], object),
                  "price": np.asarray([1.0], np.float32),
                  "volume": np.asarray([bad], np.int64)},
                 timestamps=np.asarray([1000], np.int64))
    rt.shutdown()
    # the chunk is a data error: dropped at the @OnError boundary, never
    # emitted with a wrapped/nulled payload
    assert not log
    assert errors, "out-of-range LONG must surface a runtime data error"
    assert "LONG" in str(errors[0])


def test_dwin_long_pm_2_61_exact():
    """Values just inside the guard round-trip exactly."""
    # exact range is [-2^62 + 2^31, 2^62): hi must fit int32 and miss
    # the INT_NONE sentinel (hi == -2^31)
    good = [2 ** 61, -(2 ** 61), 2 ** 62 - 1, -(2 ** 62) + 2 ** 31]
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "@app:playback @app:engine('device') " + CSE +
        "@info(name='q') from cse#window.lengthBatch(4) "
        "select symbol, volume insert into out;")
    log = _collect(rt)
    rt.start()
    h = rt.get_input_handler("cse")
    h.send_batch({"symbol": np.asarray(list("ABCD"), object),
                  "price": np.zeros(4, np.float32),
                  "volume": np.asarray(good, np.int64)},
                 timestamps=np.arange(1000, 1004, dtype=np.int64))
    rt.shutdown()
    assert [row[1] for row in log] == good


# ------------------------------------------------------- UTF-16 string order

SUPP = "\U00010000"          # surrogates D800 DC00 — UTF-16 < U+E000
BMP = "\ue000"               # code point < U+10000


@pytest.mark.parametrize("engine", ["host", "device"])
def test_string_order_utf16_code_units(engine):
    """Java: SUPP < BMP (surrogate 0xD800 < 0xE000); Python code points
    say the opposite.  Both backends must produce the Java order."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        f"@app:playback @app:engine('{engine}') " + CSE +
        f"@info(name='q') from cse[symbol > '{BMP}'] "
        "select symbol insert into out;")
    log = _collect(rt)
    rt.start()
    h = rt.get_input_handler("cse")
    h.send_batch({"symbol": np.asarray([SUPP, BMP, "\ufffd", "a"], object),
                  "price": np.zeros(4, np.float32),
                  "volume": np.arange(4, dtype=np.int64)},
                 timestamps=np.arange(1000, 1004, dtype=np.int64))
    rt.shutdown()
    # 'a' (0x61) < U+E000; U+FFFD > U+E000 in both orders.  SUPP must
    # NOT match (UTF-16 order), though code-point order says it would.
    assert sorted(r[0] for r in log) == ["\ufffd"]


@pytest.mark.parametrize("engine", ["host", "device"])
def test_string_var_vs_var_utf16(engine):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        f"@app:playback @app:engine('{engine}') " +
        "define stream s (a string, b string);\n"
        "@info(name='q') from s[a < b] select a, b insert into out;")
    log = _collect(rt)
    rt.start()
    h = rt.get_input_handler("s")
    h.send_batch({"a": np.asarray([SUPP, BMP], object),
                  "b": np.asarray([BMP, SUPP], object)},
                 timestamps=np.asarray([1000, 1001], np.int64))
    rt.shutdown()
    # UTF-16: SUPP < BMP, so only the first row matches
    assert [r for r in log] == [(SUPP, BMP)]


@pytest.mark.parametrize("engine", ["host", "device"])
def test_pattern_string_order_utf16(engine):
    """Device NFA path (derived_lane): a pattern whose string ORDER
    predicate involves a supplementary-plane constant must follow UTF-16
    code-unit order, matching the host oracle."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        f"@app:playback @app:engine('{engine}') " + CSE +
        f"@info(name='q') from e1=cse[symbol > '{BMP}'] -> "
        "e2=cse[price > 0.0] "
        "select e1.symbol as s1, e2.symbol as s2 insert into out;")
    log = _collect(rt)
    rt.start()
    h = rt.get_input_handler("cse")
    # SUPP must NOT arm e1 (UTF-16: SUPP < BMP); U+FFFD must
    h.send_batch({"symbol": np.asarray([SUPP, "\ufffd", "x"], object),
                  "price": np.asarray([0.0, 0.0, 1.0], np.float32),
                  "volume": np.arange(3, dtype=np.int64)},
                 timestamps=np.asarray([1000, 1001, 1002], np.int64))
    rt.shutdown()
    assert log == [("\ufffd", "x")]


@pytest.mark.parametrize("engine", ["host", "device"])
def test_duplicate_select_names_rejected(engine):
    """Reference SelectorParser throws DuplicateAttributeException;
    columnar output would silently overwrite the earlier column."""
    from siddhi_tpu.utils.errors import SiddhiAppCreationError
    m = SiddhiManager()
    with pytest.raises(SiddhiAppCreationError, match="[Dd]uplicate"):
        m.create_siddhi_app_runtime(
            f"@app:playback @app:engine('{engine}') " + CSE +
            "@info(name='q') from e1=cse[price > 0.0] -> "
            "e2=cse[price > 1.0] "
            "select e1.symbol, e2.symbol insert into out;")


# ------------------------------------------------------------ flush hygiene

def test_concurrent_flush_no_stall():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream s (v int);\n"
        "@async(buffer.size='64', workers='2')\n"
        "define stream inner (v int);\n"
        "@info(name='q') from s select v insert into inner;\n"
        "@info(name='q2') from inner select v insert into out;")
    rt.start()
    j = rt.junctions["inner"]
    errs = []

    def hammer():
        try:
            for _ in range(25):
                j.flush()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    alive = [t for t in threads if t.is_alive()]
    rt.shutdown()
    assert not errs and not alive, (errs, alive)


def test_external_persist_races_worker_persist():
    """An external persist() holding the snapshot lock must not deadlock
    with a persist() issued from a junction worker callback (the worker
    would never consume its flush-barrier copy while blocked on the
    lock)."""
    m = SiddhiManager()
    m.set_persistence_store(InMemoryPersistenceStore())
    rt = m.create_siddhi_app_runtime(
        "@async(workers='1')\n"
        "define stream s (v int);\n"
        "@info(name='q') from s select v insert into out;")
    errs = []
    rt.app_ctx.exception_listeners.append(errs.append)
    done = threading.Event()

    def cb(events):
        rt.persist()
        done.set()

    rt.add_callback("out", StreamCallback(cb))
    rt.start()
    ext_done = threading.Event()

    def external():
        for _ in range(10):
            rt.persist()
        ext_done.set()

    t = threading.Thread(target=external)
    t.start()
    rt.get_input_handler("s").send([1])
    assert done.wait(timeout=60.0), "worker-callback persist deadlocked"
    assert ext_done.wait(timeout=60.0), "external persist deadlocked"
    t.join(timeout=10.0)
    rt.shutdown()
    # the junction flush must not log AttributeErrors for synchronous
    # device runtimes that have no pipelined work to retire
    assert not errs, errs


def test_engine_device_rejects_host_only_window_projection():
    """Sort windows gained a device kernel (plan/dwin_compiler
    DEVICE_KINDS, round 5), so engine('device') now routes the
    projection instead of rejecting it — assert the device plan.  The
    strict no-silent-host-fallback contract still holds for window
    kinds without a device kernel (window.frequent)."""
    from siddhi_tpu.utils.errors import SiddhiAppCreationError
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "@app:engine('device') define stream s (v int);\n"
        "@info(name='q') from s#window.sort(5, v) "
        "select v insert into out;")
    try:
        qr = rt.query_runtimes["q"]
        assert qr.backend == "device"
        assert "dwin" in (qr.backend_reason or "")
    finally:
        rt.shutdown()
    with pytest.raises(SiddhiAppCreationError):
        m.create_siddhi_app_runtime(
            "@app:engine('device') define stream s2 (v int);\n"
            "@info(name='q2') from s2#window.frequent(3) "
            "select v insert into out2;")


def test_persist_from_worker_callback_no_deadlock():
    m = SiddhiManager()
    m.set_persistence_store(InMemoryPersistenceStore())
    rt = m.create_siddhi_app_runtime(
        "@async(workers='1')\n"
        "define stream s (v int);\n"
        "@info(name='q') from s select v insert into out;")
    done = threading.Event()

    def cb(events):
        rt.persist()          # from the junction worker thread itself
        done.set()

    rt.add_callback("out", StreamCallback(cb))
    rt.start()
    rt.get_input_handler("s").send([1])
    assert done.wait(timeout=60.0), \
        "persist() from a worker callback deadlocked"
    rt.shutdown()
