"""On-device NFA/window state telemetry
(@app:statistics(telemetry='true'), observability PR).

Contract: the telemetry leaf is an int32 side-channel accumulated from
masks the transition logic ALREADY computes (ops/nfa.py, ops/dwin.py) —
matches, payloads and dropped counters must be BIT-IDENTICAL with
telemetry on vs off, for every batch_b, for stacked pattern banks and on
the conftest-forced virtual 8-device CPU mesh.  The static cost model
stays byte-exact with the telem leaf counted (analysis/cost_model.py),
and the series surface on /metrics, rt.statistics and the flight ring.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from siddhi_tpu import QueryCallback, SiddhiManager, StreamCallback  # noqa: E402
from siddhi_tpu.analysis.cost_model import nfa_state_bytes  # noqa: E402
from siddhi_tpu.analysis.plan_ir import automaton_ir_from_nfa  # noqa: E402
from siddhi_tpu.core.statistics import (DeviceTelemetry,  # noqa: E402
                                        prometheus_text)
from siddhi_tpu.ops.nfa import make_carry  # noqa: E402
from siddhi_tpu.plan.nfa_compiler import (CompiledPatternBank,  # noqa: E402
                                          CompiledPatternNFA)

STREAM = "define stream S (price float, kind int);\n"

SHAPES = {
    "every_within":
        "from every e1=S[kind == 0] -> "
        "e2=S[kind == 1 and price > e1.price] within 3 sec "
        "select e1.price as p1, e2.price as p2 insert into Out;",
    "count":
        "from every e1=S[kind == 0] -> e2=S[kind == 1]<1:3> -> "
        "e3=S[kind == 0] "
        "select e1.price as p1, e3.price as p3 insert into Out;",
}


def _feed(n=200, seed=0, parts=2):
    rng = np.random.default_rng(seed)
    pids = rng.integers(0, parts, n).astype(np.int64)
    cols = {"price": rng.uniform(0, 100, n).astype(np.float32),
            "kind": rng.integers(0, 3, n).astype(np.float32)}
    ts = 1_000_000 + np.cumsum(rng.integers(0, 900, n)).astype(np.int64)
    return pids, cols, ts


def _run(nfa, feed):
    pids, cols, ts = feed
    out = list(nfa.process_events(pids, cols, ts))
    return out, int(nfa.last_dropped_total)


def _reset(nfa):
    nfa.carry = nfa._place_carry(make_carry(nfa.spec, nfa.n_partitions))
    nfa.base_ts = None


# ------------------------------------------------------------ bit identity

@pytest.mark.parametrize("shape", sorted(SHAPES))
@pytest.mark.parametrize("B", [1, 4])
def test_matches_bit_identical_with_telemetry(shape, B):
    """Randomized feeds: telemetry-on produces the exact same matches,
    payloads and dropped counters as telemetry-off, B in {1, 4}."""
    app = STREAM + SHAPES[shape]
    plain = CompiledPatternNFA(app, n_partitions=2, n_slots=4, mesh=None,
                               batch_b=B)
    telem = CompiledPatternNFA(app, n_partitions=2, n_slots=4, mesh=None,
                               batch_b=B, telemetry=True)
    assert not plain.spec.telemetry and telem.spec.telemetry
    assert "telem" not in plain.carry and "telem" in telem.carry
    total = 0
    for seed in (0, 1):
        feed = _feed(seed=seed)
        want, wdrop = _run(plain, feed)
        got, gdrop = _run(telem, feed)
        assert got == want, f"{shape} B={B} seed={seed}: diverged"
        assert gdrop == wdrop
        total += len(want)
        _reset(plain)
        _reset(telem)
    assert total > 0, f"{shape}: degenerate cell (0 matches)"


def test_stacked_bank_bit_identical_with_telemetry():
    """Stacked C>1 pattern-bank super-dispatch: the telem leaf rides the
    generic [C, N, P, ...] broadcast without perturbing counts/rings."""
    P = 8
    stream = "define stream S (partition int, price float, kind int);\n"
    apps = [stream +
            f"from every e1=S[kind == 0 and price > {thr}] -> "
            "e2=S[kind == 1 and price > e1.price] within 9 sec "
            "select e1.price as p1, e2.price as p2 insert into Out;"
            for thr in (10.0, 40.0, 60.0, 90.0)]

    def bank(telemetry):
        b = CompiledPatternBank(apps, n_partitions=P, n_slots=4,
                                pattern_chunk=2, ring=4, stack=True,
                                telemetry=telemetry)
        b.base_ts = 1_000_000
        return b

    def feed(b, seed):
        from siddhi_tpu.ops.nfa import pack_blocks
        rng = np.random.default_rng(seed)
        counts = np.zeros(b.n_patterns, np.int64)
        rows = []
        t0 = 1_000_000
        for _ in range(3):
            n = P * 10
            pids = np.tile(np.arange(P, dtype=np.int64), 10)
            j = np.repeat(np.arange(10, dtype=np.int64), P)
            ts = t0 + j * 1_000 + pids * (1_000 // P)
            cols = {"partition": pids.astype(np.float32),
                    "price": rng.uniform(0, 100, n).astype(np.float32),
                    "kind": rng.integers(0, 2, n).astype(np.float32)}
            block = pack_blocks(pids, cols, ts, np.zeros(n, np.int32), P,
                                base_ts=1_000_000)
            t0 += 10 * 1_000
            out = b.process_block(block)
            counts += np.asarray(out[0], np.int64)
            dec = b.decode_ring(*out[1:])
            rows.append(sorted(zip(*(np.asarray(v).tolist()
                                     for v in dec.values()))))
        return counts, rows, b.total_dropped()

    plain, telem = bank(False), bank(True)
    assert telem.stacked and telem.n_chunks == 2
    assert "telem" in telem.nfa.carry
    wc, wr, wd = feed(plain, seed=3)
    gc, gr, gd = feed(telem, seed=3)
    assert (gc == wc).all() and gr == wr and gd == wd
    assert wc.sum() > 0


def test_mesh_engine_bit_identical_with_telemetry():
    """The virtual 8-device mesh path: the telem leaf shards on its
    leading partition dim like every other carry leaf (parallel/mesh
    tree-maps lead_axis_sharding over make_carry)."""
    app = STREAM + SHAPES["every_within"]
    telem = CompiledPatternNFA(app, n_partitions=8, telemetry=True)
    plain = CompiledPatternNFA(app, n_partitions=8)
    assert telem.mesh is not None and telem.mesh.devices.size == 8
    feed = _feed(n=280, parts=8, seed=5)
    got, _ = _run(telem, feed)
    want, _ = _run(plain, feed)
    assert got == want and len(want) > 0
    tel = telem.last_telemetry
    assert tel is not None and tel.shape == (8, 3 * 2 + 1)


# ------------------------------------------------------------ semantics

def test_telemetry_counters_are_meaningful():
    """occupancy counts live slots per state, gate passes at the accept
    gate equal completed matches for a 2-state pattern, and within
    expiry shows up in the drops counter."""
    app = STREAM + SHAPES["every_within"]
    nfa = CompiledPatternNFA(app, n_partitions=2, n_slots=4, mesh=None,
                             telemetry=True)
    feed = _feed(n=200, seed=0)
    out, _ = _run(nfa, feed)
    tel = np.asarray(nfa.last_telemetry).sum(axis=0)
    S = len(nfa.spec.units)
    occ, gate_pass = tel[:S], tel[S:2 * S]
    within_drops = int(tel[3 * S])
    assert gate_pass[1] == len(out) > 0     # e2 gate fires exactly per match
    assert (occ >= 0).all() and occ.sum() <= 2 * 4
    assert within_drops > 0                 # 3 s window over a 200-event feed


# ------------------------------------------------------- cost model / IR

def test_cost_model_byte_exact_with_telemetry():
    app = STREAM + ("from every e1=S[kind == 0] -> "
                    "e2=S[kind == 1 and price > e1.price] within 10 sec "
                    "select e1.price as p1 insert into Out;")
    nfa = CompiledPatternNFA(app, n_partitions=3, mesh=None, telemetry=True)
    ir = automaton_ir_from_nfa(nfa, "q")
    assert ir.telemetry
    predicted = nfa_state_bytes(ir)
    assert predicted["telem"] == 3 * (3 * len(ir.states) + 1) * 4
    actual = sum(int(np.asarray(v).nbytes) for v in nfa.carry.values())
    assert sum(predicted.values()) == actual
    # defaults stay off — goldens and PC001 accounting unchanged
    off = automaton_ir_from_nfa(
        CompiledPatternNFA(app, n_partitions=3, mesh=None), "q")
    assert not off.telemetry and "telem" not in nfa_state_bytes(off)


def test_plan_ir_dump_carries_telem_flag():
    from siddhi_tpu.analysis.plan_ir import PlanIR
    app = STREAM + SHAPES["every_within"]
    nfa = CompiledPatternNFA(app, n_partitions=2, mesh=None, telemetry=True)
    plan = PlanIR(app_name="t",
                  automata=[automaton_ir_from_nfa(nfa, "q")])
    dump = plan.dump()
    assert "telem" in dump.split("flags=[", 1)[1].split("]", 1)[0]
    assert plan.as_dict()["automata"][0]["telemetry"] is True


# ------------------------------------------------------- runtime surface

def test_runtime_snapshot_metrics_and_windows():
    """Full engine path: @app:statistics(telemetry='true') populates
    rt.statistics['telemetry'], the siddhi_nfa_*/siddhi_dwin_* series
    and the flight ring; window fill/eviction counters are exact."""
    from siddhi_tpu.core.flight import flight
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:statistics(reporter='console', interval='300',
                        telemetry='true')
        define stream S (sym string, price float);
        define stream cse (symbol string, price float, volume long);
        @info(name='p')
        from every e1=S[price > 10.0] -> e2=S[price > e1.price]
        select e1.price as p1, e2.price as p2 insert into Out;
        @info(name='w') from cse#window.length(5)
        select symbol, price, volume insert all events into wout;
    """)
    assert rt.app_ctx.telemetry_enabled and rt.device_telemetry is not None
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.add_callback("w", QueryCallback(lambda *a: None))
    rt.start()
    h = rt.get_input_handler("S")
    rng = np.random.default_rng(0)
    for _ in range(40):
        h.send(["A", float(rng.uniform(5, 30))])
    n = 30
    rt.get_input_handler("cse").send_batch(
        {"symbol": np.asarray(["A"] * n, object),
         "price": rng.uniform(0, 10, n).astype(np.float32),
         "volume": np.arange(n, dtype=np.int64)},
        timestamps=1_000_000 + np.arange(n, dtype=np.int64) * 250)
    rt.flush()
    snap = rt.statistics["telemetry"]
    text = prometheus_text([rt.app_ctx.statistics_manager],
                           telemetry=[rt.device_telemetry])
    ring = flight().ring()
    rt.shutdown()

    q = snap["nfa"]["p"]
    assert sum(q["gate_pass"]) == len(got) > 0
    assert len(q["occupancy"]) == 2
    w = snap["windows"]["cse"]
    assert w["fill"] == 5 and w["evictions"] == n - 5 and w["overflow"] == 0

    assert 'siddhi_nfa_state_occupancy{' in text
    assert "# TYPE siddhi_nfa_gate_pass_total counter" in text
    assert 'siddhi_dwin_ring_fill{' in text and '",window="cse"' in text
    # the flight ring saw per-block telemetry rows from the pattern path
    assert any("telemetry" in r for r in ring if r.get("stream") == "S")


def test_device_telemetry_holder_is_standalone():
    dt = DeviceTelemetry("a")
    dt.update_nfa("q", np.arange(7, dtype=np.int32).reshape(1, 7), 2,
                  ["simple", "simple"])
    dt.update_window("w", np.asarray([3, 9, 1], np.int32))
    snap = dt.snapshot()
    assert snap["nfa"]["q"]["within_drops"] == 6
    assert snap["windows"]["w"] == {"fill": 3, "evictions": 9,
                                    "overflow": 1}
    lines = dt.prometheus_lines()
    assert any(ln.startswith("siddhi_nfa_state_occupancy") for ln in lines)
    assert any(ln.startswith("siddhi_dwin_overflow_total") for ln in lines)
