"""Join and partition conformance tests modeled on the reference suites
(query/join/JoinTestCase.java, query/join/OuterJoinTestCase.java,
query/partition/PartitionTestCase1.java, PatternPartitionTestCase.java,
SequencePartitionTestCase.java).
"""
from ref_harness import run_query

CSE_TW = """
define stream cse (symbol string, price float, volume int);
define stream twitter (user string, tweet string, company string);
"""
Q = "@info(name = 'query1') "


def test_join_time_windows_on_condition():
    run_query(CSE_TW + Q + """
        from cse#window.time(1 sec) join twitter#window.time(1 sec)
            on cse.symbol == twitter.company
        select cse.symbol as symbol, twitter.tweet, cse.price
        insert into out;""",
        [("cse", ["WSO2", 55.6, 100], 1000),
         ("twitter", ["User1", "Hello World", "WSO2"], 1100),
         ("cse", ["IBM", 75.6, 100], 1200),
         ("cse", ["WSO2", 57.6, 100], 1700)],
        [("WSO2", "Hello World", 55.6), ("WSO2", "Hello World", 57.6)],
        playback=True, advance_to=4000)


def test_join_with_aliases():
    run_query(CSE_TW + Q + """
        from cse#window.time(1 sec) as a join twitter#window.time(1 sec) as b
            on a.symbol == b.company
        select a.symbol as symbol, b.tweet, a.price
        insert into out;""",
        [("cse", ["WSO2", 55.6, 100], 1000),
         ("twitter", ["User1", "Hello World", "WSO2"], 1100),
         ("cse", ["IBM", 75.6, 100], 1200),
         ("cse", ["WSO2", 57.6, 100], 1700)],
        [("WSO2", "Hello World", 55.6), ("WSO2", "Hello World", 57.6)],
        playback=True, advance_to=4000)


def test_self_join():
    run_query("""
        define stream cse (symbol string, price float, volume int);
        @info(name = 'query1')
        from cse#window.time(500 milliseconds) as a
             join cse#window.time(500 milliseconds) as b
            on a.symbol == b.symbol
        select a.symbol as symbol, a.price as priceA, b.price as priceB
        insert into out;""",
        [("cse", ["IBM", 75.6, 100], 1000),
         ("cse", ["WSO2", 57.6, 100], 1010)],
        [("IBM", 75.6, 75.6), ("WSO2", 57.6, 57.6)],
        playback=True, advance_to=3000)


def test_join_length_windows():
    run_query(CSE_TW + Q + """
        from cse#window.length(1) join twitter#window.length(1)
            on cse.symbol == twitter.company
        select cse.symbol as symbol, twitter.tweet, cse.price
        insert into out;""",
        [("cse", ["WSO2", 55.6, 100]),
         ("twitter", ["User1", "Hello World", "WSO2"]),
         ("cse", ["IBM", 75.6, 100]),
         ("cse", ["WSO2", 57.6, 100])],
        [("WSO2", "Hello World", 55.6), ("WSO2", "Hello World", 57.6)])


def test_join_unidirectional():
    # only the left side triggers output
    run_query(CSE_TW + Q + """
        from cse#window.length(2) unidirectional
             join twitter#window.length(2)
            on cse.symbol == twitter.company
        select cse.symbol as symbol, twitter.tweet
        insert into out;""",
        [("twitter", ["User1", "t1", "WSO2"]),
         ("cse", ["WSO2", 55.6, 100]),
         ("twitter", ["User2", "t2", "WSO2"])],
        [("WSO2", "t1")])


def test_left_outer_join_unmatched_left():
    run_query(CSE_TW + Q + """
        from cse#window.length(2) left outer join twitter#window.length(2)
            on cse.symbol == twitter.company
        select cse.symbol as symbol, twitter.tweet
        insert into out;""",
        [("cse", ["WSO2", 55.6, 100]),
         ("twitter", ["User1", "t1", "WSO2"]),
         ("cse", ["IBM", 75.6, 100])],
        [("WSO2", None), ("WSO2", "t1"), ("IBM", None)])


def test_right_outer_join_unmatched_right():
    run_query(CSE_TW + Q + """
        from cse#window.length(2) right outer join twitter#window.length(2)
            on cse.symbol == twitter.company
        select twitter.tweet, cse.symbol as symbol
        insert into out;""",
        [("twitter", ["User1", "t1", "GOOG"]),
         ("cse", ["WSO2", 55.6, 100])],
        [("t1", None)])


def test_full_outer_join():
    run_query(CSE_TW + Q + """
        from cse#window.length(2) full outer join twitter#window.length(2)
            on cse.symbol == twitter.company
        select cse.symbol as symbol, twitter.tweet
        insert into out;""",
        [("cse", ["WSO2", 55.6, 100]),
         ("twitter", ["User1", "t1", "GOOG"])],
        [("WSO2", None), (None, "t1")])


def test_join_stream_with_table():
    run_query("""
        define stream S (symbol string, qty int);
        define table T (symbol string, price float);
        @info(name='insQ') from S[qty < 0] select symbol, 1.0f as price
            insert into T;
        @info(name = 'query1')
        from S[qty > 0] join T on S.symbol == T.symbol
        select S.symbol as symbol, T.price, S.qty
        insert into out;""",
        [("S", ["WSO2", -1]), ("S", ["WSO2", 5])],
        [("WSO2", 1.0, 5)])


# ------------------------------------------------------------ partitions

def test_partition_isolated_sums():
    run_query("""
        define stream cse (symbol string, price float, volume int);
        partition with (symbol of cse)
        begin
            @info(name = 'query1')
            from cse select symbol, sum(price) as total insert into out;
        end;""",
        [("cse", ["WSO2", 10.0, 1]), ("cse", ["IBM", 20.0, 1]),
         ("cse", ["WSO2", 30.0, 1]), ("cse", ["IBM", 40.0, 1])],
        [("WSO2", 10.0), ("IBM", 20.0), ("WSO2", 40.0), ("IBM", 60.0)])


def test_partition_window_per_key():
    run_query("""
        define stream cse (symbol string, price float, volume int);
        partition with (symbol of cse)
        begin
            @info(name = 'query1')
            from cse#window.length(2) select symbol, sum(volume) as t
            insert into out;
        end;""",
        [("cse", ["A", 1.0, 10]), ("cse", ["B", 1.0, 20]),
         ("cse", ["A", 1.0, 30]), ("cse", ["A", 1.0, 50])],
        [("A", 10), ("B", 20), ("A", 40), ("A", 80)])


def test_partition_range():
    run_query("""
        define stream cse (symbol string, price float, volume int);
        partition with (price < 100 as 'cheap' or price >= 100 as 'pricey'
                        of cse)
        begin
            @info(name = 'query1')
            from cse select symbol, count() as n insert into out;
        end;""",
        [("cse", ["A", 50.0, 1]), ("cse", ["B", 150.0, 1]),
         ("cse", ["C", 60.0, 1])],
        [("A", 1), ("B", 1), ("C", 2)])


def test_pattern_partition_per_key():
    # reference PatternPartitionTestCase: partials never cross keys
    run_query("""
        define stream A (symbol string, v float);
        partition with (symbol of A)
        begin
            @info(name = 'query1')
            from every e1=A[v > 10.0] -> e2=A[v > e1.v]
            select e1.v as v1, e2.v as v2 insert into out;
        end;""",
        [("A", ["X", 20.0]), ("A", ["Y", 30.0]), ("A", ["X", 25.0]),
         ("A", ["Y", 5.0]), ("A", ["Y", 35.0])],
        [(20.0, 25.0), (30.0, 35.0)])


def test_sequence_partition_per_key():
    # reference SequencePartitionTestCase: contiguity is per key
    run_query("""
        define stream A (symbol string, v float);
        partition with (symbol of A)
        begin
            @info(name = 'query1')
            from every e1=A[v > 10.0], e2=A[v > e1.v]
            select e1.v as v1, e2.v as v2 insert into out;
        end;""",
        [("A", ["X", 20.0]), ("A", ["Y", 1.0]), ("A", ["X", 25.0]),
         ("A", ["Y", 30.0]), ("A", ["Y", 35.0])],
        [(20.0, 25.0), (30.0, 35.0)])


def test_partition_inner_stream():
    run_query("""
        define stream cse (symbol string, price float, volume int);
        partition with (symbol of cse)
        begin
            from cse select symbol, price insert into #inner;
            @info(name = 'query1')
            from #inner[price > 15.0] select symbol, price insert into out;
        end;""",
        [("cse", ["A", 10.0, 1]), ("cse", ["B", 20.0, 1]),
         ("cse", ["A", 30.0, 1])],
        [("B", 20.0), ("A", 30.0)])


def test_group_by_two_keys():
    run_query("""
        define stream cse (symbol string, kind int, volume int);
        @info(name = 'query1')
        from cse select symbol, kind, sum(volume) as t
        group by symbol, kind insert into out;""",
        [("cse", ["A", 1, 10]), ("cse", ["A", 2, 20]),
         ("cse", ["A", 1, 30]), ("cse", ["B", 1, 40])],
        [("A", 1, 10), ("A", 2, 20), ("A", 1, 40), ("B", 1, 40)])


def test_order_by_limit():
    run_query("""
        define stream cse (symbol string, price float, volume int);
        @info(name = 'query1')
        from cse#window.lengthBatch(4)
        select symbol, price order by price desc limit 2
        insert into out;""",
        [("cse", ["A", 10.0, 1]), ("cse", ["B", 40.0, 1]),
         ("cse", ["C", 20.0, 1]), ("cse", ["D", 30.0, 1])],
        [("B", 40.0), ("D", 30.0)])


def test_having_filters_aggregate():
    run_query("""
        define stream cse (symbol string, volume int);
        @info(name = 'query1')
        from cse select symbol, sum(volume) as t group by symbol
        having t > 25 insert into out;""",
        [("cse", ["A", 10]), ("cse", ["B", 30]), ("cse", ["A", 20])],
        [("B", 30), ("A", 30)])
