"""Golden persistent-state schema dumps for every shipped sample.

The static extractor (analysis/state_schema.py) derives each sample
app's complete persistent-state layout — element ids, governing
@persistent_schema declarations, engine routing, layout digests —
WITHOUT executing any jax.  The stable textual dump is pinned under
tests/golden/; a refactor that silently moves state (a query dropping
off the device path, a window changing its carry layout, a schema
evolving without a version bump) shows up as a reviewable golden diff
instead of a checkpoint-restore incident.

Regenerate after an INTENTIONAL schema/routing change with:

    REGEN_SCHEMA_GOLDEN=1 python -m pytest tests/test_schema_golden.py

This file deliberately never imports jax: the whole extraction runs on
the parsed query API + AST-scanned declarations (asserted by the
jax-free subprocess test in test_state_schema.py).
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from siddhi_tpu.analysis.state_schema import (apps_in_source,  # noqa: E402
                                              schema_of_variants)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SAMPLES_DIR = os.path.join(ROOT, "samples")
GOLDEN_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "golden")
REGEN = os.environ.get("REGEN_SCHEMA_GOLDEN") == "1"


def _sample_files():
    return sorted(f for f in os.listdir(SAMPLES_DIR) if f.endswith(".py"))


@pytest.mark.parametrize("fname", _sample_files())
def test_sample_schema_matches_golden(fname):
    apps = apps_in_source(os.path.join(SAMPLES_DIR, fname))
    assert apps, f"{fname}: no SiddhiQL app string found"
    for i, variants in enumerate(apps):
        schema = schema_of_variants(variants)
        assert not schema.findings, (
            f"{fname} app #{i} has schema audit findings:\n" +
            "\n".join(m for _c, m in schema.findings))
        dump = schema.dump()
        assert dump.rstrip().endswith(schema.digest())
        golden = os.path.join(
            GOLDEN_DIR, f"{fname[:-3]}__app{i}.schema.txt")
        if REGEN:
            os.makedirs(GOLDEN_DIR, exist_ok=True)
            with open(golden, "w") as f:
                f.write(dump)
            continue
        assert os.path.exists(golden), (
            f"missing golden {os.path.relpath(golden, ROOT)} — run "
            f"REGEN_SCHEMA_GOLDEN=1 pytest tests/test_schema_golden.py")
        want = open(golden).read()
        assert dump == want, (
            f"{fname} app #{i}: state-schema dump changed.  If the "
            f"layout/routing change is intentional, bump the affected "
            f"@persistent_schema version(s) and regenerate with "
            f"REGEN_SCHEMA_GOLDEN=1.\n--- golden\n{want}\n--- now\n{dump}")
