"""Attribute-aggregator conformance ported from the reference corpus
(query/aggregator/ — And/Or/MaxForever/MinForever/Max aggregator test
cases).  Behaviors mirrored; assertions are the reference tests'
expectations."""
from ref_harness import run_query


# --------------------------------------------- MaxForever / MinForever

def test_max_forever_never_decreases():
    """testMaxForeverAggregatorExtension1: maxForever keeps the historical
    maximum even after larger values leave any window."""
    run_query("""
        define stream inputStream (price1 double, price2 double,
                                   price3 double);
        @info(name='query1')
        from inputStream select maxForever(price1) as maxForeverValue
        insert into outputStream;""",
        [("inputStream", [36.0, 36.75, 35.75]),
         ("inputStream", [37.88, 38.12, 37.62]),
         ("inputStream", [39.00, 39.25, 38.62]),
         ("inputStream", [36.88, 37.75, 36.75]),
         ("inputStream", [38.12, 38.12, 37.75]),
         ("inputStream", [38.12, 40.0, 37.75])],
        [(36.0,), (37.88,), (39.0,), (39.0,), (39.0,), (39.0,)],
        stream="outputStream", playback=True)


def test_max_forever_with_window_still_monotonic():
    """maxForever inside a length window ignores expiry."""
    run_query("""
        define stream S (v double);
        @info(name='query1')
        from S#window.length(1)
        select maxForever(v) as m insert into outputStream;""",
        [("S", [5.0]), ("S", [9.0]), ("S", [3.0])],
        [(5.0,), (9.0,), (9.0,)], stream="outputStream", playback=True)


def test_min_forever_never_increases():
    run_query("""
        define stream inputStream (price1 double);
        @info(name='query1')
        from inputStream select minForever(price1) as m
        insert into outputStream;""",
        [("inputStream", [36.0]), ("inputStream", [35.0]),
         ("inputStream", [37.0])],
        [(36.0,), (35.0,), (35.0,)], stream="outputStream", playback=True)


# --------------------------------------------------------- and / or

AND_APP = """
    define stream cscStream (messageID string, isFraud bool, price double);
    @info(name='query1')
    from cscStream#window.lengthBatch(3)
    select messageID, and(isFraud) as isValidTransaction
    group by messageID
    insert all events into outputStream;
"""


def test_and_aggregator_all_true():
    """testAndAggregatorTrueOnlyScenario: and() over a batch of trues."""
    run_query(AND_APP,
              [("cscStream", ["messageId1", True, 35.75]),
               ("cscStream", ["messageId1", True, 35.75]),
               ("cscStream", ["messageId1", True, 35.75])],
              [("messageId1", True)], stream="outputStream", playback=True)


def test_and_aggregator_all_false():
    run_query(AND_APP,
              [("cscStream", ["messageId1", False, 35.75]),
               ("cscStream", ["messageId1", False, 35.75]),
               ("cscStream", ["messageId1", False, 35.75])],
              [("messageId1", False)], stream="outputStream", playback=True)


def test_and_aggregator_mixed():
    run_query(AND_APP,
              [("cscStream", ["messageId1", True, 35.75]),
               ("cscStream", ["messageId1", False, 35.75]),
               ("cscStream", ["messageId1", True, 35.75])],
              [("messageId1", False)], stream="outputStream", playback=True)


def test_or_aggregator_any_true():
    app = AND_APP.replace("and(isFraud)", "or(isFraud)")
    run_query(app,
              [("cscStream", ["messageId1", False, 35.75]),
               ("cscStream", ["messageId1", True, 35.75]),
               ("cscStream", ["messageId1", False, 35.75])],
              [("messageId1", True)], stream="outputStream", playback=True)


def test_or_aggregator_all_false():
    app = AND_APP.replace("and(isFraud)", "or(isFraud)")
    run_query(app,
              [("cscStream", ["messageId1", False, 35.75]),
               ("cscStream", ["messageId1", False, 35.75]),
               ("cscStream", ["messageId1", False, 35.75])],
              [("messageId1", False)], stream="outputStream", playback=True)


def test_and_aggregator_sliding_window_expiry():
    """and() over a sliding length window recomputes as events expire."""
    run_query("""
        define stream S (ok bool);
        @info(name='query1')
        from S#window.length(2)
        select and(ok) as allok insert into outputStream;""",
        [("S", [True]), ("S", [False]), ("S", [True]), ("S", [True])],
        [(True,), (False,), (False,), (True,)],
        stream="outputStream", playback=True)


# ------------------------------------------- custom aggregator extension

def test_custom_string_concat_aggregator_extension():
    """query/extension corpus shape (StringConcatAggregatorString): a
    user-registered AttributeAggregator resolves by ns:name in selects."""
    import numpy as np
    from siddhi_tpu import SiddhiManager, StreamCallback
    from siddhi_tpu.core.aggregator import AttributeAggregator
    from siddhi_tpu.core.event import CURRENT, EXPIRED, RESET
    from siddhi_tpu.query_api.definition import AttrType

    class StringConcatAggregator(AttributeAggregator):
        name = "concat"

        def __init__(self, input_type):
            super().__init__(input_type)
            self.parts = []

        @property
        def output_type(self):
            return AttrType.STRING

        def process(self, values, types):
            out = np.empty(len(types), object)
            for i, t in enumerate(types):
                if t == CURRENT:
                    self.parts.append(str(values[i]))
                elif t == EXPIRED:
                    self.parts.remove(str(values[i]))
                elif t == RESET:
                    self.parts.clear()
                out[i] = "".join(self.parts)
            return out

        def state(self):
            return {"parts": list(self.parts)}

        def restore(self, s):
            self.parts = list(s["parts"])

    m = SiddhiManager()
    m.set_extension("custom:concat", StringConcatAggregator)
    rt = m.create_siddhi_app_runtime("""
        @app:playback
        define stream S (sym string);
        @info(name='q')
        from S#window.length(2)
        select custom:concat(sym) as joined insert into Out;""")
    got = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: got.extend(e.data[0] for e in evs)))
    rt.start()
    ts = 1_000_000
    for s in ("A", "B", "C"):
        rt.get_input_handler("S").send([s], timestamp=ts)
        ts += 100
    rt.shutdown()
    assert got == ["A", "AB", "BC"]


# ------------------------------------------------------------- stdDev

def test_stddev_aggregator():
    """Attribute stdDev over a growing set (reference
    attribute/StdDevAggregator tests)."""
    run_query("""
        define stream S (v double);
        @info(name='query1')
        from S select stdDev(v) as sd insert into outputStream;""",
        [("S", [2.0]), ("S", [4.0])],
        [(0.0,), (1.0,)], stream="outputStream", playback=True)
