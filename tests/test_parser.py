"""SiddhiQL parser tests (reference model: siddhi-query-compiler test suite —
grammar round-trips into the object model)."""
import pytest

from siddhi_tpu.compiler import SiddhiCompiler
from siddhi_tpu.query_api import (AttrType, Compare, CompareOp, Constant,
                                  CountStateElement, EveryStateElement,
                                  InsertIntoStream, JoinInputStream,
                                  LogicalStateElement, MathExpr,
                                  NextStateElement, Partition, Query,
                                  SingleInputStream, StateInputStream,
                                  StateType, StreamStateElement, TimeConstant,
                                  Variable)
from siddhi_tpu.utils.errors import SiddhiParserException


def test_stream_definition():
    app = SiddhiCompiler.parse(
        "define stream StockStream (symbol string, price float, volume long);")
    d = app.stream_definitions["StockStream"]
    assert [a.name for a in d.attributes] == ["symbol", "price", "volume"]
    assert d.attributes[1].type == AttrType.FLOAT


def test_filter_query():
    app = SiddhiCompiler.parse("""
        define stream S (a string, b int);
        @info(name='q1')
        from S[b > 10 and a == 'x']
        select a, b * 2 as b2
        insert into Out;
    """)
    q = app.execution_elements[0]
    assert isinstance(q, Query)
    assert q.name == "q1"
    s = q.input_stream
    assert isinstance(s, SingleInputStream)
    assert len(s.handlers) == 1
    assert isinstance(q.output_stream, InsertIntoStream)
    assert q.output_stream.target_id == "Out"
    assert q.selector.attributes[1].rename == "b2"


def test_window_and_groupby():
    app = SiddhiCompiler.parse("""
        define stream S (sym string, p double);
        from S#window.time(5 sec)
        select sym, avg(p) as ap
        group by sym having ap > 10.0
        order by ap desc limit 5 offset 1
        insert expired events into Out;
    """)
    q = app.execution_elements[0]
    w = q.input_stream.window_handler
    assert w.name == "time"
    assert isinstance(w.params[0], TimeConstant)
    assert w.params[0].value == 5000
    assert q.selector.group_by[0].attribute == "sym"
    assert q.selector.having is not None
    assert q.selector.limit == 5 and q.selector.offset == 1
    assert not q.selector.order_by[0].ascending


def test_time_constants():
    e = SiddhiCompiler.parse_expression("1 min 30 sec")
    assert isinstance(e, TimeConstant) and e.value == 90_000


def test_pattern_query():
    app = SiddhiCompiler.parse("""
        define stream A (x int); define stream B (x int);
        from every e1=A[x > 5] -> e2=B[x > e1.x] within 2 sec
        select e1.x as a, e2.x as b insert into Out;
    """)
    q = app.execution_elements[0]
    st = q.input_stream
    assert isinstance(st, StateInputStream)
    assert st.state_type == StateType.PATTERN
    assert st.within_ms == 2000
    nxt = st.state
    assert isinstance(nxt, NextStateElement)
    assert isinstance(nxt.state, EveryStateElement)
    inner = nxt.state.state
    assert isinstance(inner, StreamStateElement)
    assert inner.stream.stream_ref == "e1"


def test_sequence_and_count():
    app = SiddhiCompiler.parse("""
        define stream A (x int);
        from e1=A[x>1]<2:5>, e2=A[x>10]
        select e1[0].x as first, e2.x as last insert into Out;
    """)
    st = app.execution_elements[0].input_stream
    assert st.state_type == StateType.SEQUENCE
    cnt = st.state.state
    assert isinstance(cnt, CountStateElement)
    assert cnt.min_count == 2 and cnt.max_count == 5
    v = app.execution_elements[0].selector.attributes[0].expr
    assert isinstance(v, Variable) and v.stream_index == 0


def test_logical_and_absent():
    app = SiddhiCompiler.parse("""
        define stream A (x int); define stream B (y int);
        from every (e1=A and e2=B) -> not A for 1 sec
        select e1.x as x insert into Out;
    """)
    st = app.execution_elements[0].input_stream
    nxt = st.state
    logical = nxt.state.state
    assert isinstance(logical, LogicalStateElement)
    absent = nxt.next
    from siddhi_tpu.query_api import AbsentStreamStateElement
    assert isinstance(absent, AbsentStreamStateElement)
    assert absent.waiting_time_ms == 1000


def test_join_query():
    app = SiddhiCompiler.parse("""
        define stream L (a string, x int);
        define stream R (b string, y int);
        from L#window.length(5) as l join R#window.length(3) as r
            on l.a == r.b
        select l.a, r.y insert into Out;
    """)
    j = app.execution_elements[0].input_stream
    assert isinstance(j, JoinInputStream)
    assert j.left.stream_ref == "l"
    assert isinstance(j.on, Compare)


def test_partition():
    app = SiddhiCompiler.parse("""
        define stream S (sym string, p double);
        partition with (sym of S)
        begin
            @info(name='pq')
            from S select sym, sum(p) as total insert into Out;
        end;
    """)
    p = app.execution_elements[0]
    assert isinstance(p, Partition)
    assert len(p.queries) == 1


def test_annotations():
    app = SiddhiCompiler.parse("""
        @app:name('TestApp')
        @source(type='inMemory', topic='t1', @map(type='passThrough'))
        define stream S (a int);
    """)
    assert app.name == "TestApp"
    src = app.stream_definitions["S"].annotations[0]
    assert src.name == "source"
    assert src.get("topic") == "t1"
    assert src.annotations[0].name == "map"


def test_table_and_window_defs():
    app = SiddhiCompiler.parse("""
        @PrimaryKey('id')
        define table T (id string, v int);
        define window W (a int) length(5) output all events;
        define trigger Trig at every 5 sec;
    """)
    assert "T" in app.table_definitions
    w = app.window_definitions["W"]
    assert w.window_name == "length"
    assert app.trigger_definitions["Trig"].at_every_ms == 5000


def test_store_query_parse():
    sq = SiddhiCompiler.parse_store_query(
        "from T on v > 5 select id, v order by v desc limit 3")
    assert sq.input_store.store_id == "T"
    assert sq.selector.limit == 3


def test_syntax_error_has_location():
    with pytest.raises(SiddhiParserException):
        SiddhiCompiler.parse("define stream S (a in);"
                             " from S select insert into O;")


def test_math_precedence():
    e = SiddhiCompiler.parse_expression("1 + 2 * 3")
    assert isinstance(e, MathExpr)
    assert isinstance(e.right, MathExpr)  # 2*3 binds tighter


def test_function_definition():
    app = SiddhiCompiler.parse("""
        define function double_it[python] return int { data[0] * 2 };
        define stream S (x int);
        from S select double_it(x) as y insert into Out;
    """)
    assert "double_it" in app.function_definitions
    assert app.function_definitions["double_it"].body.strip() == "data[0] * 2"
