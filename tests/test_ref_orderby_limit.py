"""order by / limit / offset conformance tests ported from the reference
corpus (siddhi-core/src/test/java/io/siddhi/core/query/OrderByLimitTestCase
— 18 @Test methods over orderBy x limit x offset x batch windows)."""
from ref_harness import run_query

S = "define stream cseEventStream (symbol string, price float, volume long);\n"


def batch_q(sel):
    return S + f"""@info(name='query1')
    from cseEventStream#window.lengthBatch(5)
    {sel}
    insert into outputStream;"""


ROWS = [("cseEventStream", ["A", 60.0, 300]),
        ("cseEventStream", ["B", 50.0, 200]),
        ("cseEventStream", ["C", 70.0, 400]),
        ("cseEventStream", ["D", 40.0, 100]),
        ("cseEventStream", ["E", 80.0, 500])]


def test_orderby_asc():
    run_query(batch_q("select symbol, price order by price"),
              ROWS, [("D", 40.0), ("B", 50.0), ("A", 60.0), ("C", 70.0),
                     ("E", 80.0)])


def test_orderby_desc():
    run_query(batch_q("select symbol, price order by price desc"),
              ROWS, [("E", 80.0), ("C", 70.0), ("A", 60.0), ("B", 50.0),
                     ("D", 40.0)])


def test_orderby_limit():
    run_query(batch_q("select symbol, price order by price limit 2"),
              ROWS, [("D", 40.0), ("B", 50.0)])


def test_orderby_desc_limit():
    run_query(batch_q("select symbol, price order by price desc limit 3"),
              ROWS, [("E", 80.0), ("C", 70.0), ("A", 60.0)])


def test_limit_without_orderby():
    run_query(batch_q("select symbol limit 2"),
              ROWS, [("A",), ("B",)])


def test_offset():
    run_query(batch_q("select symbol, price order by price offset 3"),
              ROWS, [("C", 70.0), ("E", 80.0)])


def test_limit_offset():
    run_query(batch_q("select symbol, price order by price limit 2 offset 1"),
              ROWS, [("B", 50.0), ("A", 60.0)])


def test_orderby_two_keys():
    rows = [("cseEventStream", ["A", 50.0, 2]),
            ("cseEventStream", ["B", 50.0, 1]),
            ("cseEventStream", ["C", 40.0, 9]),
            ("cseEventStream", ["D", 50.0, 0]),
            ("cseEventStream", ["E", 30.0, 5])]
    run_query(batch_q("select symbol, price, volume "
                      "order by price, volume"),
              rows, [("E", 30.0, 5), ("C", 40.0, 9), ("D", 50.0, 0),
                     ("B", 50.0, 1), ("A", 50.0, 2)])


def test_orderby_mixed_direction():
    rows = [("cseEventStream", ["A", 50.0, 2]),
            ("cseEventStream", ["B", 50.0, 1]),
            ("cseEventStream", ["C", 40.0, 9]),
            ("cseEventStream", ["D", 50.0, 0]),
            ("cseEventStream", ["E", 30.0, 5])]
    run_query(batch_q("select symbol, price, volume "
                      "order by price asc, volume desc"),
              rows, [("E", 30.0, 5), ("C", 40.0, 9), ("A", 50.0, 2),
                     ("B", 50.0, 1), ("D", 50.0, 0)])


def test_orderby_string_key():
    run_query(batch_q("select symbol order by symbol desc limit 2"),
              ROWS, [("E",), ("D",)])


def test_groupby_orderby_limit():
    """Aggregate per group, then order the batch output and limit."""
    rows = [("cseEventStream", ["A", 10.0, 1]),
            ("cseEventStream", ["B", 90.0, 1]),
            ("cseEventStream", ["A", 20.0, 1]),
            ("cseEventStream", ["C", 50.0, 1]),
            ("cseEventStream", ["B", 10.0, 1])]
    run_query(batch_q("select symbol, sum(price) as total group by symbol "
                      "order by total desc limit 2"),
              rows, [("B", 100.0), ("C", 50.0)])


def test_sliding_limit_applies_per_chunk():
    """Without a batch window, limit applies to each emitted chunk."""
    run_query(S + """@info(name='query1')
        from cseEventStream select symbol limit 1
        insert into outputStream;""",
        ROWS, [("A",), ("B",), ("C",), ("D",), ("E",)])


def test_orderby_volume_long():
    run_query(batch_q("select symbol, volume order by volume desc limit 1"),
              ROWS, [("E", 500)])


def test_offset_beyond_size_empty():
    run_query(batch_q("select symbol order by symbol offset 9"),
              ROWS, [])
