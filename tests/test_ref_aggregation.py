"""Incremental-aggregation conformance tests ported from the reference
corpus (siddhi-core/src/test/java/io/siddhi/core/aggregation/
Aggregation1TestCase and friends — within wildcards, per from joined
stream attributes, last-value lanes, multi-key group by)."""
import pytest

from siddhi_tpu import QueryCallback, SiddhiManager

STOCK = ("define stream stockStream (symbol string, price float, "
         "lastClosingPrice float, volume long, quantity int, "
         "timestamp long);")

AGG = """
define aggregation stockAggregation
from stockStream
select symbol, avg(price) as avgPrice, sum(price) as totalPrice,
       (price * quantity) as lastTradeValue
group by symbol
aggregate by timestamp every sec ... hour;
"""

SENDS5 = [
    ["WSO2", 50.0, 60.0, 90, 6, 1496289950000],
    ["WSO2", 70.0, None, 40, 10, 1496289950000],
    ["WSO2", 60.0, 44.0, 200, 56, 1496289952000],
    ["WSO2", 100.0, None, 200, 16, 1496289952000],
    ["IBM", 100.0, None, 200, 26, 1496289954000],
    ["IBM", 100.0, None, 200, 96, 1496289954000],
]


def build(app):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(app)
    rt.start()
    return rt


def test_within_wildcard_per_seconds():
    """incrementalStreamProcessorTest5: wildcard `within`, last-value
    lane reflects the bucket's last event."""
    rt = build(STOCK + AGG)
    h = rt.get_input_handler("stockStream")
    for row in SENDS5:
        h.send(list(row))
    events = rt.query('from stockAggregation '
                      'within "2017-06-** **:**:**" per "seconds"')
    rt.shutdown()
    rows = sorted([tuple(e.data) for e in events])
    assert rows == sorted([
        (1496289952000, "WSO2", 80.0, 160.0, 1600.0),
        (1496289950000, "WSO2", 60.0, 120.0, 700.0),
        (1496289954000, "IBM", 100.0, 200.0, 9600.0),
    ])


def test_join_with_per_from_stream_attribute():
    """incrementalStreamProcessorTest6: within/per values flow from the
    joined stream's attributes; output ordered by AGG_TIMESTAMP."""
    rt = build(STOCK + AGG.replace("sec ... hour", "sec ... year") + """
        define stream inputStream (symbol string, value int,
            startTime string, endTime string, perValue string);
        @info(name = 'query1')
        from inputStream as i join stockAggregation as s
        within i.startTime, i.endTime
        per i.perValue
        select AGG_TIMESTAMP, s.symbol, avgPrice,
               totalPrice as sumPrice, lastTradeValue
        order by AGG_TIMESTAMP
        insert all events into outputStream;
    """)
    got = []
    rt.add_callback("query1", QueryCallback(
        lambda ts, cur, exp: got.extend(tuple(e.data) for e in (cur or []))))
    sh = rt.get_input_handler("stockStream")
    sh.send(["WSO2", 50.0, 60.0, 90, 6, 1496289950000])
    sh.send(["WSO2", 70.0, None, 40, 10, 1496289950000])
    sh.send(["IBM", 100.0, None, 200, 26, 1496289951000])
    sh.send(["IBM", 900.0, None, 200, 60, 1496289952000])
    rt.get_input_handler("inputStream").send(
        ["IBM", 1, "2017-06-01 04:05:50", "2017-06-01 04:05:53",
         "seconds"])
    rt.shutdown()
    assert got == [
        (1496289950000, "WSO2", 60.0, 120.0, 700.0),
        (1496289951000, "IBM", 100.0, 100.0, 2600.0),
        (1496289952000, "IBM", 900.0, 900.0, 54000.0),
    ]


def test_no_group_by_single_bucket_stream():
    """incrementalStreamProcessorTest1 family: aggregation without
    group-by keeps one bucket per window."""
    rt = build(STOCK + """
        define aggregation stockAggregation
        from stockStream
        select sum(price) as sumPrice
        aggregate by timestamp every sec ... min;
    """)
    h = rt.get_input_handler("stockStream")
    h.send(["WSO2", 50.0, 60.0, 90, 6, 1496289950000])
    h.send(["IBM", 70.0, None, 40, 10, 1496289950500])
    h.send(["IBM", 30.0, None, 40, 10, 1496289952000])
    events = rt.query('from stockAggregation '
                      'within "2017-06-** **:**:**" per "seconds"')
    rt.shutdown()
    rows = sorted(tuple(e.data) for e in events)
    assert rows == [(1496289950000, 120.0), (1496289952000, 30.0)]


def test_group_by_two_keys():
    """incrementalStreamProcessorTest4: composite group key."""
    rt = build(STOCK + """
        define aggregation stockAggregation
        from stockStream
        select symbol, volume, sum(price) as sumPrice
        group by symbol, volume
        aggregate by timestamp every sec ... min;
    """)
    h = rt.get_input_handler("stockStream")
    h.send(["WSO2", 50.0, 60.0, 90, 6, 1496289950000])
    h.send(["WSO2", 70.0, None, 90, 10, 1496289950100])
    h.send(["WSO2", 10.0, None, 40, 10, 1496289950200])
    events = rt.query('from stockAggregation '
                      'within "2017-06-** **:**:**" per "seconds"')
    rt.shutdown()
    rows = sorted(tuple(e.data) for e in events)
    assert rows == [
        (1496289950000, "WSO2", 40, 10.0),
        (1496289950000, "WSO2", 90, 120.0),
    ]


def test_minute_rollup_from_second_buckets():
    """Duration cascade: the same events queried per 'minutes' roll up."""
    rt = build(STOCK + AGG)
    h = rt.get_input_handler("stockStream")
    for row in SENDS5:
        h.send(list(row))
    events = rt.query('from stockAggregation '
                      'within "2017-06-** **:**:**" per "minutes"')
    rt.shutdown()
    rows = sorted(tuple(e.data) for e in events)
    # minute bucket 1496289900000: WSO2 avg 70 total 280, IBM avg 100
    assert rows == [
        (1496289900000, "IBM", 100.0, 200.0, 9600.0),
        (1496289900000, "WSO2", 70.0, 280.0, 1600.0),
    ]


def test_within_explicit_range_filters_buckets():
    rt = build(STOCK + AGG)
    h = rt.get_input_handler("stockStream")
    for row in SENDS5:
        h.send(list(row))
    events = rt.query(
        'from stockAggregation within "2017-06-01 04:05:52", '
        '"2017-06-01 04:05:54" per "seconds"')
    rt.shutdown()
    rows = sorted(tuple(e.data) for e in events)
    assert rows == [(1496289952000, "WSO2", 80.0, 160.0, 1600.0)]


def test_on_condition_with_per():
    """Store query with `on` filter over the aggregation selection."""
    rt = build(STOCK + AGG)
    h = rt.get_input_handler("stockStream")
    for row in SENDS5:
        h.send(list(row))
    events = rt.query('from stockAggregation on symbol == "IBM" '
                      'within "2017-06-** **:**:**" per "seconds" '
                      'select symbol, totalPrice')
    rt.shutdown()
    assert [tuple(e.data) for e in events] == [("IBM", 200.0)]


def test_min_max_count_lanes():
    rt = build(STOCK + """
        define aggregation stockAggregation
        from stockStream
        select symbol, min(price) as lo, max(price) as hi, count() as n
        group by symbol
        aggregate by timestamp every sec ... min;
    """)
    h = rt.get_input_handler("stockStream")
    h.send(["WSO2", 50.0, 60.0, 90, 6, 1496289950000])
    h.send(["WSO2", 70.0, None, 40, 10, 1496289950100])
    h.send(["WSO2", 20.0, None, 40, 10, 1496289950200])
    events = rt.query('from stockAggregation '
                      'within "2017-06-** **:**:**" per "seconds"')
    rt.shutdown()
    assert [tuple(e.data) for e in events] == \
        [(1496289950000, "WSO2", 20.0, 70.0, 3)]


def test_distinct_count_aggregation():
    """DistinctCountAggregationTestCase: distinctCount over a duration
    (host-only lane: falls back from the slab path)."""
    rt = build(STOCK + """
        define aggregation stockAggregation
        from stockStream
        select symbol, distinctCount(volume) as dv
        group by symbol
        aggregate by timestamp every sec ... min;
    """)
    h = rt.get_input_handler("stockStream")
    h.send(["WSO2", 50.0, 60.0, 90, 6, 1496289950000])
    h.send(["WSO2", 70.0, None, 90, 10, 1496289950100])
    h.send(["WSO2", 10.0, None, 40, 10, 1496289950200])
    events = rt.query('from stockAggregation '
                      'within "2017-06-** **:**:**" per "seconds"')
    rt.shutdown()
    assert [tuple(e.data) for e in events] == \
        [(1496289950000, "WSO2", 2)]
