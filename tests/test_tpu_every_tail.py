"""Device/host conformance for trailing `every` (VERDICT r2 next #5):
`A -> every B` — the continuous-monitoring staple — must compile onto the
NFA kernel and match the host oracle byte-for-byte, including re-arm
floods into the slot ring and `within` bounding every firing from the
chain start.

Reference: util/parser/StateInputStreamParser.java:272-273 (the last post
processor of the every group loops to its first pre processor),
StreamPostStateProcessor.java:66-68 (addEveryState clone)."""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback

STREAMS = """
define stream A (k int, v float);
define stream B (k int, w float);
"""


def run_app(app, sends, engine=None):
    prefix = f"@app:engine('{engine}') " if engine else ""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(prefix + app)
    out = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: out.extend(tuple(e.data) for e in evs)))
    rt.start()
    for sid, row, ts in sends:
        rt.get_input_handler(sid).send(row, timestamp=ts)
    backend = rt.query_runtimes["q"].backend
    reason = rt.query_runtimes["q"].backend_reason
    rt.shutdown()
    return backend, reason, out


def assert_parity(app, sends):
    bh, _, host = run_app(app, sends, engine="host")
    bd, reason, dev = run_app(app, sends)
    assert bh == "host"
    assert bd == "device", f"did not plan onto the device: {reason}"
    assert host == dev, f"host={host} dev={dev}"
    return host


def A(ts, k, v):
    return ("A", [k, v], ts)


def B(ts, k, w):
    return ("B", [k, w], ts)


def test_simple_tail_every_fires_per_match():
    app = STREAMS + """
        @info(name='q')
        from e1=A[v > 10.0] -> every e2=B[w > e1.v]
        select e1.v as v1, e2.w as w2 insert into Out;
    """
    # one arming A, then every qualifying B fires (b=25, b=30, not b=5)
    out = assert_parity(app, [
        A(1000, 1, 20.0), B(1100, 1, 25.0), B(1200, 1, 5.0),
        B(1300, 1, 30.0), A(1400, 1, 90.0), B(1500, 1, 50.0)])
    assert len(out) >= 3


def test_leading_and_trailing_every():
    app = STREAMS + """
        @info(name='q')
        from every e1=A[v > 10.0] -> every e2=B[w > e1.v]
        select e1.v as v1, e2.w as w2 insert into Out;
    """
    # each armed A keeps firing on every later qualifying B
    out = assert_parity(app, [
        A(1000, 1, 20.0), A(1100, 1, 40.0), B(1200, 1, 25.0),
        B(1300, 1, 45.0), B(1400, 1, 50.0), A(1500, 1, 60.0),
        B(1600, 1, 70.0)])
    assert len(out) >= 5


def test_tail_every_with_within_expires():
    app = STREAMS + """
        @info(name='q')
        from every e1=A[v > 10.0] -> every e2=B[w > e1.v] within 2 sec
        select e1.v as v1, e2.w as w2 insert into Out;
    """
    # firings stop once the chain start is > 2s old
    assert_parity(app, [
        A(1000, 1, 20.0), B(1500, 1, 25.0), B(2500, 1, 30.0),
        B(3500, 1, 40.0),          # expired for the first A
        A(4000, 1, 15.0), B(4500, 1, 50.0), B(7000, 1, 60.0)])


def test_tail_every_logical_or_group():
    app = STREAMS + """
        @info(name='q')
        from e1=A[v > 10.0] -> every (e2=B[w > 5.0] or e3=A[k == 7])
        select e1.v as v1, e2.w as w2, e3.v as v3 insert into Out;
    """
    assert_parity(app, [
        A(1000, 1, 20.0), B(1100, 1, 8.0), A(1200, 7, 3.0),
        B(1300, 1, 9.0), A(1400, 7, 4.0), B(1500, 1, 2.0)])


def test_tail_every_group_two_steps():
    app = STREAMS + """
        @info(name='q')
        from e1=A[v > 10.0] -> every (e2=B[w > 5.0] -> e3=B[w > e2.w])
        select e1.v as v1, e2.w as w2, e3.w as w3 insert into Out;
    """
    # the two-step group re-arms as a whole after each completion
    assert_parity(app, [
        A(1000, 1, 20.0), B(1100, 1, 6.0), B(1200, 1, 9.0),
        B(1300, 1, 7.0), B(1400, 1, 11.0), B(1500, 1, 3.0),
        B(1600, 1, 8.0)])


def test_tail_every_sequence_mode():
    app = STREAMS + """
        @info(name='q')
        from e1=A[v > 10.0], every e2=A[v > e1.v]
        select e1.v as v1, e2.v as v2 insert into Out;
    """
    # SEQUENCE: the re-armed partial must advance on the very next event
    # or die (per-event reset barriers)
    assert_parity(app, [
        A(1000, 1, 20.0), A(1100, 1, 30.0), A(1200, 1, 25.0),
        A(1300, 1, 40.0)])


def test_tail_every_rearm_flood_grows_slots():
    """Many armed chains all re-firing: the keyed engine path must grow
    its slot ring rather than drop (StreamPreStateProcessor pending lists
    never drop)."""
    app = """
    define stream S (sym string, price float, kind int);
    partition with (sym of S) begin
    @info(name='q')
    from every e1=S[kind == 0] -> every e2=S[kind == 1 and price > e1.price]
    select e1.price as p1, e2.price as p2 insert into Out;
    end;
    """
    rng = np.random.default_rng(3)
    n = 400
    cols = {"sym": np.asarray([f"k{i}" for i in
                               rng.integers(0, 4, n)], object),
            "price": rng.uniform(0, 100, n).astype(np.float32),
            "kind": rng.integers(0, 2, n).astype(np.int32)}
    ts = 1_000_000 + np.arange(n, dtype=np.int64)

    def run(engine):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(
            f"@app:playback @app:engine('{engine}') {app}")
        got = []
        rt.add_callback("Out", StreamCallback(
            lambda evs: got.extend((round(e.data[0], 3), round(e.data[1], 3))
                                   for e in evs)))
        rt.start()
        rt.get_input_handler("S").send_batch(cols, timestamps=ts)
        rt.shutdown()
        return sorted(got)

    host = run("host")
    dev = run("device")
    assert len(host) > 100 and host == dev


def test_mid_chain_every_compiles_to_device():
    # round 4: mid-chain `every` forks clones via the kernel's
    # alloc_clones; nested every remains host-only
    app = STREAMS + """
        @info(name='q')
        from e1=A[v > 10.0] -> every e2=B[w > 5.0] -> e3=A[v > 50.0]
        select e1.v as v1, e2.w as w2, e3.v as v3 insert into Out;
    """
    b, _reason, out = run_app(app, [A(1000, 1, 20.0), B(1100, 1, 8.0),
                                    A(1200, 1, 60.0)])
    assert b == "device"
    assert out == [(20.0, 8.0, 60.0)]


def test_tail_every_group_within_expiry_parity():
    """Top-level within + multi-unit trailing group: the oracle forwards a
    C-expired partial to the group head B (different unit — reference
    behavior), where it dies on its own expiry check; the kernel just
    expires the slot.  Outputs must agree."""
    app = STREAMS + """
        @info(name='q')
        from (e1=A[v > 10.0] -> every (e2=B[w > 5.0] -> e3=B[w > e2.w]))
            within 2 sec
        select e1.v as v1, e2.w as w2, e3.w as w3 insert into Out;
    """
    assert_parity(app, [
        A(1000, 1, 20.0), B(1400, 1, 6.0), B(1800, 1, 9.0),
        B(2600, 1, 7.0), B(3200, 1, 11.0),    # expired for the chain
        A(4000, 1, 15.0), B(4400, 1, 6.0), B(4800, 1, 8.0)])
