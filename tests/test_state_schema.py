"""Persistent-state schema registry + SC0xx checkpoint verifier.

Covers the restore-compatibility contract end to end:

  * the SC002 lint gate — every ``current_state`` definer in the engine
    source carries its own ``@persistent_schema`` (empty allowlist);
  * the static AST declaration scan recovers declarations bit-identical
    (same digests) to the import-time registry;
  * the v2 snapshot envelope embeds per-element descriptions + the
    routing digest, and ``restore`` verifies them BEFORE touching any
    carry — ≥5 distinct mutation classes each raise a typed
    CannotRestoreStateError with an SC0xx code and a field-level diff,
    never a raw jax/pickle error;
  * randomized config round trips: compatible pairs (NFA batch B=4↔B=1,
    ladder-grown K) restore bit-identically, incompatible pairs (shard
    count changes) fail typed;
  * ``analyze --schema`` stays jax-free (subprocess-asserted) and the
    schema report rides rt.state_schema / rt.analysis.schema / /stats.
"""
import os
import pickle
import random
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from siddhi_tpu import SiddhiManager, StreamCallback  # noqa: E402
from siddhi_tpu.core import stateschema as ss  # noqa: E402
from siddhi_tpu.core.snapshot import (FileSystemPersistenceStore,  # noqa: E402
                                      InMemoryPersistenceStore)
from siddhi_tpu.utils.errors import (CannotRestoreStateError,  # noqa: E402
                                     SiddhiAppRuntimeException)

PATTERN_APP = """
@app:name('schemaPat')
define stream S (k string, p double);
from every e1=S[p > 1.0] -> e2=S[p > e1.p] within 3600 sec
select e1.k as k, e1.p as p1, e2.p as p2 insert into Out;
"""

AGG_APP = """
@app:name('schemaAgg')
define stream S (k string, p double);
from S select k, sum(p) as total group by k insert into Out;
"""

PARTITION_APP = """
@app:name('schemaPart')
define stream S (k string, p double);
partition with (k of S) begin
  from every e1=S[p > 1.0] -> e2=S[p > e1.p] within 3600 sec
  select e1.k as k, e2.p as p insert into Out;
end;
"""


def _rt(app, store=None):
    m = SiddhiManager()
    if store is not None:
        m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: got.extend(tuple(e.data) for e in evs)))
    rt.start()
    return m, rt, got


def _envelope(rt):
    return pickle.loads(rt.snapshot_service.full_snapshot())


def _restore(rt, env):
    rt.snapshot_service.restore(
        pickle.dumps(env, protocol=pickle.HIGHEST_PROTOCOL))


def _pattern_eid(env):
    eids = [e for e in env["schema"] if e.endswith(":state")]
    assert eids, sorted(env["schema"])
    return eids[0]


# ================================================================ lint gate

def test_sc002_audit_gate_empty_allowlist():
    """Tier-1 gate: no current_state definer may ship undeclared.  The
    allowlist is deliberately empty — a new stateful processor must
    declare its layout before it can merge."""
    from siddhi_tpu.analysis.state_schema import audit_declarations
    findings = audit_declarations(allow=())
    assert findings == [], "\n".join(m for _c, m in findings)


def test_static_scan_matches_runtime_registry():
    """The AST scan recovers every declaration bit-identically (same
    name/version/digest) to what the decorators register at import."""
    from siddhi_tpu.analysis.state_schema import static_declarations
    import siddhi_tpu.core.aggregation  # noqa: F401
    import siddhi_tpu.core.named_window  # noqa: F401
    import siddhi_tpu.core.partition  # noqa: F401
    import siddhi_tpu.core.pattern  # noqa: F401
    import siddhi_tpu.core.record_table  # noqa: F401
    import siddhi_tpu.core.selector  # noqa: F401
    import siddhi_tpu.core.table  # noqa: F401
    import siddhi_tpu.core.window  # noqa: F401
    import siddhi_tpu.plan.dwin_compiler  # noqa: F401
    import siddhi_tpu.plan.gagg_compiler  # noqa: F401
    import siddhi_tpu.plan.iagg_compiler  # noqa: F401
    import siddhi_tpu.plan.nfa_compiler  # noqa: F401
    import siddhi_tpu.plan.planner  # noqa: F401
    import siddhi_tpu.plan.wagg_compiler  # noqa: F401
    static = static_declarations()
    runtime = ss.registry()
    assert set(static) == set(runtime)
    for dotted, decl in static.items():
        live = runtime[dotted]
        assert decl.name == live.name, dotted
        assert decl.version == live.version, dotted
        assert decl.digest() == live.digest(), dotted


# ============================================================= envelope v2

def test_full_snapshot_is_v2_envelope():
    m, rt, _ = _rt(AGG_APP)
    try:
        rt.get_input_handler("S").send(["a", 2.0])
        env = _envelope(rt)
        assert env["v"] == ss.SCHEMA_ENVELOPE_VERSION
        assert set(env) >= {"v", "schema", "routing", "state"}
        assert set(env["schema"]) == set(env["state"])
        for eid, d in env["schema"].items():
            assert d["name"] and d["digest"], eid
    finally:
        m.shutdown()


def test_legacy_pre_schema_pickle_still_restores():
    """A bare {eid: state} pickle (pre-envelope format) restores
    unverified — old checkpoints are not orphaned by the upgrade."""
    m, rt, got = _rt(AGG_APP)
    try:
        rt.get_input_handler("S").send(["a", 2.0])
        env = _envelope(rt)
        legacy = pickle.dumps(env["state"],
                              protocol=pickle.HIGHEST_PROTOCOL)
        m2, rt2, got2 = _rt(AGG_APP)
        try:
            rt2.snapshot_service.restore(legacy)
            rt2.get_input_handler("S").send(["a", 3.0])
            assert got2[-1][1] == pytest.approx(5.0)
        finally:
            m2.shutdown()
    finally:
        m.shutdown()


# ====================================================== mutation classes
# ≥5 distinct incompatibility classes, each a typed SC0xx with a
# field-level diff — never a raw jax or pickle error.

def test_mutation_version_tamper_is_sc001():
    m, rt, _ = _rt(PATTERN_APP)
    try:
        rt.get_input_handler("S").send(["a", 2.0])
        env = _envelope(rt)
        eid = _pattern_eid(env)
        env["schema"][eid]["version"] = 99
        with pytest.raises(CannotRestoreStateError) as ei:
            _restore(rt, env)
        assert ei.value.code == "SC001"
        assert "version" in str(ei.value) and eid in str(ei.value)
    finally:
        m.shutdown()


SELECTOR_APP = """
@app:name('schemaSel') @app:engine('host')
define stream S (k string, p double);
@info(name='q')
from S select k, sum(p) as total group by k having total > 1.0
order by total desc limit 2 insert into Out;
"""


def test_mutation_selector_version_tamper_is_sc001():
    """The host QuerySelector's envelope section (``q:selector`` — the
    selection-tail fallback path of round 19) verifies like every other
    element: a version tamper is a typed SC001, not a pickle error."""
    m, rt, _ = _rt(SELECTOR_APP)
    try:
        rt.get_input_handler("S").send(["a", 2.0])
        env = _envelope(rt)
        assert "q:selector" in env["schema"], sorted(env["schema"])
        assert env["schema"]["q:selector"]["name"] == "selector"
        env["schema"]["q:selector"]["version"] = 99
        with pytest.raises(CannotRestoreStateError) as ei:
            _restore(rt, env)
        assert ei.value.code == "SC001"
        assert "version" in str(ei.value) and "q:selector" in str(ei.value)
    finally:
        m.shutdown()


def test_mutation_digest_tamper_same_version_is_sc010():
    m, rt, _ = _rt(PATTERN_APP)
    try:
        rt.get_input_handler("S").send(["a", 2.0])
        env = _envelope(rt)
        eid = _pattern_eid(env)
        env["schema"][eid]["digest"] = "feedc0ffee00"
        with pytest.raises(CannotRestoreStateError) as ei:
            _restore(rt, env)
        assert ei.value.code == "SC010"
        assert "version bump" in str(ei.value)
    finally:
        m.shutdown()


def test_mutation_elastic_dim_off_ladder_is_sc004():
    m, rt, _ = _rt(PATTERN_APP)
    try:
        rt.get_input_handler("S").send(["a", 2.0])
        env = _envelope(rt)
        eid = _pattern_eid(env)
        sub = env["schema"][eid]["sub"]
        assert sub is not None and "K" in sub["dims"]
        sub["dims"]["K"] = int(sub["dims"]["K"]) * 3   # 3x is off-ladder
        with pytest.raises(CannotRestoreStateError) as ei:
            _restore(rt, env)
        assert ei.value.code == "SC004"
        assert "grow ladder" in str(ei.value)
    finally:
        m.shutdown()


def test_mutation_exact_dim_mismatch_is_sc001():
    """A snapshot of a structurally different pattern (3 units vs 2)
    refuses with the dim-level diff."""
    m, rt, _ = _rt(PATTERN_APP)
    try:
        rt.get_input_handler("S").send(["a", 2.0])
        env = _envelope(rt)
        eid = _pattern_eid(env)
        sub = env["schema"][eid]["sub"]
        sub["dims"]["S"] = int(sub["dims"]["S"]) + 1
        with pytest.raises(CannotRestoreStateError) as ei:
            _restore(rt, env)
        assert ei.value.code == "SC001"
        assert "fixed by the plan" in str(ei.value)
    finally:
        m.shutdown()


def test_mutation_missing_and_foreign_elements_are_sc001():
    m, rt, _ = _rt(PATTERN_APP)
    try:
        rt.get_input_handler("S").send(["a", 2.0])
        env = _envelope(rt)
        eid = _pattern_eid(env)
        # snapshot lacks a section the live runtime persists
        dropped = dict(env, schema=dict(env["schema"]),
                       state=dict(env["state"]))
        del dropped["schema"][eid]
        del dropped["state"][eid]
        with pytest.raises(CannotRestoreStateError) as ei:
            _restore(rt, dropped)
        assert ei.value.code == "SC001"
        assert "no section" in str(ei.value)
        # snapshot carries a section for an element this runtime lacks
        foreign = dict(env, schema=dict(env["schema"]))
        foreign["schema"]["ghost:state"] = dict(env["schema"][eid])
        with pytest.raises(CannotRestoreStateError) as ei:
            _restore(rt, foreign)
        assert ei.value.code == "SC001"
        assert "does not exist" in str(ei.value)
    finally:
        m.shutdown()


def test_mutation_routing_drift_is_sc005():
    m, rt, _ = _rt(PATTERN_APP)
    try:
        rt.get_input_handler("S").send(["a", 2.0])
        env = _envelope(rt)
        assert env["routing"]
        env["routing"] = "0000deadbeef"
        with pytest.raises(CannotRestoreStateError) as ei:
            _restore(rt, env)
        assert ei.value.code == "SC005"
        assert "routing" in str(ei.value)
    finally:
        m.shutdown()


def test_sc005_shard_mismatch_message_has_counts_and_digest():
    from siddhi_tpu.parallel.shards import routing_digest
    msg = ss.shard_mismatch_message(4, 2)
    assert "2 shard slab(s)" in msg and "has 4" in msg
    assert routing_digest() in msg


def test_portable_scan_flags_raw_instance_sc003():
    class Opaque:
        pass
    findings = ss.portable_scan({"ok": np.arange(3), "bad": Opaque()})
    assert [c for c, _m in findings] == ["SC003"]
    assert "bad" in findings[0][1]
    assert ss.portable_scan({"xs": [1, 2.5, "s", None, b"b"]}) == []


def test_mutation_incremental_chain_gap_is_sc006(tmp_path):
    store = FileSystemPersistenceStore(str(tmp_path))
    m, rt, _ = _rt(AGG_APP, store)
    try:
        h = rt.get_input_handler("S")
        h.send(["a", 1.5])
        rt.persist()                                 # full base
        h.send(["a", 2.5])
        inc1 = rt.persist(incremental=True)
        h.send(["b", 3.5])
        inc2 = rt.persist(incremental=True)
        assert inc1.endswith("_inc") and inc2.endswith("_inc")
        os.remove(os.path.join(str(tmp_path), rt.name, inc1))
        m2, rt2, _g = _rt(AGG_APP, store)
        try:
            with pytest.raises(CannotRestoreStateError) as ei:
                rt2.restore_revision(inc2)
            assert ei.value.code == "SC006"
            assert inc1 in str(ei.value)
        finally:
            m2.shutdown()
    finally:
        m.shutdown()


# ================================================= randomized round trips

def _feed(rt, events):
    h = rt.get_input_handler("S")
    for k, p in events:
        h.send([k, p])


def _events(seed, n, keys):
    rng = random.Random(seed)
    return [(rng.choice(keys), round(rng.uniform(0.5, 9.5), 3))
            for _ in range(n)]


def _run_config(env_overrides, app, events, snap=None, cont=None):
    """Build a runtime under ``env_overrides``; either persist after
    ``events`` (returns snapshot bytes) or restore ``snap`` first and
    return the outputs produced by ``cont``."""
    saved = {k: os.environ.get(k) for k in env_overrides}
    os.environ.update(env_overrides)
    try:
        m, rt, got = _rt(app)
        try:
            if snap is None:
                _feed(rt, events)
                return rt.snapshot_service.full_snapshot()
            rt.snapshot_service.restore(snap)
            del got[:]
            _feed(rt, cont)
            return list(got)
        finally:
            m.shutdown()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@pytest.mark.parametrize("seed", [7, 23])
def test_nfa_batch_b4_vs_b1_snapshots_interchange(seed):
    """B is a consumption width, not a state dim: snapshots taken under
    SIDDHI_TPU_NFA_BATCH=4 restore into B=1 runtimes (and vice versa)
    with bit-identical continuation output."""
    events = _events(seed, 24, ["a", "b"])
    cont = _events(seed + 1, 12, ["a", "b"])
    b1, b4 = {"SIDDHI_TPU_NFA_BATCH": "1"}, {"SIDDHI_TPU_NFA_BATCH": "4"}
    for src, dst in [(b4, b1), (b1, b4)]:
        snap = _run_config(src, PATTERN_APP, events)
        base = _run_config(src, PATTERN_APP, [], snap=snap, cont=cont)
        cross = _run_config(dst, PATTERN_APP, [], snap=snap, cont=cont)
        assert cross == base, (src, dst)


def test_grown_k_snapshot_restores_into_fresh_runtime():
    """The key-lane capacity K doubles as keys arrive; a snapshot taken
    after growth restores into a fresh (minimum-K) runtime because the
    values sit on the same power-of-two ladder."""
    keys = [f"k{i}" for i in range(40)]       # forces K growth
    events = [(k, 2.0) for k in keys]
    cont = [(k, 5.0) for k in keys[:6]]
    snap = _run_config({}, PARTITION_APP, events)
    base = _run_config({}, PARTITION_APP, [], snap=snap, cont=cont)
    cross = _run_config({}, PARTITION_APP, [], snap=snap, cont=cont)
    assert cross == base
    assert base, "grown-K restore lost the open pattern instances"


@pytest.mark.parametrize("src,dst", [
    ({"SIDDHI_TPU_SHARDS": "2"}, {}),
    ({}, {"SIDDHI_TPU_SHARDS": "2"}),
    ({"SIDDHI_TPU_SHARDS": "2"}, {"SIDDHI_TPU_SHARDS": "3"}),
])
def test_incompatible_configs_fail_typed_never_raw(src, dst):
    """Every incompatible config pair yields a typed SC0xx — a raw jax
    shape error or pickle error out of restore() is itself a bug."""
    events = _events(11, 24, [f"k{i}" for i in range(8)])
    snap = _run_config(src, PARTITION_APP, events)
    try:
        _run_config(dst, PARTITION_APP, [], snap=snap, cont=[])
    except CannotRestoreStateError as e:
        assert e.code is not None and e.code.startswith("SC0"), e
        assert "shard" in str(e) or "routing" in str(e) or \
            "section" in str(e), e
    else:
        pytest.fail("restore across shard configs must refuse typed")


@pytest.mark.parametrize("seed", [3])
def test_randomized_tampers_always_fail_typed(seed):
    """Property sweep: random single-field tampers of the embedded
    schema header either still verify (no-op tamper) or raise a typed
    CannotRestoreStateError — never any other exception type."""
    m, rt, _ = _rt(PATTERN_APP)
    try:
        _feed(rt, _events(seed, 16, ["a", "b"]))
        env = _envelope(rt)
        eid = _pattern_eid(env)
        rng = random.Random(seed)
        for _ in range(12):
            tam = pickle.loads(pickle.dumps(env))
            d = tam["schema"][eid]
            target = rng.choice(["version", "digest", "name",
                                 "K", "S", "routing"])
            if target == "version":
                d["version"] = rng.randint(2, 50)
            elif target == "digest":
                d["digest"] = f"{rng.getrandbits(48):012x}"
            elif target == "name":
                d["name"] = "some-other-schema"
            elif target == "routing":
                tam["routing"] = f"{rng.getrandbits(48):012x}"
            elif d["sub"] is not None and target in d["sub"]["dims"]:
                d["sub"]["dims"][target] = \
                    int(d["sub"]["dims"][target]) * rng.choice([3, 5, 7])
            try:
                _restore(rt, tam)
            except CannotRestoreStateError as e:
                assert e.code and e.code.startswith("SC0"), e
                assert e.findings, "typed error must carry the diff"
    finally:
        m.shutdown()


# ==================================================== report + surfaces

def test_runtime_schema_report_attached():
    m, rt, _ = _rt(PATTERN_APP)
    try:
        rep = rt.state_schema
        assert rep is not None
        assert rt.analysis.schema is rep
        assert len(rep.digest()) == 12
        assert any(e.endswith(":state") for e in rep.elements)
        doc = rep.as_dict()
        assert doc["digest"] == rep.digest()
        assert doc["elements"]
        assert rep.findings == []
    finally:
        m.shutdown()


def test_stats_json_embeds_state_schema():
    from siddhi_tpu.service.rest import SiddhiService
    svc = SiddhiService(port=0)
    try:
        rt = svc.manager.create_siddhi_app_runtime(
            "@app:statistics(enable='true') " + AGG_APP)
        doc = svc._stats_json()
        app_doc = doc["apps"][rt.name]
        assert "state_schema" in app_doc
        assert app_doc["state_schema"]["digest"] == \
            rt.state_schema.digest()
        assert app_doc["state_schema"]["elements"]
    finally:
        svc.manager.shutdown()


def test_persist_restore_keeps_snapshot_verified_roundtrip():
    """The happy path through the verifier: persist → fresh runtime →
    restore_last_revision → continuation agrees."""
    store = InMemoryPersistenceStore()
    m, rt, _ = _rt(AGG_APP, store)
    try:
        rt.get_input_handler("S").send(["a", 2.0])
        rt.get_input_handler("S").send(["a", 3.0])
        rt.persist()
    finally:
        m.shutdown()
    m2, rt2, got = _rt(AGG_APP, store)
    try:
        rt2.restore_last_revision()
        rt2.get_input_handler("S").send(["a", 5.0])
        assert got[-1][1] == pytest.approx(10.0)
    finally:
        m2.shutdown()


# ============================================================== analyze CLI

def test_analyze_schema_cli_is_jax_free(tmp_path):
    app = tmp_path / "a.siddhi"
    app.write_text(PATTERN_APP)
    code = (
        "import sys\n"
        "import siddhi_tpu.analyze as A\n"
        f"rc = A.main([{str(app)!r}, '--schema'])\n"
        "assert rc == 0, rc\n"
        "assert 'jax' not in sys.modules, 'jax leaked into --schema'\n"
    )
    r = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))))
    assert r.returncode == 0, r.stderr
    assert not r.stderr.strip(), r.stderr


def test_analyze_schema_registry_mode(tmp_path):
    r = subprocess.run(
        [sys.executable, "-m", "siddhi_tpu.analyze", "--schema"],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 0, r.stderr
    assert "0 audit finding(s)" in r.stdout
    assert "nfa-engine" in r.stdout


def test_extract_app_schema_static_dump_stable():
    from siddhi_tpu.analysis.state_schema import extract_app_schema
    s1 = extract_app_schema(PATTERN_APP)
    s2 = extract_app_schema(PATTERN_APP)
    assert s1.dump() == s2.dump()
    assert s1.digest() == s2.digest()
    assert s1.findings == []
    assert any(e.decl_name == "keyed-pattern" for e in s1.elements)
    assert "nfa-engine" in s1.versions()
