"""Hopping window behaviour (reference HopingWindowProcessor)."""
from siddhi_tpu import QueryCallback, SiddhiManager


def test_hoping_window_emits_on_hops():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:playback
        define stream S (v int);
        @info(name='q')
        from S#window.hoping(2 sec, 1 sec) select v
        insert all events into Out;
    """)
    currents, expireds = [], []
    rt.add_callback("q", QueryCallback(lambda ts, cur, exp: (
        currents.extend(e.data[0] for e in (cur or [])),
        expireds.extend(e.data[0] for e in (exp or [])))))
    rt.start()
    h = rt.get_input_handler("S")
    h.send([1], timestamp=1000)
    h.send([2], timestamp=1500)
    h.send([3], timestamp=2100)    # hop at 2000 emits current [1, 2]
    h.send([4], timestamp=3200)    # hop at 3000: current [2, 3], 1 expired
    rt.app_ctx.timestamp_generator.observe_event_time(4200)
    rt.app_ctx.scheduler.advance_to(4200)  # hop at 4000: 2 expired
    rt.shutdown()
    assert currents[:2] == [1, 2]
    assert 3 in currents
    assert 1 in expireds and 2 in expireds


def test_hoping_window_batch_spans_hop_boundary():
    """Events at/before a hop boundary arriving in the same batch as a
    later event must be included in that hop's emission."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:playback
        define stream S (v int);
        @info(name='q')
        from S#window.hoping(2 sec, 1 sec) select v
        insert all events into Out;
    """)
    hops = []
    rt.add_callback("q", QueryCallback(lambda ts, cur, exp: hops.append(
        [e.data[0] for e in (cur or [])])))
    rt.start()
    h = rt.get_input_handler("S")
    h.send([1], timestamp=1000)
    # one batch spanning the hop at 2000: 1500 belongs to that hop
    h.send_batch({"v": [2, 3]}, timestamps=[1500, 2100])
    rt.shutdown()
    assert hops[0] == [1, 2]
