"""Round-5 SEQUENCE leading/mid kleene device algebra: randomized parity
against the host oracle for the family the r4 review pinned host-only,
now modeled in-kernel (ops/nfa.py):

- dead-start (min >= 2 leading kleene never matches — barrier algebra),
- min-1 single-live-chain occupancy with pre-event cnt_prev,
- min-0 virgin closer-block after a freeze (seq_froze carry lane),
- every-clone seed on same-event close+append,
- single-admission arm blocking (CountPost re-add owns the new-list),
- self-indexed e[last] refs in kleene CONDITIONS with __cnt null-law
  gates (reference ExpressionParser.java:1366 self-shifted last index).
"""
import numpy as np
import pytest

from siddhi_tpu import QueryCallback, SiddhiManager

A = "define stream A (v float, w float);\n"


def run(app, rows, engine=None, expect_backend=None):
    m = SiddhiManager()
    pre = "@app:playback " + (f"@app:engine('{engine}') " if engine else "")
    rt = m.create_siddhi_app_runtime(pre + app)
    got = []
    rt.add_callback("q", QueryCallback(
        lambda ts, cur, exp: got.extend(
            (ts, tuple(e.data)) for e in (cur or []))))
    rt.start()
    h = rt.get_input_handler("A")
    for row, ts in rows:
        h.send(row, timestamp=ts)
    backend = rt.query_runtimes["q"].backend
    if expect_backend:
        assert backend == expect_backend, rt.query_runtimes["q"].backend_reason
    rt.shutdown()
    return got


def parity(app, rows):
    dev = run(app, rows, expect_backend="device")
    host = run(app, rows, engine="host", expect_backend="host")
    assert dev == host, f"device {dev[:6]}... vs host {host[:6]}..."
    return dev


def gen(seed, n=60, vmax=10.0, step=200):
    rng = np.random.default_rng(seed)
    ts = 1_000_000
    rows = []
    for _ in range(n):
        ts += int(rng.integers(1, step))
        rows.append(([float(np.float32(rng.uniform(0, vmax))),
                      float(np.float32(rng.uniform(0, vmax)))], ts))
    return rows


HEADS = ["every e1=A[v < 6.0]*", "e1=A[v < 6.0]*",
         "every e1=A[v < 6.0]+", "e1=A[v < 6.0]+",
         "every e1=A[v < 6.0]?", "e1=A[v < 6.0]?",
         "every e1=A[v < 6.0]<0:3>", "every e1=A[v < 6.0]<0:1>",
         "every e1=A[v < 6.0]<1:2>", "e1=A[v < 6.0]<1:3>"]


@pytest.mark.parametrize("head", HEADS)
def test_leading_kleene_overlapping_close(head):
    """Single-stream: events in (4, 6) both append and close — exercises
    the reversed unit order, the seed, and the closer-block."""
    app = A + f"""@info(name='q')
    from {head}, e2=A[v > 4.0]
    select e1[0].v as a, e1[1].v as b, e2.v as g insert into Out;"""
    for seed in (13, 29, 7):
        parity(app, gen(seed))


@pytest.mark.parametrize("head", ["every e1=A[v < 9.0]<2:6>",
                                  "e1=A[v < 9.0]<2:6>",
                                  "every e1=A[v < 9.0]<3:4>"])
def test_leading_kleene_dead_start(head):
    """min >= 2 leading kleene in SEQUENCE: zero matches ever."""
    app = A + f"""@info(name='q')
    from {head}, e2=A[v > 1.0]
    select e1[1].v as b, e2.v as g insert into Out;"""
    for seed in (13, 29):
        assert parity(app, gen(seed, n=80)) == []


@pytest.mark.parametrize("seed", [3, 17, 23, 31])
def test_mid_kleene_self_last_rising(seed):
    """The conformance rising-run shape: self e2[last] in the kleene's
    own condition + cross e2[last] in the closer."""
    app = A + """@info(name='q')
    from every e1=A[v > 2.0],
         e2=A[(e2[last].v is null and v >= e1.v) or
              ((not (e2[last].v is null)) and v >= e2[last].v)]+,
         e3=A[v < e2[last].v]
    select e1.v as a, e2[0].v as b, e2[1].v as c, e2[last].v as d,
           e3.v as g insert into Out;"""
    parity(app, gen(seed, n=80))


@pytest.mark.parametrize("seed", [5, 19])
def test_mid_kleene_self_last_unguarded(seed):
    """Unguarded self-last compare: the null law (empty chain compares
    false) must ride the __cnt gate, not the zero-filled lane."""
    app = A + """@info(name='q')
    from every e1=A[v > 2.0], e2=A[v >= e2[last].v or v >= e1.v]+,
         e3=A[v < e2[last].v]
    select e1.v as a, e2[0].v as b, e2[last].v as d, e3.v as g
    insert into Out;"""
    parity(app, gen(seed, n=80))


@pytest.mark.parametrize("seed", [11, 37])
def test_mid_kleene_bounded_self_last(seed):
    """Bounded mid kleene with a self-last condition: freeze-at-max plus
    the single-admission arm block."""
    app = A + """@info(name='q')
    from every e1=A[v > 5.0], e2=A[v < 5.0 and (e2[last].v is null or
         v >= e2[last].v - 2.0)]<1:3>, e3=A[v > 8.0]
    select e1.v as a, e2[0].v as b, e3.v as g insert into Out;"""
    parity(app, gen(seed, n=80))


@pytest.mark.parametrize("seed", [13, 29])
def test_leading_kleene_self_last_condition(seed):
    """Self e[last] inside the LEADING kleene's own condition: each
    re-arm is a fresh empty chain, so the arm (and the every-clone seed)
    must evaluate the condition in a VIRGIN capture context, not slot 0's
    stale banks (review r5)."""
    app = A + """@info(name='q')
    from every e1=A[e1[last].v is null or v > e1[last].v]<1:3>,
         e2=A[v > 6.0]
    select e1[0].v as a, e2.v as g insert into Out;"""
    parity(app, gen(seed, n=60))


@pytest.mark.parametrize("seed", [13, 29])
def test_leading_min0_self_last_condition(seed):
    app = A + """@info(name='q')
    from every e1=A[(e1[last].v is null and v < 5.0) or
                    ((not (e1[last].v is null)) and v > e1[last].v)]*,
         e2=A[v > 6.0]
    select e1[0].v as a, e1[1].v as b, e2.v as g insert into Out;"""
    parity(app, gen(seed, n=60))


def test_mid_kleene_min2_dead_in_sequence():
    """A mid-chain <2:n> kleene also never reaches min in a SEQUENCE (the
    barrier kills sub-min accumulators) — both engines emit nothing."""
    app = A + """@info(name='q')
    from every e1=A[v > 8.0], e2=A[v < 5.0]<2:3>, e3=A[v > 8.0]
    select e1.v as a, e2[0].v as b, e3.v as g insert into Out;"""
    rows = [([9.0, 0.0], 1000), ([1.0, 0.0], 1010), ([2.0, 0.0], 1020),
            ([9.5, 0.0], 1030)]
    assert parity(app, rows) == []


def test_leading_kleene_two_stream_cross_ref():
    """The conformance shape of test_seq_4/5/6: two streams, e1[0] read
    by the closer's condition, min-0 chain."""
    app = ("define stream S1 (sym string, p float);\n"
           "define stream S2 (sym string, p float);\n"
           """@info(name='q')
           from every e1=S2[p > 20.0]*, e2=S1[p > e1[0].p]
           select e1[0].p as a, e1[1].p as b, e2.p as g
           insert into Out;""")
    m_rows = [("S1", 59.6, 1000), ("S2", 55.6, 1100), ("S2", 55.7, 1200),
              ("S1", 57.6, 1300), ("S2", 58.0, 1400), ("S1", 58.5, 1500)]

    def go(engine):
        m = SiddhiManager()
        pre = "@app:playback " + (f"@app:engine('{engine}') " if engine
                                  else "")
        rt = m.create_siddhi_app_runtime(pre + app)
        got = []
        rt.add_callback("q", QueryCallback(
            lambda ts, cur, exp: got.extend(tuple(e.data)
                                            for e in (cur or []))))
        rt.start()
        for sid, p, ts in m_rows:
            rt.get_input_handler(sid).send([sid, float(p)], timestamp=ts)
        b = rt.query_runtimes["q"].backend
        rt.shutdown()
        return b, got
    bd, dev = go(None)
    bh, host = go("host")
    assert bd == "device" and bh == "host"
    assert dev == host and dev
