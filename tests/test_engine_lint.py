"""Engine concurrency auditor (PR 13): CE0xx/CE1xx static checks.

Unit tests drive each check against tiny synthetic modules via
``analyze_module_source``; the gate test runs the real audit over the
installed engine source and asserts it is clean modulo the justified
allowlist — so any lock/thread/hot-path regression in a future PR fails
tier-1 with a named diagnostic instead of a flaky deadlock.
"""
import json
import os
import subprocess
import sys
import textwrap

from siddhi_tpu.analysis import CATALOG, analyze_engine, catalog_markdown
from siddhi_tpu.analysis.engine import ALLOWLIST
from siddhi_tpu.analysis.engine import hotpath as hp
from siddhi_tpu.analysis.engine import lockgraph as lg

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lock_codes(src):
    a = lg.analyze_module_source(textwrap.dedent(src))
    return [f.code for f in a.findings]


def _hot_codes(src):
    a = hp.analyze_module_source(textwrap.dedent(src))
    return [f.code for f in a.findings]


# ------------------------------------------------------------------ catalog


def test_catalog_covers_engine_families():
    ce0 = [c for c in CATALOG if c.startswith("CE0")]
    ce1 = [c for c in CATALOG if c.startswith("CE1")]
    lw = [c for c in CATALOG if c.startswith("LW")]
    assert len(ce0) + len(ce1) >= 8      # acceptance: >= 8 distinct checks
    assert set(ce0) == {"CE001", "CE002", "CE003", "CE004", "CE005",
                        "CE006", "CE007", "CE008"}
    assert set(ce1) == {"CE101", "CE102", "CE103"}
    assert set(lw) == {"LW001", "LW002"}
    md = catalog_markdown()
    for title in ("Engine concurrency audit", "Engine hot-path lint",
                  "Runtime lock-witness"):
        assert f"### {title}" in md


# ------------------------------------------------------------ lock discovery


def test_lock_discovery_and_witness_names():
    a = lg.analyze_module_source(textwrap.dedent("""
        import threading
        from siddhi_tpu.core.lockwitness import maybe_wrap

        class Junction:
            def __init__(self):
                self._lock = threading.Lock()
                self._flush = maybe_wrap(
                    threading.Lock(), "core.stream.Junction._flush")
                self._cond = threading.Condition()
                self._not_a_lock = []
    """), modrel="core.stream")
    assert a.locks == {"core.stream.Junction._lock",
                       "core.stream.Junction._flush",
                       "core.stream.Junction._cond"}


def test_engine_locks_discovered():
    report = analyze_engine()
    expected = {
        "core.stream.StreamJunction._flush_lock",
        "core.resilience.CircuitBreaker._lock",
        "core.resilience.InMemoryErrorStore._lock",
        "core.scheduler.Scheduler._lock",
        "core.timestamp.TimestampGenerator._lock",
        "core.flight.FlightRecorder._lock",
        "core.ledger.LatencyLedger._lock",
    }
    missing = expected - set(report.lock_ids)
    assert not missing, f"auditor lost engine locks: {missing}"
    assert len(report.lock_ids) >= 20    # the rim really is this locky


# ------------------------------------------------------------ CE001 cycles


def test_ce001_lock_order_cycle():
    codes = _lock_codes("""
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def fwd(self):
                with self._a:
                    with self._b:
                        pass

            def bwd(self):
                with self._b:
                    with self._a:
                        pass
    """)
    assert "CE001" in codes


def test_ce001_clean_on_consistent_order():
    codes = _lock_codes("""
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def f(self):
                with self._a:
                    with self._b:
                        pass

            def g(self):
                with self._a:
                    with self._b:
                        pass
    """)
    assert "CE001" not in codes


def test_ce001_cycle_through_one_level_call():
    codes = _lock_codes("""
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def fwd(self):
                with self._a:
                    self.take_b()

            def take_b(self):
                with self._b:
                    pass

            def bwd(self):
                with self._b:
                    self.take_a()

            def take_a(self):
                with self._a:
                    pass
    """)
    assert "CE001" in codes


# ------------------------------------------------------------ CE002 callbacks


def test_ce002_callback_under_lock():
    codes = _lock_codes("""
        import threading

        class Breaker:
            def __init__(self):
                self._lock = threading.Lock()
                self.on_transition = None

            def trip(self):
                with self._lock:
                    self.on_transition("closed", "open")
    """)
    assert "CE002" in codes


def test_ce002_listener_loop_under_lock():
    codes = _lock_codes("""
        import threading

        class Gen:
            def __init__(self):
                self._lock = threading.Lock()
                self._listeners = []

            def tick(self):
                with self._lock:
                    for fn in list(self._listeners):
                        fn(1)
    """)
    assert "CE002" in codes


def test_ce002_via_one_level_call():
    codes = _lock_codes("""
        import threading

        class Breaker:
            def __init__(self):
                self._lock = threading.Lock()
                self.on_transition = None

            def record(self):
                with self._lock:
                    self._transition()

            def _transition(self):
                self.on_transition("a", "b")
    """)
    assert "CE002" in codes


def test_ce002_clean_when_fired_outside_lock():
    # the PR 10 fix shape: collect under the lock, fire after release
    codes = _lock_codes("""
        import threading

        class Breaker:
            def __init__(self):
                self._lock = threading.Lock()
                self._pending = []
                self.on_transition = None

            def record(self):
                with self._lock:
                    self._pending.append(("a", "b"))
                for old, new in self._pending:
                    self.on_transition(old, new)
    """)
    assert "CE002" not in codes


# ---------------------------------------------------- CE003-CE007 blocking


def test_ce003_sleep_anywhere_in_engine():
    codes = _lock_codes("""
        import time

        def backoff():
            time.sleep(0.5)
    """)
    assert "CE003" in codes


def test_ce003_clean_on_event_wait():
    codes = _lock_codes("""
        import threading

        class W:
            def __init__(self):
                self._stop = threading.Event()

            def backoff(self):
                self._stop.wait(0.5)
    """)
    assert codes == []


def test_ce004_timeoutless_join_in_worker():
    codes = _lock_codes("""
        import threading

        class M:
            def start(self):
                self._t = threading.Thread(target=self._run, name="x")
                self._t.start()

            def _run(self):
                other = self.spawn_child()
                other.join()
    """)
    assert "CE004" in codes


def test_ce005_timeoutless_put_under_lock_and_timeout_ok():
    bad = _lock_codes("""
        import threading

        class J:
            def __init__(self):
                self._lock = threading.Lock()
                self._queue = None

            def send(self):
                with self._lock:
                    self._queue.put(1)
    """)
    assert "CE005" in bad
    good = _lock_codes("""
        import threading

        class J:
            def __init__(self):
                self._lock = threading.Lock()
                self._queue = None

            def send(self):
                with self._lock:
                    self._queue.put(1, timeout=0.5)
    """)
    assert "CE005" not in good


def test_ce006_io_under_lock():
    codes = _lock_codes("""
        import json
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def save(self, path, doc):
                with self._lock:
                    with open(path, "w") as f:
                        json.dump(doc, f)
    """)
    assert "CE006" in codes


def test_ce007_timeoutless_wait_in_worker():
    codes = _lock_codes("""
        import threading

        class W:
            def __init__(self):
                self._event = threading.Event()

            def start(self):
                t = threading.Thread(target=self._run, name="w")
                t.start()

            def _run(self):
                self._event.wait()
    """)
    assert "CE007" in codes


def test_ce008_unnamed_thread_and_named_ok():
    bad = _lock_codes("""
        import threading

        def start():
            t = threading.Thread(target=print, daemon=True)
            t.start()
    """)
    assert "CE008" in bad
    good = _lock_codes("""
        import threading

        def start():
            t = threading.Thread(target=print, daemon=True,
                                 name="siddhi-x")
            t.start()
    """)
    assert "CE008" not in good
    # the Timer pattern: no name kwarg exists, named via attribute
    timer = _lock_codes("""
        import threading

        def arm():
            t = threading.Timer(1.0, print)
            t.name = "siddhi-sched-timer"
            t.start()
    """)
    assert "CE008" not in timer


# ------------------------------------------------------------ CE1xx hot path


def test_ce101_env_read_on_hot_path_direct_and_via_helper():
    direct = _hot_codes("""
        import os
        from siddhi_tpu.core.hotpath import hot_path

        @hot_path("per-event")
        def deliver(e):
            if os.environ.get("KNOB"):
                return None
            return e
    """)
    assert "CE101" in direct
    via_helper = _hot_codes("""
        import os
        from siddhi_tpu.core.hotpath import hot_path

        def knob_on():
            return bool(os.environ.get("KNOB"))

        @hot_path("per-event")
        def deliver(e):
            if knob_on():
                return None
            return e
    """)
    assert "CE101" in via_helper


def test_ce101_fast_idiom_helper_passes():
    # the core/ledger.py shape: direct _data read, public-API fallback.
    # Structural verification — drop the _data read and it flags again.
    codes = _hot_codes("""
        import os
        from siddhi_tpu.core.hotpath import hot_path

        _ENV_DATA = getattr(os.environ, "_data", None)
        _KEY = "KNOB"

        def knob_on():
            if _ENV_DATA is not None:
                return _ENV_DATA.get(_KEY) is not None
            return os.environ.get("KNOB") is not None

        @hot_path("per-event")
        def deliver(e):
            if knob_on():
                return None
            return e
    """)
    assert "CE101" not in codes


def test_ce101_property_resolution():
    # record_block's shape: hot fn -> self.enabled property -> helper
    codes = _hot_codes("""
        import os
        from siddhi_tpu.core.hotpath import hot_path

        def slow_knob():
            return os.environ.get("KNOB")

        class R:
            @property
            def enabled(self):
                return slow_knob()

            @hot_path("per-block")
            def record(self, rec):
                if not self.enabled:
                    return
    """)
    assert "CE101" in codes


def test_ce102_eager_to_events():
    codes = _hot_codes("""
        from siddhi_tpu.core.hotpath import hot_path

        @hot_path("per-block")
        def egress(chunk):
            return [e.data for e in chunk.to_events()]
    """)
    assert "CE102" in codes


def test_ce103_dict_per_event():
    codes = _hot_codes("""
        from siddhi_tpu.core.hotpath import hot_path

        @hot_path("per-block")
        def render(rows):
            out = []
            for ts, row in rows:
                out.append({"ts": ts, "row": row})
            return out
    """)
    assert "CE103" in codes
    clean = _hot_codes("""
        from siddhi_tpu.core.hotpath import hot_path

        @hot_path("per-block")
        def render(rows):
            return {"n": len(rows)}      # one dict per block is fine
    """)
    assert "CE103" not in clean


# ------------------------------------------------------------------ the gate


def test_engine_is_clean_modulo_allowlist():
    report = analyze_engine()
    assert not report.diagnostics, (
        "engine audit regressed — fix the finding or (only for a "
        "provably-safe pattern) add a justified allowlist entry:\n"
        + report.render())
    assert not report.stale_allowlist, (
        f"allowlist entries match no finding (remove them): "
        f"{report.stale_allowlist}")


def test_allowlist_entries_are_justified():
    for (code, where), why in ALLOWLIST.items():
        assert code in CATALOG, f"allowlist references unknown code {code}"
        assert "::" in where, f"allowlist key {where!r} must be path::qual"
        assert why and len(why) >= 60, (
            f"allowlist entry ({code}, {where}) needs a real written "
            f"justification, not a stub")


def test_static_hot_registry_matches_runtime():
    """The AST scan and the runtime @hot_path registry must agree —
    otherwise the lint silently stops covering a decorated function."""
    import importlib

    report = analyze_engine()
    static = {f"siddhi_tpu.{name}" for name in report.hot_functions}
    # importing the owning modules fills the runtime registry
    for name in report.hot_functions:
        importlib.import_module("siddhi_tpu." + name.rsplit(".", 2)[0])
    from siddhi_tpu.core.hotpath import registry
    assert static == set(registry())


def test_cli_engine_audit_runs_without_jax():
    """`analyze --engine --strict` exits 0 and never imports jax —
    subprocess-asserted like the tests/test_plan_verify.py pattern."""
    code = (
        "import sys\n"
        "from siddhi_tpu.analyze import main\n"
        "rc = main(['--engine', '--strict', '--json'])\n"
        "assert 'jax' not in sys.modules, 'engine audit imported jax'\n"
        "sys.exit(rc)\n")
    proc = subprocess.run([sys.executable, "-c", code], cwd=ROOT,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["ok"] is True
    assert doc["engine_audit"]["hot_functions"]
    assert len(doc["engine_audit"]["locks"]) >= 20


def test_cli_engine_value_still_overrides_sp_mode():
    """--engine auto/device/host keeps its pre-PR-13 meaning."""
    proc = subprocess.run(
        [sys.executable, "-m", "siddhi_tpu.analyze", "--engine=host", "-"],
        input="define stream S (v int); @info(name='q') "
              "from S select v insert into Out;",
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
