"""Engine-integrated mesh sharding (VERDICT r2 next #2): the planner-built
device path must shard the partition axis over all local devices — these
tests run on the conftest-forced 8-virtual-device CPU mesh and assert
device==host THROUGH THE PUBLIC SiddhiManager API, plus sharded snapshot /
restore and keyed-lane slab growth.

Reference semantics: partition/PartitionRuntime.java:255-308 (per-key
runtime clones — here rows of one mesh-sharded state slab)."""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback

PAT_APP = """
define stream S (sym string, price float, kind int);
partition with (sym of S) begin
@info(name='q')
from every e1=S[kind == 0 and price > 50.0] ->
     e2=S[kind == 1 and price > e1.price]
    within 10 sec
select e1.price as p1, e2.price as p2
insert into Out;
end;
"""


def _batches(n_keys=32, n_batches=3, n=128, seed=11):
    rng = np.random.default_rng(seed)
    out, t0 = [], 1_000_000
    for _ in range(n_batches):
        out.append((
            {"sym": np.asarray([f"k{i}" for i in
                                rng.integers(0, n_keys, n)], object),
             "price": rng.uniform(0, 100, n).astype(np.float32),
             "kind": rng.integers(0, 2, n).astype(np.int32)},
            t0 + np.arange(n, dtype=np.int64)))
        t0 += 20_000
    return out


def _run(app, engine, batches, restore_mid=False):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(f"@app:playback "
                                     f"@app:engine('{engine}') {app}")
    got = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: got.extend((round(e.data[0], 3), round(e.data[1], 3))
                               for e in evs)))
    rt.start()
    h = rt.get_input_handler("S")
    for bi, (cols, ts) in enumerate(batches):
        h.send_batch(cols, timestamps=ts)
        if restore_mid and bi == 0:
            # snapshot → fresh runtime → restore → continue
            snap = rt.snapshot()
            rt.shutdown()
            rt = m.create_siddhi_app_runtime(
                f"@app:playback @app:engine('{engine}') {app}")
            rt.restore(snap)
            rt.add_callback("Out", StreamCallback(
                lambda evs: got.extend(
                    (round(e.data[0], 3), round(e.data[1], 3))
                    for e in evs)))
            rt.start()
            h = rt.get_input_handler("S")
    return sorted(got), rt


def _device_nfa(rt):
    prs = rt.partition_runtimes
    assert prs and prs[0].device_mode
    return next(iter(prs[0].device_query_runtimes.values())) \
        .device_runtime.nfa


def test_public_api_pattern_sharded_matches_host():
    import jax
    batches = _batches()
    dev, dev_rt = _run(PAT_APP, "device", batches)
    nfa = _device_nfa(dev_rt)
    assert nfa.mesh is not None and \
        int(nfa.mesh.devices.size) == len(jax.devices())
    # carry leaves actually live on every device of the mesh
    devs = {d for v in nfa.carry.values() for d in v.sharding.device_set}
    assert len(devs) == len(jax.devices())
    dev_rt.shutdown()
    host, host_rt = _run(PAT_APP, "host", batches)
    host_rt.shutdown()
    assert len(dev) > 0 and dev == host


def test_sharded_snapshot_restore_continues():
    batches = _batches(seed=5)
    dev, dev_rt = _run(PAT_APP, "device", batches, restore_mid=True)
    dev_rt.shutdown()
    host, host_rt = _run(PAT_APP, "host", batches)
    host_rt.shutdown()
    assert len(dev) > 0 and dev == host


def test_keyed_lane_growth_under_mesh():
    # more keys than the initial slab capacity (GROW_START=8): the sharded
    # carry must grow in mesh-divisible steps without losing live partials
    import jax
    batches = _batches(n_keys=100, n=256, seed=7)
    dev, dev_rt = _run(PAT_APP, "device", batches)
    nfa = _device_nfa(dev_rt)
    nd = len(jax.devices())
    assert nfa.n_partitions >= 100 and nfa.n_partitions % nd == 0
    dev_rt.shutdown()
    host, host_rt = _run(PAT_APP, "host", batches)
    host_rt.shutdown()
    assert len(dev) > 0 and dev == host


def test_unpartitioned_pattern_rounds_lane_count():
    import jax
    app = """
    define stream S (price float, kind int);
    @info(name='q')
    from every e1=S[kind == 0] -> e2=S[kind == 1 and price > e1.price]
        within 10 sec
    select e1.price as p1, e2.price as p2 insert into Out;
    """
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(f"@app:playback "
                                     f"@app:engine('device') {app}")
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    qr = rt.query_runtimes["q"]
    nfa = qr.device_runtime.nfa
    assert nfa.n_partitions == len(jax.devices())   # 1 rounded up
    rng = np.random.default_rng(0)
    n = 64
    rt.get_input_handler("S").send_batch(
        {"price": rng.uniform(0, 100, n).astype(np.float32),
         "kind": rng.integers(0, 2, n).astype(np.int32)},
        timestamps=1_000_000 + np.arange(n, dtype=np.int64))
    rt.shutdown()
    assert len(got) > 0
