"""Window processor behavioural tests (reference model: siddhi-core
query/window/* — 15 test classes over the window taxonomy; playback used for
deterministic time windows as in managment/PlaybackTestCase)."""
import numpy as np
import pytest

from siddhi_tpu import QueryCallback, SiddhiManager, StreamCallback


def playback_app(app):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("@app:playback\n" + app)
    return rt


def test_length_window_expiry():
    rt = playback_app("""
        define stream S (sym string, p double);
        @info(name='q')
        from S#window.length(2) select sym, sum(p) as total
        insert all events into Out;
    """)
    rows = []
    rt.add_callback("q", QueryCallback(lambda ts, c, e: rows.append((c, e))))
    rt.start()
    h = rt.get_input_handler("S")
    for i, p in enumerate([10.0, 20.0, 30.0, 40.0]):
        h.send(["A", p], timestamp=1000 + i)
    rt.shutdown()
    # running sums: 10, 30, (expire 10) 50, (expire 20) 70
    currents = [c[0].data[1] for c, e in rows if c]
    assert currents == [10.0, 30.0, 50.0, 70.0]


def test_length_batch():
    rt = playback_app("""
        define stream S (p long);
        from S#window.lengthBatch(2) select sum(p) as t insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    h = rt.get_input_handler("S")
    for i in range(5):
        h.send([i + 1], timestamp=1000 + i)
    rt.shutdown()
    # batches [1,2] and [3,4]; 5 pending. batch chunks summarize: one
    # aggregated row per flush (reference processInBatchNoGroupBy)
    assert [e.data[0] for e in got] == [3, 7]


def test_length_batch_multi_flush_one_send():
    """A single send_batch spanning two batch flushes must emit BOTH batches'
    aggregates — one summarized chunk per flush, not one concat chunk."""
    rt = playback_app("""
        define stream S (p long);
        from S#window.lengthBatch(2) select sum(p) as t insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    rt.get_input_handler("S").send_batch({"p": np.asarray([1, 2, 3, 4])})
    rt.shutdown()
    assert [e.data[0] for e in got] == [3, 7]


def test_length_batch_filter_keeps_summarize():
    """A filter between a batch window and the selector must not strip the
    batch mark (EventChunk transforms carry is_batch)."""
    rt = playback_app("""
        define stream S (sym string, p double);
        from S#window.lengthBatch(3)[p > 15.0]
        select sym, sum(p) as t insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    h = rt.get_input_handler("S")
    for sym, p in [("A", 10.0), ("B", 20.0), ("C", 30.0)]:
        h.send([sym, p], timestamp=1000)
    rt.shutdown()
    # batch [A,B,C] filtered to [B,C]; summarize → one row, sum 50
    assert [(e.data[0], e.data[1]) for e in got] == [("C", 50.0)]


def test_external_time_batch_multi_window_one_send():
    rt = playback_app("""
        define stream S (ets long, p double);
        from S#window.externalTimeBatch(ets, 1 sec)
        select sum(p) as t insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    rt.get_input_handler("S").send_batch(
        {"ets": np.asarray([500, 1500, 2500]),
         "p": np.asarray([1.0, 2.0, 4.0])})
    rt.shutdown()
    # windows [500,1500) -> sum 1, [1500,2500) -> sum 2; 4.0 still buffered
    assert [e.data[0] for e in got] == [1.0, 2.0]


def test_time_window():
    rt = playback_app("""
        define stream S (p double);
        @info(name='q')
        from S#window.time(1 sec) select sum(p) as t
        insert all events into Out;
    """)
    rows = []
    rt.add_callback("q", QueryCallback(lambda ts, c, e: rows.append((ts, c, e))))
    rt.start()
    h = rt.get_input_handler("S")
    h.send([10.0], timestamp=1000)
    h.send([20.0], timestamp=1800)
    h.send([1.0], timestamp=2500)   # 10.0 expired at 2000
    rt.shutdown()
    ts, cur, exp = rows[-1]
    assert cur[0].data == [21.0]


def test_time_batch():
    rt = playback_app("""
        define stream S (p double);
        from S#window.timeBatch(1 sec) select sum(p) as t insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    h = rt.get_input_handler("S")
    h.send([1.0], timestamp=1000)
    h.send([2.0], timestamp=1500)
    h.send([5.0], timestamp=2100)   # flush of [1,2] happens at 2000
    rt.shutdown()
    assert [e.data[0] for e in got] == [3.0]


def test_external_time_window():
    rt = playback_app("""
        define stream S (ts long, p double);
        @info(name='q')
        from S#window.externalTime(ts, 1 sec) select sum(p) as t
        insert all events into Out;
    """)
    rows = []
    rt.add_callback("q", QueryCallback(lambda ts, c, e: rows.append((c, e))))
    rt.start()
    h = rt.get_input_handler("S")
    h.send([1000, 10.0], timestamp=1000)
    h.send([1500, 20.0], timestamp=1500)
    h.send([2300, 5.0], timestamp=2300)
    rt.shutdown()
    currents = [c[0].data[0] for c, e in rows if c]
    assert currents[-1] == 25.0  # 10 expired (1000 <= 2300-1000)


def test_external_time_batch():
    rt = playback_app("""
        define stream S (ts long, p double);
        from S#window.externalTimeBatch(ts, 1 sec) select sum(p) as t
        insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    h = rt.get_input_handler("S")
    h.send([1000, 1.0])
    h.send([1200, 2.0])
    h.send([2100, 4.0])   # flushes [1,2]
    rt.shutdown()
    assert [e.data[0] for e in got] == [3.0]


def test_batch_window():
    rt = playback_app("""
        define stream S (p double);
        from S#window.batch() select sum(p) as t insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    h = rt.get_input_handler("S")
    h.send([[1.0], [2.0]][0])
    rt.get_input_handler("S").send_batch({"p": np.asarray([3.0, 4.0])})
    rt.shutdown()
    # batch chunks summarize: one aggregated row per chunk
    assert [e.data[0] for e in got] == [1.0, 7.0]


def test_sort_window():
    rt = playback_app("""
        define stream S (p long);
        @info(name='q')
        from S#window.sort(2, p) select p insert all events into Out;
    """)
    rows = []
    rt.add_callback("q", QueryCallback(lambda ts, c, e: rows.append((c, e))))
    rt.start()
    h = rt.get_input_handler("S")
    for v in [5, 1, 9, 3]:
        h.send([v])
    rt.shutdown()
    expired = [e[0].data[0] for c, e in rows if e]
    # keeps the 2 smallest; evicts largest each overflow: 9 then 5
    assert expired == [9, 5]


def test_session_window():
    rt = playback_app("""
        define stream S (user string, p double);
        @info(name='q')
        from S#window.session(1 sec, user) select user, sum(p) as t
        group by user insert all events into Out;
    """)
    rows = []
    rt.add_callback("q", QueryCallback(lambda ts, c, e: rows.append((c, e))))
    rt.start()
    h = rt.get_input_handler("S")
    h.send(["u1", 1.0], timestamp=1000)
    h.send(["u1", 2.0], timestamp=1400)
    h.send(["u1", 10.0], timestamp=3000)  # gap > 1s: previous session expires
    rt.shutdown()
    expired_totals = [e[-1].data[1] for c, e in rows if e]
    assert expired_totals and expired_totals[-1] == 0.0  # both removed


def test_delay_window():
    rt = playback_app("""
        define stream S (p long);
        from S#window.delay(1 sec) select p insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    h = rt.get_input_handler("S")
    h.send([1], timestamp=1000)
    h.send([2], timestamp=1100)
    assert got == []            # nothing emitted yet
    h.send([3], timestamp=2500)  # 1 and 2 now due
    rt.shutdown()
    assert [e.data[0] for e in got] == [1, 2]


def test_frequent_window():
    rt = playback_app("""
        define stream S (sym string);
        from S#window.frequent(1, sym) select sym insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    h = rt.get_input_handler("S")
    for s in ["A", "A", "B", "A"]:
        h.send([s])
    rt.shutdown()
    # B arrives at capacity, only decrements A's count, and is dropped
    # unemitted (reference FrequentWindowProcessor)
    assert [e.data[0] for e in got] == ["A", "A", "A"]


def test_timelength_window():
    rt = playback_app("""
        define stream S (p long);
        @info(name='q')
        from S#window.timeLength(10 sec, 2) select sum(p) as t
        insert all events into Out;
    """)
    rows = []
    rt.add_callback("q", QueryCallback(lambda ts, c, e: rows.append((c, e))))
    rt.start()
    h = rt.get_input_handler("S")
    for i, v in enumerate([1, 2, 4]):
        h.send([v], timestamp=1000 + i)
    rt.shutdown()
    currents = [c[0].data[0] for c, e in rows if c]
    assert currents == [1, 3, 6]  # length-2 eviction: 2+4


def test_named_window_shared():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (p long);
        define window W (p long) length(3) output all events;
        from S select p insert into W;
        @info(name='q')
        from W select sum(p) as t insert into Out;
    """)
    got = []
    rt.add_callback("Out", StreamCallback(lambda evs: got.extend(evs)))
    rt.start()
    h = rt.get_input_handler("S")
    for v in [1, 2, 3]:
        h.send([v])
    rt.shutdown()
    assert [e.data[0] for e in got] == [1, 3, 6]
