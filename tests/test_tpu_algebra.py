"""Device/host conformance for the extended compiled-NFA algebra
(VERDICT r1 item 3): logical and/or pairs, absent `not … for t`, SEQUENCE
strict contiguity, non-leading kleene counts, every-prefix groups — each
construct the planner compiles must produce byte-identical output to the
host oracle (reference semantics: query/input/stream/state/*).
"""
import numpy as np
import pytest

from siddhi_tpu import QueryCallback, SiddhiManager, StreamCallback

STREAMS = """
define stream A (k int, v float);
define stream B (k int, w float);
"""


def run_app(app, sends, engine=None, until=None):
    prefix = f"@app:engine('{engine}') " if engine else ""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(prefix + app)
    out = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: out.extend(tuple(e.data) for e in evs)))
    rt.start()
    for sid, row, ts in sends:
        rt.get_input_handler(sid).send(row, timestamp=ts)
    if until is not None:      # playback apps: advance virtual time
        rt.app_ctx.timestamp_generator.observe_event_time(until)
        rt.app_ctx.scheduler.advance_to(until)
    backend = rt.query_runtimes["q"].backend
    reason = rt.query_runtimes["q"].backend_reason
    rt.shutdown()
    return backend, reason, out


def assert_parity(app, sends, until=None):
    bh, _, host = run_app(app, sends, engine="host", until=until)
    bd, reason, dev = run_app(app, sends, until=until)
    assert bh == "host"
    assert bd == "device", f"did not plan onto the device: {reason}"
    assert host == dev, f"host={host} dev={dev}"


def A(ts, k, v):
    return ("A", [k, v], ts)


def B(ts, k, w):
    return ("B", [k, w], ts)


# --------------------------------------------------------------- logical

def test_logical_and_two_streams():
    app = STREAMS + """
        @info(name='q')
        from every (e1=A[v > 10.0] and e2=B[w > 5.0]) -> e3=A[v > 50.0]
        select e1.v as v1, e2.w as w2, e3.v as v3 insert into Out;
    """
    sends = [A(1000, 1, 20.0), B(1100, 1, 7.0), A(1200, 1, 60.0),
             B(1300, 1, 9.0), A(1400, 1, 30.0), A(1500, 1, 70.0)]
    assert_parity(app, sends)


def test_logical_and_same_stream_single_event_completes():
    app = STREAMS + """
        @info(name='q')
        from every (e1=A[v > 10.0] and e2=A[k == 3]) -> e3=A[v > 50.0]
        select e1.v as v1, e2.v as v2, e3.v as v3 insert into Out;
    """
    # the first event satisfies BOTH sides at once
    sends = [A(1000, 3, 20.0), A(1100, 1, 60.0),
             A(1200, 3, 5.0), A(1300, 1, 12.0), A(1400, 9, 99.0)]
    assert_parity(app, sends)


def test_logical_and_first_side_wins():
    app = STREAMS + """
        @info(name='q')
        from every (e1=A[v > 10.0] and e2=B[w > 5.0])
        select e1.v as v1, e2.w as w2 insert into Out;
    """
    # two A's before the B: the FIRST capture sticks
    sends = [A(1000, 1, 20.0), A(1100, 1, 30.0), B(1200, 1, 8.0),
             A(1300, 1, 40.0), B(1400, 1, 9.0)]
    assert_parity(app, sends)


def test_logical_or_same_event_left_side_wins():
    """One event satisfying BOTH or-sides captures only the left side
    (oracle: the left pre-processor completes first and removes the
    partner; LogicalPreStateProcessor)."""
    app = STREAMS + """
        @info(name='q')
        from every e1=A[v > 10.0] ->
             e2=A[v > e1.v] or e3=A[k == 2]
        select e1.v as v1, e2.v as v2, e3.v as v3 insert into Out;
    """
    # k=2 event also has v > e1.v: both sides true
    assert_parity(app, [A(1_000_000, 0, 20.0), A(1_000_100, 2, 30.0)])


def test_sequence_logical_unit_is_strict():
    """A sequence partial whose or-unit matches neither side on the next
    event dies (strict contiguity applies to logical units too)."""
    app = STREAMS.replace("define stream A", "define stream A2").replace(
        "define stream B", "define stream B2") + """
        @info(name='q')
        from every e1=A2[v > 20.0],
             e2=A2[v > e1.v] or e3=A2[k == 2]
        select e1.v as v1, e2.v as v2, e3.v as v3 insert into Out;
    """
    app = app.replace("A2", "A").replace("B2", "B")
    sends = [A(1_000_000, 0, 59.6), A(1_000_100, 0, 55.6),
             A(1_000_200, 2, 55.7), A(1_000_300, 0, 57.6)]
    assert_parity(app, sends)


def test_logical_and_same_event_both_capture():
    """One event satisfying BOTH and-sides completes the unit with both
    captures referencing that event (host law)."""
    app = STREAMS + """
        @info(name='q')
        from every e1=A[v > 10.0] ->
             e2=A[v > e1.v] and e3=A[k == 2]
        select e1.v as v1, e2.v as v2, e3.v as v3 insert into Out;
    """
    assert_parity(app, [A(1_000_000, 0, 20.0), A(1_000_100, 2, 30.0)])


def test_sequence_and_half_done_partial_survives():
    """A sequence and-partial with one side satisfied survives events that
    match neither free side (the oracle's logical pending entry waits for
    its partner); a partial with NO side satisfied dies."""
    app = STREAMS + """
        @info(name='q')
        from every e1=A[v > 20.0], e2=A[v > e1.v] and e3=B[w > 5.0]
        select e1.v as v1, e2.v as v2, e3.w as w3 insert into Out;
    """
    assert_parity(app, [A(1, 0, 30.0), A(2, 0, 40.0), A(3, 0, 50.0),
                        B(4, 0, 9.0)])
    assert_parity(app, [A(1, 0, 30.0), A(2, 0, 40.0), A(3, 0, 10.0),
                        B(4, 0, 9.0)])


def test_leading_or_same_event_left_side_wins():
    """A leading or-group armed by an event satisfying BOTH sides captures
    only the left side."""
    app = STREAMS + """
        @info(name='q')
        from every (e1=A[v > 10.0] or e2=A[k == 2]) -> e3=A[v > 50.0]
        select e1.v as v1, e2.v as v2, e3.v as v3 insert into Out;
    """
    assert_parity(app, [A(1, 2, 20.0), A(2, 0, 60.0)])


def test_leading_or_arm_leaves_clean_lmask_for_downstream_logical():
    """A leading or-group that completes on arming must hand the partial to
    the next unit with a CLEAN side mask — stale bits made a downstream
    `and` believe one side was already satisfied."""
    app = STREAMS + """
        @info(name='q')
        from every (e1=A[v > 10.0] or e2=B[w > 100.0])
             -> e3=A[v > 50.0] and e4=B[w > 5.0]
        select e1.v as v1, e3.v as v3, e4.w as w4 insert into Out;
    """
    assert_parity(app, [A(1, 0, 20.0), B(2, 0, 9.0)])
    app_seq = app.replace("-> e3=", ", e3=")
    assert_parity(app_seq, [A(1, 0, 20.0), A(2, 0, 30.0), A(3, 0, 60.0),
                            B(4, 0, 9.0)])


def test_logical_or_null_side_decodes_none():
    app = STREAMS + """
        @info(name='q')
        from every (e1=A[v > 10.0] or e2=B[w > 5.0])
        select e1.v as v1, e2.w as w2 insert into Out;
    """
    sends = [A(1000, 1, 20.0), B(1100, 1, 8.0), A(1200, 1, 5.0),
             B(1300, 1, 6.5)]
    assert_parity(app, sends)


def test_logical_or_then_chain_with_guarded_ref():
    app = STREAMS + """
        @info(name='q')
        from every (e1=A[v > 10.0] or e2=B[w > 5.0]) -> e3=A[v > e1.v]
        select e1.v as v1, e3.v as v3 insert into Out;
    """
    # when the or fired on the B side, e1.v is null → e3 filter never true
    sends = [B(1000, 1, 8.0), A(1100, 1, 50.0), A(1200, 1, 20.0),
             A(1300, 1, 25.0)]
    assert_parity(app, sends)


def test_logical_within_expiry():
    app = STREAMS + """
        @info(name='q')
        from every (e1=A[v > 10.0] and e2=B[w > 5.0]) -> e3=A[v > 50.0]
            within 1 sec
        select e1.v as v1, e3.v as v3 insert into Out;
    """
    sends = [A(1000, 1, 20.0), B(1100, 1, 7.0), A(2500, 1, 60.0),
             A(2600, 1, 21.0), B(2700, 1, 7.5), A(2800, 1, 61.0)]
    assert_parity(app, sends)


# ----------------------------------------------------------------- counts

def test_nonleading_count_bounds():
    app = STREAMS + """
        @info(name='q')
        from every e1=A[v > 50.0] -> e2=A[v < 10.0]<2:3> -> e3=A[v > 50.0]
        select e1.v as v1, e2[0].v as first2, e2[last].v as last2,
               e3.v as v3
        insert into Out;
    """
    sends = [A(1000, 1, 60.0), A(1100, 1, 1.0), A(1200, 1, 2.0),
             A(1300, 1, 3.0), A(1400, 1, 70.0),
             A(1500, 1, 61.0), A(1600, 1, 4.0), A(1700, 1, 71.0)]
    assert_parity(app, sends)


def test_nonleading_count_live_append_until_next():
    # after min is reached the kleene keeps absorbing while e3 pends;
    # e2[last] reflects every append up to the closing event
    app = STREAMS + """
        @info(name='q')
        from every e1=A[v > 50.0] -> e2=A[v < 10.0]<1:4> -> e3=A[v > 50.0]
        select e2[0].v as first2, e2[last].v as last2 insert into Out;
    """
    sends = [A(1000, 1, 60.0), A(1100, 1, 1.0), A(1200, 1, 2.0),
             A(1300, 1, 3.0), A(1400, 1, 70.0)]
    assert_parity(app, sends)


def test_nonleading_star_zero_occurrence():
    app = STREAMS + """
        @info(name='q')
        from every e1=A[v > 50.0] -> e2=A[v < 10.0]* -> e3=B[w > 0.0]
        select e1.v as v1, e2[0].v as first2, e3.w as w3 insert into Out;
    """
    # match with zero e2 events (B follows A directly) and with some
    sends = [A(1000, 1, 60.0), B(1100, 1, 5.0),
             A(1200, 1, 61.0), A(1300, 1, 2.0), A(1400, 1, 3.0),
             B(1500, 1, 6.0)]
    assert_parity(app, sends)


def test_trailing_count_matches_at_min():
    app = STREAMS + """
        @info(name='q')
        from every e1=A[v > 50.0] -> e2=A[v < 10.0]<2:4>
        select e1.v as v1, e2[0].v as first2, e2[last].v as last2
        insert into Out;
    """
    sends = [A(1000, 1, 60.0), A(1100, 1, 1.0), A(1200, 1, 2.0),
             A(1300, 1, 3.0)]
    assert_parity(app, sends)


def test_count_within_expiry():
    app = STREAMS + """
        @info(name='q')
        from every e1=A[v > 50.0] -> e2=A[v < 10.0]<2:3> -> e3=A[v > 50.0]
            within 1 sec
        select e1.v as v1, e3.v as v3 insert into Out;
    """
    sends = [A(1000, 1, 60.0), A(1100, 1, 1.0), A(2500, 1, 2.0),
             A(2600, 1, 61.0), A(2700, 1, 3.0), A(2800, 1, 4.0),
             A(2900, 1, 70.0)]
    assert_parity(app, sends)


# ----------------------------------------------------------------- absent

def test_absent_fires_after_wait():
    app = "@app:playback " + STREAMS + """
        @info(name='q')
        from e1=A[v > 20.0] -> not B[w > e1.v] for 1 sec
        select e1.v as v1 insert into Out;
    """
    assert_parity(app, [A(1000, 1, 25.0)], until=2100)


def test_absent_suppressed_by_arrival():
    app = "@app:playback " + STREAMS + """
        @info(name='q')
        from e1=A[v > 20.0] -> not B[w > e1.v] for 1 sec
        select e1.v as v1 insert into Out;
    """
    assert_parity(app, [A(1000, 1, 25.0), B(1500, 1, 30.0)], until=2100)


def test_absent_arrival_below_filter_does_not_suppress():
    app = "@app:playback " + STREAMS + """
        @info(name='q')
        from e1=A[v > 20.0] -> not B[w > e1.v] for 1 sec
        select e1.v as v1 insert into Out;
    """
    assert_parity(app, [A(1000, 1, 25.0), B(1500, 1, 10.0)], until=2100)


def test_absent_middle_then_next_state():
    app = "@app:playback " + STREAMS + """
        @info(name='q')
        from every e1=A[v > 20.0] -> not B[w > 0.0] for 1 sec
            -> e3=A[v > 50.0]
        select e1.v as v1, e3.v as v3 insert into Out;
    """
    sends = [A(1000, 1, 25.0), A(2500, 1, 60.0),
             A(3000, 1, 26.0), B(3200, 1, 5.0), A(4500, 1, 61.0)]
    assert_parity(app, sends, until=5000)


# -------------------------------------------------------------- sequences

def test_sequence_basic_strict():
    app = STREAMS + """
        @info(name='q')
        from e1=A[v > 20.0], e2=A[v > e1.v]
        select e1.v as v1, e2.v as v2 insert into Out;
    """
    # the interleaved low event breaks contiguity
    sends = [A(1000, 1, 25.0), A(1100, 1, 5.0), A(1200, 1, 30.0),
             A(1300, 1, 40.0)]
    assert_parity(app, sends)


def test_sequence_every():
    app = STREAMS + """
        @info(name='q')
        from every e1=A[v > 20.0], e2=A[v > e1.v]
        select e1.v as v1, e2.v as v2 insert into Out;
    """
    sends = [A(1000, 1, 25.0), A(1100, 1, 30.0), A(1200, 1, 40.0),
             A(1300, 1, 10.0), A(1400, 1, 50.0), A(1500, 1, 60.0)]
    assert_parity(app, sends)


def test_sequence_two_streams_strict_across_streams():
    app = STREAMS + """
        @info(name='q')
        from every e1=A[v > 20.0], e2=B[w > 0.0]
        select e1.v as v1, e2.w as w2 insert into Out;
    """
    # an intervening A event must break the contiguity of a pending pair
    sends = [A(1000, 1, 25.0), A(1100, 1, 2.0), B(1200, 1, 5.0),
             A(1300, 1, 30.0), B(1400, 1, 6.0)]
    assert_parity(app, sends)


def test_sequence_nonleading_plus():
    app = STREAMS + """
        @info(name='q')
        from every e1=A[v > 50.0], e2=A[v < 10.0]+, e3=A[v > 50.0]
        select e1.v as v1, e2[0].v as first2, e2[last].v as last2,
               e3.v as v3
        insert into Out;
    """
    sends = [A(1000, 1, 60.0), A(1100, 1, 1.0), A(1200, 1, 2.0),
             A(1300, 1, 70.0),
             A(1400, 1, 61.0), A(1500, 1, 20.0), A(1600, 1, 71.0)]
    assert_parity(app, sends)


def test_sequence_nonleading_star():
    app = STREAMS + """
        @info(name='q')
        from every e1=A[v > 50.0], e2=A[v < 10.0]*, e3=B[w > 0.0]
        select e1.v as v1, e3.w as w3 insert into Out;
    """
    sends = [A(1000, 1, 60.0), B(1100, 1, 5.0),
             A(1200, 1, 61.0), A(1300, 1, 2.0), B(1400, 1, 6.0),
             A(1500, 1, 62.0), A(1600, 1, 20.0), B(1700, 1, 7.0)]
    assert_parity(app, sends)


def test_sequence_or_pair():
    app = STREAMS + """
        @info(name='q')
        from every e1=A[v > 20.0], e2=A[v > e1.v] or e3=A[k == 7]
        select e1.v as v1, e2.v as v2, e3.v as v3 insert into Out;
    """
    sends = [A(1000, 1, 25.0), A(1100, 7, 2.0), A(1200, 1, 30.0),
             A(1300, 1, 40.0)]
    assert_parity(app, sends)


def test_sequence_within():
    app = STREAMS + """
        @info(name='q')
        from every e1=A[v > 20.0], e2=A[v > e1.v] within 1 sec
        select e1.v as v1, e2.v as v2 insert into Out;
    """
    sends = [A(1000, 1, 25.0), A(2500, 1, 30.0), A(2600, 1, 40.0)]
    assert_parity(app, sends)


# ----------------------------------------------------- every-prefix groups

def test_every_full_chain_group():
    app = STREAMS + """
        @info(name='q')
        from every (e1=A[v > 10.0] -> e2=A[v > e1.v])
        select e1.v as v1, e2.v as v2 insert into Out;
    """
    # one partial in flight at a time; re-arms only after completion
    sends = [A(1000, 1, 20.0), A(1100, 1, 30.0), A(1200, 1, 25.0),
             A(1300, 1, 40.0), A(1400, 1, 11.0), A(1500, 1, 50.0)]
    assert_parity(app, sends)


def test_every_group_within():
    app = STREAMS + """
        @info(name='q')
        from every (e1=A[v > 10.0] -> e2=A[v > e1.v]) within 1 sec
        select e1.v as v1, e2.v as v2 insert into Out;
    """
    sends = [A(1000, 1, 20.0), A(2500, 1, 30.0), A(2600, 1, 40.0)]
    assert_parity(app, sends)


def test_logical_unnamed_sides_plan_onto_device():
    # synthesized refs for unnamed sides must not collide
    app = STREAMS + """
        @info(name='q')
        from every (A[v > 10.0] and B[w > 5.0]) -> e3=A[v > 50.0]
        select e3.v as v3 insert into Out;
    """
    sends = [A(1000, 1, 20.0), B(1100, 1, 7.0), A(1200, 1, 60.0)]
    assert_parity(app, sends)


def test_sequence_absent_compiles_to_device():
    # round 4: sequence-absent stabilize semantics are mirrored on the
    # device (the kill-at-step-start barrier in ops/nfa.py)
    app = "@app:playback " + STREAMS + """
        @info(name='q')
        from e1=A[v > 10.0], not B[w > 0.0] for 1 sec, e3=A[v > 50.0]
        select e1.v as v1, e3.v as v3 insert into Out;
    """
    backend, _reason, _ = run_app(app, [A(1000, 1, 20.0)], until=2500)
    assert backend == "device"


# ------------------------------------------------------------------- fuzz

FUZZ_APPS = [
    STREAMS + """
        @info(name='q')
        from every e1=A[v > 60.0] -> e2=A[v < 30.0]<1:3> -> e3=A[v > 60.0]
            within 2 sec
        select e1.v as v1, e2[0].v as f2, e2[last].v as l2, e3.v as v3
        insert into Out;
    """,
    STREAMS + """
        @info(name='q')
        from every (e1=A[v > 60.0] and e2=B[w > 60.0]) -> e3=A[v > 80.0]
            within 2 sec
        select e1.v as v1, e2.w as w2, e3.v as v3 insert into Out;
    """,
    STREAMS + """
        @info(name='q')
        from every (e1=A[v > 70.0] or e2=B[w > 70.0]) -> e3=B[w > 80.0]
        select e1.v as v1, e2.w as w2, e3.w as w3 insert into Out;
    """,
    STREAMS + """
        @info(name='q')
        from every e1=A[v > 50.0], e2=A[v < 50.0]*, e3=A[v > 90.0]
        select e1.v as v1, e3.v as v3 insert into Out;
    """,
    STREAMS + """
        @info(name='q')
        from every e1=A[v > 50.0], e2=B[w > e1.v]
        select e1.v as v1, e2.w as w2 insert into Out;
    """,
]


@pytest.mark.parametrize("app_i", range(len(FUZZ_APPS)))
@pytest.mark.parametrize("seed", [0, 1])
def test_fuzz_parity(app_i, seed):
    rng = np.random.default_rng(1000 * app_i + seed)
    sends = []
    ts = 1_000_000
    for _ in range(60):
        ts += int(rng.integers(50, 400))
        if rng.random() < 0.7:
            sends.append(A(ts, int(rng.integers(0, 3)),
                           float(np.round(rng.uniform(0, 100), 1))))
        else:
            sends.append(B(ts, int(rng.integers(0, 3)),
                           float(np.round(rng.uniform(0, 100), 1))))
    assert_parity(FUZZ_APPS[app_i], sends)
