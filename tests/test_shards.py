"""Partition-axis shard-out (round 15).

The routing contract is load-bearing: ``fnv1a(str(key))`` picks the
owning shard (and the owning PROCESS in parallel/multihost.py), and
per-shard checkpoints are addressed by that assignment — so the literal
hash vectors pinned here must NEVER change.  A drift would silently
re-route keys away from their carried NFA state after a restore.

Beyond the routing pins: randomized sharded-vs-monolithic parity for
the pattern / windowed-agg / grouped-agg device runtimes, elastic
per-shard growth that provably leaves sibling carries untouched
(object identity), the per-shard snapshot/restore path, and the
plan-IR / cost-model / statistics shard surfaces.
"""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.snapshot import InMemoryPersistenceStore
from siddhi_tpu.parallel.shards import (fnv1a, fnv1a_vec, owner_ids,
                                        routing_digest, split_rows)
from siddhi_tpu.utils.errors import SiddhiAppRuntimeException

PATTERN_APP = """
@app:name('ShardPat')
define stream In (k string, v double);
partition with (k of In)
begin
  @info(name='q')
  from every e1=In[v > 1.0] -> e2=In[v > 2.0]
  select e1.k as k, e1.v as a, e2.v as b insert into Out;
end;
"""

WAGG_APP = """
@app:name('ShardWagg')
define stream S (k int, v float);
partition with (k of S)
begin
  @info(name='q')
  from S[v > 2.0]#window.length(5)
  select k, sum(v) as total, count() as n group by k
  insert into Out;
end;
"""

GAGG_APP = """
@app:name('ShardGagg')
define stream S (k string, v double);
partition with (k of S)
begin
  @info(name='q')
  from S select k, sum(v) as total group by k insert into Out;
end;
"""


def _shard_env(monkeypatch, n):
    monkeypatch.setenv("SIDDHI_TPU_MESH", "off")
    monkeypatch.setenv("SIDDHI_TPU_SHARDS", str(n))


def _pattern_dev(rt):
    pr = rt.partition_runtimes[0]
    assert pr.device_mode
    (qr,) = pr.device_query_runtimes.values()
    return qr.device_runtime


# ------------------------------------------------------------ routing pins

def test_fnv1a_pinned_literals():
    # canonical FNV-1a 64 over str(key) utf-8 — the checkpoint contract
    assert fnv1a("") == 0xCBF29CE484222325          # offset basis
    assert fnv1a("a") == 0xAF63DC4C8601EC8C
    assert fnv1a("key-0") == 0x71135BF295F28059
    assert fnv1a("key-1") == 0x71135AF295F27EA6
    assert fnv1a("ABC") == 0xFA2FE219A07442EB
    # int keys hash via str(key) — NOT repr, NOT the raw bytes
    assert fnv1a(0) == 0xAF63AD4C86019CAF
    assert fnv1a(1) == 0xAF63AC4C86019AFC
    assert fnv1a(42) == 0x07EE7E07B4B19223
    assert fnv1a(12345678901234) == 0x687867B9E0181BF8
    assert fnv1a(7) == fnv1a("7") == fnv1a(np.int64(7))


def test_routing_digest_pinned():
    assert routing_digest() == "8ab7ab948ebacb18"


def test_owner_ids_pinned_vectors():
    keys = np.array([f"key-{i}" for i in range(8)], object)
    assert owner_ids(keys, 8).tolist() == [1, 6, 3, 0, 5, 2, 7, 4]
    assert owner_ids(keys, 4).tolist() == [1, 2, 3, 0, 1, 2, 3, 0]
    assert owner_ids(np.arange(8), 8).tolist() == [7, 4, 5, 2, 3, 0, 1, 6]


def test_multihost_owner_of_matches_shard_router():
    from siddhi_tpu.parallel.multihost import owner_of
    for key in ("key-0", "key-1", "ABC", 0, 42, "", "k" * 100):
        for nproc in (2, 4, 8):
            assert owner_of(key, nproc) == fnv1a(key) % nproc
    # the pinned process assignment at nproc=8 (satellite 1: the
    # vectorized send_batch router must keep this forever)
    assert [owner_of(f"key-{i}", 8) for i in range(8)] == \
        [1, 6, 3, 0, 5, 2, 7, 4]


def test_fnv1a_vec_matches_scalar():
    rng = np.random.default_rng(5)
    str_keys = np.array([f"sym-{i}" for i in range(200)] + ["", "a", "Z"],
                        object)
    int_keys = rng.integers(-10**12, 10**12, 200)
    for arr in (str_keys, int_keys,
                np.array(["x"], object), np.array([], object)):
        vec = fnv1a_vec(arr)
        assert vec.tolist() == [fnv1a(k) for k in arr.tolist()]


def test_split_rows_partitions_by_owner():
    rng = np.random.default_rng(7)
    keys = np.array([f"k{i}" for i in rng.integers(0, 50, 400)], object)
    for n in (2, 4, 8):
        owners = owner_ids(keys, n)
        seen = []
        for sid, rows in split_rows(keys, n):
            assert len(rows) > 0                      # empty shards omitted
            assert (np.diff(rows) > 0).all()          # per-key order kept
            assert (owners[rows] == sid).all()
            seen.extend(rows.tolist())
        assert sorted(seen) == list(range(len(keys)))  # disjoint cover


def test_owner_balance_at_scale():
    # 100k distinct keys over 8 owners: FNV must stay within a few
    # percent of uniform (this is the bench --fail-on-imbalance contract)
    keys = np.arange(100_000)
    counts = np.bincount(owner_ids(keys, 8), minlength=8)
    assert counts.sum() == 100_000
    assert counts.max() / counts.mean() < 1.05


# ------------------------------------------------------------ key lanes

def test_keylanes_vectorized_lookup_all_hit():
    from siddhi_tpu.plan.planner import KeyLanes, map_keys_to_lanes
    kl = KeyLanes()
    keys = np.arange(100, dtype=np.int64)
    first = map_keys_to_lanes(kl, keys, 128, lambda c: None)
    assert len(set(first.tolist())) == 100            # distinct lanes
    again = map_keys_to_lanes(kl, keys[::-1].copy(), 128, lambda c: None)
    assert np.array_equal(again, first[::-1])         # stable mapping
    # the cached sorted-key view must notice appended keys
    more = map_keys_to_lanes(kl, np.arange(100, 140, dtype=np.int64),
                             256, lambda c: None)
    assert len(set(kl.values())) == 140
    assert not set(more.tolist()) & set(first.tolist())


def test_keylanes_string_keys():
    from siddhi_tpu.plan.planner import KeyLanes, map_keys_to_lanes
    kl = KeyLanes()
    keys = np.array([f"s{i:03d}" for i in range(80)], object)
    first = map_keys_to_lanes(kl, keys, 128, lambda c: None)
    again = map_keys_to_lanes(kl, keys, 128, lambda c: None)
    assert np.array_equal(first, again)
    assert kl.lookup(np.array(["s000", "s079"])) is not None


# ------------------------------------------------------------ parity

def _feed_pattern(n_shards, monkeypatch, n_keys=40, n_blocks=8,
                  block=300, seed=11):
    _shard_env(monkeypatch, n_shards)
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(PATTERN_APP)
    got = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: got.extend(tuple(e.data) for e in evs)))
    rt.start()
    h = rt.get_input_handler("In")
    rng = np.random.default_rng(seed)
    t0 = 1_000_000
    for _ in range(n_blocks):
        ki = rng.integers(0, n_keys, block)
        h.send_batch(
            {"k": np.array([f"key-{i}" for i in ki], object),
             "v": rng.uniform(0.0, 3.0, block)},
            timestamps=t0 + np.arange(block, dtype=np.int64))
        t0 += block
    rt.flush()
    snap = rt.statistics
    m.shutdown()
    return sorted(got), snap


def test_sharded_pattern_parity_and_stats(monkeypatch):
    mono, snap0 = _feed_pattern(0, monkeypatch)
    assert len(mono) > 0
    assert "shards" not in snap0                    # kill switch: no rows
    for n in (2, 4):
        shard, snap = _feed_pattern(n, monkeypatch)
        assert shard == mono, f"pattern parity FAILED at S={n}"
        rows = next(iter(snap["shards"].values()))
        assert len(rows) == n
        assert sum(r["keys"] for r in rows) == 40
        assert sum(r["events"] for r in rows) == 8 * 300
        assert len({r["device"] for r in rows}) == n  # own device each


def _feed_wagg(n_shards, monkeypatch, seed=3):
    _shard_env(monkeypatch, n_shards)
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(WAGG_APP)
    got = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: got.extend(tuple(e.data) for e in evs)))
    rt.start()
    h = rt.get_input_handler("S")
    rng = np.random.default_rng(seed)
    t0 = 1_000_000
    for _ in range(6):
        n = 500
        h.send_batch(
            {"k": rng.integers(0, 24, n).astype(np.int32),
             "v": rng.uniform(0.0, 10.0, n).astype(np.float32)},
            timestamps=t0 + np.arange(n, dtype=np.int64))
        t0 += n
    rt.flush()
    m.shutdown()
    return sorted(got)


def test_sharded_wagg_parity(monkeypatch):
    mono = _feed_wagg(0, monkeypatch)
    assert len(mono) > 0
    for n in (2, 4):
        assert _feed_wagg(n, monkeypatch) == mono, \
            f"wagg parity FAILED at S={n}"


def _feed_gagg(n_shards, monkeypatch, seed=9):
    _shard_env(monkeypatch, n_shards)
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(GAGG_APP)
    got = []
    rt.add_callback("Out", StreamCallback(
        lambda evs: got.extend(tuple(e.data) for e in evs)))
    rt.start()
    h = rt.get_input_handler("S")
    rng = np.random.default_rng(seed)
    t0 = 1_000_000
    for _ in range(6):
        n = 400
        ki = rng.integers(0, 30, n)
        h.send_batch(
            {"k": np.array([f"g{i}" for i in ki], object),
             "v": rng.uniform(0.0, 5.0, n)},
            timestamps=t0 + np.arange(n, dtype=np.int64))
        t0 += n
    rt.flush()
    m.shutdown()
    return sorted(got)


def test_sharded_gagg_parity(monkeypatch):
    mono = _feed_gagg(0, monkeypatch)
    assert len(mono) > 0
    for n in (2, 4):
        assert _feed_gagg(n, monkeypatch) == mono, \
            f"gagg parity FAILED at S={n}"


# ------------------------------------------------------------ elasticity

def test_hot_shard_growth_leaves_siblings_untouched(monkeypatch):
    """Mid-feed growth of ONE shard must not touch sibling engines: no
    re-trace, no replay, not even a new carry object — the whole point
    of per-shard elasticity."""
    _shard_env(monkeypatch, 4)
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(PATTERN_APP)
    got = [0]
    rt.add_callback("Out", StreamCallback(
        lambda evs: got.__setitem__(0, got[0] + len(evs))))
    rt.start()
    h = rt.get_input_handler("In")

    # phase 1: a few keys on every shard
    warm = np.array([f"key-{i}" for i in range(8)], object)
    h.send_batch({"k": warm[np.arange(64) % 8],
                  "v": np.tile([1.5, 2.5], 32)},
                 timestamps=1_000_000 + np.arange(64, dtype=np.int64))
    rt.flush()

    dev = _pattern_dev(rt)
    assert dev.shards is not None and len(dev.shards) == 4
    hot = dev.shards[0]
    # keys owned by the hot shard only — enough distinct ones to force
    # its lane slab past capacity
    candidates = np.array([f"grow-{i}" for i in range(4000)], object)
    mine = candidates[owner_ids(candidates, 4) == 0]
    need = int(hot.engine.n_partitions) + 8
    assert len(mine) >= need
    mine = mine[:need]

    before = {i: (sh.engine.carry, sh.engine.n_partitions, sh.grows)
              for i, sh in enumerate(dev.shards) if i != 0}
    cap0 = hot.engine.n_partitions

    reps = np.repeat(mine, 2)           # e1 then e2 per key -> matches
    vals = np.tile([1.5, 2.5], len(mine))
    h.send_batch({"k": reps, "v": vals},
                 timestamps=2_000_000 + np.arange(len(reps),
                                                  dtype=np.int64))
    rt.flush()

    assert hot.engine.n_partitions > cap0, "hot shard never grew"
    assert hot.grows > 0
    for i, sh in enumerate(dev.shards):
        if i == 0:
            continue
        carry, cap, grows = before[i]
        assert sh.engine.carry is carry, \
            f"sibling shard {i} carry was touched by shard 0's growth"
        assert sh.engine.n_partitions == cap
        assert sh.grows == grows
    assert got[0] > 0
    m.shutdown()


# ------------------------------------------------------------ checkpoint

def test_sharded_persist_restore_roundtrip(monkeypatch):
    _shard_env(monkeypatch, 4)
    store = InMemoryPersistenceStore()
    rng = np.random.default_rng(21)
    n = 600
    ki = rng.integers(0, 32, 2 * n)
    vv = rng.uniform(0.0, 5.0, 2 * n)

    def fresh():
        m = SiddhiManager()
        m.set_persistence_store(store)
        rt = m.create_siddhi_app_runtime(GAGG_APP)
        last = {}
        rt.add_callback("Out", StreamCallback(
            lambda evs: [last.__setitem__(e.data[0], e.data[1])
                         for e in evs]))
        rt.start()
        return m, rt, last

    def feed(rt, lo, hi, t0):
        rt.get_input_handler("S").send_batch(
            {"k": np.array([f"g{i}" for i in ki[lo:hi]], object),
             "v": vv[lo:hi]},
            timestamps=t0 + np.arange(hi - lo, dtype=np.int64))
        rt.flush()

    m1, rt1, _ = fresh()
    feed(rt1, 0, n, 1_000_000)
    rt1.persist()
    rt1.shutdown()

    m2, rt2, last = fresh()
    rt2.restore_last_revision()
    feed(rt2, n, 2 * n, 2_000_000)
    rt2.shutdown()

    expect = {}
    for i, v in zip(ki, vv):
        expect[f"g{i}"] = expect.get(f"g{i}", 0.0) + v
    # every key fed in phase 2 must report its FULL (pre+post restore)
    # running sum — per-shard carries really came back
    for key in {f"g{i}" for i in ki[n:]}:
        assert last[key] == pytest.approx(expect[key], rel=1e-5)


def test_shard_count_mismatch_rejected(monkeypatch):
    _shard_env(monkeypatch, 4)
    store = InMemoryPersistenceStore()
    m = SiddhiManager()
    m.set_persistence_store(store)
    rt = m.create_siddhi_app_runtime(GAGG_APP)
    rt.start()
    rt.get_input_handler("S").send_batch(
        {"k": np.array([f"g{i}" for i in range(16)], object),
         "v": np.ones(16)},
        timestamps=1_000_000 + np.arange(16, dtype=np.int64))
    rt.flush()
    rt.persist()
    rt.shutdown()

    # the routing is modular in the shard count: restoring 4-shard
    # state into a 2-shard runtime would scatter keys away from their
    # carries — must be rejected loudly
    _shard_env(monkeypatch, 2)
    m2 = SiddhiManager()
    m2.set_persistence_store(store)
    rt2 = m2.create_siddhi_app_runtime(GAGG_APP)
    rt2.start()
    with pytest.raises(SiddhiAppRuntimeException, match="shard"):
        rt2.restore_last_revision()
    rt2.shutdown()


# ------------------------------------------------------------ surfaces

def test_plan_ir_and_cost_model_shard_surfaces(monkeypatch):
    from siddhi_tpu.analysis.cost_model import (nfa_egress_bytes,
                                                nfa_state_bytes,
                                                plan_cost)
    from siddhi_tpu.analysis.plan_ir import extract_plan
    _shard_env(monkeypatch, 4)
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(PATTERN_APP)
    rt.start()
    plan = extract_plan(rt)
    (a,) = plan.automata
    assert a.shards == 4
    assert len(a.shard_partitions) == 4
    assert f"shards={a.shards} " in plan.dump()
    d = a.as_dict()
    assert d["shards"] == 4 and len(d["shard_partitions"]) == 4
    (entry,) = plan_cost(plan).entries
    want = sum(sum(nfa_state_bytes(a, n_partitions=p).values())
               for p in a.shard_partitions) + nfa_egress_bytes(a)
    assert entry.hbm_bytes == want
    m.shutdown()

    # monolithic control: the new fields stay invisible (goldens)
    _shard_env(monkeypatch, 0)
    m2 = SiddhiManager()
    rt2 = m2.create_siddhi_app_runtime(PATTERN_APP)
    rt2.start()
    p2 = extract_plan(rt2)
    assert p2.automata[0].shards == 0
    assert "shards" not in p2.automata[0].as_dict()
    assert "shards=" not in p2.dump()
    m2.shutdown()


def test_shard_eligibility_gate_absent(monkeypatch):
    _shard_env(monkeypatch, 4)
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (k string, v double);
        partition with (k of S) begin
        @info(name='q')
        from e1=S[v > 1.0] -> not S[v > e1.v] for 1 sec
        select e1.k as k insert into Out; end;
    """)
    rt.start()
    dev = _pattern_dev(rt)
    assert dev.shards is None
    assert "absent" in (dev.shard_reason or "")
    m.shutdown()


def test_sa080_diagnostic():
    from siddhi_tpu.analysis import analyze
    absent_app = """
        define stream S (k string, v double);
        partition with (k of S) begin
        from e1=S[v > 1.0] -> not S[v > e1.v] for 1 sec
        select e1.k as k insert into Out; end;
    """
    r = analyze(absent_app)
    hits = [d for d in r.diagnostics if d.code == "SA080"]
    assert hits and "absent" in hits[0].message
    # an eligible keyed partition stays silent
    assert not [d for d in analyze(PATTERN_APP).diagnostics
                if d.code == "SA080"]
