"""Record-table SPI tests — external stores receive compiled conditions
(store-neutral RecordExpr trees) and selection pushdown
(reference: table/record/AbstractRecordTable.java,
AbstractQueryableRecordTable.java; rendered to SQL by stores/sqlite.py)."""
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager, StreamCallback
from siddhi_tpu.core.record_table import (AbstractRecordTable, Cmp, Col,
                                          Param)

APP_HEAD = """
define stream StockStream (symbol string, price float, volume long);
define stream CheckStockStream (symbol string, volume long);
define stream UpdateStockStream (symbol string, price float, volume long);
define stream DeleteStockStream (symbol string);
"""


class DictStore(AbstractRecordTable):
    """Minimal list-of-dicts store with a call log, used to assert what the
    engine actually pushes through the SPI."""

    instances = []

    def init(self, definition, store_annotation):
        self.rows = []
        self.calls = []
        DictStore.instances.append(self)

    def _eval(self, e, row, params):
        from siddhi_tpu.core.record_table import (Agg, Arith, BoolAnd,
                                                  BoolNot, BoolOr, Cmp, Col,
                                                  Const, NullCheck, Param)
        if e is None:
            return True
        if isinstance(e, Col):
            return row[e.name]
        if isinstance(e, Const):
            return e.value
        if isinstance(e, Param):
            return params[e.name]
        if isinstance(e, Cmp):
            import operator
            l, r = self._eval(e.left, row, params), \
                self._eval(e.right, row, params)
            return {"==": operator.eq, "!=": operator.ne, "<": operator.lt,
                    "<=": operator.le, ">": operator.gt,
                    ">=": operator.ge}[e.op](l, r)
        if isinstance(e, BoolAnd):
            return self._eval(e.left, row, params) and \
                self._eval(e.right, row, params)
        if isinstance(e, BoolOr):
            return self._eval(e.left, row, params) or \
                self._eval(e.right, row, params)
        if isinstance(e, BoolNot):
            return not self._eval(e.expr, row, params)
        if isinstance(e, NullCheck):
            return self._eval(e.expr, row, params) is None
        if isinstance(e, Arith):
            import operator
            l, r = self._eval(e.left, row, params), \
                self._eval(e.right, row, params)
            return {"+": operator.add, "-": operator.sub, "*": operator.mul,
                    "/": operator.truediv, "%": operator.mod}[e.op](l, r)
        raise AssertionError(f"unexpected node {e}")

    def add(self, records):
        self.calls.append(("add", len(records)))
        self.rows.extend(dict(r) for r in records)

    def find_records(self, condition, params):
        self.calls.append(("find", condition, dict(params)))
        return [r for r in self.rows if self._eval(condition, r, params)]

    def update_records(self, condition, param_rows, assignments):
        self.calls.append(("update", condition))
        for pr in param_rows:
            for r in self.rows:
                if self._eval(condition, r, pr):
                    for col, e in assignments:
                        r[col] = self._eval(e, r, pr)

    def delete_records(self, condition, param_rows):
        self.calls.append(("delete", condition))
        for pr in param_rows:
            self.rows = [r for r in self.rows
                         if not self._eval(condition, r, pr)]


@pytest.fixture(autouse=True)
def _reset_dictstore():
    DictStore.instances = []
    yield
    DictStore.instances = []


def _manager_with_dictstore():
    m = SiddhiManager()
    m.set_extension("store:dict", DictStore)
    return m


def _run(m, app, sends, out_stream="OutStream"):
    rt = m.create_siddhi_app_runtime(app)
    got = []
    if out_stream:
        rt.add_callback(out_stream, StreamCallback(
            lambda evs: got.extend(tuple(e.data) for e in evs)))
    rt.start()
    ts = 1_000_000
    for sid, row in sends:
        ts += 100
        rt.get_input_handler(sid).send(row, timestamp=ts)
    rt.shutdown()
    return got


FILL = [("StockStream", ["WSO2", 55.6, 100]),
        ("StockStream", ["IBM", 75.6, 10])]


def test_record_table_insert_and_join_pushes_condition():
    m = _manager_with_dictstore()
    got = _run(m, APP_HEAD + """
        @Store(type='dict')
        define table StockTable (symbol string, price float, volume long);
        from StockStream insert into StockTable;
        @info(name='q')
        from CheckStockStream join StockTable
            on CheckStockStream.symbol == StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;""",
        FILL + [("CheckStockStream", ["IBM", 0]),
                ("CheckStockStream", ["WSO2", 0])])
    assert got == [("IBM", 10), ("WSO2", 100)]
    store = DictStore.instances[0]
    assert ("add", 1) in store.calls
    # the join probed through the SPI — a compiled Cmp(Col == Param)
    finds = [c for c in store.calls if c[0] == "find"]
    assert any(isinstance(c[1], Cmp) and isinstance(c[1].left, Col)
               or isinstance(c[1], Cmp) and isinstance(c[1].right, Col)
               for c in finds if c[1] is not None), finds


def test_record_table_update_and_delete():
    m = _manager_with_dictstore()
    _run(m, APP_HEAD + """
        @Store(type='dict')
        define table StockTable (symbol string, price float, volume long);
        from StockStream insert into StockTable;
        from UpdateStockStream update StockTable
            set StockTable.volume = UpdateStockStream.volume
            on StockTable.symbol == UpdateStockStream.symbol;
        from DeleteStockStream delete StockTable
            on StockTable.symbol == DeleteStockStream.symbol;""",
        FILL + [("UpdateStockStream", ["IBM", 75.6, 99]),
                ("DeleteStockStream", ["WSO2"])], out_stream=None)
    store = DictStore.instances[0]
    assert store.rows == [{"symbol": "IBM", "price": pytest.approx(75.6),
                           "volume": 99}]


def test_record_table_update_or_insert():
    m = _manager_with_dictstore()
    _run(m, APP_HEAD + """
        @Store(type='dict')
        define table StockTable (symbol string, price float, volume long);
        from UpdateStockStream update or insert into StockTable
            set StockTable.volume = UpdateStockStream.volume
            on StockTable.symbol == UpdateStockStream.symbol;""",
        [("UpdateStockStream", ["IBM", 75.6, 10]),
         ("UpdateStockStream", ["IBM", 75.6, 30]),
         ("UpdateStockStream", ["WSO2", 55.6, 5])], out_stream=None)
    store = DictStore.instances[0]
    by_sym = {r["symbol"]: r["volume"] for r in store.rows}
    assert by_sym == {"IBM": 30, "WSO2": 5}


def test_record_table_in_membership():
    m = _manager_with_dictstore()
    got = _run(m, APP_HEAD + """
        @Store(type='dict') @PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        from StockStream insert into StockTable;
        @info(name='q')
        from CheckStockStream[CheckStockStream.symbol in StockTable]
        select symbol, volume insert into OutStream;""",
        FILL + [("CheckStockStream", ["IBM", 1]),
                ("CheckStockStream", ["FB", 2])])
    assert got == [("IBM", 1)]


def test_record_table_store_query_find():
    m = _manager_with_dictstore()
    rt = m.create_siddhi_app_runtime(APP_HEAD + """
        @Store(type='dict')
        define table StockTable (symbol string, price float, volume long);
        from StockStream insert into StockTable;""")
    rt.start()
    h = rt.get_input_handler("StockStream")
    for _, row in FILL:
        h.send(row)
    events = rt.query("from StockTable on volume < 50 "
                      "select symbol, volume")
    assert [tuple(e.data) for e in events] == [("IBM", 10)]
    rt.shutdown()


# ---------------------------------------------------------------- sqlite

def _sqlite_table_of(rt):
    return rt.tables["StockTable"]


def test_sqlite_store_end_to_end():
    m = SiddhiManager()
    got = _run(m, APP_HEAD + """
        @Store(type='sqlite')
        define table StockTable (symbol string, price float, volume long);
        from StockStream insert into StockTable;
        from UpdateStockStream update StockTable
            set StockTable.volume = UpdateStockStream.volume,
                StockTable.price = StockTable.price + 1.0
            on StockTable.symbol == UpdateStockStream.symbol;
        @info(name='q')
        from CheckStockStream join StockTable
            on CheckStockStream.symbol == StockTable.symbol
               and StockTable.volume > CheckStockStream.volume
        select StockTable.symbol, StockTable.volume
        insert into OutStream;""",
        FILL + [("UpdateStockStream", ["IBM", 0.0, 500]),
                ("CheckStockStream", ["IBM", 400]),
                ("CheckStockStream", ["WSO2", 400])])
    assert got == [("IBM", 500)]


def test_sqlite_store_query_selection_pushdown():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, price float, volume long);
        @Store(type='sqlite')
        define table StockTable (symbol string, price float, volume long);
        from S insert into StockTable;""")
    rt.start()
    h = rt.get_input_handler("S")
    for row in (["IBM", 10.0, 5], ["IBM", 20.0, 7], ["WSO2", 30.0, 2],
                ["WSO2", 40.0, 1], ["MSFT", 5.0, 9]):
        h.send(row)
    events = rt.query(
        "from StockTable select symbol, sum(volume) as total "
        "group by symbol order by total desc limit 2")
    assert [tuple(e.data) for e in events] == [("IBM", 12), ("MSFT", 9)]
    table = _sqlite_table_of(rt)
    assert any("GROUP BY" in s and "ORDER BY" in s and "LIMIT" in s
               for s in table.sql_log), table.sql_log
    rt.shutdown()


def test_sqlite_store_query_on_condition_pushdown():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, price float, volume long);
        @Store(type='sqlite')
        define table StockTable (symbol string, price float, volume long);
        from S insert into StockTable;""")
    rt.start()
    h = rt.get_input_handler("S")
    for row in (["IBM", 10.0, 5], ["WSO2", 30.0, 2]):
        h.send(row)
    events = rt.query("from StockTable on volume >= 5 "
                      "select symbol, volume")
    assert [tuple(e.data) for e in events] == [("IBM", 5)]
    table = _sqlite_table_of(rt)
    assert any("WHERE" in s and "volume" in s for s in table.sql_log)
    rt.shutdown()


def test_sqlite_having_alias_shadows_column():
    """HAVING reads the output row (host QuerySelector semantics) even when
    a select rename shadows a table column of the same name."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, price float);
        @Store(type='sqlite')
        define table T (symbol string, price float);
        from S insert into T;""")
    rt.start()
    h = rt.get_input_handler("S")
    for row in (["IBM", 10.0], ["IBM", 200.0], ["W", 30.0]):
        h.send(row)
    events = rt.query("from T select symbol, avg(price) as price "
                      "group by symbol having price > 50")
    assert [tuple(e.data) for e in events] == [("IBM", 105.0)]
    rt.shutdown()


def test_sqlite_empty_table_ungrouped_aggregate_matches_host():
    """SUM over an empty store must return no rows, like the host path —
    not SQL's single NULL row."""
    for store_ann in ("@Store(type='sqlite')", ""):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(f"""
            define stream S (symbol string, volume long);
            {store_ann}
            define table T (symbol string, volume long);
            from S insert into T;""")
        rt.start()
        events = rt.query("from T select sum(volume) as total")
        assert [tuple(e.data) for e in events] == [], store_ann or "host"
        # arithmetic over COUNT yields a non-NULL/non-0 SQL value on zero
        # rows — must still emit nothing (host parity)
        events = rt.query("from T select count(volume) + 1 as n")
        assert [tuple(e.data) for e in events] == [], store_ann or "host"
        rt.shutdown()


def test_record_table_batched_update_single_spi_call():
    """A multi-event update batch arrives as ONE update_records call."""
    m = _manager_with_dictstore()
    rt = m.create_siddhi_app_runtime(APP_HEAD + """
        @Store(type='dict')
        define table StockTable (symbol string, price float, volume long);
        from StockStream insert into StockTable;
        from UpdateStockStream update StockTable
            set StockTable.volume = UpdateStockStream.volume
            on StockTable.symbol == UpdateStockStream.symbol;""")
    rt.start()
    for _, row in FILL:
        rt.get_input_handler("StockStream").send(row)
    rt.get_input_handler("UpdateStockStream").send_batch(
        {"symbol": np.asarray(["IBM", "WSO2"], object),
         "price": np.asarray([1.0, 2.0], np.float32),
         "volume": np.asarray([7, 8], np.int64)})
    rt.shutdown()
    store = DictStore.instances[0]
    assert [c for c in store.calls if c[0] == "update"] == \
        [("update", store.calls[-1][1])]       # exactly one update call
    assert {r["symbol"]: r["volume"] for r in store.rows} == \
        {"IBM": 7, "WSO2": 8}


def test_record_table_update_without_set_overwrites_same_named():
    """`update T on ...` with no SET clause copies same-named stream columns
    (InMemoryTable._apply_set parity)."""
    for ann in ("@Store(type='sqlite')", ""):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(APP_HEAD + f"""
            {ann}
            define table StockTable (symbol string, price float, volume long);
            from StockStream insert into StockTable;
            from UpdateStockStream update StockTable
                on StockTable.symbol == UpdateStockStream.symbol;""")
        rt.start()
        for _, row in FILL:
            rt.get_input_handler("StockStream").send(row)
        rt.get_input_handler("UpdateStockStream").send(["IBM", 99.0, 777])
        events = rt.query("from StockTable on symbol == 'IBM' "
                          "select symbol, volume")
        assert [tuple(e.data) for e in events] == [("IBM", 777)], ann
        rt.shutdown()


def test_grouped_store_query_parity_host_vs_pushdown():
    """Grouped aggregates in a pull query summarize to one row per group on
    BOTH paths — the host selector must not emit running per-row rows."""
    results = {}
    for ann in ("@Store(type='sqlite')", ""):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(f"""
            define stream S (symbol string, volume long);
            {ann}
            define table T (symbol string, volume long);
            from S insert into T;""")
        rt.start()
        h = rt.get_input_handler("S")
        for row in (["IBM", 5], ["WSO2", 9], ["IBM", 2]):
            h.send(row)
        events = rt.query("from T select symbol, sum(volume) as total "
                          "group by symbol order by total desc limit 5")
        results[ann or "host"] = [tuple(e.data) for e in events]
        rt.shutdown()
    assert results["@Store(type='sqlite')"] == results["host"] == \
        [("WSO2", 9), ("IBM", 7)]


def test_sqlite_bool_column_pushdown_parity():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, flag bool);
        @Store(type='sqlite')
        define table T (symbol string, flag bool);
        from S insert into T;""")
    rt.start()
    rt.get_input_handler("S").send(["IBM", True])
    events = rt.query("from T select symbol, flag")
    assert [tuple(e.data) for e in events] == [("IBM", True)]
    assert isinstance(events[0].data[1], bool)
    rt.shutdown()


def test_sqlite_string_concat_condition_parity():
    """Engine `+` on strings is concatenation — the sqlite store must
    render `||`, not numeric `+`."""
    results = {}
    for ann in ("@Store(type='sqlite')", ""):
        m = SiddhiManager()
        rt = m.create_siddhi_app_runtime(f"""
            define stream S (a string, b string);
            define stream P (a string);
            {ann}
            define table T (a string, b string);
            from S insert into T;
            @info(name='q')
            from P join T on P.a + 'y' == T.b
            select T.a, T.b insert into OutStream;""")
        got = []
        rt.add_callback("OutStream", StreamCallback(
            lambda evs: got.extend(tuple(e.data) for e in evs)))
        rt.start()
        rt.get_input_handler("S").send(["x", "xy"])
        rt.get_input_handler("P").send(["x"])
        rt.shutdown()
        results[ann or "host"] = got
    assert results["@Store(type='sqlite')"] == results["host"] == \
        [("x", "xy")]


def test_sqlite_float_mod_falls_back_to_host_semantics():
    """SQLite '%' truncates REALs to INTEGER; the store refuses that
    condition so the join evaluates it host-side (fmod semantics)."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, v double);
        define stream P (sym string);
        @Store(type='sqlite')
        define table T (sym string, v double);
        from S insert into T;
        @info(name='q')
        from P join T on T.v % 2.0 > 1.0
        select T.sym, T.v insert into OutStream;""")
    got = []
    rt.add_callback("OutStream", StreamCallback(
        lambda evs: got.extend(tuple(e.data) for e in evs)))
    rt.start()
    rt.get_input_handler("S").send(["A", 5.5])     # fmod(5.5,2)=1.5 > 1
    rt.get_input_handler("S").send(["B", 4.5])     # fmod(4.5,2)=0.5
    rt.get_input_handler("P").send(["x"])
    rt.shutdown()
    assert got == [("A", 5.5)]


def test_sqlite_quoted_table_name():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream S (sym string, v long);
        @Store(type='sqlite', table='odd "name"')
        define table T (sym string, v long);
        from S insert into T;""")
    rt.start()
    rt.get_input_handler("S").send(["A", 1])
    events = rt.query("from T select sym, v")
    assert [tuple(e.data) for e in events] == [("A", 1)]
    rt.shutdown()


def test_sqlite_snapshot_skips_external_state():
    """@Store contents are owned by the external system — persist()/restore
    round-trips must not try to serialize the connection."""
    m = SiddhiManager()
    from siddhi_tpu.core.snapshot import InMemoryPersistenceStore
    m.set_persistence_store(InMemoryPersistenceStore())
    rt = m.create_siddhi_app_runtime("""
        define stream S (symbol string, volume long);
        @Store(type='sqlite')
        define table StockTable (symbol string, volume long);
        from S insert into StockTable;""")
    rt.start()
    rt.get_input_handler("S").send(["IBM", 5])
    rt.persist()
    rt.restore_last_revision()
    events = rt.query("from StockTable select symbol, volume")
    assert [tuple(e.data) for e in events] == [("IBM", 5)]
    rt.shutdown()


def test_sqlite_store_native_upsert_on_conflict():
    """With a declared @PrimaryKey and a PK-equality match condition the
    sqlite store must use its atomic INSERT ... ON CONFLICT upsert (no
    probe→write race against external writers) — visible in sql_log."""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(APP_HEAD + """
        @Store(type='sqlite') @PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        from UpdateStockStream update or insert into StockTable
            set StockTable.volume = UpdateStockStream.volume
            on StockTable.symbol == UpdateStockStream.symbol;""")
    rt.start()
    h = rt.get_input_handler("UpdateStockStream")
    for i, row in enumerate([["IBM", 75.6, 10], ["IBM", 75.6, 30],
                             ["WSO2", 55.6, 5]]):
        h.send(row, timestamp=1_000_000 + i * 100)
    table = _sqlite_table_of(rt)
    rows = sorted(table.find_records(None, {}), key=lambda r: r["symbol"])
    assert [(r["symbol"], r["volume"]) for r in rows] == \
        [("IBM", 30), ("WSO2", 5)]
    assert any("ON CONFLICT" in s for s in table.sql_log), table.sql_log
    rt.shutdown()
