"""Small reference conformance suites: IsNullTestCase,
BooleanCompareTestCase, StringCompareTestCase, PassThroughTestCase
(siddhi-core/src/test/java/io/siddhi/core/query/)."""
from ref_harness import run_query

S = "define stream cseEventStream (symbol string, price float, volume long);\n"


def test_is_null_filter_matches_null_payload():
    """IsNullTestCase.testIsNull1: only the null-symbol event passes."""
    run_query(S + """@info(name='query1')
        from cseEventStream[symbol is null]
        select price insert into outputStream;""",
        [("cseEventStream", ["IBM", 700.0, 100]),
         ("cseEventStream", [None, 60.5, 200]),
         ("cseEventStream", ["WSO2", 60.5, 200])],
        [(60.5,)])


def test_not_is_null_filter():
    run_query(S + """@info(name='query1')
        from cseEventStream[not (symbol is null)]
        select symbol insert into outputStream;""",
        [("cseEventStream", ["IBM", 700.0, 100]),
         ("cseEventStream", [None, 60.5, 200]),
         ("cseEventStream", ["WSO2", 60.5, 200])],
        [("IBM",), ("WSO2",)])


def test_is_null_in_select():
    run_query(S + """@info(name='query1')
        from cseEventStream
        select symbol is null as noSym insert into outputStream;""",
        [("cseEventStream", ["IBM", 1.0, 1]),
         ("cseEventStream", [None, 2.0, 2])],
        [(False,), (True,)])


# ------------------------------------------------- BooleanCompareTestCase

BOOL_S = "define stream S (symbol string, ok bool, price float);\n"


def test_bool_compare_true_literal():
    run_query(BOOL_S + """@info(name='query1')
        from S[ok == true] select symbol insert into Out;""",
        [("S", ["A", True, 1.0]), ("S", ["B", False, 2.0]),
         ("S", ["C", True, 3.0])],
        [("A",), ("C",)])


def test_bool_compare_false_literal():
    run_query(BOOL_S + """@info(name='query1')
        from S[ok == false] select symbol insert into Out;""",
        [("S", ["A", True, 1.0]), ("S", ["B", False, 2.0])],
        [("B",)])


def test_bool_not_equal():
    run_query(BOOL_S + """@info(name='query1')
        from S[ok != true] select symbol insert into Out;""",
        [("S", ["A", True, 1.0]), ("S", ["B", False, 2.0])],
        [("B",)])


# ------------------------------------------------- StringCompareTestCase

def test_string_equal_and_not_equal():
    run_query(S + """@info(name='query1')
        from cseEventStream[symbol == 'WSO2'] select volume
        insert into outputStream;""",
        [("cseEventStream", ["IBM", 1.0, 10]),
         ("cseEventStream", ["WSO2", 2.0, 20])],
        [(20,)])
    run_query(S + """@info(name='query1')
        from cseEventStream[symbol != 'WSO2'] select volume
        insert into outputStream;""",
        [("cseEventStream", ["IBM", 1.0, 10]),
         ("cseEventStream", ["WSO2", 2.0, 20])],
        [(10,)])


def test_string_compare_both_sides_variables():
    run_query("""define stream S (a string, b string);
        @info(name='query1')
        from S[a == b] select a insert into Out;""",
        [("S", ["x", "x"]), ("S", ["x", "y"]), ("S", ["z", "z"])],
        [("x",), ("z",)])


# ------------------------------------------------- PassThroughTestCase

def test_passthrough_select_star():
    run_query(S + """@info(name='query1')
        from cseEventStream select * insert into outputStream;""",
        [("cseEventStream", ["IBM", 700.0, 100]),
         ("cseEventStream", ["WSO2", 60.5, 200])],
        [("IBM", 700.0, 100), ("WSO2", 60.5, 200)])


def test_passthrough_projection_reorder():
    run_query(S + """@info(name='query1')
        from cseEventStream select volume, symbol
        insert into outputStream;""",
        [("cseEventStream", ["IBM", 700.0, 100])],
        [(100, "IBM")])
