"""REST service tests (reference model: siddhi-service deploy/undeploy API)."""
import json
import urllib.request

from siddhi_tpu.service import SiddhiService

APP = """
@app:name('restapp')
define stream S (symbol string, price float);
@info(name='q1') from S[price > 10] select symbol, price insert into Out;
"""


def _req(method, url, body=None):
    data = body.encode() if isinstance(body, str) else (
        json.dumps(body).encode() if body is not None else None)
    req = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_deploy_send_query_undeploy():
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        out = _req("POST", f"{base}/siddhi/artifact/deploy", APP)
        assert out == {"status": "deployed", "app": "restapp"}
        assert _req("GET", f"{base}/siddhi/apps")["apps"] == ["restapp"]
        _req("POST", f"{base}/siddhi/apps/restapp/streams/S",
             [{"data": ["IBM", 50.0]}, {"data": ["X", 5.0]}])
        assert _req("GET", f"{base}/health") == {"status": "up"}
        out = _req("GET", f"{base}/siddhi/artifact/undeploy/restapp")
        assert out["status"] == "undeployed"
        assert _req("GET", f"{base}/siddhi/apps")["apps"] == []
    finally:
        svc.stop()


def test_store_query_over_http():
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        _req("POST", f"{base}/siddhi/artifact/deploy", """
            @app:name('tapp')
            define stream S (symbol string, price float);
            define table T (symbol string, price float);
            from S insert into T;
        """)
        _req("POST", f"{base}/siddhi/apps/tapp/streams/S",
             [{"data": ["IBM", 42.0]}])
        out = _req("POST", f"{base}/siddhi/apps/tapp/query",
                   "from T select symbol, price")
        assert out["events"][0]["data"] == ["IBM", 42.0]
    finally:
        svc.stop()
