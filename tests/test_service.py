"""REST service tests (reference model: siddhi-service deploy/undeploy API)."""
import json
import urllib.error
import urllib.request

from siddhi_tpu.service import SiddhiService

APP = """
@app:name('restapp')
define stream S (symbol string, price float);
@info(name='q1') from S[price > 10] select symbol, price insert into Out;
"""


def _req(method, url, body=None):
    data = body.encode() if isinstance(body, str) else (
        json.dumps(body).encode() if body is not None else None)
    req = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_deploy_send_query_undeploy():
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        out = _req("POST", f"{base}/siddhi/artifact/deploy", APP)
        assert out == {"status": "deployed", "app": "restapp"}
        assert _req("GET", f"{base}/siddhi/apps")["apps"] == ["restapp"]
        _req("POST", f"{base}/siddhi/apps/restapp/streams/S",
             [{"data": ["IBM", 50.0]}, {"data": ["X", 5.0]}])
        health = _req("GET", f"{base}/health")
        assert health["status"] == "up" and health["ready"] is True
        assert health["apps"]["restapp"]["started"] is True
        out = _req("GET", f"{base}/siddhi/artifact/undeploy/restapp")
        assert out["status"] == "undeployed"
        assert _req("GET", f"{base}/siddhi/apps")["apps"] == []
    finally:
        svc.stop()


def test_store_query_over_http():
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        _req("POST", f"{base}/siddhi/artifact/deploy", """
            @app:name('tapp')
            define stream S (symbol string, price float);
            define table T (symbol string, price float);
            from S insert into T;
        """)
        _req("POST", f"{base}/siddhi/apps/tapp/streams/S",
             [{"data": ["IBM", 42.0]}])
        out = _req("POST", f"{base}/siddhi/apps/tapp/query",
                   "from T select symbol, price")
        assert out["events"][0]["data"] == ["IBM", 42.0]
    finally:
        svc.stop()


ERR_APP = """
@app:name('errapp')
@app:errorStore(type='memory')
define stream S (v int);
@sink(type='chaos', chaos.id='resterr', retry.max.attempts='2',
      retry.base.delay.ms='1', retry.jitter='0', circuit.reset.ms='0')
define stream O (v int);
@info(name='q') from S select v insert into O;
"""


def _raw(url):
    with urllib.request.urlopen(url) as r:
        return r.status, r.read().decode()


def test_health_error_store_and_metrics_endpoints():
    """Resilience surface over HTTP: /health readiness, error-store
    list/replay/purge, and the siddhi_* resilience series on /metrics."""
    import chaos
    chaos.reset()
    chaos.SCRIPTS["resterr"] = chaos.FailureScript.fail_always()
    svc = SiddhiService(port=0).start()
    chaos.register(svc.manager)
    base = f"http://127.0.0.1:{svc.port}"
    try:
        _req("POST", f"{base}/siddhi/artifact/deploy", ERR_APP)
        _req("POST", f"{base}/siddhi/apps/errapp/streams/S",
             [{"data": [i]} for i in range(5)])
        assert chaos.INSTANCES["resterr"].retry_join(30.0)

        out = _req("GET", f"{base}/siddhi/apps/errapp/errors")
        assert out["store"] == "InMemoryErrorStore"
        assert sum(e["events"] for e in out["errors"]) == 5
        assert all(e["origin"] == "sink" for e in out["errors"])

        health = _req("GET", f"{base}/health")
        assert health["status"] == "up"
        assert health["apps"]["errapp"]["errors_stored"] == len(
            out["errors"])

        status, text = _raw(f"{base}/metrics")
        assert status == 200
        assert "# TYPE siddhi_errors_stored_total counter" in text
        assert 'siddhi_errors_stored_total{app="errapp"' in text
        assert 'siddhi_circuit_state{app="errapp",sink="O"}' in text

        # endpoint heals → replay over HTTP drains the store
        chaos.SCRIPTS["resterr"].heal()
        out = _req("POST", f"{base}/siddhi/apps/errapp/errors/replay", {})
        assert out["replayed"] == 5
        assert chaos.INSTANCES["resterr"].retry_join(30.0)
        assert sorted(e.data[0] for e in chaos.delivered("resterr")) == \
            list(range(5))
        out = _req("GET", f"{base}/siddhi/apps/errapp/errors")
        assert out["errors"] == []

        # purge path (nothing left → purged 0)
        out = _req("POST", f"{base}/siddhi/apps/errapp/errors/purge", {})
        assert out["purged"] == 0
    finally:
        svc.stop()


def test_error_endpoints_409_without_store():
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        _req("POST", f"{base}/siddhi/artifact/deploy", APP)
        try:
            _req("POST", f"{base}/siddhi/apps/restapp/errors/replay", {})
            raise AssertionError("expected HTTP 409")
        except urllib.error.HTTPError as e:
            assert e.code == 409
            assert json.loads(e.read())["error"] == \
                "no error store configured"
        out = _req("GET", f"{base}/siddhi/apps/restapp/errors")
        assert out == {"errors": [], "store": None}
    finally:
        svc.stop()


# ------------------------------------------------- exposition contract

STATS_APP = """
@app:name('expoapp')
@app:statistics(reporter='console', interval='300', telemetry='true')
define stream S (sym string, price float);
@info(name='q')
from every e1=S[price > 10.0] -> e2=S[price > e1.price]
select e1.price as p1, e2.price as p2 insert into Out;
"""


def test_metrics_exposition_is_prometheus_clean():
    """/metrics contract: the version=0.0.4 text content type, every
    emitted sample series covered by exactly one # HELP/# TYPE pair
    (PR 6-9 added series faster than the header table — kernel
    scan_ticks/live_bytes/batch_b had drifted), headers before samples."""
    import numpy as np
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        _req("POST", f"{base}/siddhi/artifact/deploy", STATS_APP)
        rng = np.random.default_rng(0)
        _req("POST", f"{base}/siddhi/apps/expoapp/streams/S",
             [{"data": ["A", float(rng.uniform(5, 30))]}
              for _ in range(25)])
        svc.manager.get_siddhi_app_runtime("expoapp").flush()
        with urllib.request.urlopen(f"{base}/metrics") as r:
            ctype = r.headers.get("Content-Type", "")
            text = r.read().decode()
    finally:
        svc.stop()

    assert ctype.startswith("text/plain; version=0.0.4")

    lines = text.splitlines()
    helps, types = {}, {}
    first_sample_of = {}
    for i, ln in enumerate(lines):
        if ln.startswith("# HELP "):
            name = ln.split()[2]
            assert name not in helps, f"duplicate HELP for {name}"
            helps[name] = i
        elif ln.startswith("# TYPE "):
            name = ln.split()[2]
            assert name not in types, f"duplicate TYPE for {name}"
            types[name] = i
        elif ln:
            s = ln.split("{")[0].split(" ")[0]
            first_sample_of.setdefault(s, i)
    assert set(helps) == set(types)

    def family(series):
        for suf in ("_bucket", "_sum", "_count"):
            if series.endswith(suf) and series[: -len(suf)] in helps:
                return series[: -len(suf)]
        return series

    for s, i in first_sample_of.items():
        fam = family(s)
        assert fam in helps, f"series {s} has no # HELP/# TYPE header"
        assert helps[fam] < i and types[fam] < i, \
            f"header for {s} appears after its first sample"

    # the drifted kernel series and the new telemetry series are covered
    for name in ("siddhi_kernel_scan_ticks_total",
                 "siddhi_kernel_live_bytes", "siddhi_kernel_batch_b",
                 "siddhi_nfa_state_occupancy",
                 "siddhi_nfa_gate_pass_total"):
        assert name in helps, f"missing header for {name}"
        assert name in first_sample_of, f"no samples for {name}"


# ---------------------------------------------- rim + ledger parity

def test_rim_and_ledger_parity_across_surfaces():
    """The host-rim counters and the latency ledger must agree across
    the three read surfaces: ``rt.statistics``, ``GET /stats`` and
    ``GET /metrics``."""
    from siddhi_tpu.core.profiling import rim_stats
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        _req("POST", f"{base}/siddhi/artifact/deploy", STATS_APP)
        _req("POST", f"{base}/siddhi/apps/expoapp/streams/S",
             [{"data": ["A", 10.0 + i]} for i in range(20)])
        rt = svc.manager.get_siddhi_app_runtime("expoapp")
        rt.flush()

        snap = rt.statistics
        stats = _req("GET", f"{base}/stats")
        _, text = _raw(f"{base}/metrics")

        # rim: rt.statistics["rim"] == /stats["rim"] == the live counters
        live = rim_stats().snapshot()
        assert snap["rim"]["events_materialized"] == \
            stats["rim"]["events_materialized"] == \
            live["events_materialized"]
        assert f"siddhi_events_materialized_total " \
               f"{live['events_materialized']}" in text
        assert "siddhi_host_rim_seconds_total" in text

        # ledger: same per-app stage histograms on both JSON surfaces
        lg_rt = snap["ledger"]["apps"]["expoapp"]["stages_ms"]
        lg_http = stats["apps"]["expoapp"]["ledger"]["apps"]["expoapp"][
            "stages_ms"]
        assert lg_rt.keys() == lg_http.keys()
        for stage in lg_rt:
            assert lg_rt[stage]["count"] == lg_http[stage]["count"], stage
        assert lg_rt["device"]["count"] >= 1
        assert "siddhi_ledger_stage_latency_ms" in text
        assert 'siddhi_ledger_stage_seconds_total{stage="device"}' in text
        assert "siddhi_event_time_lag_ms" in text
    finally:
        svc.stop()
