"""REST service tests (reference model: siddhi-service deploy/undeploy API)."""
import json
import urllib.error
import urllib.request

from siddhi_tpu.service import SiddhiService

APP = """
@app:name('restapp')
define stream S (symbol string, price float);
@info(name='q1') from S[price > 10] select symbol, price insert into Out;
"""


def _req(method, url, body=None):
    data = body.encode() if isinstance(body, str) else (
        json.dumps(body).encode() if body is not None else None)
    req = urllib.request.Request(url, data=data, method=method)
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_deploy_send_query_undeploy():
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        out = _req("POST", f"{base}/siddhi/artifact/deploy", APP)
        assert out == {"status": "deployed", "app": "restapp"}
        assert _req("GET", f"{base}/siddhi/apps")["apps"] == ["restapp"]
        _req("POST", f"{base}/siddhi/apps/restapp/streams/S",
             [{"data": ["IBM", 50.0]}, {"data": ["X", 5.0]}])
        health = _req("GET", f"{base}/health")
        assert health["status"] == "up" and health["ready"] is True
        assert health["apps"]["restapp"]["started"] is True
        out = _req("GET", f"{base}/siddhi/artifact/undeploy/restapp")
        assert out["status"] == "undeployed"
        assert _req("GET", f"{base}/siddhi/apps")["apps"] == []
    finally:
        svc.stop()


def test_store_query_over_http():
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        _req("POST", f"{base}/siddhi/artifact/deploy", """
            @app:name('tapp')
            define stream S (symbol string, price float);
            define table T (symbol string, price float);
            from S insert into T;
        """)
        _req("POST", f"{base}/siddhi/apps/tapp/streams/S",
             [{"data": ["IBM", 42.0]}])
        out = _req("POST", f"{base}/siddhi/apps/tapp/query",
                   "from T select symbol, price")
        assert out["events"][0]["data"] == ["IBM", 42.0]
    finally:
        svc.stop()


ERR_APP = """
@app:name('errapp')
@app:errorStore(type='memory')
define stream S (v int);
@sink(type='chaos', chaos.id='resterr', retry.max.attempts='2',
      retry.base.delay.ms='1', retry.jitter='0', circuit.reset.ms='0')
define stream O (v int);
@info(name='q') from S select v insert into O;
"""


def _raw(url):
    with urllib.request.urlopen(url) as r:
        return r.status, r.read().decode()


def test_health_error_store_and_metrics_endpoints():
    """Resilience surface over HTTP: /health readiness, error-store
    list/replay/purge, and the siddhi_* resilience series on /metrics."""
    import chaos
    chaos.reset()
    chaos.SCRIPTS["resterr"] = chaos.FailureScript.fail_always()
    svc = SiddhiService(port=0).start()
    chaos.register(svc.manager)
    base = f"http://127.0.0.1:{svc.port}"
    try:
        _req("POST", f"{base}/siddhi/artifact/deploy", ERR_APP)
        _req("POST", f"{base}/siddhi/apps/errapp/streams/S",
             [{"data": [i]} for i in range(5)])
        assert chaos.INSTANCES["resterr"].retry_join(30.0)

        out = _req("GET", f"{base}/siddhi/apps/errapp/errors")
        assert out["store"] == "InMemoryErrorStore"
        assert sum(e["events"] for e in out["errors"]) == 5
        assert all(e["origin"] == "sink" for e in out["errors"])

        health = _req("GET", f"{base}/health")
        assert health["status"] == "up"
        assert health["apps"]["errapp"]["errors_stored"] == len(
            out["errors"])

        status, text = _raw(f"{base}/metrics")
        assert status == 200
        assert "# TYPE siddhi_errors_stored_total counter" in text
        assert 'siddhi_errors_stored_total{app="errapp"' in text
        assert 'siddhi_circuit_state{app="errapp",sink="O"}' in text

        # endpoint heals → replay over HTTP drains the store
        chaos.SCRIPTS["resterr"].heal()
        out = _req("POST", f"{base}/siddhi/apps/errapp/errors/replay", {})
        assert out["replayed"] == 5
        assert chaos.INSTANCES["resterr"].retry_join(30.0)
        assert sorted(e.data[0] for e in chaos.delivered("resterr")) == \
            list(range(5))
        out = _req("GET", f"{base}/siddhi/apps/errapp/errors")
        assert out["errors"] == []

        # purge path (nothing left → purged 0)
        out = _req("POST", f"{base}/siddhi/apps/errapp/errors/purge", {})
        assert out["purged"] == 0
    finally:
        svc.stop()


def test_error_endpoints_409_without_store():
    svc = SiddhiService(port=0).start()
    base = f"http://127.0.0.1:{svc.port}"
    try:
        _req("POST", f"{base}/siddhi/artifact/deploy", APP)
        try:
            _req("POST", f"{base}/siddhi/apps/restapp/errors/replay", {})
            raise AssertionError("expected HTTP 409")
        except urllib.error.HTTPError as e:
            assert e.code == 409
            assert json.loads(e.read())["error"] == \
                "no error store configured"
        out = _req("GET", f"{base}/siddhi/apps/restapp/errors")
        assert out == {"errors": [], "store": None}
    finally:
        svc.stop()
