#!/usr/bin/env python
"""Tier-1 timing report: turn a pytest log into a per-file table.

The tier-1 suite runs under a wall-clock budget, so knowing WHERE the
seconds go is the difference between "the suite is slow" and "one file
regressed 3x".  This parses the output of

    pytest tests/ -q -m 'not slow' --durations=0 ... 2>&1 | tee t1.log

(the ``--durations=0`` section lists every test phase as
``<sec>s <call|setup|teardown> <file>::<test>``) and emits

  * a per-file timing table on stdout (seconds by phase, test count),
  * optionally a bench-style JSON artifact (``-o T1_rNN.json``) so
    rounds can be diffed the same way BENCH_rNN.json rounds are.

Also extracted: the pass/fail/skip/error tallies, total wall time, and
the DOTS count (progress characters), which is the cross-round
comparison number the tier-1 budget workflow uses.

Usage:
    python tools/t1_report.py /tmp/_t1.log [-o T1_r10.json] [--top 25]
    python tools/t1_report.py --compare T1_r11.json T1_r12.json

``--compare OLD.json NEW.json`` diffs two such artifacts: per-file
regressions beyond 2x are flagged (exit 1), new and vanished files are
listed, and the tally deltas are printed — the round-over-round
regression gate for the tier-1 timing budget.
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from collections import defaultdict

DUR_RE = re.compile(
    r"^\s*(\d+(?:\.\d+)?)s\s+(call|setup|teardown)\s+"
    r"([\w./\\-]+\.py)::(\S+)")
#: "==== 857 passed, 3 skipped in 612.33s ====" (plain form under -q:
#: "857 passed, 3 skipped in 612.33s (0:10:12)")
SUMMARY_RE = re.compile(
    r"^(?:=+ )?((?:\d+ [a-z]+,? ?)+) in (\d+(?:\.\d+)?)s")
TALLY_RE = re.compile(r"(\d+) (passed|failed|skipped|errors?|xfailed|"
                      r"xpassed|warnings?|deselected)")
#: pytest -q progress lines: dots/letters, optionally ending "[ 37%]"
DOTS_RE = re.compile(r"^[.FEsx]+( *\[ *\d+%\])?$")


def parse_log(lines):
    per_file = defaultdict(lambda: {"call_s": 0.0, "setup_s": 0.0,
                                    "teardown_s": 0.0, "tests": set()})
    tallies, wall_s, dots = {}, None, 0
    for line in lines:
        line = line.rstrip("\n")
        m = DUR_RE.match(line)
        if m:
            sec, phase, path, test = m.groups()
            rec = per_file[path]
            rec[f"{phase}_s"] += float(sec)
            rec["tests"].add(test.split("[")[0])
            continue
        m = DOTS_RE.match(line)
        if m:
            dots += line.split("[")[0].count(".")
            continue
        m = SUMMARY_RE.search(line)
        if m:
            # a concatenation of several pytest runs (the 870 s budget
            # forces the suite into slices) sums naturally
            wall_s = round((wall_s or 0.0) + float(m.group(2)), 2)
            for n, what in TALLY_RE.findall(m.group(1)):
                key = what.rstrip("s") if what != "passed" else what
                tallies[key] = tallies.get(key, 0) + int(n)
    files = {}
    for path, rec in sorted(per_file.items()):
        total = rec["call_s"] + rec["setup_s"] + rec["teardown_s"]
        files[path] = {
            "total_s": round(total, 2),
            "call_s": round(rec["call_s"], 2),
            "setup_s": round(rec["setup_s"], 2),
            "teardown_s": round(rec["teardown_s"], 2),
            "n_tests": len(rec["tests"]),
        }
    return {"files": files, "tallies": tallies, "wall_s": wall_s,
            "dots_passed": dots,
            "timed_s": round(sum(f["total_s"] for f in files.values()), 2)}


def render_table(report, top=None):
    files = sorted(report["files"].items(),
                   key=lambda kv: -kv[1]["total_s"])
    if top:
        files = files[:top]
    w = max([len(p) for p, _ in files] or [4])
    out = [f"{'file':<{w}}  {'total':>8}  {'call':>8}  {'setup':>8}  "
           f"{'teardn':>8}  {'tests':>5}"]
    out.append("-" * len(out[0]))
    for path, f in files:
        out.append(f"{path:<{w}}  {f['total_s']:>7.2f}s  "
                   f"{f['call_s']:>7.2f}s  {f['setup_s']:>7.2f}s  "
                   f"{f['teardown_s']:>7.2f}s  {f['n_tests']:>5}")
    out.append("-" * len(out[1]))
    t = report["tallies"]
    out.append(f"{'TOTAL':<{w}}  {report['timed_s']:>7.2f}s   "
               f"wall={report['wall_s']}s  dots={report['dots_passed']}  "
               + " ".join(f"{k}={v}" for k, v in sorted(t.items())))
    return "\n".join(out)


#: a file is only a flagged regression when it grew beyond both the
#: ratio and this absolute floor — 2x of 0.1 s is scheduler noise
_COMPARE_MIN_S = 1.0


def compare(old, new, ratio=2.0):
    """Diff two parse_log artifacts.  Returns (lines, regressed) where
    ``regressed`` is True when any per-file total grew > ``ratio``x
    (above the noise floor) or a tally got worse."""
    lines, regressed = [], False
    of, nf = old.get("files", {}), new.get("files", {})
    for path in sorted(set(of) | set(nf)):
        o, n = of.get(path), nf.get(path)
        if o is None:
            lines.append(f"NEW      {path}  {n['total_s']:.2f}s "
                         f"({n['n_tests']} tests)")
            continue
        if n is None:
            lines.append(f"VANISHED {path}  was {o['total_s']:.2f}s "
                         f"({o['n_tests']} tests)")
            continue
        os_, ns_ = o["total_s"], n["total_s"]
        if ns_ > max(os_ * ratio, _COMPARE_MIN_S):
            lines.append(f"SLOWER   {path}  {os_:.2f}s -> {ns_:.2f}s "
                         f"({ns_ / os_ if os_ else float('inf'):.1f}x)")
            regressed = True
        elif os_ > max(ns_ * ratio, _COMPARE_MIN_S):
            lines.append(f"faster   {path}  {os_:.2f}s -> {ns_:.2f}s")
    osh, nsh = old.get("shards"), new.get("shards")
    if nsh is not None and osh is not None:
        od, nd = osh.get("routing_digest"), nsh.get("routing_digest")
        if od != nd:
            # the key->shard map is part of the checkpoint contract:
            # a digest change silently orphans every saved shard state
            lines.append(f"shards   routing_digest: {od} -> {nd}")
            regressed = True
    oc, nc = old.get("compile"), new.get("compile")
    if nc is not None and oc is not None:
        os_, ns_ = oc.get("seconds_total", 0.0), nc.get("seconds_total", 0.0)
        if ns_ > max(os_ * ratio, _COMPARE_MIN_S):
            lines.append(f"compile  probe seconds_total: {os_:.2f}s -> "
                         f"{ns_:.2f}s "
                         f"({ns_ / os_ if os_ else float('inf'):.1f}x)")
            regressed = True
    osc, nsc = old.get("schema"), new.get("schema")
    if osc is not None and nsc is not None:
        osm, nsm = osc.get("samples", {}), nsc.get("samples", {})
        for fname in sorted(set(osm) & set(nsm)):
            by_app = {r.get("app"): r for r in osm[fname]}
            for row in nsm[fname]:
                o = by_app.get(row.get("app"))
                if o is None or o.get("digest") == row.get("digest"):
                    continue
                ov, nv = o.get("versions", {}), row.get("versions", {})
                bumped = any(nv.get(k) != ov.get(k)
                             for k in set(ov) | set(nv))
                lines.append(
                    f"schema   {fname}:{row.get('app')}  "
                    f"{o.get('digest')} -> {row.get('digest')}"
                    + ("" if bumped else "  (NO version bump)"))
                if not bumped:
                    # a layout change that kept every declaration version
                    # breaks old checkpoints silently — SC010 at the
                    # round-artifact level
                    regressed = True
    osel, nsel = old.get("selection"), new.get("selection")
    if osel is not None and nsel is not None:
        osm, nsm = osel.get("samples", {}), nsel.get("samples", {})
        for fname in sorted(set(osm) & set(nsm)):
            od = osm[fname].get("device", 0)
            nd = nsm[fname].get("device", 0)
            oh = osm[fname].get("host", 0)
            nh = nsm[fname].get("host", 0)
            if (od, oh) == (nd, nh):
                continue
            lines.append(f"select   {fname}  device {od} -> {nd}, "
                         f"host {oh} -> {nh}")
            if nd < od or nh > oh:
                # a query that compiled to the device selection kernel
                # last round now pays the per-emission host pass — the
                # silent-perf-regression this artifact section exists
                # to catch
                regressed = True
    onum, nnum = old.get("numeric"), new.get("numeric")
    if nnum is not None:
        # old artifacts predating the NS verifier simply count as 0
        ot = onum.get("findings_total", 0) if onum else 0
        nt = nnum.get("findings_total", 0)
        if nt != ot:
            lines.append(
                f"numeric  NS findings: {ot} -> {nt}  (codes: "
                + (",".join(sorted({c for by in
                                    nnum.get("samples", {}).values()
                                    for c in by})) or "-") + ")")
            if nt > ot:     # new numeric-safety findings are a regression
                regressed = True
    oe, ne = old.get("engine_lint"), new.get("engine_lint")
    if ne is not None:
        od = oe.get("diagnostics", 0) if oe else 0
        nd = ne.get("diagnostics", 0)
        if nd != od:
            lines.append(f"engine   diagnostics: {od} -> {nd}  "
                         f"(codes: {','.join(ne.get('codes', [])) or '-'})")
            if nd > od:     # new CE/LW findings are a regression, full stop
                regressed = True
    ot, nt = old.get("tallies", {}), new.get("tallies", {})
    for key in sorted(set(ot) | set(nt)):
        a, b = ot.get(key, 0), nt.get(key, 0)
        if a != b:
            lines.append(f"tally    {key}: {a} -> {b}")
            if key in ("failed", "error") and b > a:
                regressed = True
            if key == "passed" and b < a:
                regressed = True
    lines.append(f"timed    {old.get('timed_s')}s -> "
                 f"{new.get('timed_s')}s   wall {old.get('wall_s')}s -> "
                 f"{new.get('wall_s')}s")
    return lines, regressed


def _engine_lint_summary():
    """Snapshot of the CE/LW engine self-audit, carried in the round
    artifact so --compare flags newly-introduced findings.  Returns
    None (key still written, tolerated by compare) if the package is
    not importable from here."""
    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        from siddhi_tpu.analysis.engine import analyze_engine
        rep = analyze_engine()
    except Exception as e:
        sys.stderr.write(f"[t1_report] engine lint skipped: {e}\n")
        return None
    return {"diagnostics": len(rep.diagnostics),
            "allowlisted": len(rep.allowlisted),
            "codes": sorted({d.code for d in rep.diagnostics})}


def _shards_summary():
    """Pin the key-routing contract into the round artifact: the FNV-1a
    owner digest must never drift (it addresses per-shard checkpoint
    state), so --compare treats any change as a regression.  Same
    import/tolerance pattern as the engine lint."""
    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        from siddhi_tpu.parallel.shards import routing_digest
    except Exception as e:
        sys.stderr.write(f"[t1_report] shards summary skipped: {e}\n")
        return None
    return {"routing_digest": routing_digest()}


def _compile_summary():
    """Pin the compile-observatory health into the round artifact: one
    tiny registry-routed probe compile, reported as attributed seconds +
    persistent-cache traffic.  --compare flags a > 2x compile-seconds
    growth (above a 1 s floor) — the early-warning for 'every round got
    slower because every test recompiles more'.  Same import/tolerance
    pattern as the engine lint."""
    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from siddhi_tpu.plan.shapes import shape_registry
        import jax.numpy as jnp
        reg = shape_registry()
        rj = reg.jit("t1.probe", {"n": 32}, lambda x: (x * 2 + 1).sum())
        rj(jnp.arange(32))
        tot = reg.totals()
    except Exception as e:
        sys.stderr.write(f"[t1_report] compile summary skipped: {e}\n")
        return None
    return {"seconds_total": round(tot["compile_seconds"], 4),
            "cache_hits": tot["cache_hits"],
            "cache_misses": tot["cache_misses"]}


def _schema_summary():
    """Pin the static persistent-state schema of every shipped sample
    into the round artifact (analysis/state_schema.py — jax-free).
    --compare flags any per-sample digest change whose declaration
    versions did NOT move: layout drift without a version bump is the
    report-level twin of the SC010 restore diagnostic.  Same
    import/tolerance pattern as the engine lint."""
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    try:
        from siddhi_tpu.analysis.state_schema import sample_schema_digests
        samples = sample_schema_digests(os.path.join(root, "samples"))
    except Exception as e:
        sys.stderr.write(f"[t1_report] schema summary skipped: {e}\n")
        return None
    return {"samples": samples}


def _selection_summary():
    """Pin the device-selection coverage of every shipped sample into
    the round artifact (analysis/state_schema.py — jax-free): per
    sample, how many selection-active queries (having / order-by /
    limit / offset) compile to the device egress kernel vs stay on the
    host QuerySelector, with the blocking reason for each host one.
    --compare treats any device->host slide as a regression.  Same
    import/tolerance pattern as the engine lint."""
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    try:
        from siddhi_tpu.analysis.state_schema import \
            sample_selection_coverage
        samples = sample_selection_coverage(os.path.join(root, "samples"))
    except Exception as e:
        sys.stderr.write(f"[t1_report] selection summary skipped: {e}\n")
        return None
    return {"samples": samples,
            "device_total": sum(v["device"] for v in samples.values()),
            "host_total": sum(v["host"] for v in samples.values())}


def _numeric_summary():
    """Pin the numeric-safety posture of every shipped sample into the
    round artifact (analysis/ranges.py — jax-free): warning-level NS0xx
    finding counts per sample plus the total.  --compare treats any
    growth in the total as a regression (a sample started overflowing,
    or the verifier got stricter without the samples being annotated).
    Same import/tolerance pattern as the engine lint."""
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    try:
        from siddhi_tpu.analysis.ranges import sample_numeric_counts
        samples = sample_numeric_counts(os.path.join(root, "samples"))
    except Exception as e:
        sys.stderr.write(f"[t1_report] numeric summary skipped: {e}\n")
        return None
    return {"samples": {f: by for f, by in sorted(samples.items()) if by},
            "findings_total": sum(sum(by.values())
                                  for by in samples.values())}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("log", nargs="?",
                    help="pytest log (run with --durations=0)")
    ap.add_argument("-o", "--out", help="write bench-style JSON artifact")
    ap.add_argument("--top", type=int, default=None,
                    help="only show the N slowest files in the table")
    ap.add_argument("--compare", nargs=2, metavar=("OLD", "NEW"),
                    help="diff two JSON artifacts (exit 1 on a > 2x "
                         "per-file regression or worse tallies)")
    args = ap.parse_args(argv)
    if args.compare:
        with open(args.compare[0]) as f:
            old = json.load(f)
        with open(args.compare[1]) as f:
            new = json.load(f)
        lines, regressed = compare(old, new)
        print("\n".join(lines))
        if regressed:
            sys.stderr.write("[t1_report] FAIL: regression vs "
                             f"{args.compare[0]}\n")
        return 1 if regressed else 0
    if not args.log:
        ap.error("a pytest log is required (or use --compare)")
    with open(args.log, errors="replace") as f:
        report = parse_log(f)
    if not report["files"]:
        sys.stderr.write("no --durations entries found in the log — "
                         "run pytest with --durations=0\n")
    print(render_table(report, top=args.top))
    if args.out:
        report["engine_lint"] = _engine_lint_summary()
        report["numeric"] = _numeric_summary()
        report["shards"] = _shards_summary()
        report["compile"] = _compile_summary()
        report["schema"] = _schema_summary()
        report["selection"] = _selection_summary()
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
            f.write("\n")
        sys.stderr.write(f"wrote {args.out}\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
