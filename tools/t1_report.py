#!/usr/bin/env python
"""Tier-1 timing report: turn a pytest log into a per-file table.

The tier-1 suite runs under a wall-clock budget, so knowing WHERE the
seconds go is the difference between "the suite is slow" and "one file
regressed 3x".  This parses the output of

    pytest tests/ -q -m 'not slow' --durations=0 ... 2>&1 | tee t1.log

(the ``--durations=0`` section lists every test phase as
``<sec>s <call|setup|teardown> <file>::<test>``) and emits

  * a per-file timing table on stdout (seconds by phase, test count),
  * optionally a bench-style JSON artifact (``-o T1_rNN.json``) so
    rounds can be diffed the same way BENCH_rNN.json rounds are.

Also extracted: the pass/fail/skip/error tallies, total wall time, and
the DOTS count (progress characters), which is the cross-round
comparison number the tier-1 budget workflow uses.

Usage:
    python tools/t1_report.py /tmp/_t1.log [-o T1_r10.json] [--top 25]
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from collections import defaultdict

DUR_RE = re.compile(
    r"^\s*(\d+(?:\.\d+)?)s\s+(call|setup|teardown)\s+"
    r"([\w./\\-]+\.py)::(\S+)")
#: "==== 857 passed, 3 skipped in 612.33s ====" (plain form under -q:
#: "857 passed, 3 skipped in 612.33s (0:10:12)")
SUMMARY_RE = re.compile(
    r"^(?:=+ )?((?:\d+ [a-z]+,? ?)+) in (\d+(?:\.\d+)?)s")
TALLY_RE = re.compile(r"(\d+) (passed|failed|skipped|errors?|xfailed|"
                      r"xpassed|warnings?|deselected)")
#: pytest -q progress lines: dots/letters, optionally ending "[ 37%]"
DOTS_RE = re.compile(r"^[.FEsx]+( *\[ *\d+%\])?$")


def parse_log(lines):
    per_file = defaultdict(lambda: {"call_s": 0.0, "setup_s": 0.0,
                                    "teardown_s": 0.0, "tests": set()})
    tallies, wall_s, dots = {}, None, 0
    for line in lines:
        line = line.rstrip("\n")
        m = DUR_RE.match(line)
        if m:
            sec, phase, path, test = m.groups()
            rec = per_file[path]
            rec[f"{phase}_s"] += float(sec)
            rec["tests"].add(test.split("[")[0])
            continue
        m = DOTS_RE.match(line)
        if m:
            dots += line.split("[")[0].count(".")
            continue
        m = SUMMARY_RE.search(line)
        if m:
            # a concatenation of several pytest runs (the 870 s budget
            # forces the suite into slices) sums naturally
            wall_s = round((wall_s or 0.0) + float(m.group(2)), 2)
            for n, what in TALLY_RE.findall(m.group(1)):
                key = what.rstrip("s") if what != "passed" else what
                tallies[key] = tallies.get(key, 0) + int(n)
    files = {}
    for path, rec in sorted(per_file.items()):
        total = rec["call_s"] + rec["setup_s"] + rec["teardown_s"]
        files[path] = {
            "total_s": round(total, 2),
            "call_s": round(rec["call_s"], 2),
            "setup_s": round(rec["setup_s"], 2),
            "teardown_s": round(rec["teardown_s"], 2),
            "n_tests": len(rec["tests"]),
        }
    return {"files": files, "tallies": tallies, "wall_s": wall_s,
            "dots_passed": dots,
            "timed_s": round(sum(f["total_s"] for f in files.values()), 2)}


def render_table(report, top=None):
    files = sorted(report["files"].items(),
                   key=lambda kv: -kv[1]["total_s"])
    if top:
        files = files[:top]
    w = max([len(p) for p, _ in files] or [4])
    out = [f"{'file':<{w}}  {'total':>8}  {'call':>8}  {'setup':>8}  "
           f"{'teardn':>8}  {'tests':>5}"]
    out.append("-" * len(out[0]))
    for path, f in files:
        out.append(f"{path:<{w}}  {f['total_s']:>7.2f}s  "
                   f"{f['call_s']:>7.2f}s  {f['setup_s']:>7.2f}s  "
                   f"{f['teardown_s']:>7.2f}s  {f['n_tests']:>5}")
    out.append("-" * len(out[1]))
    t = report["tallies"]
    out.append(f"{'TOTAL':<{w}}  {report['timed_s']:>7.2f}s   "
               f"wall={report['wall_s']}s  dots={report['dots_passed']}  "
               + " ".join(f"{k}={v}" for k, v in sorted(t.items())))
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("log", help="pytest log (run with --durations=0)")
    ap.add_argument("-o", "--out", help="write bench-style JSON artifact")
    ap.add_argument("--top", type=int, default=None,
                    help="only show the N slowest files in the table")
    args = ap.parse_args(argv)
    with open(args.log, errors="replace") as f:
        report = parse_log(f)
    if not report["files"]:
        sys.stderr.write("no --durations entries found in the log — "
                         "run pytest with --durations=0\n")
    print(render_table(report, top=args.top))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
            f.write("\n")
        sys.stderr.write(f"wrote {args.out}\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
