"""Device join-probe throughput: a range-condition stream-table join
(10k-row table — no hash path exists for `>` conditions) through the
public API, device probe vs forced-host numpy mask.

The probe is the reference JoinProcessor's per-event find() hot loop
(JoinProcessor.java:36-122); here each arriving chunk evaluates the
on-condition as one [chunk, table] broadcast program on the device
(core/join.py JoinRuntime._device_mask).
"""
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


APP = """
define stream L (id int, price float);
define table T (tid int, threshold float, band int);
define stream Fill (tid int, threshold float, band int);
from Fill insert into T;
@info(name='q')
from L join T on L.price > T.threshold and T.band == 3
select L.id as lid, T.tid as tid
insert into Out;
"""

N_TABLE = 10_000
CHUNK = 16_384
CHUNKS = 4


def run(engine):
    from siddhi_tpu import SiddhiManager, StreamCallback
    m = SiddhiManager()
    prefix = f"@app:engine('{engine}') " if engine else ""
    rt = m.create_siddhi_app_runtime("@app:playback " + prefix + APP)
    matched = [0]
    rt.add_callback("Out", StreamCallback(
        lambda evs: matched.__setitem__(0, matched[0] + len(evs))))
    rt.start()
    rng = np.random.default_rng(0)
    rt.get_input_handler("Fill").send_batch(
        {"tid": np.arange(N_TABLE, dtype=np.int64),
         # high thresholds keep the match count (and host emission cost)
         # small so the measured difference is the PROBE, not the emit
         "threshold": rng.uniform(99, 100, N_TABLE).astype(np.float32),
         "band": rng.integers(0, 8, N_TABLE).astype(np.int64)},
        timestamps=np.full(N_TABLE, 1_000_000, np.int64))
    h = rt.get_input_handler("L")
    qr = rt.query_runtimes["q"]
    backend = qr.backend
    # warmup at the MEASURED chunk shape (device: jit compile at
    # [CHUNK, N_TABLE] + the compaction-cap growth retrace)
    for _ in range(2):
        h.send_batch(
            {"id": np.arange(CHUNK, dtype=np.int64),
             "price": rng.uniform(0, 100, CHUNK).astype(np.float32)},
            timestamps=np.full(CHUNK, 1_001_000, np.int64))
    matched[0] = 0
    t0 = time.perf_counter()
    total = 0
    for ci in range(CHUNKS):
        n = CHUNK
        h.send_batch(
            {"id": np.arange(n, dtype=np.int64),
             "price": rng.uniform(0, 100, n).astype(np.float32)},
            timestamps=np.full(n, 1_002_000 + ci, np.int64))
        total += n
    dt = time.perf_counter() - t0
    rt.shutdown()
    return backend, total / dt, matched[0]


def main():
    b_dev, rate_dev, m_dev = run(None)
    b_host, rate_host, m_host = run("host")
    assert b_dev == "device" and b_host == "host", (b_dev, b_host)
    assert m_dev == m_host, (m_dev, m_host)
    print(f"table rows:        {N_TABLE}")
    print(f"probe pairs/chunk: {CHUNK * N_TABLE:,}")
    print(f"device probe:      {rate_dev:,.0f} events/s "
          f"({rate_dev * N_TABLE / 1e9:.2f}B pairs/s)")
    print(f"host numpy mask:   {rate_host:,.0f} events/s "
          f"({rate_host * N_TABLE / 1e9:.2f}B pairs/s)")
    print(f"speedup:           {rate_dev / rate_host:.2f}x "
          f"(matches identical: {m_dev})")


if __name__ == "__main__":
    main()
