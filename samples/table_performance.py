"""Table-probe throughput harness (reference model: performance-samples
NoIndexingTablePerformance.java:80-180 — stream-table join probes), run
twice: full-scan table vs @Index'd table to show the index-plan speedup
(util/parser/CollectionExpressionParser.java role)."""
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from siddhi_tpu import SiddhiManager, StreamCallback  # noqa: E402


def run(indexed: bool, table_rows=20_000, probes=2_000):
    ann = "@Index('symbol')" if indexed else ""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(f"""
        define stream FillStream (symbol string, volume long);
        define stream ProbeStream (symbol string);
        {ann}
        define table StockTable (symbol string, volume long);
        from FillStream insert into StockTable;
        from ProbeStream join StockTable
            on StockTable.symbol == ProbeStream.symbol
        select StockTable.symbol, StockTable.volume
        insert into OutputStream;
    """)
    count = [0]
    rt.add_callback("OutputStream", StreamCallback(
        lambda evs: count.__setitem__(0, count[0] + len(evs))))
    rt.start()
    rng = np.random.default_rng(0)
    syms = np.asarray([f"s{i}" for i in range(table_rows)], object)
    rt.get_input_handler("FillStream").send_batch(
        {"symbol": syms, "volume": rng.integers(1, 100, table_rows)})
    probe = rt.get_input_handler("ProbeStream")
    start = time.perf_counter()
    probe.send_batch({"symbol": syms[rng.integers(0, table_rows, probes)]})
    elapsed = time.perf_counter() - start
    rt.shutdown()
    label = "indexed" if indexed else "full-scan"
    print(f"{label:9s}: {probes / elapsed:,.0f} probes/sec over "
          f"{table_rows:,} rows ({count[0]:,} hits)")
    return probes / elapsed


def main():
    scan = run(indexed=False)
    idx = run(indexed=True)
    print(f"index speedup: {idx / scan:.1f}x")


if __name__ == "__main__":
    main()
