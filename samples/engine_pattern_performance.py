"""End-to-end ENGINE throughput: pattern matching through the public
SiddhiManager API on the device backend — junction → planner-built
DevicePatternRuntime (keyed NFA lanes) → match decode → callbacks.

This measures what a user actually gets (VERDICT r2 weak #5): the full
ingest/egress path including key→lane mapping, packing, device step,
payload decode and callback delivery — unlike samples/
tpu_pattern_performance.py, which benchmarks the raw compiled bank.
"""
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

APP = """
define stream S (sym string, price float, kind int);
partition with (sym of S) begin
@info(name='q')
from every e1=S[kind == 0] -> e2=S[kind == 1 and price > e1.price]
    within 40 sec
select e1.price as p1, e2.price as p2 insert into Out;
end;
"""

N_KEYS = 1024
CHUNK = 65_536
CHUNKS = 4
TS_STEP = 2          # ms between events: per-key gap ~2s << within 40s


def run(engine):
    from siddhi_tpu import SiddhiManager, StreamCallback
    m = SiddhiManager()
    prefix = f"@app:engine('{engine}') " if engine else ""
    rt = m.create_siddhi_app_runtime("@app:playback " + prefix + APP)
    matched = [0]
    rt.add_callback("Out", StreamCallback(
        lambda evs: matched.__setitem__(0, matched[0] + len(evs))))
    rt.start()
    h = rt.get_input_handler("S")
    rng = np.random.default_rng(0)
    syms = np.asarray([f"k{i}" for i in range(N_KEYS)], object)

    def chunk(t0):
        n = CHUNK
        return ({"sym": syms[np.arange(n) % N_KEYS],
                 "price": rng.uniform(0, 100, n).astype(np.float32),
                 "kind": rng.integers(0, 2, n).astype(np.int64)},
                t0 + np.arange(n, dtype=np.int64) * TS_STEP)

    cols, ts = chunk(1_000_000)
    h.send_batch(cols, timestamps=ts)            # warmup / compile
    dev = any(pr.device_mode for pr in rt.partition_runtimes)
    t0 = time.perf_counter()
    total = 0
    base = 1_000_000 + CHUNK * TS_STEP
    for ci in range(CHUNKS):
        cols, ts = chunk(base + ci * CHUNK * TS_STEP)
        h.send_batch(cols, timestamps=ts)
        total += CHUNK
    dt = time.perf_counter() - t0
    rt.shutdown()
    return dev, total / dt, matched[0]


def main():
    dev, rate_dev, m_dev = run(None)
    host, rate_host, m_host = run("host")
    assert dev and not host
    print(f"keys (lanes):    {N_KEYS}")
    print(f"engine (device): {rate_dev:,.0f} events/s, "
          f"{m_dev:,} matches delivered")
    print(f"engine (host):   {rate_host:,.0f} events/s, "
          f"{m_host:,} matches delivered")
    print(f"speedup:         {rate_dev / rate_host:.1f}x "
          f"(match parity: {m_dev == m_host})")


if __name__ == "__main__":
    main()
