"""End-to-end ENGINE throughput: pattern matching through the public
SiddhiManager API on the device backend — junction → planner-built
DevicePatternRuntime (keyed NFA lanes) → match decode → callbacks.

This measures what a user actually gets (VERDICT r2 weak #5): the full
ingest/egress path including key→lane mapping, packing, device step,
payload decode and callback delivery — unlike samples/
tpu_pattern_performance.py, which benchmarks the raw compiled bank.

Configurations measured:
  - device+@Async: the production shape — the async junction pipelines
    chunks (plan/planner.py DevicePatternRuntime keeps several egress
    reads in flight, ≙ the ingest/compute overlap of the reference's
    @Async disruptor junction, stream/StreamJunction.java:280-316);
    rt.flush() bounds the clock at full match delivery.
  - device sync: matches delivered before send_batch returns.
  - host: the host oracle on the same workload.
Each is reported twice: with the classic Event[] callback (per-match
python objects, reference StreamCallback semantics) and with a columnar
callback (receive_chunk override — the TPU-native zero-copy delivery).
"""
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

APP_BODY = """
define stream S (sym string, price float, kind int);
partition with (sym of S) begin
@info(name='q')
from every e1=S[kind == 0] -> e2=S[kind == 1 and price > e1.price]
    within 40 sec
select e1.price as p1, e2.price as p2 insert into Out;
end;
"""

N_KEYS = 1024
CHUNK = 65_536
CHUNKS = 8
TS_STEP = 2          # ms between events: per-key gap ~2s << within 40s


def run(engine, use_async, columnar=False):
    from siddhi_tpu import SiddhiManager, StreamCallback
    m = SiddhiManager()
    app = APP_BODY
    if use_async:
        app = app.replace(
            "define stream S",
            f"@Async(buffer.size='64', batch.size.max='{CHUNK}')\n"
            "define stream S", 1)
    prefix = f"@app:engine('{engine}') " if engine else ""
    rt = m.create_siddhi_app_runtime("@app:playback " + prefix + app)
    matched = [0]
    if columnar:
        cb = StreamCallback()
        cb.receive_chunk = lambda chunk: matched.__setitem__(
            0, matched[0] + len(chunk))
    else:
        cb = StreamCallback(
            lambda evs: matched.__setitem__(0, matched[0] + len(evs)))
    rt.add_callback("Out", cb)
    rt.start()
    h = rt.get_input_handler("S")
    rng = np.random.default_rng(0)
    syms = np.asarray([f"k{i}" for i in range(N_KEYS)], object)

    def chunk(t0):
        n = CHUNK
        return ({"sym": syms[np.arange(n) % N_KEYS],
                 "price": rng.uniform(0, 100, n).astype(np.float32),
                 "kind": rng.integers(0, 2, n).astype(np.int64)},
                t0 + np.arange(n, dtype=np.int64) * TS_STEP)

    cols, ts = chunk(1_000_000)
    h.send_batch(cols, timestamps=ts)            # warmup / compile
    rt.flush()
    dev = any(pr.device_mode for pr in rt.partition_runtimes)
    t0 = time.perf_counter()
    total = 0
    base = 1_000_000 + CHUNK * TS_STEP
    for ci in range(CHUNKS):
        cols, ts = chunk(base + ci * CHUNK * TS_STEP)
        h.send_batch(cols, timestamps=ts)
        total += CHUNK
    rt.flush()                                    # all matches delivered
    dt = time.perf_counter() - t0
    rt.shutdown()
    return dev, total / dt, matched[0]


def main():
    dev, rate_pipe, m_pipe = run(None, use_async=True)
    _, rate_pipe_col, m_col = run(None, use_async=True, columnar=True)
    dev_s, rate_sync, m_sync = run(None, use_async=False)
    host, rate_host, m_host = run("host", use_async=False)
    assert dev and dev_s and not host
    print(f"keys (lanes):              {N_KEYS}")
    print(f"engine device @Async:      {rate_pipe:,.0f} events/s, "
          f"{m_pipe:,} matches (Event[] callbacks)")
    print(f"engine device @Async col.: {rate_pipe_col:,.0f} events/s, "
          f"{m_col:,} matches (columnar callbacks)")
    print(f"engine device sync:        {rate_sync:,.0f} events/s, "
          f"{m_sync:,} matches")
    print(f"engine host:               {rate_host:,.0f} events/s, "
          f"{m_host:,} matches")
    parity = m_pipe == m_col == m_sync == m_host
    print(f"speedup vs host:           {rate_pipe / rate_host:.1f}x "
          f"(match parity: {parity})")
    assert parity, "device/host match counts diverge"


if __name__ == "__main__":
    main()
