"""Quick-start: registering a custom function extension (reference model:
quick-start-samples ExtensionSample.java + util/CustomFunctionExtension —
here via the @extension decorator / set_extension registry)."""
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from siddhi_tpu import SiddhiManager, StreamCallback  # noqa: E402
from siddhi_tpu.query_api.definition import AttrType  # noqa: E402
from siddhi_tpu.utils.extension import (FunctionExtension,  # noqa: E402
                                        extension)


@extension(namespace="custom", name="plus",
           description="Sum of all numeric arguments",
           parameters=[("values...", "numeric", "values to add")],
           returns="double",
           examples=["custom:plus(price, tax) adds the two columns"])
class PlusFunction(FunctionExtension):
    return_type = AttrType.DOUBLE

    def apply(self, *cols):
        out = cols[0]
        for c in cols[1:]:
            out = out + c
        return out


def main():
    m = SiddhiManager()
    m.set_extension("custom:plus", PlusFunction)
    rt = m.create_siddhi_app_runtime("""
        define stream S (price double, tax double);
        from S select custom:plus(price, tax) as total
        insert into OutputStream;
    """)
    rt.add_callback("OutputStream", StreamCallback(
        lambda evs: [print("->", e.data) for e in evs]))
    rt.start()
    rt.get_input_handler("S").send([100.0, 17.5])
    rt.shutdown()


if __name__ == "__main__":
    main()
