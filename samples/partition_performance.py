"""Partition throughput harness (reference model: performance-samples
PartitionPerformance.java — per-key partitioned sum over a value
partition)."""
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from siddhi_tpu import SiddhiManager, StreamCallback  # noqa: E402


def main(total=200_000, batch=10_000, n_keys=1000):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream TradeStream (symbol string, price double, volume long);
        partition with (symbol of TradeStream)
        begin
            from TradeStream select symbol, sum(volume) as total
            insert into OutputStream;
        end;
    """)
    count = [0]
    rt.add_callback("OutputStream", StreamCallback(
        lambda evs: count.__setitem__(0, count[0] + len(evs))))
    rt.start()
    h = rt.get_input_handler("TradeStream")
    rng = np.random.default_rng(0)
    keys = np.asarray([f"k{i}" for i in range(n_keys)], object)
    sent = 0
    start = time.perf_counter()
    while sent < total:
        h.send_batch({
            "symbol": keys[rng.integers(0, n_keys, batch)],
            "price": rng.uniform(0.0, 100.0, batch),
            "volume": rng.integers(1, 10, batch)})
        sent += batch
    elapsed = time.perf_counter() - start
    rt.shutdown()
    print(f"partitioned ({n_keys} keys): {sent / elapsed:,.0f} events/sec "
          f"({count[0]:,} outputs, {elapsed:.2f}s)")


if __name__ == "__main__":
    main()
