"""Quick-start: built-in and script functions in a select clause
(reference model: quick-start-samples FunctionSample.java)."""
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from siddhi_tpu import SiddhiManager, StreamCallback  # noqa: E402


def main():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream TempStream (room string, tempF double);
        from TempStream
        select room, convert((tempF - 32) * 5 / 9, 'double') as tempC,
               ifThenElse(tempF > 100.0, 'hot', 'ok') as status
        insert into OutputStream;
    """)
    rt.add_callback("OutputStream", StreamCallback(
        lambda evs: [print("->", e.data) for e in evs]))
    rt.start()
    h = rt.get_input_handler("TempStream")
    h.send(["kitchen", 98.6])
    h.send(["server-rack", 140.0])
    rt.shutdown()


if __name__ == "__main__":
    main()
