"""@Async-junction filter throughput harness (reference model:
performance-samples SimpleFilterSingleQueryWithDisruptorPerformance.java —
the disruptor ring becomes the @Async queue+worker re-batching junction,
stream/StreamJunction.java:280-316)."""
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from siddhi_tpu import SiddhiManager, StreamCallback  # noqa: E402


def main(total=1_000_000, batch=10_000):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @Async(buffer.size='1024', workers='2', batch.size.max='4096')
        define stream cseEventStream (symbol string, price float, volume long);
        from cseEventStream[volume < 150]
        select symbol, price insert into outputStream;
    """)
    count = [0]
    rt.add_callback("outputStream", StreamCallback(
        lambda evs: count.__setitem__(0, count[0] + len(evs))))
    rt.start()
    h = rt.get_input_handler("cseEventStream")
    rng = np.random.default_rng(0)
    sent = 0
    start = time.perf_counter()
    while sent < total:
        h.send_batch({
            "symbol": np.full(batch, "WSO2", object),
            "price": rng.uniform(0.0, 100.0, batch).astype(np.float32),
            "volume": rng.integers(0, 300, batch)})
        sent += batch
    rt.shutdown()      # drains the async queue
    elapsed = time.perf_counter() - start
    print(f"@Async: {sent / elapsed:,.0f} events/sec "
          f"({count[0]:,} matches, {elapsed:.2f}s)")


if __name__ == "__main__":
    main()
