"""TPU pattern-bank harness (the BASELINE north-star config at reduced
default size; see bench.py for the full 1k x 10k measurement)."""
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main(n_patterns=100, n_partitions=1000):
    import time

    import numpy as np

    from siddhi_tpu.ops.nfa import pack_blocks
    from siddhi_tpu.plan.nfa_compiler import CompiledPatternBank
    apps = [f"""
        define stream S (partition int, price float, kind int);
        @info(name='q')
        from every e1=S[kind == 0 and price > {thr}] -> e2=S[kind == 1 and price > e1.price]
            within 40 sec
        select e1.price as p1, e2.price as p2 insert into Out;
    """ for thr in np.linspace(5, 95, n_patterns)]
    bank = CompiledPatternBank(apps, n_partitions=n_partitions, n_slots=8,
                               pattern_chunk=n_patterns)
    rng = np.random.default_rng(0)
    t_per = 16
    n = n_partitions * t_per
    pids = np.repeat(np.arange(n_partitions), t_per)
    cols = {"partition": pids.astype(np.float32),
            "price": rng.uniform(0, 100, n).astype(np.float32),
            "kind": rng.integers(0, 2, n).astype(np.float32)}
    ts = 1_000_000 + np.arange(n, dtype=np.int64)
    block = pack_blocks(pids, cols, ts, np.zeros(n, np.int32), n_partitions,
                        base_ts=1_000_000)
    import jax
    jax.block_until_ready(bank.process_block(block))   # compile
    start = time.perf_counter()
    counts = bank.process_block(block)
    jax.block_until_ready(counts)
    elapsed = time.perf_counter() - start
    print(f"{n_patterns} NFAs x {n_partitions} partitions: "
          f"{n / elapsed:,.0f} events/sec, "
          f"matches={int(np.asarray(counts).sum())}")


if __name__ == "__main__":
    main(*(int(a) for a in sys.argv[1:3]))
