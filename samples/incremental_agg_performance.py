"""Incremental-aggregation ingest harness: host bucket cascade vs the
device slab segment-reduction path (ops/incremental_agg.py; reference
model: aggregation/IncrementalExecutor.java ingest)."""
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from siddhi_tpu import SiddhiManager  # noqa: E402

APP = """
define stream TradeStream (symbol string, price double, volume long, ts long);
define aggregation TradeAgg
from TradeStream
select symbol, avg(price) as avgPrice, sum(price) as total, count() as n
group by symbol
aggregate by ts every sec ... hour;
"""


def run(engine, total=200_000, batch=20_000, n_keys=50):
    prefix = f"@app:engine('{engine}') " if engine else ""
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(prefix + APP)
    rt.start()
    h = rt.get_input_handler("TradeStream")
    rng = np.random.default_rng(0)
    keys = np.asarray([f"k{i}" for i in range(n_keys)], object)
    base = 1_496_289_950_000
    sent = 0
    start = time.perf_counter()
    while sent < total:
        h.send_batch({
            "symbol": keys[rng.integers(0, n_keys, batch)],
            "price": rng.uniform(1.0, 100.0, batch),
            "volume": rng.integers(1, 10, batch),
            "ts": base + rng.integers(0, 3_600_000, batch)})
        sent += batch
    # materialise one query so lazy device sync is inside the clock
    rt.query("from TradeAgg within 1496289000000, 1496296000000 "
             "per 'seconds' select AGG_TIMESTAMP, symbol, total")
    elapsed = time.perf_counter() - start
    rt.shutdown()
    label = engine or "device(auto)"
    print(f"{label:12s}: {sent / elapsed:,.0f} events/sec ({elapsed:.2f}s)")
    return sent / elapsed


def main():
    host = run("host")
    dev = run(None)
    print(f"device speedup: {dev / host:.1f}x")


if __name__ == "__main__":
    main()
