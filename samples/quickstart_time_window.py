"""Quick-start: time-window aggregation per symbol (reference model:
quick-start-samples TimeWindowSample.java) — playback mode makes the
5-second window deterministic."""
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from siddhi_tpu import QueryCallback, SiddhiManager  # noqa: E402


def main():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        @app:playback
        define stream StockStream (symbol string, price float, volume long);
        @info(name='query1')
        from StockStream#window.time(5 sec)
        select symbol, avg(price) as avgPrice, count() as n
        group by symbol
        insert all events into OutputStream;
    """)
    rt.add_callback("query1", QueryCallback(
        lambda ts, cur, exp: print("@", ts,
                                   [e.data for e in (cur or [])],
                                   [e.data for e in (exp or [])])))
    rt.start()
    h = rt.get_input_handler("StockStream")
    h.send(["IBM", 100.0, 10], timestamp=1000)
    h.send(["IBM", 200.0, 10], timestamp=2000)
    h.send(["WSO2", 50.0, 10], timestamp=3000)
    h.send(["IBM", 300.0, 10], timestamp=8000)   # first two expired
    rt.shutdown()


if __name__ == "__main__":
    main()
