"""Quick-start: filter query with a stream callback (reference model:
quick-start-samples SimpleFilterSample.java)."""
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from siddhi_tpu import SiddhiManager, StreamCallback  # noqa: E402


def main():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream StockStream (symbol string, price float, volume long);
        from StockStream[volume < 150]
        select symbol, price insert into OutputStream;
    """)
    rt.add_callback("OutputStream", StreamCallback(
        lambda evs: [print("->", e.timestamp, e.data) for e in evs]))
    rt.start()
    h = rt.get_input_handler("StockStream")
    h.send(["WSO2", 700.0, 100])
    h.send(["IBM", 75.6, 100])
    h.send(["GOOG", 50.0, 200])     # filtered out
    rt.shutdown()


if __name__ == "__main__":
    main()
