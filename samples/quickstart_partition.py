"""Quick-start: per-key partitioned aggregation (reference model:
quick-start-samples PartitionSample.java)."""
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from siddhi_tpu import SiddhiManager, StreamCallback  # noqa: E402


def main():
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream TradeStream (symbol string, price double, volume long);
        partition with (symbol of TradeStream)
        begin
            from TradeStream
            select symbol, sum(volume) as total
            insert into OutputStream;
        end;
    """)
    rt.add_callback("OutputStream", StreamCallback(
        lambda evs: [print("->", e.data) for e in evs]))
    rt.start()
    h = rt.get_input_handler("TradeStream")
    h.send(["IBM", 75.6, 100])
    h.send(["WSO2", 57.6, 10])
    h.send(["IBM", 75.6, 100])     # IBM total -> 200, WSO2 unaffected
    rt.shutdown()


if __name__ == "__main__":
    main()
