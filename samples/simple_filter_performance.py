"""Filter-throughput harness (reference model:
siddhi-samples/performance-samples SimpleFilterSingleQueryPerformance.java —
prints events/sec + avg latency per 1M events, host path)."""
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from siddhi_tpu import SiddhiManager, StreamCallback  # noqa: E402


def main(total=1_000_000, batch=10_000):
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime("""
        define stream cseEventStream (symbol string, price float, volume long);
        from cseEventStream[volume < 150]
        select symbol, price insert into outputStream;
    """)
    count = [0]
    rt.add_callback("outputStream",
                    StreamCallback(lambda evs: count.__setitem__(
                        0, count[0] + len(evs))))
    rt.start()
    h = rt.get_input_handler("cseEventStream")
    rng = np.random.default_rng(0)
    sent = 0
    start = time.perf_counter()
    while sent < total:
        n = min(batch, total - sent)
        h.send_batch({
            "symbol": np.asarray(["WSO2"] * n, object),
            "price": rng.uniform(40, 80, n).astype(np.float32),
            "volume": rng.integers(50, 250, n).astype(np.int64)})
        sent += n
    elapsed = time.perf_counter() - start
    rt.shutdown()
    print(f"sent={sent} matched={count[0]} "
          f"throughput={sent / elapsed:,.0f} events/sec "
          f"avg_batch_latency={elapsed / (sent / batch) * 1000:.2f} ms")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000)
