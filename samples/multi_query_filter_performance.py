"""Multiple-filter-query throughput harness (reference model:
performance-samples SimpleFilterMultipleQueryPerformance.java — N filter
queries fanned out from one junction, events/sec per 1M events)."""
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from siddhi_tpu import SiddhiManager, StreamCallback  # noqa: E402


def main(total=1_000_000, batch=10_000, n_queries=10):
    queries = "\n".join(
        f"from cseEventStream[volume < {150 + i}] "
        f"select symbol, price insert into outputStream{i};"
        for i in range(n_queries))
    m = SiddhiManager()
    rt = m.create_siddhi_app_runtime(
        "define stream cseEventStream (symbol string, price float, "
        "volume long);\n" + queries)
    count = [0]
    rt.add_callback("outputStream0", StreamCallback(
        lambda evs: count.__setitem__(0, count[0] + len(evs))))
    rt.start()
    h = rt.get_input_handler("cseEventStream")
    rng = np.random.default_rng(0)
    sent = 0
    start = time.perf_counter()
    while sent < total:
        h.send_batch({
            "symbol": np.full(batch, "WSO2", object),
            "price": rng.uniform(0.0, 100.0, batch).astype(np.float32),
            "volume": rng.integers(0, 300, batch)})
        sent += batch
    elapsed = time.perf_counter() - start
    rt.shutdown()
    print(f"{n_queries} queries: {sent / elapsed:,.0f} events/sec "
          f"({count[0]:,} matches on q0, {elapsed:.2f}s)")


if __name__ == "__main__":
    main()
